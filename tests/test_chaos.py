"""Seeded chaos matrix: system invariants under probabilistic faults.

ChaosDrive rolls a seeded RNG on every storage call for intermittent
errors, latency spikes, and torn writes — the fault mix of a real aging
disk, replayable because the sequence is a pure function of (seed, call
order).  The matrix sweeps PUT/GET/ranged-GET/heal over several seeds
and asserts what no single-fault test can:

  - zero data loss: every ACKNOWLEDGED write reads back byte-identical
    (during the storm a read may fail with a clean StorageError, but
    bytes that do come back are never wrong);
  - rejected writes stay invisible — no partial artifact becomes data;
  - quorum edges stay clean errors, never corrupt bytes;
  - once the weather stops, heal converges: a bounded number of passes
    restores full stripe width and the next pass heals nothing.

A one-seed smoke runs in tier-1; the full seed matrix is `slow`.
"""

import os
import random

import numpy as np
import pytest

from minio_tpu.engine import heal as heal_mod
from minio_tpu.engine.erasure_set import BLOCK_SIZE, ErasureSet
from minio_tpu.storage.chaos import ChaosDrive, ErrChaosInjected
from minio_tpu.storage.errors import StorageError

pytestmark = pytest.mark.chaos


def payload(size, seed):
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def build_set(tmp, seed, n=4, m=2, tag=""):
    """Chaos drives start calm (rates 0) so bucket/format setup is
    deterministic; storm() turns the weather on."""
    drives = [ChaosDrive(f"{tmp}/{tag}s{seed}d{i}", seed=seed * 101 + i)
              for i in range(n)]
    es = ErasureSet(drives, default_parity=m)
    es.make_bucket("cb")
    return es, drives


def storm(drives, error_rate=0.05, slow_rate=0.05, torn_rate=0.04,
          slow_s=0.002):
    for d in drives:
        d.error_rate = error_rate
        d.slow_rate = slow_rate
        d.torn_rate = torn_rate
        d.slow_s = slow_s


SIZES = [700, 64_000, 300_000, BLOCK_SIZE + 77]


def run_scenario(tmp_path, seed, n=4, m=2, sizes=SIZES, rounds=1,
                 with_tier=False):
    es, drives = build_set(str(tmp_path), seed, n=n, m=m)
    if with_tier:
        from minio_tpu.engine.hotcache import (HotObjectCache,
                                               attach_sets)
        attach_sets(es, HotObjectCache(total_bytes=32 << 20))
    rng = np.random.default_rng(seed)
    storm(drives)

    acknowledged: dict[str, bytes] = {}
    rejected: list[str] = []
    for i, size in enumerate(sizes):
        name = f"o{i}"
        data = payload(size, seed * 1000 + i)
        try:
            es.put_object("cb", name, data)
        except StorageError:
            rejected.append(name)
        else:
            acknowledged[name] = data

    # -- reads under the storm: exact bytes or a clean error ----------
    for _ in range(rounds):
        for name, data in acknowledged.items():
            try:
                _, got = es.get_object("cb", name)
            except StorageError:
                continue                    # clean failure is allowed
            assert bytes(got) == data, (seed, name, "full GET corrupt")
            if len(data) > 10:
                off = int(rng.integers(0, len(data) - 2))
                ln = int(rng.integers(1, len(data) - off))
                try:
                    _, part = es.get_object("cb", name, offset=off,
                                            length=ln)
                except StorageError:
                    continue
                assert bytes(part) == data[off:off + ln], \
                    (seed, name, off, ln, "ranged GET corrupt")

    # -- weather stops: heal must converge ----------------------------
    for d in drives:
        d.chaos_off()
    for name in acknowledged:
        for _ in range(2 * n):
            rs = heal_mod.heal_object(es, "cb", name, deep=True)
            if all(not r.healed for r in rs):
                break
        rs = heal_mod.heal_object(es, "cb", name, deep=True)
        assert all(not r.healed for r in rs), \
            (seed, name, "heal did not converge")
        for r in rs:
            assert r.after == [heal_mod.DRIVE_OK] * n, (seed, name)

    # -- zero data loss, full width restored --------------------------
    for name, data in acknowledged.items():
        _, got = es.get_object("cb", name)
        assert bytes(got) == data, (seed, name, "data loss after heal")
    # rejected writes never became visible objects
    for name in rejected:
        with pytest.raises(StorageError):
            es.get_object("cb", name)
    return es, drives, acknowledged


class TestChaosSmoke:
    def test_one_seed_matrix(self, tmp_path):
        """Tier-1 smoke: one seed through the full scenario."""
        es, drives, acked = run_scenario(tmp_path, seed=7)
        # the storm actually injected something, or this tested nothing
        assert sum(sum(d.injected.values()) for d in drives) > 0

    def test_one_seed_matrix_hotcache(self, tmp_path, monkeypatch):
        """The same matrix with the RAM hot tier armed: every byte
        assertion in run_scenario now also polices reads SERVED FROM
        CACHE under the storm — a tainted (reconstructed/errored) read
        that slipped into the cache, or a stale entry surviving an
        overwrite, would fail the byte-exactness checks.  rounds=3 so
        repeat reads actually hit."""
        monkeypatch.setenv("MTPU_HOTCACHE", "1")
        es, drives, acked = run_scenario(tmp_path, seed=7, rounds=3,
                                         with_tier=True)
        st = es.hot_tier.stats()
        # Under the storm, injected faults taint reads off the verified
        # fast path — every tainted read must have BYPASSED the fill
        # (this is the corruption-never-cached rule doing its job).
        assert st["bypassed"] > 0
        # Weather is off now (run_scenario healed to convergence):
        # calm verified reads fill, then hit, still byte-exact.
        big = max(acked, key=lambda k: len(acked[k]))
        for _ in range(3):
            _, got = es.get_object("cb", big)
            assert bytes(got) == acked[big]
        assert es.hot_tier.stats()["hits"] > 0
        # zero stale reads: overwrite through the warm cache, the very
        # next read must be the new bytes.
        for j, name in enumerate(sorted(acked)[:2]):
            new = payload(len(acked[name]) + 17, seed=7000 + j)
            es.put_object("cb", name, new)
            _, got = es.get_object("cb", name)
            assert bytes(got) == new

    def test_determinism_same_seed_same_faults(self, tmp_path):
        """A failing seed is a reproducer: identical call sequences on
        identical seeds inject identical fault sequences."""
        logs = []
        for run in ("a", "b"):
            d = ChaosDrive(f"{tmp_path}/det{run}", seed=42)
            d.make_volume("v")
            d.error_rate, d.slow_rate, d.torn_rate = 0.3, 0.2, 0.2
            d.slow_s = 0.0
            outcomes = []
            for i in range(60):
                try:
                    d.write_all("v", f"f{i}", b"x" * 64)
                    outcomes.append("ok")
                except StorageError as e:
                    outcomes.append(type(e).__name__)
            logs.append((outcomes, dict(d.injected)))
        assert logs[0] == logs[1]

    def test_torn_write_never_becomes_data(self, tmp_path):
        """One drive tearing EVERY write: the stripe still quorums, the
        readback is byte-exact — the half-written artifacts on the torn
        drive never serve."""
        es, drives = build_set(str(tmp_path), seed=3, tag="torn")
        drives[0].torn_rate = 1.0
        data = payload(300_000, seed=31)
        es.put_object("cb", "t", data)
        _, got = es.get_object("cb", "t")
        assert bytes(got) == data
        assert drives[0].injected["torn"] > 0
        # ... and heal repairs the torn drive once the weather stops
        drives[0].chaos_off()
        r = heal_mod.heal_object(es, "cb", "t", deep=True)[0]
        assert 0 in r.healed_drives or r.before[0] == heal_mod.DRIVE_OK
        r2 = heal_mod.heal_object(es, "cb", "t", deep=True)[0]
        assert not r2.healed and r2.after == [heal_mod.DRIVE_OK] * 4

    def test_quorum_edge_stays_clean(self, tmp_path):
        """m fully-dead drives: exact bytes.  m+1: a clean StorageError
        — never wrong bytes, never a hang."""
        es, drives = build_set(str(tmp_path), seed=5, tag="edge")
        data = payload(200_000, seed=51)
        es.put_object("cb", "q", data)
        for d in drives[:2]:                    # = m
            d.error_rate = 1.0
        _, got = es.get_object("cb", "q")
        assert bytes(got) == data
        drives[2].error_rate = 1.0              # m + 1
        with pytest.raises(StorageError):
            es.get_object("cb", "q")


class TestTornRename:
    """Torn rename_data: the fault lands BETWEEN the two halves of
    publish — data dir moved into place, xl.meta never updated — the
    exact on-disk state crash point rename.pre_meta leaves behind."""

    def _set_with_torn_rename(self, tmp, seed=13):
        """Drive 0 tears every rename_data (and ONLY rename_data — the
        methods filter keeps the other write paths clean)."""
        drives = [ChaosDrive(f"{tmp}/trd{i}", seed=seed * 101 + i,
                             **({"methods": ("rename_data",),
                                 "torn_rate": 1.0} if i == 0 else {}))
                  for i in range(4)]
        es = ErasureSet(drives, default_parity=2)
        es.make_bucket("cb")
        return es, drives

    def test_orphan_data_dir_stays_invisible(self, tmp_path):
        es, drives = self._set_with_torn_rename(str(tmp_path))
        data = payload(300_000, seed=131)
        es.put_object("cb", "t", data)          # quorums on drives 1-3
        assert drives[0].injected["torn"] == 1
        # Drive 0 on disk: the data dir arrived, xl.meta never did —
        # an unreferenced orphan that must not serve.
        obj_dir = os.path.join(drives[0].root, "cb", "t")
        entries = os.listdir(obj_dir)
        assert "xl.meta" not in entries and entries, entries
        _, got = es.get_object("cb", "t")
        assert bytes(got) == data
        # Heal republishes the SAME data_dir and reclaims the orphan.
        drives[0].chaos_off()
        for _ in range(4):
            rs = heal_mod.heal_object(es, "cb", "t", deep=True)
            if all(not r.healed for r in rs):
                break
        r = heal_mod.heal_object(es, "cb", "t", deep=True)[0]
        assert not r.healed and r.after == [heal_mod.DRIVE_OK] * 4
        assert "xl.meta" in os.listdir(obj_dir)
        _, got = es.get_object("cb", "t")
        assert bytes(got) == data

    def test_draw_sequence_is_seed_oracle(self, tmp_path):
        """Determinism pin: the injected fault schedule is EXACTLY the
        one a bare random.Random(seed) predicts — three unconditional
        draws (slow, torn, err) per intercepted call.  This is what
        makes a failing seed a reproducer, and it's the invariant that
        adding rename_data to TORN_METHODS must not shift."""
        seed, rate = 77, 0.25
        d = ChaosDrive(f"{tmp_path}/oracle", seed=seed,
                       error_rate=rate, torn_rate=rate,
                       methods=("write_all",))
        d.make_volume("v")
        got = []
        for i in range(50):
            try:
                d.write_all("v", f"f{i}", b"y" * 32)
                got.append("ok")
            except ErrChaosInjected as e:
                got.append("torn" if "torn" in str(e) else "err")
            except StorageError:
                got.append("err")
        oracle_rng = random.Random(seed)
        want = []
        for _ in range(50):
            oracle_rng.random()                  # r_slow (rate 0)
            r_torn = oracle_rng.random()
            r_err = oracle_rng.random()
            want.append("torn" if r_torn < rate
                        else ("err" if r_err < rate else "ok"))
        assert got == want
        assert "torn" in got and "err" in got    # schedule non-trivial

    def test_torn_rename_with_scripted_overwrite(self, tmp_path):
        """Chaos + naughty compose: tear the publish of an OVERWRITE.
        The previous version must keep serving byte-exact (the torn
        republish displaced the old data dir on drive 0 only — below
        read quorum, so the committed version still wins)."""
        es, drives = self._set_with_torn_rename(str(tmp_path), seed=17)
        drives[0].chaos_off()
        v1 = payload(200_000, seed=171)
        es.put_object("cb", "ow", v1)            # clean commit
        drives[0].torn_rate = 1.0
        v2 = payload(200_000, seed=172)
        es.put_object("cb", "ow", v2)            # drive 0 tears; quorums
        _, got = es.get_object("cb", "ow")
        assert bytes(got) == v2                  # latest committed wins
        drives[0].chaos_off()
        for _ in range(4):
            rs = heal_mod.heal_object(es, "cb", "ow", deep=True)
            if all(not r.healed for r in rs):
                break
        _, got = es.get_object("cb", "ow")
        assert bytes(got) == v2


@pytest.mark.slow
class TestChaosSoak:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_seed_matrix_4p2(self, tmp_path, seed):
        run_scenario(tmp_path, seed=seed, rounds=3)

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_seed_matrix_6p2(self, tmp_path, seed):
        run_scenario(tmp_path, seed=seed, n=6, m=2,
                     sizes=SIZES + [2 * BLOCK_SIZE + 1234], rounds=2)

    def test_put_retry_under_storm_eventually_lands(self, tmp_path):
        """A client retrying rejected PUTs (fresh attempt, same key)
        eventually lands every object, and all land byte-exact."""
        es, drives = build_set(str(tmp_path), seed=9, n=6, m=2,
                               tag="retry")
        storm(drives, error_rate=0.12, torn_rate=0.08)
        want = {}
        for i in range(6):
            data = payload(150_000 + i * 7919, seed=900 + i)
            for attempt in range(25):
                try:
                    es.put_object("cb", f"r{i}", data)
                    break
                except StorageError:
                    continue
            else:
                pytest.fail(f"object r{i} never landed in 25 attempts")
            want[f"r{i}"] = data
        for d in drives:
            d.chaos_off()
        for name, data in want.items():
            for _ in range(12):
                rs = heal_mod.heal_object(es, "cb", name, deep=True)
                if all(not r.healed for r in rs):
                    break
            _, got = es.get_object("cb", name)
            assert bytes(got) == data


class TestTierChaos:
    """Satellite: the seeded fault storm pointed at the WARM tier
    backend instead of the drives.  Under injected tier errors/latency
    every outcome must be CLEAN — a transition either completes or
    leaves the full hot version (or a valid stub) intact, a GET through
    a stub either streams byte-exact or 503s, and once the weather
    stops the tier journal retries converge: journal at zero, tier
    object set exactly matching the live stubs, zero corrupt reads."""

    def _build(self, tmp_path, seed=5, error_rate=0.3):
        from minio_tpu.bucket.tier import (ChaosTierBackend,
                                           DirTierBackend, TierManager)
        from minio_tpu.engine.pools import ServerPools
        from minio_tpu.engine.sets import ErasureSets
        from minio_tpu.storage.drive import LocalDrive
        drives = [LocalDrive(str(tmp_path / "hot" / f"d{i}"))
                  for i in range(4)]
        pools = ServerPools([ErasureSets(drives, set_drive_count=4)])
        tm = TierManager(pools)
        chaotic = ChaosTierBackend(
            DirTierBackend(str(tmp_path / "warm")), seed=seed,
            error_rate=error_rate, slow_rate=0.1, slow_s=0.001)
        tm.add_tier("WARM", chaotic)
        return pools, tm, chaotic

    @staticmethod
    def _stub_or_hot(pools, tm, key, size):
        """The binary invariant under any fault: full hot version or a
        valid stub carrying the tier metadata — nothing in between."""
        fi = pools.head_object("cb", key)
        if tm.is_transitioned(fi):
            assert fi.size == 0, "torn stub carries data bytes"
            assert fi.metadata.get("x-mtpu-internal-tier-size") == \
                str(size)
            return "stub"
        assert fi.size == size, "hot version truncated by tier fault"
        return "hot"

    def test_tier_fault_storm_then_journal_convergence(self, tmp_path):
        from minio_tpu.server.client import S3Client, S3ClientError
        from minio_tpu.server.server import S3Server
        from minio_tpu.server.sigv4 import Credentials
        pools, tm, chaotic = self._build(tmp_path)
        pools.make_bucket("cb")
        want = {f"t{i}": payload(90_000 + i * 7919, seed=500 + i)
                for i in range(6)}
        for key, data in want.items():
            pools.put_object("cb", key, data)

        srv = S3Server(pools, Credentials("chaos", "chaos-secret"),
                       tier_mgr=tm).start()
        try:
            cli = S3Client(srv.endpoint, "chaos", "chaos-secret")
            # -- storm: transitions fail cleanly, stub-or-hot always --
            for key, data in want.items():
                for _ in range(12):
                    try:
                        if tm.transition_object("cb", key, "WARM"):
                            break
                    except StorageError:
                        pass  # injected: must have left hot or stub
                    if self._stub_or_hot(pools, tm, key,
                                         len(want[key])) == "stub":
                        break
            assert chaotic.injected["errors"] > 0, \
                "storm never fired — the scenario tested nothing"
            # -- storm: GETs through stubs 503 cleanly or stream exact --
            clean_errs = ok_reads = 0
            for key, data in want.items():
                if self._stub_or_hot(pools, tm, key, len(data)) != "stub":
                    continue
                for _ in range(4):
                    try:
                        got = cli.get_object("cb", key)
                    except S3ClientError as e:
                        assert e.status == 503, \
                            f"tier fault surfaced as {e.status}/{e.code}"
                        clean_errs += 1
                        continue
                    assert got == data, f"CORRUPT read through stub {key}"
                    ok_reads += 1
            assert ok_reads > 0
            # -- storm: a failed restore leaves the stub serviceable --
            stubs = [k for k in want if tm.is_transitioned(
                pools.head_object("cb", k))]
            if stubs:
                key = stubs[0]
                try:
                    tm.restore_object("cb", key)
                except StorageError:
                    pass
                self._stub_or_hot(pools, tm, key, len(want[key]))

            # -- calm weather: journal retries converge to zero --------
            chaotic.chaos_off()
            for _ in range(8):
                tm.drain_journal()
                if tm.journal.pending() == 0:
                    break
            assert tm.journal.pending() == 0, \
                f"journal never drained: {tm.journal.pending()} pending"
            # Tier object set == live stub set: no orphans, no leaks.
            live_tkeys = set()
            for key, data in want.items():
                fi = pools.head_object("cb", key)
                if tm.is_transitioned(fi):
                    live_tkeys.add(
                        fi.metadata["x-mtpu-internal-tier-key"])
                    assert cli.get_object("cb", key) == data
                else:
                    assert pools.get_object("cb", key)[1] == data
            on_tier = set()
            for dirpath, _, names in os.walk(str(tmp_path / "warm")):
                rel = os.path.relpath(dirpath, str(tmp_path / "warm"))
                for n in names:
                    on_tier.add(os.path.normpath(os.path.join(rel, n)))
            # DirTierBackend flattens "/" in keys to "_" on disk.
            assert on_tier == {t.replace("/", "_")
                               for t in live_tkeys}, (on_tier, live_tkeys)
        finally:
            srv.shutdown()
