"""Quorum-edge matrix over programmable drive faults (VERDICT r4 #8).

The reference's naughty-disk technique (cmd/naughty-disk_test.go +
the quorum sweeps in cmd/erasure-object_test.go TestGetObjectNoQuorum /
TestPutObjectNoQuorum): for each EC geometry, sweep the number of
failing drives across the write/read quorum boundary and assert the
EXACT API error — not just "it failed".
"""

import numpy as np
import pytest

from minio_tpu.engine.erasure_set import ErasureSet
from minio_tpu.storage.errors import (ErrDiskNotFound,
                                      ErrErasureReadQuorum,
                                      ErrErasureWriteQuorum,
                                      ErrObjectNotFound)
from minio_tpu.storage.naughty import NaughtyDrive


def build_set(tmp, n, parity, tag=""):
    drives = [NaughtyDrive(f"{tmp}/{tag}d{i}") for i in range(n)]
    es = ErasureSet(drives, default_parity=parity)
    es.make_bucket("qb")
    return es, drives


def payload(size=400_000, seed=1):
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


# (drives, parity): the reference's common geometries
GEOMETRIES = [(4, 2), (6, 2), (12, 4)]


@pytest.mark.parametrize("n,m", GEOMETRIES)
class TestWriteQuorumMatrix:
    def test_put_across_the_write_quorum_edge(self, n, m, tmp_path, ):
        """Writes survive exactly up to n - write_quorum failing
        drives; one more fails with ErrErasureWriteQuorum."""
        k = n - m
        write_quorum = k + (1 if k == m else 0)
        max_ok = n - write_quorum
        data = payload()
        for n_fail in range(0, max_ok + 2):
            es, drives = build_set(str(tmp_path), n, m,
                                   tag=f"w{n_fail}-")
            for d in drives[:n_fail]:
                d.fail_always("append_file")
                d.fail_always("write_metadata")
                d.fail_always("rename_data")
                d.fail_always("create_file")
            if n_fail <= max_ok:
                fi = es.put_object("qb", "obj", data)
                # written data must be readable again
                _, got = es.get_object("qb", "obj")
                assert got == data, (n, m, n_fail)
            else:
                with pytest.raises(ErrErasureWriteQuorum):
                    es.put_object("qb", "obj", data)
                # the failed PUT must not have become visible
                with pytest.raises(ErrObjectNotFound):
                    es.get_object("qb", "obj")

    def test_partial_write_failure_keeps_stripe_consistent(
            self, n, m, tmp_path):
        """A drive failing only its SECOND append (mid-stream, after a
        healthy first batch) must not corrupt the object."""
        es, drives = build_set(str(tmp_path), n, m, tag="p-")
        data = payload(40 << 20, seed=3)      # > 1 batch (32 MiB)
        drives[0].fail("append_file", on_call=2)
        fi = es.put_object("qb", "obj", data)
        _, got = es.get_object("qb", "obj")
        assert got == data


@pytest.mark.parametrize("n,m", GEOMETRIES)
class TestReadQuorumMatrix:
    def test_get_across_the_read_quorum_edge(self, n, m, tmp_path):
        """Reads reconstruct through up to m failing drives; m+1
        yields ErrErasureReadQuorum."""
        data = payload(seed=2)
        for n_fail in range(0, m + 2):
            es, drives = build_set(str(tmp_path), n, m,
                                   tag=f"r{n_fail}-")
            es.put_object("qb", "obj", data)
            for d in drives[:n_fail]:
                d.fail_always("read_file")
                d.fail_always("read_file_view")
            if n_fail <= m:
                _, got = es.get_object("qb", "obj")
                assert got == data, (n, m, n_fail)
            else:
                with pytest.raises(ErrErasureReadQuorum):
                    es.get_object("qb", "obj")

    def test_metadata_quorum_loss(self, n, m, tmp_path):
        """Losing read access to xl.meta beyond quorum surfaces a
        quorum error, not a silent wrong answer."""
        data = payload(seed=4)
        es, drives = build_set(str(tmp_path), n, m, tag="mm-")
        es.put_object("qb", "obj", data)
        for d in drives[: n - (n - m) + (n - m) // 2 + 1]:
            d.fail_always("read_version")
        with pytest.raises((ErrErasureReadQuorum, ErrObjectNotFound)):
            es.get_object("qb", "obj")


class TestFlakyAndRecovery:
    def test_nth_call_failure_triggers_spare_read(self, tmp_path):
        """Up to parity-many shard reads failing exactly once: the
        engine fetches spares and the byte-identical object comes
        back. (All n drives failing once is correctly FATAL — a tried
        shard is not re-read within one GET.)"""
        es, drives = build_set(str(tmp_path), 6, 2)
        data = payload(seed=5)
        es.put_object("qb", "obj", data)
        for d in drives[:2]:                   # = parity count
            d.fail("read_file", on_call=1)
            d.fail("read_file_view", on_call=1)
        _, got = es.get_object("qb", "obj")
        assert got == data

    def test_recovered_drive_serves_again(self, tmp_path):
        es, drives = build_set(str(tmp_path), 4, 2)
        data = payload(seed=6)
        es.put_object("qb", "obj", data)
        drives[0].offline()
        _, got = es.get_object("qb", "obj")    # degraded
        assert got == data
        drives[0].heal_thyself()
        _, got = es.get_object("qb", "obj")
        assert got == data

    def test_delete_write_quorum(self, tmp_path):
        n, m = 4, 2
        es, drives = build_set(str(tmp_path), n, m)
        es.put_object("qb", "obj", payload(seed=7))
        # all drives fail the delete mark -> quorum error, object stays
        for d in drives:
            d.fail_always("write_metadata")
            d.fail_always("delete")
            d.fail_always("delete_version")
            d.fail_always("read_version")
        with pytest.raises((ErrErasureWriteQuorum, ErrErasureReadQuorum,
                            ErrObjectNotFound, ErrDiskNotFound)):
            es.delete_object("qb", "obj")
        for d in drives:
            d.heal_thyself()
        _, got = es.get_object("qb", "obj")
        assert got == payload(seed=7)

    def test_call_counters_record_engine_traffic(self, tmp_path):
        es, drives = build_set(str(tmp_path), 4, 2)
        es.put_object("qb", "obj", payload(seed=8))
        # shard appends land as vectored write_file_batches when
        # MTPU_ZEROCOPY is on, append_file under the oracle
        assert all(d.calls.get("append_file", 0)
                   + d.calls.get("write_file_batches", 0) >= 1
                   for d in drives)
        es.get_object("qb", "obj")
        reads = sum(d.calls.get("read_file", 0)
                    + d.calls.get("read_file_view", 0) for d in drives)
        assert reads >= 2                      # K shards were fetched
