"""FS single-drive backend + disk cache wrapper tests, including the
full S3 server running over the FS layer."""

import numpy as np
import pytest

from minio_tpu.fs.backend import FSObjectLayer
from minio_tpu.fs.cache import DiskCache
from minio_tpu.server.client import S3Client, S3ClientError
from minio_tpu.server.server import S3Server
from minio_tpu.server.sigv4 import Credentials
from minio_tpu.storage.errors import (ErrBucketNotEmpty, ErrObjectNotFound,
                                      StorageError)

ROOT, SECRET = "fsadmin", "fsadmin-secret-1"


def payload(size, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


class TestFSBackend:
    def test_crud_roundtrip(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        data = payload(5000)
        fi = fs.put_object("bkt", "dir/obj", data)
        assert fi.metadata["etag"]
        got_fi, got = fs.get_object("bkt", "dir/obj")
        assert got == data
        _, part = fs.get_object("bkt", "dir/obj", offset=100, length=50)
        assert part == data[100:150]
        assert [f.name for f in fs.list_objects("bkt")] == ["dir/obj"]
        fs.delete_object("bkt", "dir/obj")
        with pytest.raises(ErrObjectNotFound):
            fs.head_object("bkt", "dir/obj")
        fs.delete_bucket("bkt")

    def test_nonempty_bucket_delete_refused(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        fs.put_object("bkt", "x", b"1")
        with pytest.raises(ErrBucketNotEmpty):
            fs.delete_bucket("bkt")

    def test_path_escape_rejected(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        with pytest.raises(StorageError):
            fs.put_object("bkt", "../../evil", b"x")

    def test_multipart(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        uid = fs.new_multipart_upload("bkt", "big")
        p1, p2 = payload(1000, 1), payload(2000, 2)
        i1 = fs.put_object_part("bkt", "big", uid, 1, p1)
        i2 = fs.put_object_part("bkt", "big", uid, 2, p2)
        fi = fs.complete_multipart_upload("bkt", "big", uid,
                                          [(1, i1.etag), (2, i2.etag)])
        assert fi.metadata["etag"].endswith("-2")
        _, got = fs.get_object("bkt", "big")
        assert got == p1 + p2

    def test_server_over_fs(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        srv = S3Server(fs, Credentials(ROOT, SECRET)).start()
        try:
            cli = S3Client(srv.endpoint, ROOT, SECRET)
            cli.make_bucket("web")
            data = payload(30000, 7)
            cli.put_object("web", "a/b.txt", data)
            assert cli.get_object("web", "a/b.txt") == data
            assert cli.get_object("web", "a/b.txt",
                                  range_=(10, 99)) == data[10:100]
            keys, prefixes = cli.list_objects("web", delimiter="/")
            assert prefixes == ["a/"]
            cli.delete_object("web", "a/b.txt")
        finally:
            srv.shutdown()


class TestDiskCache:
    def test_read_through_and_hit(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        data = payload(10000, 3)
        cache.put_object("bkt", "obj", data)
        _, a = cache.get_object("bkt", "obj")
        assert a == data and cache.misses == 1 and cache.hits == 0
        _, b = cache.get_object("bkt", "obj")
        assert b == data and cache.hits == 1
        _, c = cache.get_object("bkt", "obj", offset=10, length=20)
        assert c == data[10:30] and cache.hits == 2

    def test_write_invalidates(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        cache.put_object("bkt", "obj", b"v1")
        assert cache.get_object("bkt", "obj")[1] == b"v1"
        cache.put_object("bkt", "obj", b"v2")
        assert cache.get_object("bkt", "obj")[1] == b"v2"

    def test_stale_cache_revalidated_by_etag(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        cache.put_object("bkt", "obj", b"old")
        cache.get_object("bkt", "obj")
        # backend changed BEHIND the cache
        fs.put_object("bkt", "obj", b"new contents")
        _, got = cache.get_object("bkt", "obj")
        assert got == b"new contents"

    def test_lru_eviction(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"), max_bytes=25000)
        cache.make_bucket("bkt")
        for i in range(4):                    # 4 x 10k > 25k budget
            cache.put_object("bkt", f"o{i}", payload(10000, i))
            cache.get_object("bkt", f"o{i}")
        import os
        files = [f for f in os.listdir(str(tmp_path / "cache"))
                 if f.endswith(".data")]
        assert len(files) <= 2                # evicted down to budget
        # evicted objects still readable (read-through repopulates)
        _, got = cache.get_object("bkt", "o0")
        assert got == payload(10000, 0)
