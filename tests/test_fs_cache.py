"""FS single-drive backend + disk cache wrapper tests, including the
full S3 server running over the FS layer."""

import numpy as np
import pytest

from minio_tpu.fs.backend import FSObjectLayer
from minio_tpu.fs.cache import DiskCache
from minio_tpu.server.client import S3Client, S3ClientError
from minio_tpu.server.server import S3Server
from minio_tpu.server.sigv4 import Credentials
from minio_tpu.storage.errors import (ErrBucketNotEmpty, ErrObjectNotFound,
                                      StorageError)

ROOT, SECRET = "fsadmin", "fsadmin-secret-1"


def payload(size, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


class TestFSBackend:
    def test_crud_roundtrip(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        data = payload(5000)
        fi = fs.put_object("bkt", "dir/obj", data)
        assert fi.metadata["etag"]
        got_fi, got = fs.get_object("bkt", "dir/obj")
        assert got == data
        _, part = fs.get_object("bkt", "dir/obj", offset=100, length=50)
        assert part == data[100:150]
        assert [f.name for f in fs.list_objects("bkt")] == ["dir/obj"]
        fs.delete_object("bkt", "dir/obj")
        with pytest.raises(ErrObjectNotFound):
            fs.head_object("bkt", "dir/obj")
        fs.delete_bucket("bkt")

    def test_nonempty_bucket_delete_refused(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        fs.put_object("bkt", "x", b"1")
        with pytest.raises(ErrBucketNotEmpty):
            fs.delete_bucket("bkt")

    def test_path_escape_rejected(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        with pytest.raises(StorageError):
            fs.put_object("bkt", "../../evil", b"x")

    def test_multipart(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        fs.make_bucket("bkt")
        uid = fs.new_multipart_upload("bkt", "big")
        p1, p2 = payload(1000, 1), payload(2000, 2)
        i1 = fs.put_object_part("bkt", "big", uid, 1, p1)
        i2 = fs.put_object_part("bkt", "big", uid, 2, p2)
        fi = fs.complete_multipart_upload("bkt", "big", uid,
                                          [(1, i1.etag), (2, i2.etag)])
        assert fi.metadata["etag"].endswith("-2")
        _, got = fs.get_object("bkt", "big")
        assert got == p1 + p2

    def test_server_over_fs(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        srv = S3Server(fs, Credentials(ROOT, SECRET)).start()
        try:
            cli = S3Client(srv.endpoint, ROOT, SECRET)
            cli.make_bucket("web")
            data = payload(30000, 7)
            cli.put_object("web", "a/b.txt", data)
            assert cli.get_object("web", "a/b.txt") == data
            assert cli.get_object("web", "a/b.txt",
                                  range_=(10, 99)) == data[10:100]
            keys, prefixes = cli.list_objects("web", delimiter="/")
            assert prefixes == ["a/"]
            cli.delete_object("web", "a/b.txt")
        finally:
            srv.shutdown()


class TestDiskCache:
    def test_read_through_and_hit(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        data = payload(10000, 3)
        cache.put_object("bkt", "obj", data)
        _, a = cache.get_object("bkt", "obj")
        assert a == data and cache.misses == 1 and cache.hits == 0
        _, b = cache.get_object("bkt", "obj")
        assert b == data and cache.hits == 1
        _, c = cache.get_object("bkt", "obj", offset=10, length=20)
        assert c == data[10:30] and cache.hits == 2

    def test_write_invalidates(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        cache.put_object("bkt", "obj", b"v1")
        assert cache.get_object("bkt", "obj")[1] == b"v1"
        cache.put_object("bkt", "obj", b"v2")
        assert cache.get_object("bkt", "obj")[1] == b"v2"

    def test_stale_cache_revalidated_by_etag(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        cache.put_object("bkt", "obj", b"old")
        cache.get_object("bkt", "obj")
        # backend changed BEHIND the cache
        fs.put_object("bkt", "obj", b"new contents")
        _, got = cache.get_object("bkt", "obj")
        assert got == b"new contents"

    def test_lru_eviction(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"), max_bytes=25000)
        cache.make_bucket("bkt")
        for i in range(4):                    # 4 x 10k > 25k budget
            cache.put_object("bkt", f"o{i}", payload(10000, i))
            cache.get_object("bkt", f"o{i}")
        import os
        files = [f for f in os.listdir(str(tmp_path / "cache"))
                 if f.endswith(".data")]
        assert len(files) <= 2                # evicted down to budget
        # evicted objects still readable (read-through repopulates)
        _, got = cache.get_object("bkt", "o0")
        assert got == payload(10000, 0)


class TestDiskCacheDepth:
    """r5 depth: range caching, watermark GC, streaming interception,
    multipart invalidation, backend-outage serving, metrics."""

    def test_ranged_miss_caches_the_range(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"),
                          max_bytes=1 << 20,
                          max_object_bytes=30_000)  # whole obj too big
        cache.make_bucket("bkt")
        data = payload(100_000, 7)
        cache.put_object("bkt", "big", data)
        # whole-object GET streams through uncached (too large)...
        _, got = cache.get_object("bkt", "big")
        assert got == data and cache.usage_bytes() == 0
        # ...but a ranged miss caches exactly that range
        _, r1 = cache.get_object("bkt", "big", offset=1000, length=5000)
        assert r1 == data[1000:6000] and cache.misses == 2
        assert cache.usage_bytes() == 5000
        # a sub-range of the cached range is a HIT
        _, r2 = cache.get_object("bkt", "big", offset=2000, length=1000)
        assert r2 == data[2000:3000] and cache.hits == 1
        # outside the cached range: miss, new range file
        _, r3 = cache.get_object("bkt", "big", offset=50_000,
                                 length=2000)
        assert r3 == data[50_000:52_000] and cache.misses == 3
        assert cache.usage_bytes() == 7000

    def test_ranged_hits_after_whole_object_fill(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        data = payload(20_000, 8)
        cache.put_object("bkt", "obj", data)
        cache.get_object("bkt", "obj")               # whole-object fill
        for off, ln in ((0, 100), (5000, 5000), (19_000, 1000)):
            _, got = cache.get_object("bkt", "obj", offset=off,
                                      length=ln)
            assert got == data[off:off + ln]
        assert cache.hits == 3 and cache.misses == 1

    def test_watermark_gc(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"),
                          max_bytes=100_000, high_watermark=0.8,
                          low_watermark=0.5, max_object_bytes=50_000)
        cache.make_bucket("bkt")
        import time as _t
        for i in range(7):                    # 7 x 12k; high mark at 80k
            cache.put_object("bkt", f"o{i}", payload(12_000, i))
            cache.get_object("bkt", f"o{i}")
            _t.sleep(0.01)                    # distinct atimes for LRU
        # crossing 80k triggered GC down to <= 50k
        assert cache.usage_bytes() <= 50_000
        assert cache.evictions > 0
        # newest entries survive (LRU evicts oldest)
        hits_before = cache.hits
        cache.get_object("bkt", "o6")
        assert cache.hits == hits_before + 1

    def test_get_object_iter_consults_cache(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        data = payload(15_000, 9)
        cache.put_object("bkt", "obj", data)
        fi, it = cache.get_object_iter("bkt", "obj")
        assert b"".join(it) == data and cache.misses == 1
        fi, it = cache.get_object_iter("bkt", "obj", offset=10,
                                       length=100)
        assert b"".join(it) == data[10:110] and cache.hits == 1

    def test_multipart_commit_invalidates(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        cache.put_object("bkt", "obj", b"v1-original")
        assert cache.get_object("bkt", "obj")[1] == b"v1-original"
        uid = cache.new_multipart_upload("bkt", "obj")
        part = payload(6000, 11)
        info = cache.put_object_part("bkt", "obj", uid, 1, part)
        cache.complete_multipart_upload("bkt", "obj", uid,
                                        [(1, info.etag)])
        assert cache.get_object("bkt", "obj")[1] == part

    def test_backend_down_serves_cache(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        data = payload(9000, 12)
        cache.put_object("bkt", "obj", data)
        cache.get_object("bkt", "obj")               # fill
        def boom(*a, **kw):
            raise StorageError("backend unreachable")
        cache.backend.head_object = boom
        _, got = cache.get_object("bkt", "obj")
        assert got == data
        _, rng = cache.get_object("bkt", "obj", offset=5, length=10)
        assert rng == data[5:15]

    def test_metrics_surface_through_prometheus(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        srv = S3Server(cache, Credentials(ROOT, SECRET)).start()
        try:
            cli = S3Client(srv.endpoint, ROOT, SECRET)
            cli.make_bucket("mbk")
            cli.put_object("mbk", "obj", payload(4000, 13))
            assert cli.get_object("mbk", "obj")      # miss+fill
            assert cli.get_object("mbk", "obj")      # hit
            st, _, body = cli.request(
                "GET", "/minio/v2/metrics/cluster")
            assert st == 200
            text = body.decode()
            assert "mtpu_cache_hits_total 1" in text, text[-500:]
            assert "mtpu_cache_misses_total 1" in text
            assert "mtpu_cache_usage_bytes 4000" in text
        finally:
            srv.shutdown()

    def test_small_range_of_huge_object_caches_via_iter(self, tmp_path):
        """The front-door streaming path caches small ranges even when
        the whole object exceeds the cacheable size."""
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"),
                          max_object_bytes=10_000)
        cache.make_bucket("bkt")
        data = payload(80_000, 14)            # whole object uncacheable
        cache.put_object("bkt", "huge", data)
        fi, it = cache.get_object_iter("bkt", "huge")   # streams through
        assert b"".join(it) == data and cache.usage_bytes() == 0
        fi, it = cache.get_object_iter("bkt", "huge", offset=500,
                                       length=2000)     # range miss+fill
        assert b"".join(it) == data[500:2500]
        assert cache.usage_bytes() == 2000
        fi, it = cache.get_object_iter("bkt", "huge", offset=900,
                                       length=1000)     # range HIT
        assert b"".join(it) == data[900:1900]
        assert cache.hits == 1

    def test_range_refill_refreshes_meta_and_usage(self, tmp_path):
        """Out-of-band object change: ranged reads recover (meta is
        refreshed on range fill) and usage never double-counts an
        overwritten range file."""
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"),
                          max_object_bytes=10_000)
        cache.make_bucket("bkt")
        cache.put_object("bkt", "obj", payload(50_000, 15))
        cache.get_object("bkt", "obj", offset=0, length=1000)  # fill
        assert cache.usage_bytes() == 1000
        # replaced BEHIND the cache
        fs.put_object("bkt", "obj", payload(50_000, 16))
        _, got = cache.get_object("bkt", "obj", offset=0, length=1000)
        assert got == payload(50_000, 16)[:1000]
        assert cache.usage_bytes() == 1000    # overwrite, not +1000
        # and the NEXT ranged read is a HIT again (meta refreshed)
        hits = cache.hits
        cache.get_object("bkt", "obj", offset=0, length=1000)
        assert cache.hits == hits + 1

    def test_stale_version_files_purged_on_etag_change(self, tmp_path):
        """Out-of-band change must purge ALL old-version cache files —
        a surviving old range/whole file under the refreshed etag would
        serve corrupt bytes."""
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"),
                          max_object_bytes=10_000)
        cache.make_bucket("bkt")
        v1 = payload(50_000, 21)
        cache.put_object("bkt", "obj", v1)
        cache.get_object("bkt", "obj", offset=0, length=1000)
        cache.get_object("bkt", "obj", offset=2000, length=1000)
        # replaced behind the cache
        v2 = payload(50_000, 22)
        fs.put_object("bkt", "obj", v2)
        # a DIFFERENT range misses, refreshes meta ... and must purge
        _, got = cache.get_object("bkt", "obj", offset=4000,
                                  length=1000)
        assert got == v2[4000:5000]
        # the previously cached v1 ranges must NOT serve under v2's etag
        _, got = cache.get_object("bkt", "obj", offset=0, length=1000)
        assert got == v2[:1000]
        _, got = cache.get_object("bkt", "obj", offset=2000,
                                  length=1000)
        assert got == v2[2000:3000]

    def test_head_served_from_cache_when_backend_down(self, tmp_path):
        fs = FSObjectLayer(str(tmp_path / "fs"))
        cache = DiskCache(fs, str(tmp_path / "cache"))
        cache.make_bucket("bkt")
        data = payload(8000, 23)
        cache.put_object("bkt", "obj", data)
        cache.get_object("bkt", "obj")
        def boom(*a, **kw):
            raise StorageError("unreachable")
        cache.backend.head_object = boom
        fi = cache.head_object("bkt", "obj")
        assert fi.size == len(data)
