"""Closed-loop load generator for the concurrent data plane.

Each client thread runs a closed loop (think wrk, not an open-loop
arrival process): issue one PUT or GET, wait for it, record the
latency, repeat — so `clients` IS the offered concurrency, which is
exactly the knob the dispatch coalescer packs across.  Results report
aggregate throughput, latency quantiles, and the coalescer's mean
batch occupancy over the run (from DATA_PATH snapshot deltas), the
three numbers the ISSUE's acceptance criteria compare at 1/4/16
clients.

Usable as a library (bench.py's concurrent suite) or a CLI:

    python tools/loadgen.py --clients 16 --size-kib 1024 \
        --mix 0.5 --duration 10 --root /tmp/lg

Two drive modes:

  * engine mode (default): clients call the ErasureSet directly — no
    HTTP, isolates the data plane.
  * HTTP mode (--endpoint http://...): clients speak SigV4 over the
    wire against a RUNNING server — the mode that can actually observe
    the pre-fork worker pool, since SO_REUSEPORT balancing happens at
    accept time.  --procs forks the CLIENT side into multiple
    processes too, so a GIL-bound load generator can't become the
    bottleneck while measuring a multi-process server.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from minio_tpu.observe.metrics import DATA_PATH  # noqa: E402
from minio_tpu.storage.drive import LocalDrive  # noqa: E402


def _quantile(lat_s: list[float], q: float) -> float:
    if not lat_s:
        return 0.0
    return float(np.quantile(np.asarray(lat_s), q))


def _proc_tree_cpu_s(pid: int) -> float | None:
    """user+sys CPU seconds of `pid` AND its descendants (the pre-fork
    worker pool) from /proc — the server-side bill an HTTP run can't
    get from its own rusage.  None when /proc is unreadable (non-Linux,
    process gone)."""
    try:
        tick = os.sysconf("SC_CLK_TCK")
    except (ValueError, OSError):
        return None

    def one(p: int) -> float:
        with open(f"/proc/{p}/stat", "rb") as f:
            # field 2 (comm) may contain spaces: split after ')'
            rest = f.read().rpartition(b")")[2].split()
        return (int(rest[11]) + int(rest[12])) / tick  # utime, stime

    def kids(p: int) -> list[int]:
        out: list[int] = []
        try:
            for task in os.listdir(f"/proc/{p}/task"):
                with open(f"/proc/{p}/task/{task}/children", "rb") as f:
                    out += [int(c) for c in f.read().split()]
        except OSError:
            pass
        return out

    try:
        total, queue, seen = 0.0, [pid], set()
        while queue:
            p = queue.pop()
            if p in seen:
                continue
            seen.add(p)
            try:
                total += one(p)
            except (OSError, IndexError, ValueError):
                continue
            queue += kids(p)
        return total
    except Exception:  # noqa: BLE001 — metrics-only, never break a run
        return None


def zipf_cdf(n: int, s: float) -> np.ndarray:
    """CDF of a Zipf(s) distribution over ranks 1..n: P(i) ∝ 1/i^s.
    Rank 0 is the hottest key.  Sampling = searchsorted(uniform) —
    O(log n) per draw, no rejection (np.random.zipf is unbounded)."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return np.cumsum(w / w.sum())


def hot_rank_cut(n: int) -> int:
    """Ranks [0, cut) are the 'hot' class for the SLO split: the top
    decile (min 1 key) — under Zipf s≈1.1 it absorbs most GETs."""
    return max(1, n // 10)


def _zipf_pick(cdf: np.ndarray, crng) -> int:
    return int(np.searchsorted(cdf, crng.random(), side="right"))


def hot_cold_rows(lat_hot: list[float], lat_cold: list[float],
                  lat_ranged: list[float]) -> dict:
    """The SLO report rows the Zipfian runs compare: hot-key vs
    cold-key (vs ranged) p50/p99 — the hot rows are where a RAM hot
    tier must show up, the cold rows are where it must NOT regress."""
    return {
        "hot_gets": len(lat_hot),
        "hot_p50_ms": round(_quantile(lat_hot, 0.50) * 1e3, 3),
        "hot_p99_ms": round(_quantile(lat_hot, 0.99) * 1e3, 3),
        "cold_gets": len(lat_cold),
        "cold_p50_ms": round(_quantile(lat_cold, 0.50) * 1e3, 3),
        "cold_p99_ms": round(_quantile(lat_cold, 0.99) * 1e3, 3),
        "ranged_gets": len(lat_ranged),
        "ranged_p50_ms": round(_quantile(lat_ranged, 0.50) * 1e3, 3),
        "ranged_p99_ms": round(_quantile(lat_ranged, 0.99) * 1e3, 3),
    }


def keyspace_names(es, mode: str, total: int = 32,
                   prefix: str = "ks") -> list[str]:
    """Object names with PROVEN set placement (PR 10 device sharding):
    rejection-sample candidate names through the same sipHashMod the
    engine routes with.  'spread' returns names fanning out evenly over
    every erasure set (interleaved round-robin, so a client walking the
    list touches all sets — and therefore all device lanes —
    continuously); 'pinned' returns names that ALL land on set 0 (one
    lane saturated, the others idle).  A single ErasureSet has no ring,
    so both modes degrade to plain numbered names."""
    nset = int(getattr(es, "set_count", 1))
    key = getattr(es, "_dep_key", None)
    if nset <= 1 or key is None or mode == "default":
        return [f"{prefix}-{i}" for i in range(total)]
    from minio_tpu.utils.siphash import sip_hash_mod
    per: dict[int, list[str]] = {i: [] for i in range(nset)}
    want = max(1, total // nset) if mode == "spread" else total
    i = 0
    while True:
        if mode == "spread":
            if all(len(v) >= want for v in per.values()):
                break
        elif len(per[0]) >= want:
            break
        if i > 1_000_000:
            raise RuntimeError(f"keyspace sampling runaway ({mode})")
        name = f"{prefix}-{i}"
        i += 1
        per[sip_hash_mod(name, nset, key)].append(name)
    if mode == "pinned":
        return per[0][:want]
    if mode != "spread":
        raise ValueError(f"unknown keyspace mode {mode!r}")
    return [per[s][j] for j in range(want) for s in range(nset)]


def run_load(es, *, clients: int = 4, object_size: int = 1 << 20,
             put_frac: float = 0.5, duration_s: float = 5.0,
             bucket: str = "loadgen", warm_objects: int = 8,
             seed: int = 0, keyspace: str = "default",
             zipf: float | None = None,
             range_frac: float = 0.0,
             ilm_mix: float = 0.0, tier_mgr=None,
             tier_root: str | None = None,
             use_iter: bool = False,
             small: tuple[int, int] | None = None) -> dict:
    """Drive `clients` closed-loop workers against `es` for
    `duration_s`; returns aggregate GB/s, p50/p99 latency, and mean
    coalesced dispatch occupancy over the run.  `keyspace` picks the
    set-placement shape of every key touched (see keyspace_names);
    non-default modes add a per-set hit histogram and per-device lane
    dispatch stats to the result.

    `zipf` switches GET key choice from uniform to Zipf(s) over the
    warm set (rank 0 hottest) and adds hot-vs-cold p50/p99 SLO rows to
    the result; `range_frac` makes that fraction of GETs ranged
    (random aligned window), reported as their own SLO row.

    `use_iter` consumes GETs through get_object_iter — the serving
    path the HTTP handlers drive — measuring chunk lengths without
    materializing bytes, like a socket writer that hands each buffer
    to sendmsg.  This is the mode that exposes the zero-copy hot-view
    CPU saving; the default get_object path re-copies hot bodies in
    both flag modes.

    `ilm_mix` transitions that fraction of the warm set — its COLDEST
    Zipf ranks, the shape the scanner ages out — to a warm tier before
    the run; their GETs are served through stubs (head + tier
    read-through, the same path the HTTP handlers take) and tagged as
    their own stub_p50/p99 SLO row.  Pass a live `tier_mgr` to reuse
    one (ilm_bench does), else a DirTierBackend is stood up under
    `tier_root`.

    `small=(lo, hi)` switches to the small-object mix (ISSUE 19):
    every body size is drawn Zipf-skewed from a log-spaced ladder
    between `lo` and `hi` bytes (rank 0 = smallest, the real-world
    metadata-bound shape), `object_size` is ignored, and the result
    grows ops/s rows plus server-side `meta_*` deltas — amortized
    fsyncs/object, group-commit occupancy, and metadata read
    fan-outs/request — the group-commit plane's win metrics."""
    if not es.bucket_exists(bucket):
        es.make_bucket(bucket)
    rng = np.random.default_rng(seed)
    size_ladder: list[int] = []
    size_cdf = None
    warm_size: dict[str, int] = {}
    if small:
        lo, hi = small
        nsz = 12 if hi > lo else 1
        size_ladder = sorted({int(round(lo * (hi / lo) ** (i / max(1, nsz - 1))))
                              for i in range(nsz)})
        size_cdf = zipf_cdf(len(size_ladder), 1.1)
        bodies = {s: rng.integers(0, 256, s, dtype=np.uint8).tobytes()
                  for s in size_ladder}
        body = bodies[size_ladder[0]]
    else:
        body = rng.integers(0, 256, object_size,
                            dtype=np.uint8).tobytes()
    warm = keyspace_names(es, keyspace, total=max(1, warm_objects),
                          prefix="warm")
    for name in warm:
        if small:
            warm_size[name] = size_ladder[_zipf_pick(size_cdf, rng)]
            es.put_object(bucket, name, bodies[warm_size[name]])
        else:
            es.put_object(bucket, name, body)
    cdf = zipf_cdf(len(warm), zipf) if zipf else None
    cut = hot_rank_cut(len(warm))
    stub_names: set[str] = set()
    if ilm_mix > 0:
        from minio_tpu.bucket.tier import DirTierBackend, TierManager
        if tier_mgr is None:
            tier_mgr = TierManager(es)
        if not tier_mgr.list_tiers():
            root = tier_root or os.path.join(
                tempfile.mkdtemp(prefix="mtpu-loadgen-"), "tier")
            tier_mgr.add_tier("LGWARM", DirTierBackend(root))
        tname = tier_mgr.list_tiers()[0]
        ncold = max(1, min(len(warm), int(round(len(warm) * ilm_mix))))
        for name in warm[-ncold:]:       # coldest Zipf ranks age out
            if tier_mgr.transition_object(bucket, name, tname):
                stub_names.add(name)
    tier = getattr(es, "hot_tier", None) \
        or next((t for s in getattr(es, "sets", [])
                 if (t := getattr(s, "hot_tier", None)) is not None),
                None)
    tier0 = tier.stats() if tier is not None else None
    # PUT pool: placement-proven names partitioned per client (closed
    # loops overwrite within their own slice — no cross-client races).
    put_pool = keyspace_names(es, keyspace, total=max(clients * 8, 16),
                              prefix="put")
    put_slices = [put_pool[ci::clients] for ci in range(clients)]
    name_set: dict[str, int] = {}
    if keyspace != "default" and hasattr(es, "set_for"):
        name_set = {n: es.set_for(n).set_index
                    for n in warm + put_pool}

    stop = threading.Event()
    lat_put: list[list[float]] = [[] for _ in range(clients)]
    lat_get: list[list[float]] = [[] for _ in range(clients)]
    lat_hot: list[list[float]] = [[] for _ in range(clients)]
    lat_cold: list[list[float]] = [[] for _ in range(clients)]
    lat_ranged: list[list[float]] = [[] for _ in range(clients)]
    lat_stub: list[list[float]] = [[] for _ in range(clients)]
    nbytes = [0] * clients
    set_hits = [dict() for _ in range(clients)]
    errors: list[BaseException] = []

    def stub_get(name: str, off: int | None, ln: int | None) -> bytes:
        # The handlers' read path for transitioned versions: HEAD the
        # stub, stream the bytes back from the tier.  The engine's own
        # GET would return the stub's empty body (or raise out-of-range
        # for a ranged read against size 0).
        fi = es.head_object(bucket, name)
        if not tier_mgr.is_transitioned(fi) or fi.size > 0:
            # raced a restore: the hot copy is live again
            _, got = es.get_object(bucket, name, *(
                (off, ln) if off is not None else ()))
            return got
        if off is not None:
            return b"".join(tier_mgr.read_through_iter(fi, off, ln))
        return tier_mgr.read_through(fi)

    def client(ci: int) -> None:
        crng = np.random.default_rng(seed * 1000 + ci)
        mine = put_slices[ci]
        j = 0
        try:
            while not stop.is_set():
                is_put = crng.random() < put_frac
                t0 = time.monotonic()
                got_bytes = object_size
                rank = -1
                ranged = False
                is_stub = False
                if is_put:
                    name = (mine[j % len(mine)] if name_set
                            else f"c{ci}-{j}")
                    if small:
                        sz = size_ladder[_zipf_pick(size_cdf, crng)]
                        es.put_object(bucket, name, bodies[sz])
                        got_bytes = sz
                    else:
                        es.put_object(bucket, name, body)
                    j += 1
                else:
                    rank = (_zipf_pick(cdf, crng) if cdf is not None
                            else int(crng.integers(0, len(warm))))
                    name = warm[rank]
                    obj_sz = warm_size.get(name, object_size)
                    got_bytes = obj_sz
                    ranged = (range_frac > 0
                              and crng.random() < range_frac)
                    is_stub = name in stub_names
                    if ranged:
                        off = int(crng.integers(0, obj_sz))
                        ln = int(crng.integers(
                            1, obj_sz - off + 1))
                        if is_stub:
                            got_n = len(stub_get(name, off, ln))
                        elif use_iter:
                            _, it = es.get_object_iter(bucket, name,
                                                       off, ln)
                            got_n = sum(len(c) for c in it)
                        else:
                            _, got = es.get_object(bucket, name,
                                                   off, ln)
                            got_n = len(got)
                        got_bytes = ln
                        if got_n != ln:
                            raise AssertionError("short ranged read")
                    else:
                        if is_stub:
                            got_n = len(stub_get(name, None, None))
                        elif use_iter:
                            _, it = es.get_object_iter(bucket, name)
                            got_n = sum(len(c) for c in it)
                        else:
                            _, got = es.get_object(bucket, name)
                            got_n = len(got)
                        if got_n != obj_sz:
                            raise AssertionError("short read")
                dt = time.monotonic() - t0
                (lat_put if is_put else lat_get)[ci].append(dt)
                if not is_put:
                    if is_stub:
                        lat_stub[ci].append(dt)
                    elif ranged:
                        lat_ranged[ci].append(dt)
                    elif 0 <= rank < cut:
                        lat_hot[ci].append(dt)
                    else:
                        lat_cold[ci].append(dt)
                nbytes[ci] += got_bytes
                if name_set:
                    s = name_set.get(name, -1)
                    set_hits[ci][s] = set_hits[ci].get(s, 0) + 1
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            stop.set()

    snap0 = DATA_PATH.snapshot()
    # H2D boundary ledger + device-shard-cache deltas (ISSUE 17): how
    # many bytes crossed the host->device tunnel per byte this run
    # moved, and how often verified shard batches were already
    # device-resident.  Import is lazy: the ledger lives next to the
    # cache and neither pulls in jax at import time.
    from minio_tpu.ops import devcache as _devcache
    h2d0 = _devcache.h2d_stats()
    dc0 = _devcache.stats()
    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    # CPU-seconds-per-GB attribution (ISSUE 16): the engine runs
    # in-process here, so RUSAGE_SELF over the run window IS the
    # server-side CPU bill for the bytes moved.
    import resource
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t_start = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(60.0)
    wall = time.monotonic() - t_start
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    cpu_s = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime - ru0.ru_stime)
    snap1 = DATA_PATH.snapshot()
    h2d1 = _devcache.h2d_stats()
    dc1 = _devcache.stats()
    if errors:
        raise errors[0]

    puts = [x for per in lat_put for x in per]
    gets = [x for per in lat_get for x in per]
    alls = puts + gets
    d_disp = snap1["co_dispatches"] - snap0["co_dispatches"]
    d_items = snap1["co_items"] - snap0["co_items"]
    d_wait = snap1["co_wait_s"] - snap0["co_wait_s"]
    # digest lane deltas: how hard the PUT mix drove the native
    # multi-buffer MD5 plane (0s when MTPU_NATIVE_DIGEST=0)
    d_dg_calls = snap1["dg_md5_calls"] - snap0["dg_md5_calls"]
    d_dg_streams = snap1["dg_md5_streams"] - snap0["dg_md5_streams"]
    d_dg_bytes = snap1["dg_md5_bytes"] - snap0["dg_md5_bytes"]
    # per-device lane deltas (PR 10): which coalescer lanes dispatched,
    # how much, and at what batch occupancy over this run
    lanes0 = snap0.get("lanes", {})
    lane_dispatches: dict[int, int] = {}
    lane_occupancy: dict[int, float] = {}
    for dev, row in snap1.get("lanes", {}).items():
        prev = lanes0.get(dev, {})
        dd = row["dispatches"] - prev.get("dispatches", 0)
        di = row["items"] - prev.get("items", 0)
        if dd:
            lane_dispatches[dev] = dd
            lane_occupancy[dev] = round(di / dd, 3)
    merged_hits: dict[int, int] = {}
    for per in set_hits:
        for s, n in per.items():
            merged_hits[s] = merged_hits.get(s, 0) + n
    out = {
        "clients": clients,
        "object_size": object_size,
        "ops": len(alls),
        "puts": len(puts),
        "gets": len(gets),
        "wall_s": round(wall, 3),
        "gbps": round(sum(nbytes) / wall / 1e9, 3),
        # user+sys seconds burned per GB moved — the zero-copy
        # vertical's budget metric (lower = more kernel, less Python)
        "cpu_util": round(cpu_s / wall, 3) if wall else 0.0,
        "cpu_s_per_gb": round(cpu_s / (sum(nbytes) / 1e9), 3)
        if sum(nbytes) else 0.0,
        "p50_ms": round(_quantile(alls, 0.50) * 1e3, 3),
        "p99_ms": round(_quantile(alls, 0.99) * 1e3, 3),
        "put_p50_ms": round(_quantile(puts, 0.50) * 1e3, 3),
        "get_p50_ms": round(_quantile(gets, 0.50) * 1e3, 3),
        "co_dispatches": d_disp,
        "co_occupancy": round(d_items / d_disp, 3) if d_disp else 0.0,
        "co_wait_ms_per_item": round(d_wait / d_items * 1e3, 4)
        if d_items else 0.0,
        "dg_md5_calls": d_dg_calls,
        "dg_md5_occupancy": round(d_dg_streams / d_dg_calls, 3)
        if d_dg_calls else 0.0,
        "dg_md5_gbps": round(d_dg_bytes / wall / 1e9, 3),
        "keyspace": keyspace,
        "set_hits": {int(k): v for k, v in sorted(merged_hits.items())},
        "lane_dispatches": {int(k): v for k, v
                            in sorted(lane_dispatches.items())},
        "lane_occupancy": {int(k): v for k, v
                           in sorted(lane_occupancy.items())},
    }
    # Bytes-crossing-per-byte-served (ISSUE 17): ~1.0 on first touch,
    # ~0 when the device shard cache is absorbing the verify reads.
    total_b = sum(nbytes)
    d_h2d_b = h2d1["h2d_bytes"] - h2d0["h2d_bytes"]
    out["h2d_bytes"] = d_h2d_b
    out["h2d_dispatches"] = (h2d1["h2d_dispatches"]
                             - h2d0["h2d_dispatches"])
    out["h2d_bytes_per_byte"] = (round(d_h2d_b / total_b, 4)
                                 if total_b else 0.0)
    lane_h2d: dict[int, float] = {}
    for dev, row in h2d1["lanes"].items():
        db = (row["h2d_bytes"]
              - h2d0["lanes"].get(dev, {}).get("h2d_bytes", 0))
        if db:
            lane_h2d[int(dev)] = (round(db / total_b, 4)
                                  if total_b else 0.0)
    out["lane_h2d_bytes_per_byte"] = dict(sorted(lane_h2d.items()))
    if dc1 is not None:
        dh = dc1["hits"] - (dc0["hits"] if dc0 else 0)
        dm = dc1["misses"] - (dc0["misses"] if dc0 else 0)
        out["devcache_hits"] = dh
        out["devcache_misses"] = dm
        out["devcache_hit_ratio"] = (round(dh / (dh + dm), 4)
                                     if dh + dm else 0.0)
    if small:
        # Small-object rows (ISSUE 19): the mix is metadata-bound, so
        # ops/s (not GB/s) is the headline, and the server-side meta_*
        # deltas show what the group-commit plane amortized — fsyncs
        # per published object, journal batch occupancy, and metadata
        # read fan-outs per GET/HEAD request.
        out["small_lo"] = small[0]
        out["small_hi"] = small[1]
        out["ops_per_s"] = round(len(alls) / wall, 1) if wall else 0.0
        out["put_ops_per_s"] = (round(len(puts) / wall, 1)
                                if wall else 0.0)
        out["get_ops_per_s"] = (round(len(gets) / wall, 1)
                                if wall else 0.0)
        d_pub = snap1["meta_publishes"] - snap0["meta_publishes"]
        d_fs = snap1["meta_fsyncs"] - snap0["meta_fsyncs"]
        d_gc = (snap1["meta_group_commits"]
                - snap0["meta_group_commits"])
        d_gi = snap1["meta_group_items"] - snap0["meta_group_items"]
        d_rq = (snap1["meta_read_requests"]
                - snap0["meta_read_requests"])
        d_rr = snap1["meta_read_rounds"] - snap0["meta_read_rounds"]
        out["meta_fsyncs_per_object"] = (round(d_fs / d_pub, 4)
                                         if d_pub else 0.0)
        out["meta_batch_occupancy"] = (round(d_gi / d_gc, 3)
                                       if d_gc else 0.0)
        out["meta_read_fanouts_per_request"] = (round(d_rr / d_rq, 4)
                                                if d_rq else 0.0)
        out["meta_trim_hits"] = (snap1["meta_trim_hits"]
                                 - snap0["meta_trim_hits"])
    if zipf:
        out["zipf_s"] = zipf
        out.update(hot_cold_rows(
            [x for per in lat_hot for x in per],
            [x for per in lat_cold for x in per],
            [x for per in lat_ranged for x in per]))
    if ilm_mix > 0:
        stubs = [x for per in lat_stub for x in per]
        out["ilm_mix"] = ilm_mix
        out["stub_objects"] = len(stub_names)
        out["stub_gets"] = len(stubs)
        out["stub_p50_ms"] = round(_quantile(stubs, 0.50) * 1e3, 3)
        out["stub_p99_ms"] = round(_quantile(stubs, 0.99) * 1e3, 3)
        # exactly-once evidence: nothing left in flight after the run
        out["ilm_journal_pending"] = tier_mgr.journal.pending()
    if tier0 is not None:
        t1 = tier.stats()
        d_hits = t1["hits"] - tier0["hits"]
        d_miss = t1["misses"] - tier0["misses"]
        out["hotcache_hits"] = d_hits
        out["hotcache_misses"] = d_miss
        out["hotcache_hit_ratio"] = (
            round(d_hits / (d_hits + d_miss), 4)
            if d_hits + d_miss else 0.0)
        out["hotcache_fills"] = t1["fills"] - tier0["fills"]
    return out


def _http_clients_loop(endpoint: str, creds: tuple[str, str],
                       bucket: str, warm: list[str], body: bytes,
                       clients: int, put_frac: float,
                       duration_s: float, seed: int,
                       tag_pools: bool = False,
                       zipf: float | None = None,
                       range_frac: float = 0.0,
                       stub_names: frozenset = frozenset()) -> dict:
    """One load PROCESS: `clients` closed-loop threads, each with its
    own S3Client (own connections).  Returns picklable lat/byte tallies
    so --procs can merge across forks.  tag_pools reads the
    x-mtpu-pool response header off every PUT (multi-pool placement
    histogram — --during-decom's skew evidence); zipf/range_frac mirror
    run_load's Zipfian GET mix.  GETs of `stub_names` (warm keys the
    caller transitioned to a tier) are issued raw so the x-amz-
    storage-class response header can be checked — proof the bytes
    came through a stub — and tagged as their own lat_stub bucket."""
    from minio_tpu.server.client import S3Client
    stop = threading.Event()
    lat_put: list[list[float]] = [[] for _ in range(clients)]
    lat_get: list[list[float]] = [[] for _ in range(clients)]
    lat_hot: list[list[float]] = [[] for _ in range(clients)]
    lat_cold: list[list[float]] = [[] for _ in range(clients)]
    lat_ranged: list[list[float]] = [[] for _ in range(clients)]
    lat_stub: list[list[float]] = [[] for _ in range(clients)]
    nbytes = [0] * clients
    pool_hits: list[dict[str, int]] = [dict() for _ in range(clients)]
    stub_noclass = [0] * clients
    errors: list[str] = []
    cdf = zipf_cdf(len(warm), zipf) if zipf else None
    cut = hot_rank_cut(len(warm))

    def client(ci: int) -> None:
        cli = S3Client(endpoint, creds[0], creds[1])
        crng = np.random.default_rng(seed * 1000 + ci)
        j = 0
        try:
            while not stop.is_set():
                is_put = crng.random() < put_frac
                t0 = time.monotonic()
                got_bytes = len(body)
                rank = -1
                ranged = False
                if is_put:
                    h = cli.put_object(bucket, f"p{seed}-c{ci}-{j}",
                                       body)
                    j += 1
                    if tag_pools:
                        p = (h.get("x-mtpu-pool")
                             or h.get("X-Mtpu-Pool") or "?")
                        pool_hits[ci][p] = pool_hits[ci].get(p, 0) + 1
                else:
                    rank = (_zipf_pick(cdf, crng) if cdf is not None
                            else int(crng.integers(0, len(warm))))
                    name = warm[rank]
                    ranged = (range_frac > 0
                              and crng.random() < range_frac)
                    is_stub = name in stub_names
                    if ranged:
                        off = int(crng.integers(0, len(body)))
                        end = int(crng.integers(off, len(body)))
                        got_bytes = end - off + 1
                        if is_stub:
                            st, h, got = cli.request(
                                "GET", f"/{bucket}/{name}",
                                headers={"Range":
                                         f"bytes={off}-{end}"})
                            if st != 206:
                                raise AssertionError(
                                    f"stub ranged GET -> {st}")
                            if not (h.get("x-amz-storage-class") or
                                    h.get("X-Amz-Storage-Class")):
                                stub_noclass[ci] += 1
                        else:
                            got = cli.get_object(bucket, name,
                                                 range_=(off, end))
                        if len(got) != got_bytes:
                            raise AssertionError("short ranged read")
                    else:
                        if is_stub:
                            st, h, got = cli.request(
                                "GET", f"/{bucket}/{name}")
                            if st != 200:
                                raise AssertionError(
                                    f"stub GET -> {st}")
                            if not (h.get("x-amz-storage-class") or
                                    h.get("X-Amz-Storage-Class")):
                                stub_noclass[ci] += 1
                        else:
                            got = cli.get_object(bucket, name)
                        if len(got) != len(body):
                            raise AssertionError("short read")
                dt = time.monotonic() - t0
                (lat_put if is_put else lat_get)[ci].append(dt)
                if not is_put:
                    if is_stub:
                        lat_stub[ci].append(dt)
                    elif ranged:
                        lat_ranged[ci].append(dt)
                    elif 0 <= rank < cut:
                        lat_hot[ci].append(dt)
                    else:
                        lat_cold[ci].append(dt)
                nbytes[ci] += got_bytes
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(f"{type(e).__name__}: {e}")
            stop.set()

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(60.0)
    merged: dict[str, int] = {}
    for per in pool_hits:
        for p, n in per.items():
            merged[p] = merged.get(p, 0) + n
    return {"lat_put": [x for per in lat_put for x in per],
            "lat_get": [x for per in lat_get for x in per],
            "lat_hot": [x for per in lat_hot for x in per],
            "lat_cold": [x for per in lat_cold for x in per],
            "lat_ranged": [x for per in lat_ranged for x in per],
            "lat_stub": [x for per in lat_stub for x in per],
            "stub_noclass": sum(stub_noclass),
            "nbytes": sum(nbytes), "errors": errors,
            "pool_hits": merged}


def run_load_http(endpoint: str, *, clients: int = 4,
                  object_size: int = 1 << 20, put_frac: float = 0.5,
                  duration_s: float = 5.0, bucket: str = "loadgen",
                  warm_objects: int = 8, seed: int = 0, procs: int = 1,
                  access_key: str = "minioadmin",
                  secret_key: str = "minioadmin",
                  tag_pools: bool = False,
                  zipf: float | None = None,
                  range_frac: float = 0.0,
                  ilm_mix: float = 0.0,
                  tier_path: str | None = None,
                  server_pid: int | None = None) -> dict:
    """HTTP closed loop against a running endpoint; with procs>1 the
    `clients` are spread over that many forked client processes.

    `server_pid` (a LOCAL server process) adds server_cpu_util and
    server_cpu_s_per_gb columns from /proc/<pid>/stat across the
    process tree (MTPU_WORKERS children included) — the server-side
    CPU bill per byte served, the zero-copy budget metric.  Without
    it only client_cpu_util is reported, and that is CLIENT-side CPU
    (SigV4 signing + socket reads), not the server's.
    tag_pools adds a pool_hits histogram (PUTs per placement pool,
    from the x-mtpu-pool response header) — run it against a server
    mid-decommission and the draining pool must show zero hits.

    `ilm_mix` registers an fs warm tier through the admin plane (at
    `tier_path`, which must be a directory the SERVER can reach — this
    mode assumes a local endpoint) and transitions that fraction of
    the warm set's coldest ranks before the run; their GETs come back
    through stubs and are reported as stub_p50/p99 rows, with the
    x-amz-storage-class response header checked on every one."""
    import json as _json
    import multiprocessing as mp
    from minio_tpu.server.client import S3Client

    cli = S3Client(endpoint, access_key, secret_key)
    if not cli.bucket_exists(bucket):
        cli.make_bucket(bucket)
    rng = np.random.default_rng(seed)
    body = rng.integers(0, 256, object_size, dtype=np.uint8).tobytes()
    warm = [f"warm-{i}" for i in range(max(1, warm_objects))]
    for name in warm:
        cli.put_object(bucket, name, body)

    stub_names: frozenset = frozenset()
    if ilm_mix > 0:
        tname = "LGWARM"
        path = tier_path or tempfile.mkdtemp(prefix="mtpu-lg-tier-")
        st, _, rb = cli.request(
            "POST", "/minio/admin/v3/tier",
            body=_json.dumps({"name": tname, "type": "fs",
                              "path": path}).encode(),
            headers={"Content-Type": "application/json"})
        # 409 = tier already registered from an earlier run: reuse it
        if st not in (200, 409):
            raise RuntimeError(f"tier add -> {st}: {rb[:200]!r}")
        moved = []
        ncold = max(1, min(len(warm),
                           int(round(len(warm) * ilm_mix))))
        for name in warm[-ncold:]:       # coldest Zipf ranks age out
            st, _, rb = cli.request(
                "POST", "/minio/admin/v3/ilm",
                body=_json.dumps({"bucket": bucket, "object": name,
                                  "tier": tname}).encode(),
                headers={"Content-Type": "application/json"})
            if st != 200:
                raise RuntimeError(
                    f"transition {name} -> {st}: {rb[:200]!r}")
            if _json.loads(rb).get("transitioned"):
                moved.append(name)
        stub_names = frozenset(moved)

    procs = max(1, min(procs, clients))
    # spread clients over processes; earlier procs take the remainder
    per = [clients // procs + (1 if i < clients % procs else 0)
           for i in range(procs)]
    creds = (access_key, secret_key)
    import resource
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    srv_cpu0 = _proc_tree_cpu_s(server_pid) if server_pid else None
    t_start = time.monotonic()
    if procs == 1:
        parts = [_http_clients_loop(endpoint, creds, bucket, warm, body,
                                    clients, put_frac, duration_s,
                                    seed, tag_pools, zipf, range_frac,
                                    stub_names)]
    else:
        ctx = mp.get_context("fork")
        q: mp.Queue = ctx.Queue()

        def entry(i: int, n: int) -> None:
            q.put(_http_clients_loop(endpoint, creds, bucket, warm,
                                     body, n, put_frac, duration_s,
                                     seed + i, tag_pools, zipf,
                                     range_frac, stub_names))

        ps = [ctx.Process(target=entry, args=(i, n), daemon=True)
              for i, n in enumerate(per) if n]
        for p in ps:
            p.start()
        parts = [q.get(timeout=duration_s + 120) for _ in ps]
        for p in ps:
            p.join(30.0)
    wall = time.monotonic() - t_start
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    srv_cpu1 = _proc_tree_cpu_s(server_pid) if server_pid else None
    errs = [e for part in parts for e in part["errors"]]
    if errs:
        raise RuntimeError(f"loadgen client error: {errs[0]}")
    puts = [x for part in parts for x in part["lat_put"]]
    gets = [x for part in parts for x in part["lat_get"]]
    alls = puts + gets
    total_bytes = sum(p["nbytes"] for p in parts)
    res = {
        "endpoint": endpoint, "clients": clients, "procs": procs,
        "object_size": object_size,
        "ops": len(alls), "puts": len(puts), "gets": len(gets),
        "wall_s": round(wall, 3),
        "gbps": round(total_bytes / wall / 1e9, 3),
        # CLIENT-side CPU (signing, socket reads) — NOT the server's;
        # forked --procs workers bill their own rusage, so this row is
        # only the coordinating process and is indicative at best.
        "client_cpu_util": round(
            ((ru1.ru_utime - ru0.ru_utime)
             + (ru1.ru_stime - ru0.ru_stime)) / wall, 3)
        if wall else 0.0,
        "p50_ms": round(_quantile(alls, 0.50) * 1e3, 3),
        "p99_ms": round(_quantile(alls, 0.99) * 1e3, 3),
        "put_p50_ms": round(_quantile(puts, 0.50) * 1e3, 3),
        "get_p50_ms": round(_quantile(gets, 0.50) * 1e3, 3),
    }
    if srv_cpu0 is not None and srv_cpu1 is not None:
        srv_cpu = max(0.0, srv_cpu1 - srv_cpu0)
        res["server_cpu_util"] = round(srv_cpu / wall, 3) if wall else 0.0
        res["server_cpu_s_per_gb"] = round(
            srv_cpu / (total_bytes / 1e9), 3) if total_bytes else 0.0
    if zipf:
        res["zipf_s"] = zipf
        res.update(hot_cold_rows(
            [x for p in parts for x in p.get("lat_hot", [])],
            [x for p in parts for x in p.get("lat_cold", [])],
            [x for p in parts for x in p.get("lat_ranged", [])]))
    if ilm_mix > 0:
        stubs = [x for p in parts for x in p.get("lat_stub", [])]
        noclass = sum(p.get("stub_noclass", 0) for p in parts)
        res["ilm_mix"] = ilm_mix
        res["stub_objects"] = len(stub_names)
        res["stub_gets"] = len(stubs)
        res["stub_p50_ms"] = round(_quantile(stubs, 0.50) * 1e3, 3)
        res["stub_p99_ms"] = round(_quantile(stubs, 0.99) * 1e3, 3)
        # every stub GET must carry the tier's storage class — 0 here
        # means every tagged read provably came through a stub
        res["stub_missing_storage_class"] = noclass
    if tag_pools:
        merged: dict[str, int] = {}
        for part in parts:
            for p, n in part.get("pool_hits", {}).items():
                merged[p] = merged.get(p, 0) + n
        res["pool_hits"] = dict(sorted(merged.items()))
    return res


def parse_tenant_spec(spec: str) -> list[dict]:
    """Parse --tenants 'name:class:clients[:rps],...' into tenant rows.
    `name` doubles as the tenant's access key (the identity the QoS
    plane's MTPU_QOS_TENANTS map classes by); `class` is one of
    premium/standard/best-effort; `clients` is the tenant's closed-loop
    concurrency; optional `rps` caps the tenant's offered request rate
    client-side (0 = closed-loop, as fast as the server admits)."""
    out = []
    for frag in spec.split(","):
        frag = frag.strip()
        if not frag:
            continue
        parts = frag.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"tenant spec {frag!r}: want name:class:clients[:rps]")
        name, klass, clients = parts[0], parts[1], int(parts[2])
        if klass not in ("premium", "standard", "best-effort"):
            raise ValueError(f"tenant spec {frag!r}: unknown class "
                             f"{klass!r}")
        if clients < 1:
            raise ValueError(f"tenant spec {frag!r}: clients < 1")
        rps = float(parts[3]) if len(parts) == 4 else 0.0
        out.append({"name": name, "class": klass, "clients": clients,
                    "rps": rps})
    if not out:
        raise ValueError("empty tenant spec")
    return out


def _tenant_loop(endpoint: str, creds: tuple[str, str], bucket: str,
                 warm: list[str], body: bytes, clients: int,
                 put_frac: float, duration_s: float, seed: int,
                 rps: float) -> dict:
    """One tenant's client group: closed-loop threads signing with the
    TENANT's credentials, issuing raw requests so shed responses (503
    SlowDown) are COUNTED rather than raised — under deliberate
    overload, sheds are data, not failures.  Returns goodput (bytes of
    ops that succeeded), per-op latencies of successful ops only, and
    the shed/error tallies the QoS acceptance gates compare."""
    from minio_tpu.server.client import S3Client
    stop = threading.Event()
    lat_ok: list[list[float]] = [[] for _ in range(clients)]
    ok = [0] * clients
    shed = [0] * clients
    errs = [0] * clients
    nbytes = [0] * clients
    fatal: list[str] = []
    # client-side pacing: rps is the TENANT's offered rate, spread
    # evenly over its threads (0 = pure closed loop)
    per_thread_interval = clients / rps if rps > 0 else 0.0

    def client(ci: int) -> None:
        cli = S3Client(endpoint, creds[0], creds[1])
        crng = np.random.default_rng(seed * 1000 + ci)
        j = 0
        next_t = time.monotonic()
        try:
            while not stop.is_set():
                if per_thread_interval:
                    now = time.monotonic()
                    if now < next_t:
                        time.sleep(min(next_t - now, 0.25))
                        continue
                    next_t += per_thread_interval
                is_put = crng.random() < put_frac
                t0 = time.monotonic()
                try:
                    if is_put:
                        name = f"{creds[0]}-c{ci}-{j % 64}"
                        j += 1
                        st, _, rb = cli.request(
                            "PUT", f"/{bucket}/{name}", body=body)
                        moved = len(body)
                    else:
                        rank = int(crng.integers(0, len(warm)))
                        st, _, rb = cli.request(
                            "GET", f"/{bucket}/{warm[rank]}")
                        moved = len(rb)
                except (ConnectionError, TimeoutError, OSError):
                    # Shed responses close the connection; a pooled
                    # client racing that close sees a reset.  Under
                    # deliberate overload that's shed fallout, not a
                    # server error — reconnect and count it as shed.
                    cli = S3Client(endpoint, creds[0], creds[1])
                    shed[ci] += 1
                    continue
                dt = time.monotonic() - t0
                if st in (200, 206):
                    ok[ci] += 1
                    nbytes[ci] += moved
                    lat_ok[ci].append(dt)
                elif st == 503 and b"SlowDown" in rb:
                    shed[ci] += 1          # admission/throttle shed
                else:
                    errs[ci] += 1
        except BaseException as e:  # noqa: BLE001 — surfaced below
            fatal.append(f"{type(e).__name__}: {e}")
            stop.set()

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(60.0)
    wall = time.monotonic() - t_start
    if fatal:
        raise RuntimeError(f"tenant {creds[0]} client error: {fatal[0]}")
    lats = [x for per in lat_ok for x in per]
    n_ok, n_shed, n_err = sum(ok), sum(shed), sum(errs)
    total = n_ok + n_shed + n_err
    return {
        "ok": n_ok, "shed": n_shed, "errors": n_err,
        "attempts": total,
        "shed_rate": round(n_shed / total, 4) if total else 0.0,
        "goodput_gbps": round(sum(nbytes) / wall / 1e9, 4),
        "goodput_rps": round(n_ok / wall, 1),
        "p50_ms": round(_quantile(lats, 0.50) * 1e3, 3),
        "p99_ms": round(_quantile(lats, 0.99) * 1e3, 3),
    }


def run_load_tenants(endpoint: str, *, tenants: list[dict],
                     object_size: int = 1 << 20, put_frac: float = 0.5,
                     duration_s: float = 5.0, bucket: str = "loadgen",
                     warm_objects: int = 8, seed: int = 0,
                     access_key: str = "minioadmin",
                     secret_key: str = "minioadmin") -> dict:
    """Multi-tenant HTTP load: provision one IAM user per tenant (the
    access key the server's MTPU_QOS_TENANTS map classes), then run
    every tenant's client group CONCURRENTLY against the same bucket
    and report per-tenant goodput + p50/p99 + shed rows — the table
    where per-class isolation under overload either shows up or
    doesn't.  Root credentials (`access_key`/`secret_key`) provision
    users and warm the keyspace; tenants sign with their own."""
    import json as _json
    from minio_tpu.server.client import S3Client

    cli = S3Client(endpoint, access_key, secret_key)
    if not cli.bucket_exists(bucket):
        cli.make_bucket(bucket)
    for t in tenants:
        st, _, rb = cli.request(
            "POST", "/minio/admin/v3/users",
            body=_json.dumps({"accessKey": t["name"],
                              "secretKey": tenant_secret(t["name"]),
                              "policies": ["readwrite"]}).encode(),
            headers={"Content-Type": "application/json"})
        if st != 200:
            raise RuntimeError(
                f"user add {t['name']} -> {st}: {rb[:200]!r}")
    rng = np.random.default_rng(seed)
    body = rng.integers(0, 256, object_size, dtype=np.uint8).tobytes()
    warm = [f"warm-{i}" for i in range(max(1, warm_objects))]
    for name in warm:
        cli.put_object(bucket, name, body)

    results: dict[str, dict] = {}
    errors: list[BaseException] = []

    def run_one(i: int, t: dict) -> None:
        try:
            results[t["name"]] = _tenant_loop(
                endpoint, (t["name"], tenant_secret(t["name"])),
                bucket, warm, body, t["clients"], put_frac,
                duration_s, seed + 7919 * (i + 1), t["rps"])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    runners = [threading.Thread(target=run_one, args=(i, t),
                                daemon=True)
               for i, t in enumerate(tenants)]
    t_start = time.monotonic()
    for r in runners:
        r.start()
    for r in runners:
        r.join(duration_s + 120)
    wall = time.monotonic() - t_start
    if errors:
        raise errors[0]
    rows = {}
    for t in tenants:
        row = dict(results[t["name"]])
        row["class"] = t["class"]
        row["clients"] = t["clients"]
        if t["rps"]:
            row["offered_rps"] = t["rps"]
        rows[t["name"]] = row
    return {
        "endpoint": endpoint, "object_size": object_size,
        "duration_s": duration_s, "wall_s": round(wall, 3),
        "total_goodput_gbps": round(
            sum(r["goodput_gbps"] for r in rows.values()), 4),
        "total_ok": sum(r["ok"] for r in rows.values()),
        "total_shed": sum(r["shed"] for r in rows.values()),
        "total_errors": sum(r["errors"] for r in rows.values()),
        "tenants": rows,
    }


def tenant_secret(name: str) -> str:
    """Deterministic per-tenant secret key: tests and bench legs
    re-derive it instead of plumbing credentials around."""
    return f"{name}-tenant-secret"


def print_tenant_report(res: dict) -> None:
    """Human table for run_load_tenants output: one SLO row per
    tenant — the isolation evidence at a glance."""
    print(f"total goodput {res['total_goodput_gbps']} GB/s, "
          f"ok {res['total_ok']}, shed {res['total_shed']}, "
          f"errors {res['total_errors']}")
    print(f"{'tenant':<16}{'class':<14}{'clients':>8}{'ok':>8}"
          f"{'shed':>8}{'err':>6}{'shed%':>8}{'GB/s':>8}"
          f"{'p50_ms':>9}{'p99_ms':>9}")
    for name, r in res["tenants"].items():
        print(f"{name:<16}{r['class']:<14}{r['clients']:>8}"
              f"{r['ok']:>8}{r['shed']:>8}{r['errors']:>6}"
              f"{100 * r['shed_rate']:>7.1f}%{r['goodput_gbps']:>8}"
              f"{r['p50_ms']:>9}{r['p99_ms']:>9}")


def slo_report(endpoint: str, access_key: str, secret_key: str) -> dict:
    """Scrape the server's last-minute SLO window after a run: the
    mtpu_api_last_minute_{count,errors,p50,p99} families from
    /minio/v2/metrics/node, keyed by API.  Client-side latencies above
    measure the wire; this is the server's own view of the same window
    — the two disagreeing is itself a finding (queueing outside the
    handler).  Empty when the server runs with MTPU_SLO=0."""
    import re
    from minio_tpu.server.client import S3Client

    cli = S3Client(endpoint, access_key, secret_key)
    st, _, body = cli.request("GET", "/minio/v2/metrics/node")
    if st != 200:
        return {}
    out: dict[str, dict[str, float]] = {}
    pat = re.compile(r'^mtpu_api_last_minute_(\w+)\{api="([^"]+)"\} '
                     r'([0-9.eE+-]+)$')
    for line in body.decode().splitlines():
        m = pat.match(line)
        if m:
            out.setdefault(m.group(2), {})[m.group(1)] = \
                float(m.group(3))
    return out


def repl_report(endpoint: str, access_key: str, secret_key: str) -> dict:
    """Scrape the replication plane's counters after a run: the
    mtpu_repl_* families from /minio/v2/metrics/node.  One SLO row —
    a run that left a backlog (journal_pending > 0) or positive lag is
    reporting durable-but-not-yet-mirrored writes, not loss.  Empty
    when the server has no replication pool wired."""
    import re
    from minio_tpu.server.client import S3Client

    cli = S3Client(endpoint, access_key, secret_key)
    st, _, body = cli.request("GET", "/minio/v2/metrics/node")
    if st != 200:
        return {}
    out: dict[str, float] = {}
    pat = re.compile(r'^mtpu_repl_(\w+)(?:\{[^}]*\})? ([0-9.eE+-]+)$')
    for line in body.decode().splitlines():
        m = pat.match(line)
        if m:
            name, val = m.group(1), float(m.group(2))
            # lag is per-target labelled; keep the worst target
            out[name] = max(out.get(name, 0.0), val)
    return out


def make_set(root: str, n: int = 4, parity: int | None = None):
    from minio_tpu.engine.erasure_set import ErasureSet
    drives = [LocalDrive(os.path.join(root, f"d{i}")) for i in range(n)]
    return ErasureSet(drives, default_parity=parity)


def make_sets(root: str, nsets: int = 4, set_drives: int = 4,
              parity: int | None = None):
    """A full hash ring (nsets erasure sets of set_drives drives) —
    the topology the --keyspace modes route across."""
    from minio_tpu.engine.sets import ErasureSets
    drives = [LocalDrive(os.path.join(root, f"d{i}"))
              for i in range(nsets * set_drives)]
    return ErasureSets(drives, set_drive_count=set_drives,
                       default_parity=parity)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--size-kib", type=int, default=1024)
    ap.add_argument("--mix", type=float, default=0.5,
                    help="PUT fraction (rest are GETs)")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--drives", type=int, default=4)
    ap.add_argument("--parity", type=int, default=None)
    ap.add_argument("--sets", type=int, default=1,
                    help="engine mode: build a hash ring of N erasure "
                    "sets (of --drives each) instead of one bare set — "
                    "the topology --keyspace routes across")
    ap.add_argument("--keyspace", choices=("default", "spread",
                                           "pinned"),
                    default="default",
                    help="spread: keys provably fan out over every "
                    "erasure set (all device lanes busy); pinned: all "
                    "keys land on set 0 (one lane hot).  The output's "
                    "set_hits histogram proves the placement")
    ap.add_argument("--zipf", type=float, nargs="?", const=1.1,
                    default=None, metavar="S",
                    help="Zipf(s) GET key skew over the warm set "
                    "(rank 0 hottest; bare --zipf means s=1.1). "
                    "Adds hot-key vs cold-key p50/p99 SLO rows — the "
                    "split the hot-object cache must win")
    ap.add_argument("--small", nargs="?", const="4,64",
                    default=None, metavar="N[,M]",
                    help="small-object mix (engine mode): body sizes "
                    "drawn Zipf-skewed from a log ladder between N and "
                    "M KiB (bare --small means 4,64 — the inline "
                    "small-object band).  Reports ops/s, p50/p99, and "
                    "server-side meta_* deltas: amortized "
                    "fsyncs/object, group-commit occupancy, and "
                    "metadata read fan-outs/request")
    ap.add_argument("--range-frac", type=float, default=0.0,
                    help="fraction of GETs issued as random ranged "
                    "reads (their own SLO row)")
    ap.add_argument("--ilm-mix", type=float, default=0.0,
                    metavar="FRAC",
                    help="transition FRAC of the warm set's coldest "
                    "ranks to a warm tier before the run and tag "
                    "their GETs — served through ILM stubs — as their "
                    "own stub_p50/p99 SLO row.  Engine mode reads "
                    "through a local dir tier; HTTP mode registers an "
                    "fs tier via the admin plane (local endpoint) and "
                    "checks x-amz-storage-class on every stub GET")
    ap.add_argument("--warm-objects", type=int, default=None,
                    help="warm GET keyspace size (default 8, or 64 "
                    "under --zipf so the skew has a tail)")
    ap.add_argument("--root", default="/tmp/mtpu-loadgen")
    ap.add_argument("--endpoint", default="",
                    help="http(s)://host:port — drive a RUNNING server "
                    "over the wire instead of an in-process engine")
    ap.add_argument("--procs", type=int, default=1,
                    help="HTTP mode: fork the client side into N "
                    "processes (clients are spread across them)")
    ap.add_argument("--access-key",
                    default=os.environ.get("MTPU_ROOT_USER",
                                           "minioadmin"))
    ap.add_argument("--secret-key",
                    default=os.environ.get("MTPU_ROOT_PASSWORD",
                                           "minioadmin"))
    ap.add_argument("--profile", choices=("mixed", "put-digest"),
                    default="mixed",
                    help="put-digest: PUT-only 4 MiB objects — the "
                    "ETag-digest-bound shape the multi-buffer MD5 "
                    "lanes exist for (dg_md5_* in the output show "
                    "lane occupancy and aggregate hash rate)")
    ap.add_argument("--server-pid", type=int, default=None,
                    help="HTTP mode: pid of the LOCAL server — adds "
                    "server_cpu_util / server_cpu_s_per_gb columns "
                    "from /proc across its worker tree (the zero-copy "
                    "CPU-per-GB budget).  Engine mode reports this "
                    "inherently via cpu_util/cpu_s_per_gb: the engine "
                    "runs in-process, so rusage IS the server bill")
    ap.add_argument("--tenants", default="", metavar="SPEC",
                    help="HTTP mode: multi-tenant run — comma list of "
                    "name:class:clients[:rps] (class one of premium/"
                    "standard/best-effort; name doubles as the IAM "
                    "access key the server's MTPU_QOS_TENANTS map "
                    "classes).  Provisions the users, runs every "
                    "tenant's client group concurrently, and reports "
                    "per-tenant goodput + p50/p99 + shed rows")
    ap.add_argument("--during-decom", action="store_true",
                    help="HTTP mode: tag every PUT with the pool it "
                    "landed on (x-mtpu-pool response header) and "
                    "report a pool_hits placement-skew histogram — "
                    "run it against a server mid-decommission to "
                    "prove new writes avoid the draining pool")
    args = ap.parse_args(argv)
    small = None
    if args.small is not None:
        parts = [p for p in str(args.small).split(",") if p]
        try:
            lo = int(parts[0])
            hi = int(parts[1]) if len(parts) > 1 else 64
        except (ValueError, IndexError):
            print(f"--small expects N or N,M in KiB, got "
                  f"{args.small!r}", file=sys.stderr)
            return 2
        if lo <= 0 or hi < lo:
            print(f"--small bounds must satisfy 0 < N <= M, got "
                  f"{args.small!r}", file=sys.stderr)
            return 2
        small = (lo << 10, hi << 10)
        if args.endpoint:
            print("--small is engine-mode only (the meta_* deltas "
                  "come from the in-process DATA_PATH ledger)",
                  file=sys.stderr)
            return 2
        if args.zipf is None:      # sizes ride the Zipf key picker
            args.zipf = 1.1
    if args.during_decom and not args.endpoint:
        print("--during-decom requires --endpoint (the x-mtpu-pool "
              "header is an HTTP response surface)", file=sys.stderr)
        return 2
    if args.profile == "put-digest":
        args.mix = 1.0
        if args.size_kib == 1024:          # only override the default
            args.size_kib = 4096

    warm_objects = (args.warm_objects if args.warm_objects is not None
                    else (64 if args.zipf else 8))
    if args.tenants:
        if not args.endpoint:
            print("--tenants requires --endpoint (tenants are IAM "
                  "identities on a running server)", file=sys.stderr)
            return 2
        res = run_load_tenants(args.endpoint,
                               tenants=parse_tenant_spec(args.tenants),
                               object_size=args.size_kib << 10,
                               put_frac=args.mix,
                               duration_s=args.duration,
                               warm_objects=warm_objects,
                               access_key=args.access_key,
                               secret_key=args.secret_key)
        print_tenant_report(res)
        return 0
    if args.endpoint:
        res = run_load_http(args.endpoint, clients=args.clients,
                            object_size=args.size_kib << 10,
                            put_frac=args.mix,
                            duration_s=args.duration,
                            warm_objects=warm_objects,
                            procs=args.procs,
                            access_key=args.access_key,
                            secret_key=args.secret_key,
                            tag_pools=args.during_decom,
                            zipf=args.zipf,
                            range_frac=args.range_frac,
                            ilm_mix=args.ilm_mix,
                            server_pid=args.server_pid)
    else:
        es = (make_sets(args.root, nsets=args.sets,
                        set_drives=args.drives, parity=args.parity)
              if args.sets > 1
              else make_set(args.root, n=args.drives,
                            parity=args.parity))
        from minio_tpu.engine.hotcache import attach_sets, maybe_tier
        tier = maybe_tier()
        if tier is not None:
            attach_sets(es, tier)
        res = run_load(es, clients=args.clients,
                       object_size=args.size_kib << 10,
                       put_frac=args.mix, duration_s=args.duration,
                       warm_objects=warm_objects,
                       keyspace=args.keyspace, zipf=args.zipf,
                       range_frac=args.range_frac,
                       ilm_mix=args.ilm_mix,
                       tier_root=os.path.join(args.root, "tier"),
                       small=small)
    w = max(len(k) for k in res)
    for k, v in res.items():
        print(f"{k:<{w}}  {v}")
    if args.endpoint:
        try:
            slo = slo_report(args.endpoint, args.access_key,
                             args.secret_key)
        except Exception as e:  # noqa: BLE001 — report is best-effort
            print(f"\n(slo report unavailable: {e})", file=sys.stderr)
            slo = {}
        if slo:
            print("\nserver last-minute SLO window "
                  "(mtpu_api_last_minute_*):")
            print(f"{'api':<24}{'count':>8}{'errors':>8}"
                  f"{'p50_ms':>10}{'p99_ms':>10}")
            for api, d in sorted(slo.items()):
                print(f"{api:<24}{int(d.get('count', 0)):>8}"
                      f"{int(d.get('errors', 0)):>8}"
                      f"{d.get('p50', 0.0):>10.1f}"
                      f"{d.get('p99', 0.0):>10.1f}")
        try:
            repl = repl_report(args.endpoint, args.access_key,
                               args.secret_key)
        except Exception as e:  # noqa: BLE001 — report is best-effort
            print(f"\n(repl report unavailable: {e})", file=sys.stderr)
            repl = {}
        if repl:
            print("\nreplication plane (mtpu_repl_*): "
                  f"completed={int(repl.get('completed_total', 0))} "
                  f"failed={int(repl.get('failed_total', 0))} "
                  f"retries={int(repl.get('retries_total', 0))} "
                  f"backlog={int(repl.get('journal_pending', 0))} "
                  f"worst_lag_s={repl.get('lag_seconds', 0.0):.2f} "
                  f"MiB={repl.get('bytes_total', 0.0) / 2**20:.1f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
