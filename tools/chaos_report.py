#!/usr/bin/env python3
"""Run the seeded chaos scenario standalone and report what happened.

Builds an in-process erasure set of breaker-wrapped ChaosDrives, drives
a PUT/GET/ranged-GET/heal workload through a seeded fault storm, and
pretty-prints the fault-survival story per seed:

    $ python tools/chaos_report.py --seeds 1,2,3 --drives 6 --parity 2
    == seed 1 :: 6 drives (EC 4+2), 8 objects =====================
    puts: 8 acknowledged, 0 rejected   gets: 64 ok, 3 clean errors
    drive  state    errs slow  injected(err/slow/torn)  transitions
    d0     ok          0    0        3 /   2 /   1      -
    ...
    hedged_reads=41 hedge_fired=5 hedge_spares=7 co_fallbacks=0
    heal: converged in 2 pass(es); final readback: 8/8 byte-exact

Every fault is a pure function of (seed, call order) — a seed that
prints a data-loss line is a deterministic reproducer, re-runnable
under a debugger.  Exit status is non-zero if any invariant (exact
bytes, heal convergence, rejected-stays-invisible) is violated.

`--crash-matrix` switches to the kill-9 durability matrix instead:
real server subprocesses are booted, SIGKILLed inside MTPU_CRASH
points, and rebooted, and the per-scenario durability verdicts are
rendered as a table (the same scenarios tests/test_crash.py runs):

    $ python tools/chaos_report.py --crash-matrix
    $ python tools/chaos_report.py --crash-matrix \\
          --crash-points rename.pre_meta,mp.complete.publish

`--net-matrix` runs the partition/node-kill matrix instead: a real
3-node cluster boots under per-edge chaos TCP proxies, and every fault
kind (node kill, one-way/two-way partition, black-hole, reset storm,
slow peer) is injected mid-PUT/GET/heal (the same scenarios
tests/test_netchaos.py runs under -m 'netchaos and slow'):

    $ python tools/chaos_report.py --net-matrix
    $ python tools/chaos_report.py --net-matrix \\
          --net-scenarios kill-mid-put,oneway-mid-get

`--decom` runs the decommission kill-9 matrix instead: a real 2-pool
server is SIGKILLed inside every MTPU_CRASH=decom.* point mid-drain,
rebooted, auto-resumed from the fsynced decom journal, and the
zero-loss verdicts are tabled (the same scenarios tests/test_decom.py
runs under -m 'decom and slow'):

    $ python tools/chaos_report.py --decom
    $ python tools/chaos_report.py --decom \\
          --decom-points decom.pre_delete,decom.checkpoint

`--repl` runs the replication-under-fire matrix instead: first the
kill-9 leg (a source server SIGKILLed inside every MTPU_CRASH=repl.*
point while a live target stays up, rebooted, journal replayed, the
victim converging byte-exact at the same version id — plus a
2000-object resync killed mid-enumeration and resumed), then the
two-cluster partition leg (source+target with the remote endpoint
routed through a chaos TCP proxy; black-hole mid-replication,
black-hole mid-resync, seeded fault storm — the same scenarios
tests/test_replication_fault.py runs under -m 'repl and slow'):

    $ python tools/chaos_report.py --repl
    $ python tools/chaos_report.py --repl --repl-points repl.post_copy
    $ python tools/chaos_report.py --repl --repl-skip-net --repl-skip-resync

`--ilm` runs the ILM kill-9 matrix instead: a server is SIGKILLed
inside every MTPU_CRASH=ilm.* point mid-transition (or mid tier-free),
rebooted, tier-journal replayed, and the exactly-once verdicts are
tabled (the same scenarios tests/test_crash.py runs under
-m 'crash and slow'):

    $ python tools/chaos_report.py --ilm
    $ python tools/chaos_report.py --ilm --ilm-points ilm.post_copy
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from minio_tpu.engine import heal as heal_mod  # noqa: E402
from minio_tpu.engine.erasure_set import ErasureSet  # noqa: E402
from minio_tpu.observe.metrics import DATA_PATH  # noqa: E402
from minio_tpu.storage.chaos import ChaosDrive  # noqa: E402
from minio_tpu.storage.errors import StorageError  # noqa: E402
from minio_tpu.storage.health_wrap import wrap_drives  # noqa: E402

HEDGE_KEYS = ("hedged_reads", "hedge_fired", "hedge_spares",
              "co_fallbacks")


def payload(size: int, seed: int) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def run_seed(seed: int, args, root: str) -> bool:
    chaos = [ChaosDrive(os.path.join(root, f"s{seed}d{i}"),
                        seed=seed * 101 + i)
             for i in range(args.drives)]
    drives = wrap_drives(chaos)
    es = ErasureSet(drives, default_parity=args.parity)
    es.make_bucket("cb")
    k = args.drives - args.parity
    print(f"== seed {seed} :: {args.drives} drives (EC {k}+"
          f"{args.parity}), {args.objects} objects "
          + "=" * 20)

    rng = np.random.default_rng(seed)
    for d in chaos:
        d.error_rate = args.error_rate
        d.slow_rate = args.slow_rate
        d.torn_rate = args.torn_rate
        d.slow_s = args.slow_s
    before = DATA_PATH.snapshot()

    acked, rejected = {}, []
    for i in range(args.objects):
        name = f"o{i}"
        data = payload(int(rng.integers(1_000, args.max_size)),
                       seed * 1000 + i)
        try:
            es.put_object("cb", name, data)
            acked[name] = data
        except StorageError:
            rejected.append(name)

    ok = True
    gets_ok = gets_err = 0
    for name, data in acked.items():
        for off, ln in ((0, -1), (len(data) // 3, len(data) // 2)):
            try:
                _, got = es.get_object("cb", name, offset=off,
                                       length=ln)
            except StorageError:
                gets_err += 1
                continue
            want = data[off:off + ln] if ln > 0 else data[off:]
            if bytes(got) != want:
                print(f"  !! CORRUPT read: {name} off={off} len={ln}")
                ok = False
            gets_ok += 1
    print(f"puts: {len(acked)} acknowledged, {len(rejected)} rejected"
          f"   gets: {gets_ok} ok, {gets_err} clean errors")

    # -- per-drive report ---------------------------------------------
    print(f'{"drive":<6} {"state":<8} {"errs":>4} {"slow":>4}  '
          f'{"injected(err/slow/torn)":<24} transitions')
    for i, (wd, cd) in enumerate(zip(drives, chaos)):
        hi = wd.health_info()
        inj = cd.injected
        trans = "->".join(hi["transitions"]) or "-"
        print(f'd{i:<5} {hi["state"]:<8} '
              f'{hi["consecutive_errors"]:>4} '
              f'{hi["consecutive_slow"]:>4}  '
              f'{inj.get("errors", 0):>7} / {inj.get("slow", 0):>3} '
              f'/ {inj.get("torn", 0):>3}      {trans}')
    snap = DATA_PATH.snapshot()
    print("  ".join(f"{key}={snap[key] - before[key]}"
                    for key in HEDGE_KEYS))

    # -- calm weather: heal must converge -----------------------------
    for d in chaos:
        d.chaos_off()
    for wd in drives:
        if wd.health_state() != "ok":
            wd.probe_now()
    worst = 0
    for name in acked:
        for passes in range(1, 2 * args.drives + 1):
            rs = heal_mod.heal_object(es, "cb", name, deep=True)
            if all(not r.healed for r in rs):
                break
        else:
            print(f"  !! heal did not converge for {name}")
            ok = False
        worst = max(worst, passes)
    exact = sum(
        bytes(es.get_object("cb", n)[1]) == d for n, d in acked.items())
    for name in rejected:
        try:
            es.get_object("cb", name)
        except StorageError:
            continue
        print(f"  !! rejected PUT {name} became visible")
        ok = False
    if exact != len(acked):
        ok = False
    print(f"heal: converged in {worst} pass(es); final readback: "
          f"{exact}/{len(acked)} byte-exact")
    if es.mrf is not None and es.mrf.pending():
        print(f"mrf: {es.mrf.pending()} item(s) still queued")
    print()
    return ok


def run_crash_matrix(args) -> int:
    """Kill-9 durability matrix: boot/kill/reboot real server
    subprocesses through every armed crash point and render the
    per-scenario verdicts."""
    from minio_tpu.tools import crash_matrix as cm

    scenarios = cm.SCENARIOS
    if args.crash_points:
        wanted = {p.strip() for p in args.crash_points.split(",")
                  if p.strip()}
        unknown = wanted - {s["point"] for s in cm.SCENARIOS}
        if unknown:
            print(f"unknown crash point(s): {', '.join(sorted(unknown))}")
            return 2
        scenarios = tuple(s for s in cm.SCENARIOS
                          if s["point"] in wanted)
    print(f"== kill-9 crash matrix :: seed {args.crash_seed}, "
          f"{len(scenarios)} scenario(s) " + "=" * 24)
    results = cm.run_matrix(scenarios, seed=args.crash_seed,
                            progress=print)
    print()
    print(f'{"point":<26} {"nth":>3}  {"op":<10} {"expect":<8} '
          f'{"victim":<10} result')
    bad = 0
    for r in results:
        if r.get("ok"):
            victim = ("visible" if r.get("victim_visible")
                      else "invisible")
            verdict = "ok"
        else:
            victim, verdict = "-", f"FAIL ({r.get('error', '?')})"
            bad += 1
        print(f'{r["point"]:<26} {r["nth"]:>3}  {r["op"]:<10} '
              f'{r["expect"]:<8} {victim:<10} {verdict}')
    print()
    if bad:
        print(f"{bad}/{len(results)} scenario(s) violated the "
              f"durability contract")
        return 1
    print(f"all {len(results)} scenario(s) clean: acked writes "
          f"survived every kill, no torn object ever served, tmp "
          f"swept on every recovery boot")
    return 0


def run_net_matrix(args) -> int:
    """Partition/node-kill matrix: a proxied 3-node cluster, every
    network-fault kind mid-PUT/GET/heal, per-scenario verdict table."""
    from minio_tpu.tools import net_matrix as nm

    scenarios = list(nm.SCENARIOS)
    if args.net_scenarios:
        wanted = {s.strip() for s in args.net_scenarios.split(",")
                  if s.strip()}
        unknown = wanted - {s["name"] for s in nm.SCENARIOS}
        if unknown:
            print(f"unknown scenario(s): {', '.join(sorted(unknown))}")
            return 2
        scenarios = [s for s in nm.SCENARIOS if s["name"] in wanted]
    print(f"== partition/node-kill matrix :: seed {args.net_seed}, "
          f"{len(scenarios)} scenario(s) " + "=" * 20)
    results = nm.run_matrix(scenarios, seed=args.net_seed,
                            progress=print)
    print()
    print(f'{"scenario":<22} {"victim":>6}  {"acked":>5} {"rej":>3} '
          f'{"gets":>4} {"heal":>4} {"mrf":>3} {"secs":>6}  result')
    bad = 0
    for r in results:
        verdict = "ok" if r["ok"] else f'FAIL ({"; ".join(r["errors"][:2])})'
        bad += 0 if r["ok"] else 1
        print(f'{r["name"]:<22} {"n" + str(r["target"]):>6}  '
              f'{r["acked"]:>5} {r["rejected"]:>3} {r["gets_ok"]:>4} '
              f'{r["heal_passes"]:>4} {r["mrf_pending"]:>3} '
              f'{r["seconds"]:>6}  {verdict}')
    print()
    if bad:
        print(f"{bad}/{len(results)} scenario(s) violated the "
              f"partition-tolerance contract")
        return 1
    print(f"all {len(results)} scenario(s) clean: zero acked-write "
          f"loss, no torn reads, rejected writes invisible, heal "
          f"converged after every partition healed")
    return 0


def run_decom_matrix(args) -> int:
    """Decommission kill-9 matrix: a 2-pool server killed inside
    every decom.* crash point mid-drain, rebooted, journal-resumed;
    per-scenario zero-loss verdict table."""
    from minio_tpu.tools import crash_matrix as cm

    scenarios = cm.DECOM_SCENARIOS
    if args.decom_points:
        wanted = {p.strip() for p in args.decom_points.split(",")
                  if p.strip()}
        unknown = wanted - {s["point"] for s in cm.DECOM_SCENARIOS}
        if unknown:
            print(f"unknown decom point(s): {', '.join(sorted(unknown))}")
            return 2
        scenarios = tuple(s for s in cm.DECOM_SCENARIOS
                          if s["point"] in wanted)
    print(f"== decommission kill-9 matrix :: seed {args.crash_seed}, "
          f"{len(scenarios)} scenario(s) " + "=" * 18)
    results = cm.run_decom_matrix(scenarios, seed=args.crash_seed,
                                  progress=print)
    print()
    print(f'{"point":<22} {"nth":>3}  {"moved":>5}  result')
    bad = 0
    for r in results:
        if r.get("ok"):
            verdict = "ok"
        else:
            verdict = f"FAIL ({r.get('error', '?')})"
            bad += 1
        moved = r.get("objects_moved", "-")
        print(f'{r["point"]:<22} {r["nth"]:>3}  {moved!s:>5}  {verdict}')
    print()
    if bad:
        print(f"{bad}/{len(results)} scenario(s) violated the "
              f"decommission zero-loss contract")
        return 1
    print(f"all {len(results)} scenario(s) clean: every drain resumed "
          f"from its journal after kill -9, all objects byte-exact at "
          f"their original ETags, no duplicate versions, drained pool "
          f"empty")
    return 0


def run_ilm_matrix(args) -> int:
    """ILM kill-9 matrix: a server killed inside every ilm.* crash
    point mid-transition (or mid tier-free), rebooted, tier-journal
    replayed; per-scenario exactly-once verdict table."""
    from minio_tpu.tools import crash_matrix as cm

    scenarios = cm.ILM_SCENARIOS
    if args.ilm_points:
        wanted = {p.strip() for p in args.ilm_points.split(",")
                  if p.strip()}
        unknown = wanted - {s["point"] for s in cm.ILM_SCENARIOS}
        if unknown:
            print(f"unknown ilm point(s): {', '.join(sorted(unknown))}")
            return 2
        scenarios = tuple(s for s in cm.ILM_SCENARIOS
                          if s["point"] in wanted)
    print(f"== ILM kill-9 matrix :: seed {args.crash_seed}, "
          f"{len(scenarios)} scenario(s) " + "=" * 24)
    results = cm.run_ilm_matrix(scenarios, seed=args.crash_seed,
                                progress=print)
    print()
    print(f'{"point":<18} {"nth":>3}  {"expect":<6} result')
    bad = 0
    for r in results:
        if r.get("ok"):
            verdict = "ok"
        else:
            verdict = f"FAIL ({r.get('error', '?')})"
            bad += 1
        print(f'{r["point"]:<18} {r["nth"]:>3}  {r["expect"]:<6} '
              f'{verdict}')
    print()
    if bad:
        print(f"{bad}/{len(results)} scenario(s) violated the "
              f"tiering exactly-once contract")
        return 1
    print(f"all {len(results)} scenario(s) clean: every kill left a "
          f"full hot version or a valid stub, the tier journal "
          f"drained to zero, no tier object orphaned or leaked")
    return 0


def run_repl_matrix(args) -> int:
    """Replication-under-fire report: the kill-9 leg (source killed
    inside every repl.* point while a live target stays up, plus the
    mid-resync kill), then the two-cluster partition leg behind the
    chaos TCP proxy; one verdict table per leg."""
    from minio_tpu.tools import crash_matrix as cm
    from minio_tpu.tools import net_matrix as nm

    scenarios = cm.REPL_SCENARIOS
    if args.repl_points:
        wanted = {p.strip() for p in args.repl_points.split(",")
                  if p.strip()}
        unknown = wanted - {s["point"] for s in cm.REPL_SCENARIOS}
        if unknown:
            print(f"unknown repl point(s): {', '.join(sorted(unknown))}")
            return 2
        scenarios = tuple(s for s in cm.REPL_SCENARIOS
                          if s["point"] in wanted)
    bad = total = 0

    print(f"== replication kill-9 matrix :: seed {args.crash_seed}, "
          f"{len(scenarios)} scenario(s)"
          + ("" if args.repl_skip_resync else " + resync") + " "
          + "=" * 12)
    results = cm.run_repl_matrix(scenarios, seed=args.crash_seed,
                                 progress=print,
                                 resync=not args.repl_skip_resync)
    print()
    print(f'{"point":<16} {"nth":>4}  {"op":<12} {"replayed":>8}  '
          f'result')
    for r in results:
        total += 1
        if r.get("ok"):
            verdict = "ok"
        else:
            verdict = f"FAIL ({r.get('error', '?')})"
            bad += 1
        replayed = r.get("replayed")
        print(f'{r["point"]:<16} {r["nth"]:>4}  {r.get("op", "?"):<12} '
              f'{"-" if replayed is None else replayed:>8}  {verdict}')
    print()

    if not args.repl_skip_net:
        print(f"== two-cluster partition matrix :: seed "
              f"{args.net_seed}, {len(nm.REPL_NET_SCENARIOS)} "
              f"scenario(s) " + "=" * 12)
        nresults = nm.run_repl_net_matrix(seed=args.net_seed,
                                          progress=print)
        print()
        print(f'{"scenario":<30} {"acked":>5} {"done":>5} '
              f'{"retries":>7} {"secs":>6}  result')
        for r in nresults:
            total += 1
            if r["ok"]:
                verdict = "ok"
            else:
                verdict = f'FAIL ({"; ".join(r["errors"][:2])})'
                bad += 1
            print(f'{r["name"]:<30} {r["acked"]:>5} '
                  f'{r["completed"]:>5} {r["retries"]:>7} '
                  f'{r["seconds"]:>6}  {verdict}')
        print()

    if bad:
        print(f"{bad}/{total} scenario(s) violated the replication "
              f"exactly-once/zero-loss contract")
        return 1
    print(f"all {total} scenario(s) clean: every acked write survived "
          f"kill -9 inside the repl.* window and converged byte-exact "
          f"at its version id, partitions produced lag (never loss), "
          f"and the journal drained to zero after every heal")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded chaos scenario report for minio_tpu")
    ap.add_argument("--seeds", default="1,2,3",
                    help="comma-separated RNG seeds, one scenario each")
    ap.add_argument("--drives", type=int, default=6)
    ap.add_argument("--parity", type=int, default=2)
    ap.add_argument("--objects", type=int, default=8)
    ap.add_argument("--max-size", type=int, default=400_000)
    ap.add_argument("--error-rate", type=float, default=0.05)
    ap.add_argument("--slow-rate", type=float, default=0.05)
    ap.add_argument("--torn-rate", type=float, default=0.04)
    ap.add_argument("--slow-s", type=float, default=0.002)
    ap.add_argument("--crash-matrix", action="store_true",
                    help="run the kill-9 durability matrix (real "
                         "server subprocesses) instead of the "
                         "in-process chaos storm")
    ap.add_argument("--crash-seed", type=int, default=0,
                    help="payload seed for --crash-matrix scenarios")
    ap.add_argument("--crash-points", default="",
                    help="comma-separated subset of crash points to "
                         "run (default: the full matrix)")
    ap.add_argument("--net-matrix", action="store_true",
                    help="run the partition/node-kill matrix (a real "
                         "multi-node cluster under the chaos TCP "
                         "proxy) instead of the in-process storm")
    ap.add_argument("--net-seed", type=int, default=0,
                    help="fault/payload seed for --net-matrix")
    ap.add_argument("--net-scenarios", default="",
                    help="comma-separated subset of net-matrix "
                         "scenario names (default: the full matrix)")
    ap.add_argument("--decom", action="store_true",
                    help="run the decommission kill-9 matrix (a real "
                         "2-pool server killed mid-drain at every "
                         "decom.* point, then journal-resumed)")
    ap.add_argument("--decom-points", default="",
                    help="comma-separated subset of decom.* points to "
                         "run (default: the full matrix)")
    ap.add_argument("--repl", action="store_true",
                    help="run the replication-under-fire matrix: "
                         "kill-9 inside every repl.* point against a "
                         "live target, a mid-resync kill, then the "
                         "two-cluster partition scenarios")
    ap.add_argument("--repl-points", default="",
                    help="comma-separated subset of repl.* points to "
                         "run (default: the full matrix)")
    ap.add_argument("--repl-skip-resync", action="store_true",
                    help="skip the 2000-object mid-resync kill leg")
    ap.add_argument("--repl-skip-net", action="store_true",
                    help="skip the two-cluster partition leg")
    ap.add_argument("--ilm", action="store_true",
                    help="run the ILM kill-9 matrix (a server killed "
                         "inside every ilm.* point mid-transition, "
                         "then tier-journal replayed at boot)")
    ap.add_argument("--ilm-points", default="",
                    help="comma-separated subset of ilm.* points to "
                         "run (default: the full matrix)")
    args = ap.parse_args(argv)

    if args.crash_matrix:
        return run_crash_matrix(args)
    if args.net_matrix:
        return run_net_matrix(args)
    if args.decom:
        return run_decom_matrix(args)
    if args.repl:
        return run_repl_matrix(args)
    if args.ilm:
        return run_ilm_matrix(args)

    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    failures = 0
    with tempfile.TemporaryDirectory(prefix="mtpu-chaos-") as root:
        for seed in seeds:
            if not run_seed(seed, args, root):
                failures += 1
    if failures:
        print(f"{failures}/{len(seeds)} seed(s) violated invariants")
        return 1
    print(f"all {len(seeds)} seed(s) clean: zero data loss, heal "
          f"converged, rejected writes stayed invisible")
    return 0


if __name__ == "__main__":
    sys.exit(main())
