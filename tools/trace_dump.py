#!/usr/bin/env python3
"""Live-tail a server's request trace stream, or dump top-API stats.

Streams ``POST /minio/admin/v3/trace`` (chunked NDJSON of span trees)
and pretty-prints each request as an indented stage tree, newest last:

    $ python tools/trace_dump.py --endpoint http://127.0.0.1:9000 \
          --access-key minioadmin --secret-key minioadmin --duration 30
    06:25:51.312 api.PutObject  200  /bkt/obj  44.1ms
      engine.etag                        25.31ms
      engine.encode                       5.84ms
      engine.stage                       10.87ms
        drive.write                       9.02ms

``--json`` emits the raw NDJSON records instead.  ``--top`` skips the
stream and prints ``GET /minio/admin/v3/top/apis`` aggregates (count,
errors, avg/p50/p90/p99, hottest stages per API).

Filters mirror `mc admin trace`: ``--err`` (errors only), ``--path``
(request-path prefix), ``--min-duration-ms``.  Credentials fall back to
MTPU_ACCESS_KEY / MTPU_SECRET_KEY.
"""

import argparse
import json
import os
import sys
import urllib.parse

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from minio_tpu.server.client import S3Client  # noqa: E402
from minio_tpu.server.sigv4 import sign_request  # noqa: E402


def stream_trace(cli: S3Client, query: dict):
    """POST v3/trace and yield NDJSON lines AS THEY ARRIVE (the generic
    S3Client.request buffers the whole body, which would defeat a live
    tail)."""
    path = "/minio/admin/v3/trace"
    q = {k: [v] for k, v in query.items()}
    headers = {"Host": f"{cli.host}:{cli.port}"}
    headers.update(sign_request(cli.creds, "POST", path, q, headers,
                                b""))
    qs = urllib.parse.urlencode(query)
    conn = cli._connect(max(120.0, float(query["duration"]) + 60))
    try:
        conn.request("POST", f"{path}?{qs}", headers=headers)
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                f"trace failed: HTTP {resp.status}: "
                f"{resp.read()[:200]!r}")
        buf = b""
        while True:
            piece = resp.read1(65536)
            if not piece:
                break
            buf += piece
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    yield line
        if buf.strip():
            yield buf
    finally:
        conn.close()


def _fmt_time(ts: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(ts).strftime("%H:%M:%S.%f")[:-3]


def print_rec(rec: dict) -> None:
    tags = rec.get("tags", {})
    status = tags.get("status", "?")
    mark = " ERR" if rec.get("error") else ""
    print(f'{_fmt_time(rec.get("time", 0))} {rec["name"]:<20} {status}  '
          f'{tags.get("path", "")}  {rec["dur_ms"]:.1f}ms{mark}')
    stack = [(c, 1) for c in reversed(rec.get("spans", []))]
    while stack:
        sp, depth = stack.pop()
        pad = "  " * depth
        print(f'{pad}{sp["name"]:<{34 - 2 * depth}} '
              f'{sp["dur_ms"]:>9.2f}ms')
        stack.extend((c, depth + 1)
                     for c in reversed(sp.get("spans", [])))


def dump_top(cli: S3Client) -> int:
    st, _, body = cli.request("GET", "/minio/admin/v3/top/apis")
    if st != 200:
        print(f"top/apis failed: HTTP {st}: {body[:200]!r}",
              file=sys.stderr)
        return 1
    snap = json.loads(body)
    apis = snap.get("apis", {})
    if not apis:
        print("no traced requests yet (tracing is demand-driven: "
              "start a trace stream or set MTPU_TRACE_RING)")
        return 0
    hdr = (f'{"api":<24} {"count":>6} {"errs":>5} {"avg_ms":>9} '
           f'{"p50_ms":>9} {"p90_ms":>9} {"p99_ms":>9}')
    print(hdr)
    print("-" * len(hdr))
    for api, a in sorted(apis.items(),
                         key=lambda kv: -kv[1]["count"]):
        print(f'{api:<24} {a["count"]:>6} {a["errors"]:>5} '
              f'{a["avg_ms"]:>9.2f} {a["p50_ms"]:>9.2f} '
              f'{a["p90_ms"]:>9.2f} {a["p99_ms"]:>9.2f}')
        top = sorted(a.get("stages", {}).items(),
                     key=lambda kv: -kv[1]["total_ms"])[:5]
        for name, st_ in top:
            print(f'    {name:<28} x{st_["count"]:<5} '
                  f'{st_["total_ms"]:>9.2f}ms total')
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stream request span traces from a minio_tpu server")
    ap.add_argument("--endpoint", default="http://127.0.0.1:9000")
    ap.add_argument("--access-key",
                    default=os.environ.get("MTPU_ACCESS_KEY", ""))
    ap.add_argument("--secret-key",
                    default=os.environ.get("MTPU_SECRET_KEY", ""))
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds to stream (server closes after)")
    ap.add_argument("--err", action="store_true",
                    help="only failed requests")
    ap.add_argument("--path", default="",
                    help="request-path prefix filter, e.g. /bucket")
    ap.add_argument("--min-duration-ms", type=float, default=0.0)
    ap.add_argument("--json", action="store_true",
                    help="raw NDJSON records instead of trees")
    ap.add_argument("--top", action="store_true",
                    help="print top/apis aggregates and exit")
    args = ap.parse_args(argv)
    if not args.access_key or not args.secret_key:
        ap.error("--access-key/--secret-key (or MTPU_ACCESS_KEY/"
                 "MTPU_SECRET_KEY) required")

    cli = S3Client(args.endpoint, args.access_key, args.secret_key)
    if args.top:
        return dump_top(cli)

    query = {"duration": str(args.duration)}
    if args.err:
        query["err"] = "true"
    if args.path:
        query["path"] = args.path
    if args.min_duration_ms:
        query["min-duration-ms"] = str(args.min_duration_ms)
    n = 0
    try:
        for line in stream_trace(cli, query):
            if args.json:
                sys.stdout.buffer.write(line + b"\n")
                sys.stdout.buffer.flush()
            else:
                print_rec(json.loads(line))
                sys.stdout.flush()
            n += 1
    except RuntimeError as e:
        print(e, file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        pass
    if not args.json:
        print(f"-- {n} request(s) in {args.duration:g}s --")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # `trace_dump.py | head` is fine
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
