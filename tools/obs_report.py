"""Fleet observability report: cluster metrics + healthinfo as tables.

One signed scrape of the two admin aggregates this repo's observability
plane exposes — `/minio/admin/v3/metrics/cluster` (merged Prometheus
text, every sample labelled with its node) and
`/minio/admin/v3/healthinfo` (per-node health document) — rendered as
terminal tables: node liveness, per-node request/error counts, the
last-minute SLO window per API, drive/breaker states, MRF backlog and
audit sink health.

    python tools/obs_report.py --endpoint http://127.0.0.1:9000 \
        --access-key minioadmin --secret-key minioadmin
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from minio_tpu.server.client import S3Client  # noqa: E402


def parse_prom(text: str) -> list[tuple[str, dict, float]]:
    """Flatten a Prometheus exposition into (family, labels, value)
    rows — enough structure for a terminal report, not a TSDB."""
    rows = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?$", head)
        if not m:
            continue
        labels = {}
        if m.group(2):
            for lm in re.finditer(r'(\w+)="((?:[^"\\]|\\.)*)"',
                                  m.group(2)):
                labels[lm.group(1)] = lm.group(2)
        try:
            rows.append((m.group(1), labels, float(val)))
        except ValueError:
            continue
    return rows


def table(title: str, headers: list[str],
          rows: list[list], out=sys.stdout) -> None:
    cells = [[str(c) for c in r] for r in rows]
    widths = [max([len(h)] + [len(r[i]) for r in cells])
              for i, h in enumerate(headers)]
    out.write(f"\n== {title} ==\n")
    out.write("  ".join(h.ljust(w) for h, w in zip(headers, widths))
              + "\n")
    out.write("  ".join("-" * w for w in widths) + "\n")
    for r in cells:
        out.write("  ".join(c.ljust(w) for c, w in zip(r, widths))
                  + "\n")


def fam_by_node(rows, fam: str, pick=None) -> dict[str, float]:
    """Sum a family's samples per node label (optionally filtered)."""
    out: dict[str, float] = {}
    for name, labels, v in rows:
        if name != fam:
            continue
        if pick is not None and not pick(labels):
            continue
        node = labels.get("node", "?")
        out[node] = out.get(node, 0.0) + v
    return out


def report(endpoint: str, access_key: str, secret_key: str,
           out=sys.stdout) -> int:
    cli = S3Client(endpoint, access_key, secret_key)
    st, _, body = cli.request("GET", "/minio/admin/v3/metrics/cluster")
    if st != 200:
        out.write(f"metrics/cluster -> HTTP {st}\n")
        return 1
    rows = parse_prom(body.decode())
    st, _, body = cli.request("GET", "/minio/admin/v3/healthinfo")
    if st != 200:
        out.write(f"healthinfo -> HTTP {st}\n")
        return 1
    hi = json.loads(body)

    # -- fleet liveness ------------------------------------------------------
    up = {labels.get("node", "?"): v for name, labels, v in rows
          if name == "mtpu_node_up"}
    reqs = fam_by_node(rows, "mtpu_s3_requests_total")
    errs = fam_by_node(rows, "mtpu_s3_errors_total")
    drops = fam_by_node(rows, "mtpu_audit_dropped_total")
    table("fleet", ["node", "up", "requests", "errors",
                    "audit_dropped"],
          [[n, int(up.get(n, 0)), int(reqs.get(n, 0)),
            int(errs.get(n, 0)), int(drops.get(n, 0))]
           for n in sorted(up)], out)

    # -- last-minute SLO window (merged across nodes) ------------------------
    slo: dict[str, dict[str, float]] = {}
    for name, labels, v in rows:
        if not name.startswith("mtpu_api_last_minute_"):
            continue
        key = name[len("mtpu_api_last_minute_"):]
        api = labels.get("api", "?")
        d = slo.setdefault(api, {})
        if key in ("count", "errors"):
            d[key] = d.get(key, 0.0) + v
        else:                        # p50/p99: worst node wins
            d[key] = max(d.get(key, 0.0), v)
    table("last-minute SLO (per API, fleet)",
          ["api", "count", "errors", "p50_ms", "p99_ms"],
          [[api, int(d.get("count", 0)), int(d.get("errors", 0)),
            d.get("p50", 0.0), d.get("p99", 0.0)]
           for api, d in sorted(slo.items()) if d.get("count")], out)

    # -- per-node health -----------------------------------------------------
    health_rows = []
    for node in sorted(hi.get("nodes", {})):
        doc = hi["nodes"][node]
        drives = doc.get("drives", [])
        bad = sum(1 for d in drives if d.get("state") != "ok")
        mrf = sum(r.get("pending", 0) for r in doc.get("mrf", []))
        audit = doc.get("audit", [])
        a_drop = sum(a.get("dropped", 0) for a in audit)
        health_rows.append([
            node, "drain" if doc.get("draining") else "serving",
            doc.get("inflight", 0), f"{len(drives) - bad}/{len(drives)}",
            mrf, len(audit), a_drop])
    for node, v in sorted(hi.get("node_up", {}).items()):
        if not v:
            health_rows.append([node, "DOWN", "-", "-", "-", "-", "-"])
    table("health", ["node", "state", "inflight", "drives_ok",
                     "mrf_pending", "audit_targets", "audit_dropped"],
          health_rows, out)

    # -- drive detail for anything not ok ------------------------------------
    bad_rows = []
    for node in sorted(hi.get("nodes", {})):
        for d in hi["nodes"][node].get("drives", []):
            if d.get("state") != "ok":
                bad_rows.append([node, d.get("pool"), d.get("set"),
                                 d.get("drive"), d.get("state")])
    if bad_rows:
        table("degraded drives", ["node", "pool", "set", "drive",
                                  "state"], bad_rows, out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--endpoint", required=True,
                    help="http(s)://host:port of any cluster node")
    ap.add_argument("--access-key",
                    default=os.environ.get("MTPU_ROOT_USER",
                                           "minioadmin"))
    ap.add_argument("--secret-key",
                    default=os.environ.get("MTPU_ROOT_PASSWORD",
                                           "minioadmin"))
    args = ap.parse_args(argv)
    return report(args.endpoint, args.access_key, args.secret_key)


if __name__ == "__main__":
    raise SystemExit(main())
