#!/usr/bin/env python3
"""Standalone seeded network-chaos TCP proxy.

Fronts any minio_tpu port (RPC plane or S3 front door) with the
deterministic fault injector from minio_tpu.tools.netchaos: latency
spikes, connection resets, black-holes, mid-response truncation and
one-way partitions, each a pure function of (seed, connection order).

    # a flaky link in front of a node on :9001
    $ python tools/netchaos.py --listen 19001 --target 127.0.0.1:9001 \\
          --seed 7 --reset-rate 0.05 --blackhole-rate 0.02

    # a hard two-way partition (SYN accepted, nothing answered)
    $ python tools/netchaos.py --listen 19001 --target 127.0.0.1:9001 \\
          --mode blackhole

Point the cluster's endpoint list (or a single peer) at the listen port
and drive traffic; ^C prints the injected-fault schedule so a failing
run is replayable from its seed.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from minio_tpu.tools.netchaos import ChaosTCPProxy  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic seeded TCP chaos proxy")
    ap.add_argument("--listen", type=int, required=True,
                    help="local port to listen on")
    ap.add_argument("--target", required=True, help="host:port to front")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slow-rate", type=float, default=0.0)
    ap.add_argument("--reset-rate", type=float, default=0.0)
    ap.add_argument("--blackhole-rate", type=float, default=0.0)
    ap.add_argument("--truncate-rate", type=float, default=0.0)
    ap.add_argument("--oneway-rate", type=float, default=0.0)
    ap.add_argument("--slow-s", type=float, default=0.05)
    ap.add_argument("--hold-s", type=float, default=30.0)
    ap.add_argument("--truncate-bytes", type=int, default=64)
    ap.add_argument("--mode", choices=("pass", "blackhole", "refuse"),
                    default="pass",
                    help="manual partition mode for every connection")
    args = ap.parse_args(argv)

    host, _, port = args.target.partition(":")
    proxy = ChaosTCPProxy(
        host, int(port), seed=args.seed, listen_port=args.listen,
        slow_rate=args.slow_rate, reset_rate=args.reset_rate,
        blackhole_rate=args.blackhole_rate,
        truncate_rate=args.truncate_rate, oneway_rate=args.oneway_rate,
        slow_s=args.slow_s, hold_s=args.hold_s,
        truncate_bytes=args.truncate_bytes).start()
    proxy.set_mode(args.mode)
    print(f"netchaos: 127.0.0.1:{proxy.port} -> {args.target} "
          f"seed={args.seed} mode={args.mode}")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        proxy.stop()
        print(f"\nconnections={proxy.conns} injected={proxy.injected}")
        if proxy.schedule:
            print("schedule:", ", ".join(f"{i}:{k}"
                                         for i, k in proxy.schedule))
    return 0


if __name__ == "__main__":
    sys.exit(main())
