"""Headline benchmark: EC:8+4 erasure encode throughput on TPU.

Mirrors the reference's BenchmarkErasureEncode harness
(/root/reference/cmd/erasure-encode_test.go:210-251) at the north-star
config (BASELINE.json): EC:8+4, 1 MiB blocks, batched into one device
dispatch. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

vs_baseline compares against klauspost/reedsolomon's AVX512 encode rate on a
modern single socket (BASELINE_CPU_GBPS below; BASELINE.md north-star row:
target >= 2x). The timing protocol accounts for the axon tunnel: a device
round-trip (RTT) is measured separately and subtracted from each single-
dispatch wall time; the median of several dispatches with distinct resident
inputs is reported (block_until_ready is unreliable through the tunnel, so
completion is forced by fetching one output byte).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# klauspost/reedsolomon AVX512 EC:8+4 single-socket encode throughput —
# stand-in until the in-repo C++ comparator (native/) is wired in.
BASELINE_CPU_GBPS = 7.0

K, M = 8, 4
SHARD = 131072          # 1 MiB block / 8 data shards
BLOCKS = 128            # 128 MiB data per dispatch
REPEATS = 7
WARMUP = 2


N_ITER = 20


def main() -> None:
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops.erasure_jax import ReedSolomonTPU

    on_tpu = jax.default_backend() == "tpu"
    dev = ReedSolomonTPU(K, M, use_pallas=on_tpu)
    rng = np.random.default_rng(0)
    x = jax.device_put(rng.integers(0, 256, size=(BLOCKS, K, SHARD),
                                    dtype=np.uint8))
    data_bytes = BLOCKS * K * SHARD

    # N_ITER encodes inside ONE device dispatch: amortizes tunnel dispatch
    # latency (~70-140 ms/call here, >> compute). The input is xor-perturbed
    # per iteration to defeat CSE; an identical loop without the encode is
    # timed and subtracted to remove perturb + loop overhead.
    @jax.jit
    def encode_loop(x):
        def body(i, acc):
            xi = x ^ i.astype(jnp.uint8)
            p = dev.encode_blocks(xi)
            # Fold ALL parity bytes into the carry so no backend can
            # dead-code any part of the matmul.
            return acc ^ jax.lax.reduce(p, jnp.uint8(0),
                                        jax.lax.bitwise_xor, (0, 1, 2))
        return jax.lax.fori_loop(0, N_ITER, body, jnp.uint8(0))

    @jax.jit
    def perturb_loop(x):
        def body(i, acc):
            xi = x ^ i.astype(jnp.uint8)
            return acc ^ xi[0, 0, 0]
        return jax.lax.fori_loop(0, N_ITER, body, jnp.uint8(0))

    def timed(fn):
        int(fn(x))  # compile + warm (int() forces completion through tunnel)
        times = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            int(fn(x))
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    t_encode = timed(encode_loop)
    t_base = timed(perturb_loop)
    per_encode = (t_encode - t_base) / N_ITER
    per_encode_incl = t_encode / N_ITER
    if per_encode <= 0:
        per_encode = per_encode_incl  # conservative fallback

    gbps = data_bytes / per_encode / 1e9
    print(json.dumps({
        "metric": "ec_8p4_encode_throughput",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_CPU_GBPS, 2),
    }))
    print(f"# backend={jax.default_backend()} encode_loop={t_encode*1e3:.1f}ms "
          f"perturb_loop={t_base*1e3:.1f}ms per_encode={per_encode*1e3:.2f}ms "
          f"(incl perturb {per_encode_incl*1e3:.2f}ms) data={data_bytes/2**20:.0f}MiB x{N_ITER}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
