"""Headline benchmark: EC:8+4 erasure codec throughput on TPU.

Covers the BASELINE.json config list (cf. the reference harnesses
/root/reference/cmd/erasure-encode_test.go:210, -decode_test.go:344,
-heal_test.go, bitrot-streaming verify):
  - encode           (B, 8, S) -> 4 parity rows        [headline metric]
  - decode_2lost     reconstruct 2 data rows from 8 of 12
  - heal_2lost       rebuild 1 data + 1 parity row (decode->re-encode)
  - fused_verify_decode  mxh256 bitrot digests of the 8 read rows fused
                         with the 2-row reconstruct in ONE dispatch
                         (north-star config #5; the production GET path)
  - fused_verify_decode_hh  same with HighwayHash256 (interop reads of
                         objects written before the mxh256 default)

vs_baseline divides encode throughput by a MEASURED native comparator:
native/rs_cpu.cc, the same vpshufb nibble-table algorithm the reference's
klauspost/reedsolomon assembly uses, compiled -march=native and timed on
this host at the same EC:8+4 geometry (replaces the round-1 hardcoded
constant the verdict flagged).

Timing protocol (axon tunnel): N_ITER codec calls inside ONE jitted
fori_loop; a per-iteration scalar salt is xor-folded into the input
INSIDE the kernel (SMEM scalar, zero extra HBM traffic) to defeat
CSE/loop hoisting; the full output is xor-folded into the carry so no
backend can dead-code any part; a trivial loop is timed and subtracted
to remove the fixed tunnel-fetch latency.  (The previous protocol's
host-level `x ^ i` materialized a 128 MiB copy per iteration — an extra
256 MiB of HBM traffic that did not belong to the codec and understated
throughput by ~25%; this, not a code regression, is the r01->r02
"encode regression" — r02 added fused warmups that shifted how much of
that artifact the baseline loop absorbed.)
Completion is forced by fetching the 1-byte result (block_until_ready is
unreliable through the tunnel). Median of REPEATS runs.

Prints ONE JSON line; secondary configs ride in "extras".
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K, M = 8, 4
SHARD = 131072          # 1 MiB block / 8 data shards
BLOCKS = 128            # 128 MiB data per dispatch
REPEATS = 5
N_ITER = 20
FUSED_BLOCKS = 128      # hash scan length == SHARD/32 packets regardless
FUSED_ITER = 4


def _timed(fn, x, repeats=REPEATS):
    int(fn(x))  # compile + warm (int() forces completion through tunnel)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        int(fn(x))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def e2e_bench(n_put: int = 64, n_parts: int = 4,
              part_mib: int = 64) -> dict:
    """Object-layer throughput on local drives (tracked configs 1-4):

      put_e2e_2p2_gbps        EC:2+2, 4 drives, n_put x 1 MiB PutObject
      put_e2e_8p4_mp_gbps     EC:8+4, 12 drives, part_mib MiB mp parts
      get_degraded_e2e_gbps   GET of the 8+4 object with 2 drives offline
      heal_e2e_gbps           full-set HealObject onto 2 wiped drives

    Runs against whatever jax backend the process has: the driver's TPU
    run reports the tunnel-attached numbers; main() also runs this in a
    clean JAX_PLATFORMS=cpu subprocess for the host-path numbers (see
    the tunnel note there).

    cf. the reference harnesses cmd/benchmark-utils_test.go,
    cmd/erasure-encode_test.go:210.
    """
    import shutil
    import tempfile

    from minio_tpu.engine import heal as heal_mod
    from minio_tpu.engine import multipart as mp
    from minio_tpu.engine.erasure_set import ErasureSet
    from minio_tpu.storage.drive import LocalDrive

    out = {}
    root = tempfile.mkdtemp(prefix="mtpu-bench-")
    try:
        # config 1: EC:2+2, 1 MiB objects
        es4 = ErasureSet([LocalDrive(f"{root}/a{i}") for i in range(4)])
        es4.make_bucket("bench")
        rng = np.random.default_rng(7)
        objs = [rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
                for _ in range(8)]
        es4.put_object("bench", "warm", objs[0])        # compile warm-up
        pts = []
        t0 = time.perf_counter()
        for i in range(n_put):
            t1 = time.perf_counter()
            es4.put_object("bench", f"o{i}", objs[i % len(objs)])
            pts.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        out["put_e2e_2p2_gbps"] = n_put * (1 << 20) / dt / 1e9
        # Median-rate variant: this host's 1 vCPU takes 10-90 ms
        # scheduling stalls from co-tenant processes (measured on PURE
        # tmpfs writes, bench.py-external); the median isolates the
        # framework from them where the aggregate cannot.
        out["put_e2e_2p2_median_gbps"] = \
            (1 << 20) / sorted(pts)[len(pts) // 2] / 1e9
        # Same config with the client supplying the ETag (Content-MD5
        # role): isolates the serial-MD5 wall — on a 1-core host the
        # S3 ETag alone costs ~1.7 ms/MiB that nothing can overlap
        # with (multi-core hosts absorb it in the etag thread).
        t0 = time.perf_counter()
        for i in range(n_put):
            es4.put_object("bench", f"n{i}", objs[i % len(objs)],
                           metadata={"etag": "precomputed"})
        dt = time.perf_counter() - t0
        out["put_e2e_2p2_noetag_gbps"] = n_put * (1 << 20) / dt / 1e9
        out.update(_put_stages(es4, objs[0]))
        out.update(_span_attribution(es4))

        # config 2: EC:8+4 multipart, 64 MiB parts
        es12 = ErasureSet([LocalDrive(f"{root}/b{i}") for i in range(12)],
                          default_parity=4)
        es12.make_bucket("bench")
        part = rng.integers(0, 256, part_mib << 20,
                            dtype=np.uint8).tobytes()
        up = mp.new_multipart_upload(es12, "bench", "mp")
        mp.put_object_part(es12, "bench", "mp", up, 1, part)  # warm-up
        from minio_tpu.observe.metrics import DATA_PATH as _DP
        mp0 = _DP.snapshot()
        t0 = time.perf_counter()
        for pn in range(2, 2 + n_parts):
            mp.put_object_part(es12, "bench", "mp", up, pn, part)
        dt = time.perf_counter() - t0
        out["put_e2e_8p4_mp_gbps"] = n_parts * len(part) / dt / 1e9
        etags = {p.number: p.etag
                 for p in mp.list_parts(es12, "bench", "mp", up)}
        mp.complete_multipart_upload(
            es12, "bench", "mp", up,
            [(n, etags[n]) for n in sorted(etags)])
        # In-band stage attribution from the pipeline's own counters
        # (the attributed workload IS the reported upload, not a
        # re-run).  encode/write are per-part ms and OVERLAP under the
        # StagePipeline — their sum can exceed the wall; complete is
        # the one concurrent per-drive publish.
        mp1 = _DP.snapshot()
        mp_d = {s: mp1["mp_stage_s"][s] - mp0["mp_stage_s"][s]
                for s in mp1["mp_stage_s"]}
        out["put_mp_stage_encode_ms"] = mp_d["encode"] * 1e3 / n_parts
        out["put_mp_stage_write_ms"] = mp_d["write"] * 1e3 / n_parts
        out["put_mp_stage_complete_ms"] = mp_d["complete"] * 1e3

        # healthy GET: all k data shards present — verify-only fast path
        # (no GF(2^8) work), measured BEFORE the degraded config wipes
        # drives.
        _, it = es12.get_object_iter("bench", "mp")
        next(it)                                        # warm-up chunk
        got = 0
        t0 = time.perf_counter()
        for c in it:
            got += len(c)
        dt = time.perf_counter() - t0
        out["get_healthy_e2e_gbps"] = got / dt / 1e9
        out.update(_get_healthy_stages(es12))

        # config 3: GET with 2 data shards offline (degraded reconstruct)
        saved = es12.drives[1], es12.drives[5]
        es12.drives[1] = es12.drives[5] = None
        _, it = es12.get_object_iter("bench", "mp")
        next(it)                                        # warm-up chunk
        rates = []
        got = 0
        t_start = t0 = time.perf_counter()
        for c in it:
            t1 = time.perf_counter()
            rates.append(len(c) / max(t1 - t0, 1e-9))
            got += len(c)
            t0 = t1
        dt = t0 - t_start
        out["get_degraded_e2e_gbps"] = got / dt / 1e9
        # Median per-segment rate: rides out this host's co-tenant
        # scheduling stalls (see put median note above).
        out["get_degraded_e2e_median_gbps"] = \
            sorted(rates)[len(rates) // 2] / 1e9
        out.update(_get_stages(es12))

        # config 4: full-set heal of the two wiped drives (heal_drive is
        # the resumable new-disk walk, cf. global-heal.go:166)
        es12.drives[1], es12.drives[5] = saved
        for pos in (1, 5):
            shutil.rmtree(f"{root}/b{pos}")
            es12.drives[pos] = LocalDrive(f"{root}/b{pos}")
        from minio_tpu.observe.metrics import DATA_PATH
        hp0 = DATA_PATH.snapshot()
        t0 = time.perf_counter()
        trackers = [heal_mod.heal_drive(es12, pos) for pos in (1, 5)]
        dt = time.perf_counter() - t0
        healed_bytes = sum(t.bytes_healed for t in trackers)
        if healed_bytes <= 0:
            raise RuntimeError("heal_drive rebuilt no bytes")
        out["heal_e2e_gbps"] = healed_bytes / dt / 1e9
        # Per-stage attribution from the pipeline's own counters (same
        # role as _get_stages/_put_stages, but measured in-band so the
        # attributed workload IS the reported heal, not a re-run).
        hp1 = DATA_PATH.snapshot()
        stage = {s: hp1["heal_stage_s"][s] - hp0["heal_stage_s"][s]
                 for s in hp1["heal_stage_s"]}
        out["heal_stage_read_ms"] = stage["read"] * 1e3
        out["heal_stage_decode_ms"] = stage["decode"] * 1e3
        out["heal_stage_write_ms"] = stage["write"] * 1e3
        # Stages overlap under the double-buffered pipeline, so "other"
        # is wall minus the accounted critical path, floored at 0.
        out["heal_stage_other_ms"] = max(
            dt * 1e3 - sum(stage.values()), 0.0)
        d_blk = hp1["heal_batch_blocks"] - hp0["heal_batch_blocks"]
        d_cap = hp1["heal_batch_capacity"] - hp0["heal_batch_capacity"]
        out["heal_batch_occupancy_pct"] = 100.0 * d_blk / max(d_cap, 1)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {k: round(v, 2) if isinstance(v, float) else v
            for k, v in out.items()}


def hedge_bench(n_get: int = 80, slow_ms: float = 25.0) -> dict:
    """Tail-latency config: healthy GETs against a stripe with ONE
    drive injected slow (NaughtyDrive.slow — the aging-disk fault class
    hedged reads exist for).  Reports GET p50/p99 with speculative
    parity reads off (MTPU_HEDGE=0, the sequential oracle) and on; the
    acceptance ratio is the p99 improvement.  cf. Dean & Barroso, "The
    Tail at Scale" — with erasure coding the hedge is nearly free: the
    parity shard is an alternative source, not a duplicate request."""
    import os
    import shutil
    import tempfile

    from minio_tpu.engine.erasure_set import ErasureSet
    from minio_tpu.storage.naughty import NaughtyDrive

    out = {}
    root = tempfile.mkdtemp(prefix="mtpu-hedge-")
    saved = {k: os.environ.get(k) for k in ("MTPU_HEDGE", "MTPU_HEDGE_MS")}
    try:
        drives = [NaughtyDrive(f"{root}/d{i}") for i in range(6)]
        es = ErasureSet(drives, default_parity=2)
        # The 1-core serial fan-out never launches concurrent reads, so
        # there is nothing to hedge; force the pool path (multi-core
        # deployments take it by default).
        es._SERIAL_FANOUT = False
        es.make_bucket("bench")
        data = np.random.default_rng(11).integers(
            0, 256, 1 << 20, dtype=np.uint8).tobytes()
        es.put_object("bench", "obj", data)
        es.get_object("bench", "obj")                  # warm-up
        # One straggler drive: every shard read on it stalls slow_ms.
        # Pick a drive the warm-up GET actually read from (a data-shard
        # holder for this object) — slowing a parity spare would leave
        # the healthy path nothing to hedge against.
        victim = max(drives,
                     key=lambda d: d.calls.get("read_file", 0)
                     + d.calls.get("read_file_view", 0))
        victim.slow("read_file", slow_ms / 1e3)
        victim.slow("read_file_view", slow_ms / 1e3)

        def run(flag):
            os.environ["MTPU_HEDGE"] = flag
            os.environ["MTPU_HEDGE_MS"] = "5"
            lat = []
            for _ in range(n_get):
                t0 = time.perf_counter()
                _, got = es.get_object("bench", "obj")
                lat.append((time.perf_counter() - t0) * 1e3)
                assert bytes(got) == data
            lat.sort()
            return lat[len(lat) // 2], lat[int(len(lat) * 0.99)]

        p50_off, p99_off = run("0")
        p50_on, p99_on = run("1")
        out["get_slowdrive_nohedge_p50_ms"] = round(p50_off, 2)
        out["get_slowdrive_nohedge_p99_ms"] = round(p99_off, 2)
        out["get_slowdrive_hedged_p50_ms"] = round(p50_on, 2)
        out["get_slowdrive_hedged_p99_ms"] = round(p99_on, 2)
        out["get_hedge_p99_speedup"] = round(p99_off / max(p99_on, 1e-6), 2)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(root, ignore_errors=True)
    return out


def concurrent_bench(duration_s: float = 4.0,
                     object_mib: int = 1) -> dict:
    """Concurrent data-plane suite (the dispatch-coalescer numbers):
    closed-loop mixed PUT/GET at 1/4/16 clients via tools/loadgen,
    reporting aggregate GB/s, p50/p99 latency, and the mean coalesced
    batch occupancy per client count.  The 1-client run doubles as the
    1-client x N-serial baseline (a closed loop at the same wall time
    is the serial schedule), so `conc_16c_vs_serial_speedup` is the
    acceptance ratio directly."""
    import shutil
    import tempfile

    from minio_tpu.engine.erasure_set import ErasureSet
    from minio_tpu.storage.drive import LocalDrive
    from tools.loadgen import run_load

    out = {}
    root = tempfile.mkdtemp(prefix="mtpu-conc-")
    try:
        es = ErasureSet([LocalDrive(f"{root}/d{i}") for i in range(4)])
        es.make_bucket("bench")
        rng = np.random.default_rng(5)
        warm = rng.integers(0, 256, object_mib << 20,
                            dtype=np.uint8).tobytes()
        es.put_object("bench", "warm", warm)            # compile warm-up
        es.get_object("bench", "warm")
        for n in (1, 4, 16):
            r = run_load(es, clients=n, object_size=object_mib << 20,
                         put_frac=0.5, duration_s=duration_s,
                         bucket="bench", seed=n)
            out[f"conc{n}_gbps"] = r["gbps"]
            out[f"conc{n}_p50_ms"] = r["p50_ms"]
            out[f"conc{n}_p99_ms"] = r["p99_ms"]
            out[f"conc{n}_occupancy"] = r["co_occupancy"]
        if out["conc1_gbps"] > 0:
            out["conc_16c_vs_serial_speedup"] = round(
                out["conc16_gbps"] / out["conc1_gbps"], 2)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def workers_bench(duration_s: float = 3.0, object_mib: int = 1,
                  nworkers: int | None = None) -> dict:
    """Pre-fork pool suite (server/workers.py): the same closed-loop
    HTTP mix against one server booted MTPU_WORKERS=0 (single-process
    oracle) and one booted MTPU_WORKERS=N, at 1/4/16 clients over the
    wire.  The pool's acceptance shape: 16-client aggregate above its
    own 1-client, and above the oracle at 16 clients with p99 no worse.
    That needs a multi-core host — on 1 core the pool can only tie the
    oracle (the GIL was never the limit when there is one CPU), so the
    ratios are reported, not asserted."""
    import os
    import shutil
    import socket as _socket
    import subprocess
    import tempfile
    import urllib.request

    from tools.loadgen import run_load_http

    if nworkers is None:
        nworkers = min(4, max(2, os.cpu_count() or 2))
    here = os.path.dirname(os.path.abspath(__file__))
    out = {"workers_n": nworkers}
    for label, nw in (("w0", 0), ("wN", nworkers)):
        root = tempfile.mkdtemp(prefix=f"mtpu-wb-{label}-")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MTPU_SCANNER"] = "0"
        env["MTPU_WORKERS"] = str(nw)
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        proc = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu.server",
             "--drives", f"{root}/d{{1...4}}", "--port", str(port)],
            env=env, cwd=here, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        try:
            deadline = time.monotonic() + 180
            up = False
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    break
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/minio/health/ready",
                            timeout=2) as r:
                        if r.status == 200:
                            up = True
                            break
                except Exception:  # noqa: BLE001 — keep polling
                    pass
                time.sleep(0.2)
            if not up:
                raise RuntimeError(f"workers_bench {label} never ready")
            for n in (1, 4, 16):
                r = run_load_http(
                    f"http://127.0.0.1:{port}", clients=n,
                    object_size=object_mib << 20, put_frac=0.5,
                    duration_s=duration_s, seed=n,
                    # multi-process CLIENT side for the pool runs so the
                    # load generator's own GIL can't cap the measurement
                    procs=min(4, n) if nw else 1)
                out[f"{label}_conc{n}_gbps"] = r["gbps"]
                out[f"{label}_conc{n}_p50_ms"] = r["p50_ms"]
                out[f"{label}_conc{n}_p99_ms"] = r["p99_ms"]
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
            shutil.rmtree(root, ignore_errors=True)
    if out.get("wN_conc1_gbps"):
        out["pool_16c_vs_1c_speedup"] = round(
            out["wN_conc16_gbps"] / out["wN_conc1_gbps"], 2)
    if out.get("w0_conc16_gbps"):
        out["pool_vs_oracle_16c"] = round(
            out["wN_conc16_gbps"] / out["w0_conc16_gbps"], 2)
    return out


def hotcache_bench(duration_s: float = 3.0, object_kib: int = 1024,
                   clients: int = 8, nworkers: int = 2) -> dict:
    """Hot-object-tier suite (engine/hotcache.py): a Zipf(1.1)
    GET-dominated mix (5% PUTs, 20% ranged GETs) over 64 warm keys.

    Leg 1 — engine, cache on vs the MTPU_HOTCACHE=0 oracle: hot-key
    p50/p99 and aggregate GB/s, plus the tier's own hit ratio.  The
    PUTs matter: every one bumps the bucket generation and flushes the
    whole cached bucket, so the reported ratio already prices the
    invalidation storm in.

    Leg 2 — the pool: one server at MTPU_WORKERS=2 sharing ONE
    pre-fork segment, same mix over HTTP, cache on vs off, with the
    per-worker hit/miss split scraped from the
    mtpu_worker_hotcache_* families — both workers hitting proves one
    worker's fill serves the other."""
    import os
    import re
    import shutil
    import socket as _socket
    import subprocess
    import tempfile
    import urllib.request

    from tools.loadgen import make_set, run_load, run_load_http

    out: dict = {}
    size = object_kib << 10
    mix = dict(clients=clients, object_size=size, put_frac=0.05,
               duration_s=duration_s, warm_objects=64, seed=7,
               zipf=1.1, range_frac=0.2)

    # -- leg 1: engine, tier on vs oracle -----------------------------------
    from minio_tpu.engine.hotcache import HotObjectCache, attach_sets
    for label, cached in (("off", False), ("on", True)):
        root = tempfile.mkdtemp(prefix=f"mtpu-hc-{label}-")
        try:
            es = make_set(root, n=4)
            if cached:
                attach_sets(es, HotObjectCache(total_bytes=256 << 20))
            r = run_load(es, **mix)
            out[f"hc_{label}_gbps"] = r["gbps"]
            out[f"hc_{label}_hot_p50_ms"] = r["hot_p50_ms"]
            out[f"hc_{label}_hot_p99_ms"] = r["hot_p99_ms"]
            out[f"hc_{label}_cold_p50_ms"] = r["cold_p50_ms"]
            out[f"hc_{label}_ranged_p50_ms"] = r["ranged_p50_ms"]
            if cached:
                out["hc_hit_ratio"] = r.get("hotcache_hit_ratio", 0.0)
                out["hc_fills"] = r.get("hotcache_fills", 0)
        finally:
            shutil.rmtree(root, ignore_errors=True)
    if out.get("hc_on_hot_p50_ms"):
        out["hc_hot_p50_speedup"] = round(
            out["hc_off_hot_p50_ms"] / out["hc_on_hot_p50_ms"], 2)
        out["hc_hot_p99_speedup"] = round(
            out["hc_off_hot_p99_ms"] / out["hc_on_hot_p99_ms"], 2)
        out["hc_gbps_speedup"] = round(
            out["hc_on_gbps"] / out["hc_off_gbps"], 2)

    # -- leg 2: MTPU_WORKERS=2 pool sharing one segment ---------------------
    here = os.path.dirname(os.path.abspath(__file__))
    for label, hc in (("pool_off", "0"), ("pool_on", "1")):
        root = tempfile.mkdtemp(prefix=f"mtpu-hc-{label}-")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MTPU_SCANNER"] = "0"
        env["MTPU_WORKERS"] = str(nworkers)
        env["MTPU_HOTCACHE"] = hc
        # Size the segment to hold the whole warm set: the default
        # 64 MiB against 64 x 1 MiB keys would churn CLOCK eviction on
        # every fill and measure the thrash, not the tier.
        env["MTPU_HOTCACHE_MB"] = "256"
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        proc = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu.server",
             "--drives", f"{root}/d{{1...4}}", "--port", str(port)],
            env=env, cwd=here, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        try:
            deadline = time.monotonic() + 180
            up = False
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    break
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}"
                            "/minio/health/ready", timeout=2) as r:
                        if r.status == 200:
                            up = True
                            break
                except Exception:  # noqa: BLE001 — keep polling
                    pass
                time.sleep(0.2)
            if not up:
                raise RuntimeError(f"hotcache_bench {label} never ready")
            r = run_load_http(f"http://127.0.0.1:{port}", procs=2,
                              **mix)
            out[f"hc_{label}_gbps"] = r["gbps"]
            out[f"hc_{label}_hot_p50_ms"] = r["hot_p50_ms"]
            out[f"hc_{label}_hot_p99_ms"] = r["hot_p99_ms"]
            if hc == "1":
                # Per-worker hit/miss over the ONE shared segment —
                # every worker hitting proves cross-worker fills.
                from minio_tpu.server.client import S3Client
                cli = S3Client(f"http://127.0.0.1:{port}",
                               "minioadmin", "minioadmin")
                st, _, body = cli.request(
                    "GET", "/minio/v2/metrics/node")
                text = body.decode() if st == 200 else ""
                for kind in ("hits", "misses"):
                    for w, v in re.findall(
                            rf'mtpu_worker_hotcache_{kind}_total'
                            rf'{{worker="(\d+)"}} (\d+)', text):
                        out[f"hc_worker{w}_{kind}"] = int(v)
                for w in range(nworkers):
                    h = out.get(f"hc_worker{w}_hits", 0)
                    m = out.get(f"hc_worker{w}_misses", 0)
                    out[f"hc_worker{w}_hit_ratio"] = (
                        round(h / (h + m), 4) if h + m else 0.0)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
            shutil.rmtree(root, ignore_errors=True)
    if out.get("hc_pool_on_hot_p50_ms") and out.get("hc_pool_off_hot_p50_ms"):
        out["hc_pool_hot_p50_speedup"] = round(
            out["hc_pool_off_hot_p50_ms"] / out["hc_pool_on_hot_p50_ms"],
            2)
        out["hc_pool_gbps_speedup"] = round(
            out["hc_pool_on_gbps"] / out["hc_pool_off_gbps"], 2)
    return out


def _fs_type(path: str) -> str | None:
    """Filesystem type backing `path`, by longest-prefix mount match.

    Reads /proc/mounts directly (os.statvfs has no f_type in Python);
    returns None when the table is unreadable (non-Linux)."""
    import os
    best, fstype = "", None
    try:
        real = os.path.realpath(path)
        with open("/proc/mounts") as fh:
            for line in fh:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt, typ = parts[1], parts[2]
                if (real == mnt or real.startswith(mnt.rstrip("/") + "/")
                        or mnt == "/") and len(mnt) > len(best):
                    best, fstype = mnt, typ
    except OSError:
        return None
    return fstype


_RAM_FS = {"tmpfs", "ramfs", "devtmpfs"}


def _disk_backed_dir() -> str | None:
    """First writable directory backed by a real block device (ext4/
    xfs/btrfs/virtio — anything not RAM), or None on tmpfs-only hosts."""
    import os
    import tempfile
    for cand in (tempfile.gettempdir(), os.getcwd(),
                 os.path.expanduser("~"), "/var/tmp"):
        try:
            if not os.access(cand, os.W_OK):
                continue
        except OSError:
            continue
        typ = _fs_type(cand)
        if typ is not None and typ not in _RAM_FS:
            return cand
    return None


def zerocopy_bench(duration_s: float = 3.0, clients: int = 4) -> dict:
    """Zero-copy data-path suite (ISSUE 16): GB/s AND CPU-seconds-per-
    GB, MTPU_ZEROCOPY=1 vs the =0 buffered/copying oracle, per leg.

    The engine runs in-process, so RUSAGE_SELF over each run window is
    the server-side CPU bill for the bytes moved — on a 1-core,
    GIL-bound host, CPU-s/GB IS the reciprocal throughput ceiling, and
    it's the metric the vertical budgets (the GB/s delta follows from
    it whenever the leg is CPU-bound).

    Legs, each run under both flag values:
      * healthy_get — 1 MiB whole GETs of cold-ish keys (hot tier off):
        vectored reads + view-based assembly, no response copy.
      * hotcache_get — Zipf(1.1) GETs over a RAM-resident warm set:
        arena-view hits (no bytes() per hit) — the ≥20% CPU-s/GB win
        the acceptance gate names.
      * mp_put — 1 MiB PUTs: staging fan-out through one
        fallocate+pwritev per drive instead of per-batch appends.
      * disk_put / disk_get — the mp_put and healthy_get mixes re-run
        on a real (non-tmpfs) filesystem so the vectored-IO claims see
        actual block-device semantics at least once; skipped with an
        explicit `disk_leg_skipped` marker on tmpfs-only hosts.
    """
    import os
    import shutil
    import tempfile

    from minio_tpu.engine.hotcache import HotObjectCache, attach_sets
    from tools.loadgen import make_set, run_load

    # Drives on tmpfs when available: this suite prices the CPU per
    # byte moved, and disk writeback throttling stalls arbitrary
    # client threads — ±50% run-to-run noise that swamps the flag
    # deltas.  tmpfs write cost is pure CPU (page copies), exactly the
    # axis MTPU_ZEROCOPY moves.
    shm = "/dev/shm" if os.access("/dev/shm", os.W_OK) else None
    out: dict = {}
    legs = {
        # put_frac=0 + uniform GETs over a set larger than one batch;
        # use_iter = the serving path (what the HTTP writer consumes)
        "healthy_get": dict(clients=clients, object_size=1 << 20,
                            put_frac=0.0, warm_objects=16, seed=16,
                            use_iter=True),
        # GET-dominated Zipf mix over 32 cacheable keys
        "hotcache_get": dict(clients=clients, object_size=512 << 10,
                             put_frac=0.0, warm_objects=32, seed=17,
                             zipf=1.1, use_iter=True),
        "mp_put": dict(clients=clients, object_size=1 << 20,
                       put_frac=1.0, warm_objects=2, seed=18),
    }

    def run_leg(leg: str, mix: dict, base_dir, hotcache: bool) -> None:
        # ABBA schedule: PUT-heavy legs show a systematic later-run
        # advantage on this box (writeback/frequency ramp) — running
        # zc, oracle, oracle, zc and averaging per flag cancels the
        # linear drift a single ordered pair bakes in.
        acc: dict = {"zc": [], "oracle": []}
        for label, flag in (("zc", "1"), ("oracle", "0"),
                            ("oracle", "0"), ("zc", "1")):
            os.environ["MTPU_ZEROCOPY"] = flag
            root = tempfile.mkdtemp(prefix=f"mtpu-zc-{leg}-{label}-",
                                    dir=base_dir)
            try:
                es = make_set(root, n=4)
                if hotcache:
                    attach_sets(es, HotObjectCache(
                        total_bytes=256 << 20))
                # Untimed warmup: first-use costs (kernel compilation,
                # lazy imports, cache admission) must not land inside
                # whichever flag value happens to run first — the
                # first sustained PUT run in a process measures ~2x
                # slow under EITHER flag without this.
                run_load(es, duration_s=2.0, **mix)
                r = run_load(es, duration_s=duration_s, **mix)
                acc[label].append(r)
                if hotcache and flag == "1":
                    out["hotcache_hit_ratio"] = r.get(
                        "hotcache_hit_ratio", 0.0)
            finally:
                os.environ.pop("MTPU_ZEROCOPY", None)
                shutil.rmtree(root, ignore_errors=True)
        for label, runs in acc.items():
            for key, col in (("gbps", "gbps"),
                             ("cpu_s_per_gb", "cpu_s_per_gb"),
                             ("cpu_util", "cpu_util"),
                             ("p50_ms", "p50_ms")):
                out[f"{leg}_{label}_{key}"] = round(
                    sum(r[col] for r in runs) / len(runs), 3)
        o, z = out[f"{leg}_oracle_cpu_s_per_gb"], \
            out[f"{leg}_zc_cpu_s_per_gb"]
        out[f"{leg}_cpu_per_gb_saving"] = round(1 - z / o, 3) if o else 0.0
        out[f"{leg}_gbps_ratio"] = round(
            out[f"{leg}_zc_gbps"] / out[f"{leg}_oracle_gbps"], 3) \
            if out[f"{leg}_oracle_gbps"] else 0.0

    for leg, mix in legs.items():
        run_leg(leg, mix, shm, hotcache=(leg == "hotcache_get"))

    # Real-disk leg (ISSUE 17 satellite): the tmpfs legs price pure
    # CPU, but fallocate/pwritev/O_DIRECT behave differently against a
    # real block device (alignment honored, writeback pressure real) —
    # the vectored-write claim needs at least one measurement where the
    # kernel can say no.  On tmpfs-only hosts the leg is SKIPPED with
    # an explicit marker rather than silently absent, so a reader of
    # the JSON can tell "not run here" from "forgot to run".
    disk_dir = _disk_backed_dir()
    if disk_dir is None:
        out["disk_leg_skipped"] = ("no disk-backed writable directory "
                                   "(tmpfs-only host)")
    else:
        out["disk_fs_type"] = _fs_type(disk_dir)
        run_leg("disk_put", legs["mp_put"], disk_dir, hotcache=False)
        run_leg("disk_get", legs["healthy_get"], disk_dir,
                hotcache=False)
    # transport counter deltas over the whole suite prove which paths
    # actually fired (views/sendmsg live behind the HTTP writer; the
    # engine legs exercise views + vectored writes)
    from minio_tpu.observe.metrics import DATA_PATH
    snap = DATA_PATH.snapshot()
    for k in ("zerocopy_hot_views", "zerocopy_vectored_writes",
              "zerocopy_fallbacks"):
        out[k] = snap[k]
    return out


def _smallobj_leg(root: str, flag: str, *, clients: int = 12,
                  duration_s: float = 3.0, idle_ops: int = 300,
                  warmup_s: float = 2.0) -> dict:
    """One engine leg of smallobj_bench under MTPU_METABATCH=`flag`:
    a PUT storm (4-64 KiB Zipf bodies — amortized fsyncs/object and
    group-commit occupancy), a HEAD storm (HEAD always stats, so it is
    the pure metadata-read surface the per-drive coalescing must win),
    and a single-client idle probe (the unloaded p50 the 3% gate
    protects — batching must not tax a server with nothing to batch).

    The MetaBatcher singleton is retired on both edges so lanes and
    EMA state never straddle a flag flip."""
    import os
    import threading

    from minio_tpu.observe.metrics import DATA_PATH
    from minio_tpu.ops import metalanes
    from tools.loadgen import (_quantile, _zipf_pick, make_set,
                               run_load, zipf_cdf)

    os.environ["MTPU_METABATCH"] = flag
    metalanes.reset()
    try:
        es = make_set(root, n=4)
        sm = (4 << 10, 64 << 10)
        # Untimed warmup: first-use costs (lazy imports, dir creation,
        # allocator ramp) must not land inside whichever flag value
        # happens to run first.
        run_load(es, clients=clients, put_frac=1.0,
                 duration_s=warmup_s, small=sm, zipf=1.1,
                 warm_objects=32, seed=190)
        # Settle writeback before the timed window: the previous leg's
        # dirty pages flushing mid-measurement is the dominant
        # run-to-run noise on a real disk, and it lands asymmetrically
        # across the ABBA schedule.
        os.sync()
        time.sleep(0.5)
        r_put = run_load(es, clients=clients, put_frac=1.0,
                         duration_s=duration_s, small=sm, zipf=1.1,
                         warm_objects=32, seed=191)
        leg = {
            "put_ops_per_s": r_put["put_ops_per_s"],
            "put_p50_ms": r_put["put_p50_ms"],
            "fsyncs_per_object": r_put["meta_fsyncs_per_object"],
            "batch_occupancy": r_put["meta_batch_occupancy"],
        }

        # HEAD storm: GETs are absorbed by the FileInfo cache, but
        # HEAD always elects xl.meta across the drives — sustained
        # concurrent HEADs are where read fan-outs/request must drop
        # below 1 (shared per-drive rounds beat per-request fan-outs).
        bkt = "sohead"
        if not es.bucket_exists(bkt):
            es.make_bucket(bkt)
        rng = np.random.default_rng(192)
        names = [f"h-{i}" for i in range(64)]
        for i, nm in enumerate(names):
            sz = 4096 * (1 + (i % 16))
            es.put_object(bkt, nm, rng.integers(
                0, 256, sz, dtype=np.uint8).tobytes())
        cdf = zipf_cdf(len(names), 1.1)
        stop = threading.Event()
        lats: list[list[float]] = [[] for _ in range(clients)]
        errors: list[BaseException] = []

        def head_client(ci: int) -> None:
            crng = np.random.default_rng(500 + ci)
            try:
                while not stop.is_set():
                    nm = names[_zipf_pick(cdf, crng)]
                    t0 = time.monotonic()
                    es.head_object(bkt, nm)
                    lats[ci].append(time.monotonic() - t0)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)
                stop.set()

        snap0 = DATA_PATH.snapshot()
        threads = [threading.Thread(target=head_client, args=(ci,),
                                    daemon=True)
                   for ci in range(clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(60.0)
        wall = time.monotonic() - t_start
        snap1 = DATA_PATH.snapshot()
        if errors:
            raise errors[0]
        heads = [x for per in lats for x in per]
        d_rq = (snap1["meta_read_requests"]
                - snap0["meta_read_requests"])
        d_rr = snap1["meta_read_rounds"] - snap0["meta_read_rounds"]
        leg["head_ops_per_s"] = round(len(heads) / wall, 1)
        leg["head_p50_ms"] = round(_quantile(heads, 0.50) * 1e3, 3)
        leg["head_p99_ms"] = round(_quantile(heads, 0.99) * 1e3, 3)
        leg["get_fanouts_per_request"] = (round(d_rr / d_rq, 4)
                                          if d_rq else 0.0)

        # Idle probe: strictly serial small PUT/GET pairs — no
        # concurrency, so the lane inline fast path must route every
        # op down the exact oracle code path.  Settle first: an ext4
        # journal commit from the storms landing mid-probe in one leg
        # skews a sub-millisecond p50 by far more than the 3% gate.
        os.sync()
        time.sleep(0.5)
        ib = rng.integers(0, 256, 16 << 10, dtype=np.uint8).tobytes()
        iput: list[float] = []
        iget: list[float] = []
        for i in range(idle_ops):
            t0 = time.monotonic()
            es.put_object(bkt, f"idle-{i % 8}", ib)
            iput.append(time.monotonic() - t0)
            t0 = time.monotonic()
            _, got = es.get_object(bkt, f"idle-{i % 8}")
            iget.append(time.monotonic() - t0)
            if len(got) != len(ib):
                raise AssertionError("idle probe short read")
        leg["idle_put_p50_ms"] = round(_quantile(iput, 0.50) * 1e3, 4)
        leg["idle_get_p50_ms"] = round(_quantile(iget, 0.50) * 1e3, 4)
        return leg
    finally:
        os.environ.pop("MTPU_METABATCH", None)
        metalanes.reset()


def smallobj_bench(duration_s: float = 3.0, clients: int = 16,
                   idle_ops: int = 400, warmup_s: float = 2.0) -> dict:
    """Small-object suite (ISSUE 19): ops/s, amortized fsyncs/object,
    and metadata read fan-outs/request, MTPU_METABATCH=1 vs the =0
    single-op oracle, per leg.

    Drives live on a REAL (non-tmpfs) filesystem when one exists: the
    group-commit claim is about fsync amortization, and tmpfs fsync is
    a no-op — on tmpfs the two flags tie by construction and the
    measurement says nothing.  Falls back to /dev/shm with an explicit
    `disk_leg_skipped` marker (gates can't be honestly evaluated
    there).

    ABBA schedule like zerocopy_bench: batch, oracle, oracle, batch —
    averaging per flag cancels the linear later-run drift (writeback
    ramp) a single ordered pair bakes in."""
    import os
    import shutil
    import tempfile

    disk = _disk_backed_dir()
    base = disk or ("/dev/shm" if os.access("/dev/shm", os.W_OK)
                    else None)
    out: dict = {"so_clients": clients,
                 "so_small_lo_kib": 4, "so_small_hi_kib": 64}
    if disk is None:
        out["disk_leg_skipped"] = ("no disk-backed writable directory "
                                   "(tmpfs-only host) — fsync "
                                   "amortization unmeasurable")
    else:
        out["so_fs_type"] = _fs_type(disk)
    acc: dict = {"batch": [], "oracle": []}
    for label, flag in (("batch", "1"), ("oracle", "0"),
                        ("oracle", "0"), ("batch", "1")):
        root = tempfile.mkdtemp(prefix=f"mtpu-so-{label}-", dir=base)
        try:
            acc[label].append(_smallobj_leg(
                root, flag, clients=clients, duration_s=duration_s,
                idle_ops=idle_ops, warmup_s=warmup_s))
        finally:
            shutil.rmtree(root, ignore_errors=True)
    for label, runs in acc.items():
        for k in runs[0]:
            out[f"so_{label}_{k}"] = round(
                sum(r[k] for r in runs) / len(runs), 4)
    o_ops = out["so_oracle_put_ops_per_s"]
    out["so_put_ops_ratio"] = (round(
        out["so_batch_put_ops_per_s"] / o_ops, 3) if o_ops else 0.0)
    o_fs = out["so_oracle_fsyncs_per_object"]
    out["so_fsyncs_ratio"] = (round(
        out["so_batch_fsyncs_per_object"] / o_fs, 4) if o_fs else 0.0)
    out["so_get_fanouts_per_request"] = \
        out["so_batch_get_fanouts_per_request"]
    o_ip = out["so_oracle_idle_put_p50_ms"]
    out["so_idle_put_p50_ratio"] = (round(
        out["so_batch_idle_put_p50_ms"] / o_ip, 4) if o_ip else 0.0)
    o_ig = out["so_oracle_idle_get_p50_ms"]
    out["so_idle_get_p50_ratio"] = (round(
        out["so_batch_idle_get_p50_ms"] / o_ig, 4) if o_ig else 0.0)
    return out


def ilm_bench(duration_s: float = 3.0, object_kib: int = 256,
              clients: int = 4, n_objects: int = 192) -> dict:
    """Data-temperature suite (bucket/tier.py): what tiering costs and
    what it must not break.

    Leg 1 — bulk aging: PUT n_objects, transition every one to an fs
    warm tier through the exactly-once journal (fsync per intent),
    report aggregate transition MB/s; the journal must drain to zero
    and the tier must hold exactly one object per stub.

    Leg 2 — restore: permanent restores timed per object (p50/p99 —
    the "recall from cold" latency a reader pays once, after which the
    object is hot again), byte-verified; then temporary restores whose
    copies the scanner re-expires.  Frees flow through the journal, so
    pending must return to zero and the tier must shrink by exactly
    the restored count.

    Leg 3 — serving: loadgen's Zipf(1.1) mix with --ilm-mix 0.25 (the
    coldest quarter of the warm set lives behind stubs) — stub-GET
    p50/p99 against hot p50/p99 is the read-through tax, priced under
    live concurrent traffic, not in isolation.

    n_objects is scaled for a 1-core CI host; the structure (journal
    per transition, digest verify per copy) is what the number prices,
    so it transfers to the reference's 100k-object runs."""
    import os
    import shutil
    import tempfile

    from minio_tpu.bucket.tier import DirTierBackend, TierManager
    from tools.loadgen import _quantile, make_set, run_load

    out: dict = {"ilm_objects": n_objects,
                 "ilm_object_kib": object_kib}
    size = object_kib << 10

    # -- legs 1+2: bulk transition, then restores over the same set --------
    root = tempfile.mkdtemp(prefix="mtpu-ilm-age-")
    try:
        es = make_set(root, n=4)
        es.make_bucket("ilmb")
        rng = np.random.default_rng(11)
        body = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        for i in range(n_objects):
            es.put_object("ilmb", f"o-{i}", body)
        tm = TierManager(es)
        tier_dir = os.path.join(root, "tier")
        tm.add_tier("WARM", DirTierBackend(tier_dir))
        t0 = time.monotonic()
        moved = sum(1 for i in range(n_objects)
                    if tm.transition_object("ilmb", f"o-{i}", "WARM"))
        dt = time.monotonic() - t0
        out["ilm_transitioned"] = moved
        out["ilm_transition_s"] = round(dt, 3)
        out["ilm_transition_mbps"] = round(moved * size / dt / 1e6, 1)
        out["ilm_journal_pending_after_transition"] = \
            tm.journal.pending()
        out["ilm_tier_objects"] = len(os.listdir(tier_dir))

        nrestore = min(32, n_objects)
        lat: list[float] = []
        for i in range(nrestore):
            t0 = time.monotonic()
            if not tm.restore_object("ilmb", f"o-{i}"):
                raise RuntimeError(f"restore o-{i} failed")
            lat.append(time.monotonic() - t0)
        _, got = es.get_object("ilmb", "o-0")
        if got != body:
            raise RuntimeError("restored bytes differ from original")
        for _ in range(10):                  # frees retry through the
            if tm.journal.pending() == 0:    # journal until clean
                break
            tm.drain_journal()
        out["ilm_restores"] = nrestore
        out["ilm_restore_p50_ms"] = round(
            _quantile(lat, 0.50) * 1e3, 3)
        out["ilm_restore_p99_ms"] = round(
            _quantile(lat, 0.99) * 1e3, 3)
        out["ilm_journal_pending_after_restore"] = tm.journal.pending()
        out["ilm_tier_objects_after_restore"] = \
            len(os.listdir(tier_dir))

        ntemp = min(8, n_objects - nrestore)
        for i in range(nrestore, nrestore + ntemp):
            if not tm.restore_object("ilmb", f"o-{i}", days=1):
                raise RuntimeError(f"temp restore o-{i} failed")
        out["ilm_temp_restores"] = ntemp
        out["ilm_reexpired"] = tm.expire_restores(
            "ilmb", now=time.time() + 2 * 86400)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # -- leg 3: stub-GET tax under live Zipf traffic ------------------------
    root = tempfile.mkdtemp(prefix="mtpu-ilm-load-")
    try:
        es = make_set(root, n=4)
        r = run_load(es, clients=clients, object_size=size,
                     put_frac=0.05, duration_s=duration_s,
                     warm_objects=64, seed=7, zipf=1.1,
                     range_frac=0.2, ilm_mix=0.25,
                     tier_root=os.path.join(root, "tier"))
        out["ilm_load_gbps"] = r["gbps"]
        out["ilm_hot_p50_ms"] = r["hot_p50_ms"]
        out["ilm_hot_p99_ms"] = r["hot_p99_ms"]
        out["ilm_stub_gets"] = r["stub_gets"]
        out["ilm_stub_p50_ms"] = r["stub_p50_ms"]
        out["ilm_stub_p99_ms"] = r["stub_p99_ms"]
        out["ilm_journal_pending_after_load"] = \
            r["ilm_journal_pending"]
        if r["hot_p50_ms"]:
            out["ilm_stub_vs_hot_p50"] = round(
                r["stub_p50_ms"] / r["hot_p50_ms"], 2)
            out["ilm_stub_vs_hot_p99"] = round(
                r["stub_p99_ms"] / r["hot_p99_ms"], 2)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def decom_bench(n_objects: int = 48, object_kib: int = 256) -> dict:
    """Live-decommission suite (background/decom.py): a 2-pool engine,
    pool 0 loaded then drained through the normal write path.  Reports
    the drain throughput plus the placement-skew histogram — PUTs per
    pool before the drain (tie-break pins them to pool 0) vs after
    (the drained pool must take ZERO new writes)."""
    import shutil
    import tempfile

    from minio_tpu.background.decom import Decommissioner
    from minio_tpu.engine.pools import ServerPools
    from minio_tpu.engine.sets import ErasureSets
    from minio_tpu.storage.drive import LocalDrive

    out = {}
    root = tempfile.mkdtemp(prefix="mtpu-decom-")
    try:
        p0 = ErasureSets([LocalDrive(f"{root}/p0_d{i}")
                          for i in range(4)], set_drive_count=4)
        p1 = ErasureSets([LocalDrive(f"{root}/p1_d{i}")
                          for i in range(4)], set_drive_count=4,
                         deployment_id=p0.deployment_id)
        pools = ServerPools([p0, p1])
        pools.make_bucket("bench")
        rng = np.random.default_rng(7)
        body = rng.integers(0, 256, object_kib << 10,
                            dtype=np.uint8).tobytes()
        before: dict[int, int] = {}
        for i in range(n_objects):
            fi = pools.put_object("bench", f"o{i:03d}", body)
            p = getattr(fi, "pool_idx", -1)
            before[p] = before.get(p, 0) + 1
        d = Decommissioner(pools, 0)
        t0 = time.perf_counter()
        d.run_sync()
        wall = max(time.perf_counter() - t0, 1e-9)
        st = d.status()
        if st["state"] != "complete":
            out["decom_error"] = (f"drain ended {st['state']}: "
                                  f"{st['error']}")
            return out
        after: dict[int, int] = {}
        for i in range(max(8, n_objects // 4)):
            fi = pools.put_object("bench", f"post{i:03d}", body)
            p = getattr(fi, "pool_idx", -1)
            after[p] = after.get(p, 0) + 1
        out["decom_drain_mbps"] = round(st["bytes_moved"] / wall / 1e6,
                                        2)
        out["decom_wall_s"] = round(wall, 3)
        out["decom_objects_moved"] = st["objects_moved"]
        out["decom_versions_moved"] = st["versions_moved"]
        out["decom_pool_hits_before"] = {
            str(k): v for k, v in sorted(before.items())}
        out["decom_pool_hits_after"] = {
            str(k): v for k, v in sorted(after.items())}
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def obs_bench(n_get: int = 300, object_kib: int = 64) -> dict:
    """Observability-plane overhead: the same healthy-GET loop against
    one server with the full plane on (structured audit to a file
    target + the last-minute SLO window) and one with it off.  Reports
    both p50s and the delta pct — the plane's contract is <3% on the
    hot path.  One /minio/v2/metrics/node render is timed on the
    audited server afterwards (the scrape must stay copy-free), and
    the audit sink must shed nothing during the run: a drop here means
    the bench measured back-pressure, not the handler."""
    import os
    import shutil
    import tempfile

    from minio_tpu.engine.pools import ServerPools
    from minio_tpu.engine.sets import ErasureSets
    from minio_tpu.iam.iam import IAMSys
    from minio_tpu.server.client import S3Client
    from minio_tpu.server.server import S3Server
    from minio_tpu.server.sigv4 import Credentials
    from minio_tpu.storage.drive import LocalDrive

    rng = np.random.default_rng(11)
    body = rng.integers(0, 256, object_kib << 10,
                        dtype=np.uint8).tobytes()

    def boot(enabled: bool, root: str):
        old = {k: os.environ.get(k) for k in ("MTPU_AUDIT", "MTPU_SLO")}
        os.environ["MTPU_AUDIT"] = (f"file:{root}/audit.jsonl"
                                    if enabled else "")
        os.environ["MTPU_SLO"] = "1" if enabled else "0"
        try:
            drives = [LocalDrive(f"{root}/d{i}") for i in range(4)]
            pools = ServerPools([ErasureSets(drives,
                                             set_drive_count=4)])
            srv = S3Server(pools, Credentials("bench", "bench-secret"),
                           iam=IAMSys(pools)).start()
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        cli = S3Client(srv.endpoint, "bench", "bench-secret")
        cli.make_bucket("obs")
        cli.put_object("obs", "o", body)
        cli.get_object("obs", "o")              # warm
        return srv, cli

    out = {}
    root = tempfile.mkdtemp(prefix="mtpu-obs-")
    srvs = []
    try:
        srv_off, cli_off = boot(False, f"{root}/off")
        srvs.append(srv_off)
        srv_on, cli_on = boot(True, f"{root}/on")
        srvs.append(srv_on)
        # Interleave the two loops in small batches so page-cache
        # state, GC pauses and host jitter hit both sides equally —
        # at ~1.5 ms per GET a 50 us drift is 3% on its own.
        lat_on: list[float] = []
        lat_off: list[float] = []
        batch = 10
        for _ in range(max(1, n_get // batch)):
            for lat, cli in ((lat_off, cli_off), (lat_on, cli_on)):
                for _ in range(batch):
                    t0 = time.perf_counter()
                    cli.get_object("obs", "o")
                    lat.append(time.perf_counter() - t0)
        lat_on.sort()
        lat_off.sort()
        p50_on = lat_on[len(lat_on) // 2]
        p50_off = lat_off[len(lat_off) // 2]
        t0 = time.perf_counter()
        cli_on.request("GET", "/minio/v2/metrics/node")
        out["obs_scrape_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        out["obs_get_p50_off_ms"] = round(p50_off * 1e3, 3)
        out["obs_get_p50_on_ms"] = round(p50_on * 1e3, 3)
        out["obs_overhead_pct"] = round(
            (p50_on - p50_off) / p50_off * 100, 2)
        out["obs_audit_dropped_total"] = sum(
            t.dropped for t in srv_on.audit_targets)
    finally:
        for s in srvs:
            s.shutdown()
        shutil.rmtree(root, ignore_errors=True)
    return out


def overload_bench(duration_s: float = 6.0, object_kib: int = 256,
                   nworkers: int = 2, slots: int = 8) -> dict:
    """Overload-plane suite (server/qos.py): three multi-tenant legs
    against a pre-fork pool with an EXPLICIT admission budget
    (MTPU_REQUESTS_MAX=slots, so the fork-shared cap — not the
    machine — is the capacity under test).

    Leg 1 (capacity): offered concurrency == slots, QoS on — the
    uncontended goodput/p99 reference.  Leg 2 (overload): 4x slots
    offered across three tenant classes, QoS on — the gates: total
    goodput holds >= 90% of capacity (no congestion collapse),
    best-effort sheds while premium doesn't, and premium p99 stays
    bounded by the admission deadline.  Leg 3 (collapse): the same 4x
    offered load with MTPU_QOS=0 — nothing sheds, everything queues,
    reported as the contrast row."""
    import os
    import shutil
    import socket as _socket
    import subprocess
    import tempfile
    import urllib.request

    from tools.loadgen import parse_tenant_spec, run_load_tenants

    here = os.path.dirname(os.path.abspath(__file__))
    deadline_ms = 2000.0
    tenants_env = "gold=premium,std=standard,beff=best-effort"
    # 4x saturation: slots admission slots, 4*slots offered clients,
    # half of them best-effort — the class the ladder starves first.
    overload_spec = (f"gold:premium:{slots},std:standard:{slots},"
                     f"beff:best-effort:{2 * slots}")
    # ~60% of the slot budget: comfortably under capacity, so the
    # reference leg must finish shed-free even with the best-effort
    # ladder rung at half the slots.
    capacity_spec = (f"gold:premium:{max(1, slots // 4)},"
                     f"std:standard:{max(1, slots // 4)},"
                     f"beff:best-effort:{max(1, slots // 8)}")

    def run_leg(label: str, qos_on: bool, spec: str) -> dict:
        root = tempfile.mkdtemp(prefix=f"mtpu-olb-{label}-")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MTPU_SCANNER"] = "0"
        env["MTPU_WORKERS"] = str(nworkers)
        env["MTPU_QOS"] = "1" if qos_on else "0"
        env["MTPU_REQUESTS_MAX"] = str(slots)
        env["MTPU_REQUESTS_DEADLINE_MS"] = str(deadline_ms)
        env["MTPU_QOS_QUEUE"] = str(3 * slots)
        env["MTPU_QOS_TENANTS"] = tenants_env
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        proc = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu.server",
             "--drives", f"{root}/d{{1...4}}", "--port", str(port)],
            env=env, cwd=here, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        try:
            deadline = time.monotonic() + 180
            up = False
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    break
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}"
                            "/minio/health/ready", timeout=2) as r:
                        if r.status == 200:
                            up = True
                            break
                except Exception:  # noqa: BLE001 — keep polling
                    pass
                time.sleep(0.2)
            if not up:
                raise RuntimeError(f"overload_bench {label} never ready")
            return run_load_tenants(
                f"http://127.0.0.1:{port}",
                tenants=parse_tenant_spec(spec),
                object_size=object_kib << 10, put_frac=0.5,
                duration_s=duration_s, seed=len(label))
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
            shutil.rmtree(root, ignore_errors=True)

    cap = run_leg("cap", True, capacity_spec)
    over = run_leg("over", True, overload_spec)
    off = run_leg("off", False, overload_spec)

    gold = over["tenants"]["gold"]
    be = over["tenants"]["beff"]
    cap_p99 = max(r["p99_ms"] for r in cap["tenants"].values())
    out = {
        "ol_slots": slots,
        "ol_workers": nworkers,
        "ol_deadline_ms": deadline_ms,
        "ol_offered_clients": 4 * slots,
        "ol_cap_goodput_gbps": cap["total_goodput_gbps"],
        "ol_cap_p99_ms": cap_p99,
        "ol_cap_shed": cap["total_shed"],
        "ol_over_goodput_gbps": over["total_goodput_gbps"],
        "ol_over_shed": over["total_shed"],
        "ol_over_errors": over["total_errors"],
        "ol_gold_p99_ms": gold["p99_ms"],
        "ol_gold_shed_rate": gold["shed_rate"],
        "ol_be_shed": be["shed"],
        "ol_be_shed_rate": be["shed_rate"],
        "ol_off_goodput_gbps": off["total_goodput_gbps"],
        "ol_off_p99_ms": max(r["p99_ms"]
                             for r in off["tenants"].values()),
        "ol_off_shed": off["total_shed"],
    }
    out["ol_goodput_ratio"] = round(
        over["total_goodput_gbps"] / cap["total_goodput_gbps"], 3) \
        if cap["total_goodput_gbps"] else 0.0
    # Premium p99 bound under 4x overload: one admission-queue wait
    # (the deadline) plus contended service — generous, but the
    # collapse leg shows what UNBOUNDED looks like.
    out["ol_gold_p99_bound_ms"] = round(2 * deadline_ms
                                        + 10 * cap_p99, 1)
    return out


def multichip_bench(duration_s: float = 2.5,
                    object_mib: int = 1) -> dict:
    """Device-sharding suite (PR 10, per-device coalescer lanes): the
    same spread-keyspace closed loop over a 8-set hash ring at
    MTPU_DEVICES 1/2/8, reporting aggregate GB/s, p99, and how many
    lanes actually dispatched (with their mean batch occupancy) — plus
    the device-parallel vs serial heal-sweep wall times over two
    identically damaged rings, with an end-state equality check.  On a
    host without 8 visible devices (one TPU chip, or a plain CPU) the
    whole suite re-execs itself in a forced 8-virtual-CPU-device child,
    same trick as __graft_entry__.dryrun_multichip.  On a 1-core host
    the lane counts still prove the sharding; the GB/s ratios only
    separate on real parallel hardware."""
    import os
    import shutil
    import subprocess
    import tempfile

    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    if (len(jax.devices()) < 8
            and not os.environ.get("_MTPU_MULTICHIP_BENCH_CHILD")):
        env = dict(os.environ)
        env["_MTPU_MULTICHIP_BENCH_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        env["XLA_FLAGS"] = \
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        code = (
            "import json, sys; sys.path.insert(0, sys.argv[1]); "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from bench import multichip_bench; "
            f"print(json.dumps(multichip_bench({duration_s}, "
            f"{object_mib})))")
        res = subprocess.run(
            [sys.executable, "-c", code, here], env=env, cwd=here,
            capture_output=True, text=True, timeout=900)
        if res.returncode != 0:
            raise RuntimeError(
                f"multichip_bench child failed rc={res.returncode}: "
                f"{res.stderr[-500:]}")
        return json.loads(res.stdout.strip().splitlines()[-1])

    from minio_tpu.engine import heal as heal_mod
    from minio_tpu.ops import coalesce
    from tools.loadgen import make_sets, run_load

    out = {"mc_visible_devices": len(jax.devices())}
    saved = {k: os.environ.get(k)
             for k in ("MTPU_DEVICES", "MTPU_HEAL_DEVICE_PARALLEL")}

    def restore():
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        coalesce.reset()

    try:
        # -- serving loop at 1/2/8 lanes --------------------------------
        for nd in (1, 2, 8):
            os.environ["MTPU_DEVICES"] = str(nd)
            coalesce.reset()
            root = tempfile.mkdtemp(prefix=f"mtpu-mc{nd}-")
            try:
                ring = make_sets(root, nsets=8, set_drives=2, parity=1)
                r = run_load(ring, clients=8,
                             object_size=object_mib << 20,
                             put_frac=0.5, duration_s=duration_s,
                             bucket="bench", seed=nd,
                             keyspace="spread")
                out[f"mc_dev{nd}_gbps"] = r["gbps"]
                out[f"mc_dev{nd}_p99_ms"] = r["p99_ms"]
                out[f"mc_dev{nd}_lanes_active"] = \
                    len(r["lane_dispatches"])
                out[f"mc_dev{nd}_lane_dispatches"] = \
                    sum(r["lane_dispatches"].values())
                occ = list(r["lane_occupancy"].values())
                out[f"mc_dev{nd}_lane_occupancy"] = \
                    round(sum(occ) / len(occ), 3) if occ else 0.0
                out[f"mc_dev{nd}_set_spread"] = len(r["set_hits"])
                # H2D-overlap stage attribution (ISSUE 17): where the
                # lanes' host seconds went — pack (staging copy),
                # upload (device_put wait), resolve (result sync) —
                # and what fraction of that host work ran while the
                # previous batch's kernel was still executing.
                cst = coalesce.get().stats()
                host_s = (cst["pack_s"] + cst["h2d_s"]
                          + cst["resolve_s"])
                out[f"mc_dev{nd}_pipeline_dispatches"] = \
                    cst["pipeline_dispatches"]
                out[f"mc_dev{nd}_h2d_pack_s"] = round(cst["pack_s"], 4)
                out[f"mc_dev{nd}_h2d_upload_s"] = round(cst["h2d_s"], 4)
                out[f"mc_dev{nd}_h2d_resolve_s"] = \
                    round(cst["resolve_s"], 4)
                out[f"mc_dev{nd}_h2d_overlap_frac"] = round(
                    cst["overlap_s"] / host_s, 3) if host_s else 0.0
                lane_overlap = {}
                for dev, ls in cst.get("lanes", {}).items():
                    lh = ls["pack_s"] + ls["h2d_s"] + ls["resolve_s"]
                    if ls["pipeline_dispatches"]:
                        lane_overlap[int(dev)] = round(
                            ls["overlap_s"] / lh, 3) if lh else 0.0
                out[f"mc_dev{nd}_lane_overlap_frac"] = dict(
                    sorted(lane_overlap.items()))
                out[f"mc_dev{nd}_h2d_bytes_per_byte"] = \
                    r["h2d_bytes_per_byte"]
            finally:
                shutil.rmtree(root, ignore_errors=True)
                coalesce.reset()

        # -- heal sweep: device-parallel vs serial ----------------------
        os.environ["MTPU_DEVICES"] = "8"
        coalesce.reset()
        rng = np.random.default_rng(7)
        objs = {f"heal-{i}": rng.integers(
            0, 256, 256 * 1024, dtype=np.uint8).tobytes()
            for i in range(16)}
        root_a = tempfile.mkdtemp(prefix="mtpu-mch-a-")
        root_b = None
        try:
            ring = make_sets(root_a, nsets=8, set_drives=2, parity=1)
            ring.make_bucket("heal")
            for name, body in objs.items():
                ring.put_object("heal", name, body)
            # clone the tree (same format/deployment id), then damage
            # drive 0 of every set in BOTH rings identically
            root_b = tempfile.mkdtemp(prefix="mtpu-mch-b-")
            shutil.rmtree(root_b)
            shutil.copytree(root_a, root_b)
            rings, times, healed = {}, {}, {}
            for label, root in (("serial", root_a),
                                ("parallel", root_b)):
                for si in range(8):
                    d = os.path.join(root, f"d{si * 2}", "heal")
                    shutil.rmtree(d, ignore_errors=True)
                rings[label] = make_sets(root, nsets=8, set_drives=2,
                                         parity=1)
                os.environ["MTPU_HEAL_DEVICE_PARALLEL"] = \
                    "0" if label == "serial" else "1"
                t0 = time.monotonic()
                rings[label].heal_bucket("heal")

                def job(es):
                    return heal_mod.heal_bucket_objects(es, "heal")
                heal_mod.sweep_sets_device_parallel(
                    rings[label].sets, job)
                times[label] = time.monotonic() - t0
                healed[label] = {
                    name: rings[label].get_object("heal", name)[1]
                    for name in objs}
            out["mc_heal_serial_s"] = round(times["serial"], 3)
            out["mc_heal_parallel_s"] = round(times["parallel"], 3)
            out["mc_heal_parallel_vs_serial"] = round(
                times["serial"] / times["parallel"], 2) \
                if times["parallel"] else 0.0
            out["mc_heal_equal"] = all(
                bytes(healed["serial"][n]) == objs[n]
                and bytes(healed["parallel"][n]) == objs[n]
                for n in objs)
        finally:
            shutil.rmtree(root_a, ignore_errors=True)
            if root_b:
                shutil.rmtree(root_b, ignore_errors=True)
    finally:
        restore()
    return out


def devcache_bench(batches_per_lane: int = 3) -> dict:
    """Device-residency suite (ISSUE 17): boundary accounting for the
    pinned-staging H2D pipeline and the device shard cache, without
    real tunnel hardware.  Forces the device codec path on a simulated
    8-device mesh (same re-exec trick as multichip_bench) and reports:

      dc_first_touch_h2d_bytes_per_byte   ~1.0 — a GET ships each byte
                                          across the boundary at most
                                          once (exact-batch object)
      dc_hit_h2d_dispatches / dc_hit_zero_device_put
                                          0 / True — a devcache-hit GET
                                          performs no device_put at all
      dc_pipelined_gbps vs dc_serial_gbps PUT ingest through the lanes'
                                          double-buffered staged upload
                                          vs the MTPU_H2D_PIPELINE=0
                                          per-dispatch synchronous
                                          oracle (same XLA compute)
      dc_overlap_frac                     fraction of pipelined host
                                          seconds (pack+upload+resolve)
                                          spent while the previous
                                          batch's kernel was executing

    On the XLA-CPU mesh both PUT legs pay the same (emulated) kernel
    cost, so the GB/s ratio isolates the upload discipline; the
    overlap/ratio numbers only widen on a real tunnel."""
    import os
    import shutil
    import subprocess
    import tempfile

    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    if (len(jax.devices()) < 8
            and not os.environ.get("_MTPU_DEVCACHE_BENCH_CHILD")):
        env = dict(os.environ)
        env["_MTPU_DEVCACHE_BENCH_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        env["XLA_FLAGS"] = \
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        code = (
            "import json, sys; sys.path.insert(0, sys.argv[1]); "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from bench import devcache_bench; "
            f"print(json.dumps(devcache_bench({batches_per_lane})))")
        # Generous cap: the XLA-CPU mesh recompiles the padded encode
        # shapes per device per donate-variant, which dominates wall
        # time on hosts without a real accelerator.
        res = subprocess.run(
            [sys.executable, "-c", code, here], env=env, cwd=here,
            capture_output=True, text=True, timeout=2400)
        lines = res.stdout.strip().splitlines()
        if res.returncode != 0:
            # XLA-CPU clients can abort() during interpreter teardown
            # (C++ "terminate called" with lane threads still parked on
            # devices) AFTER the suite printed its results — salvage
            # the JSON line rather than discarding a finished run.
            try:
                return json.loads(lines[-1])
            except (IndexError, ValueError):
                raise RuntimeError(
                    f"devcache_bench child failed rc={res.returncode}: "
                    f"{res.stderr[-500:]}") from None
        return json.loads(lines[-1])

    from minio_tpu.engine import erasure_set as es_mod
    from minio_tpu.ops import coalesce, devcache
    from tools.loadgen import make_set

    out = {"dc_visible_devices": len(jax.devices())}
    saved_use = es_mod._USE_DEVICE
    saved = {k: os.environ.get(k)
             for k in ("MTPU_DEVICES", "MTPU_DEVCACHE",
                       "MTPU_H2D_PIPELINE")}
    es_mod._USE_DEVICE = True
    os.environ["MTPU_DEVICES"] = "8"
    os.environ["MTPU_DEVCACHE"] = "1"

    def reset_planes():
        coalesce.reset()
        devcache.reset()
        devcache.reset_h2d()

    try:
        # -- boundary accounting: first touch vs resident hit -----------
        # One exact-batch object (BATCH_BLOCKS blocks): the GET is a
        # single dispatch whose padded rows equal the object, so the
        # first-touch bytes-per-byte is exactly the claim, no padding
        # inflation.  The lane is pinned hot so the dispatch takes the
        # queued (device) path rather than the idle-inline host path.
        os.environ["MTPU_H2D_PIPELINE"] = "1"
        reset_planes()
        size = es_mod.BATCH_BLOCKS * es_mod.BLOCK_SIZE
        root = tempfile.mkdtemp(prefix="mtpu-dcb-acct-")
        try:
            es = make_set(root, n=4)
            es.make_bucket("b")
            body = np.random.default_rng(17).integers(
                0, 256, size, dtype=np.uint8).tobytes()
            es.put_object("b", "o", body)
            coalesce.get()._ema = 2.0
            devcache.reset_h2d()
            _, got = es.get_object("b", "o")
            if bytes(got) != body:
                raise AssertionError("first-touch GET corrupt")
            h1 = devcache.h2d_stats()
            out["dc_first_touch_h2d_bytes_per_byte"] = round(
                h1["h2d_bytes"] / size, 4)
            out["dc_first_touch_h2d_dispatches"] = h1["h2d_dispatches"]
            coalesce.get()._ema = 2.0
            _, got = es.get_object("b", "o")
            if bytes(got) != body:
                raise AssertionError("devcache-hit GET corrupt")
            h2 = devcache.h2d_stats()
            st = devcache.stats() or {}
            out["dc_hit_h2d_dispatches"] = \
                h2["h2d_dispatches"] - h1["h2d_dispatches"]
            out["dc_hit_h2d_bytes"] = h2["h2d_bytes"] - h1["h2d_bytes"]
            out["dc_hit_zero_device_put"] = \
                out["dc_hit_h2d_dispatches"] == 0
            out["dc_hit_ratio"] = st.get("hit_ratio", 0.0)
            out["dc_resident_bytes"] = st.get("resident_bytes", 0)
        finally:
            shutil.rmtree(root, ignore_errors=True)

        # -- pipelined vs serial staged upload over the 8-lane mesh -----
        # PUT encode is the apples-to-apples kernel: encode_and_hash
        # runs on the lane's device under BOTH flags, so the only
        # difference is the upload discipline (double-buffered pinned
        # staging + donated device input vs one synchronous upload per
        # dispatch).  The engine's closed-loop load generator quantizes
        # too coarsely on an XLA-emulated host (single-digit seconds-
        # long dispatches per window, clients serialized behind their
        # handles), so this leg drives the lanes directly: each of the
        # 8 lanes is fed `batches_per_lane` full-budget encode batches
        # up front, keeping its queue non-empty so batch N+1's
        # pack+upload genuinely overlaps batch N's kernel.  ABBA
        # ordering cancels residual drift, same as zerocopy_bench.
        nb = es_mod.BATCH_BLOCKS
        shard = es_mod.BLOCK_SIZE // 2
        batch = np.random.default_rng(41).integers(
            0, 256, (nb, 2, shard), dtype=np.uint8)
        ndev = 8
        # Submitting at full budget weight pins one dispatch per batch,
        # so both flags see one fixed jit shape and a deterministic
        # dispatch count.
        full = coalesce.max_batch()
        acc: dict = {"pipelined": [], "serial": []}
        bpb: dict = {"pipelined": [], "serial": []}
        overlap_s = host_s = 0.0
        pipeline_disp = 0
        for label, flag in (("pipelined", "1"), ("serial", "0"),
                            ("serial", "0"), ("pipelined", "1")):
            os.environ["MTPU_H2D_PIPELINE"] = flag
            reset_planes()
            co = coalesce.get()
            kerns = {d: es._enc_kernel(2, 1, "mxh256", True, device=d)
                     for d in range(ndev)}
            # Pin every lane hot so submits take the queued (device)
            # path, then absorb this flag's per-device jit compile with
            # one untimed batch per lane.
            for d in range(ndev):
                co.lane(d)._ema = 2.0
            warm = [co.lane(d).submit(("dcb-warm", 2, 1, "mxh256", d),
                                      batch, kerns[d], weight=full)
                    for d in range(ndev)]
            for h in warm:
                h.result(timeout=2400)
                h.release()
            s0 = co.stats()
            h2d0 = devcache.h2d_stats()["h2d_bytes"]
            for d in range(ndev):
                co.lane(d)._ema = 2.0
            t0 = time.perf_counter()
            handles = [co.lane(d).submit(
                           ("dcb-enc", 2, 1, "mxh256", d),
                           batch, kerns[d], weight=full)
                       for _ in range(batches_per_lane)
                       for d in range(ndev)]
            for h in handles:
                h.result(timeout=2400)
                h.release()
            wall = time.perf_counter() - t0
            payload = len(handles) * batch.nbytes
            acc[label].append(payload / wall / 1e9)
            bpb[label].append(
                (devcache.h2d_stats()["h2d_bytes"] - h2d0) / payload)
            if flag == "1":
                s1 = co.stats()
                overlap_s += s1["overlap_s"] - s0["overlap_s"]
                host_s += ((s1["pack_s"] + s1["h2d_s"]
                            + s1["resolve_s"])
                           - (s0["pack_s"] + s0["h2d_s"]
                              + s0["resolve_s"]))
                pipeline_disp += (s1["pipeline_dispatches"]
                                  - s0["pipeline_dispatches"])
        for label in ("pipelined", "serial"):
            out[f"dc_{label}_gbps"] = round(
                sum(acc[label]) / len(acc[label]), 5)
            out[f"dc_{label}_h2d_bytes_per_byte"] = round(
                sum(bpb[label]) / len(bpb[label]), 4)
        mean_p = sum(acc["pipelined"]) / len(acc["pipelined"])
        mean_s = sum(acc["serial"]) / len(acc["serial"])
        out["dc_pipelined_vs_serial"] = round(mean_p / mean_s, 3) \
            if mean_s else 0.0
        out["dc_pipelined_vs_serial_best"] = round(
            max(acc["pipelined"]) / max(acc["serial"]), 3) \
            if acc["serial"] and max(acc["serial"]) else 0.0
        out["dc_pipeline_dispatches"] = pipeline_disp
        out["dc_overlap_frac"] = round(overlap_s / host_s, 3) \
            if host_s else 0.0
    finally:
        es_mod._USE_DEVICE = saved_use
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_planes()
    return out


def digest_bench(duration_s: float = 3.0) -> dict:
    """Native multi-buffer digest plane suite (MTPU_NATIVE_DIGEST):

      digest_md5_hashlib_gbps      one hashlib.md5 stream (the oracle —
                                   and the old serial ETag wall)
      digest_md5_native_xN_gbps    N incremental streams in SIMD
                                   lockstep through native/digest.cc,
                                   aggregate rate (acceptance: >= 3x)
      digest_sha256_*_gbps         8-buffer batch, hashlib vs native
      digest_conc{4,8}_put[_oracle]_gbps
                                   closed-loop PUT-only 1 MiB loadgen
                                   runs, native lanes vs hashlib oracle
      digest_sigv4_streamed_gbps / digest_put_unsigned_gbps
                                   aws-chunked signed PUT vs the same
                                   PUT unsigned over HTTP (the chunk
                                   sha256 chain is the delta)
      digest_mp_put[_oracle]_gbps  2x32 MiB multipart parts, part-ETag
                                   lanes on vs off
    """
    import hashlib
    import os
    import shutil
    import tempfile

    from minio_tpu.engine import multipart as mp
    from minio_tpu.engine.erasure_set import ErasureSet
    from minio_tpu.storage.drive import LocalDrive
    from tools.loadgen import run_load

    def best_rate(fn, nbytes, n=3):
        fn()
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return nbytes / best / 1e9

    out = {}
    rng = np.random.default_rng(3)

    # -- kernel: single hashlib stream vs N-lane native aggregate ------------
    try:
        from native import digest_native as dn
        dn.load()
        out["digest_isa"] = dn.isa()
        lanes = dn.md5_lanes()
        bufs = [rng.integers(0, 256, 8 << 20, dtype=np.uint8).tobytes()
                for _ in range(lanes)]
        one = best_rate(lambda: hashlib.md5(bufs[0]).digest(), len(bufs[0]))
        agg = best_rate(lambda: dn.md5_batch(bufs),
                        sum(len(b) for b in bufs))
        out["digest_md5_hashlib_gbps"] = round(one, 2)
        out[f"digest_md5_native_x{lanes}_gbps"] = round(agg, 2)
        out["digest_md5_lane_speedup"] = round(agg / one, 2)
        sha_h = best_rate(
            lambda: [hashlib.sha256(b).digest() for b in bufs],
            sum(len(b) for b in bufs))
        sha_n = best_rate(lambda: dn.sha256_batch(bufs),
                          sum(len(b) for b in bufs))
        out["digest_sha256_hashlib_gbps"] = round(sha_h, 2)
        out["digest_sha256_native_gbps"] = round(sha_n, 2)
    except Exception as e:  # noqa: BLE001 — suite must still report
        out["digest_native_error"] = f"{type(e).__name__}: {e}"

    saved_flag = os.environ.get("MTPU_NATIVE_DIGEST")

    def set_flag(v):
        if v is None:
            os.environ.pop("MTPU_NATIVE_DIGEST", None)
        else:
            os.environ["MTPU_NATIVE_DIGEST"] = v

    # -- concurrent PUT: lanes on vs hashlib oracle --------------------------
    root = tempfile.mkdtemp(prefix="mtpu-digest-")
    try:
        es = ErasureSet([LocalDrive(f"{root}/d{i}") for i in range(4)])
        es.make_bucket("bench")
        warm = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
        es.put_object("bench", "warm", warm)            # compile warm-up
        for n in (4, 8):
            for flag, tag in (("1", ""), ("0", "_oracle")):
                set_flag(flag)
                r = run_load(es, clients=n, object_size=1 << 20,
                             put_frac=1.0, duration_s=duration_s,
                             bucket="bench", seed=20 + n)
                out[f"digest_conc{n}_put{tag}_gbps"] = r["gbps"]
                if flag == "1":
                    out[f"digest_conc{n}_lane_occupancy"] = \
                        r["dg_md5_occupancy"]
        set_flag("1")

        # -- multipart part-ETag lanes on vs off -----------------------------
        part = rng.integers(0, 256, 32 << 20, dtype=np.uint8).tobytes()
        for flag, tag in (("1", ""), ("0", "_oracle")):
            set_flag(flag)
            up = mp.new_multipart_upload(es, "bench", f"mp{flag}")
            mp.put_object_part(es, "bench", f"mp{flag}", up, 1, part)
            t0 = time.perf_counter()
            for pn in (2, 3):
                mp.put_object_part(es, "bench", f"mp{flag}", up, pn, part)
            dt = time.perf_counter() - t0
            out[f"digest_mp_put{tag}_gbps"] = round(
                2 * len(part) / dt / 1e9, 2)
            etags = {p.number: p.etag
                     for p in mp.list_parts(es, "bench", f"mp{flag}", up)}
            mp.complete_multipart_upload(
                es, "bench", f"mp{flag}", up,
                [(pn, etags[pn]) for pn in sorted(etags)])
    finally:
        set_flag(saved_flag)
        shutil.rmtree(root, ignore_errors=True)

    # -- SigV4 streamed vs unsigned PUT over HTTP ----------------------------
    try:
        out.update(_sigv4_streamed_bench())
    except Exception as e:  # noqa: BLE001
        out["digest_sigv4_error"] = f"{type(e).__name__}: {e}"
    return out


def _sigv4_streamed_bench(n_put: int = 8, obj_mib: int = 8) -> dict:
    """aws-chunked (chunk-signed, sha256 per chunk) PUT vs the same PUT
    with UNSIGNED-PAYLOAD, through the real HTTP front door.  The delta
    is the price of streaming-SigV4 payload verification."""
    import datetime
    import http.client as hc
    import shutil
    import tempfile

    from minio_tpu.engine.pools import ServerPools
    from minio_tpu.engine.sets import ErasureSets
    from minio_tpu.server import sigv4
    from minio_tpu.server.client import S3Client
    from minio_tpu.server.server import S3Server
    from minio_tpu.storage.drive import LocalDrive

    out = {}
    root = tempfile.mkdtemp(prefix="mtpu-sigv4-")
    srv = None
    try:
        pools = ServerPools([ErasureSets(
            [LocalDrive(f"{root}/d{i}") for i in range(4)],
            set_drive_count=4)])
        srv = S3Server(pools, sigv4.Credentials("bench", "bench-secret")
                       ).start()
        cli = S3Client(srv.endpoint, "bench", "bench-secret")
        cli.make_bucket("sv4")
        payload = np.random.default_rng(9).integers(
            0, 256, obj_mib << 20, dtype=np.uint8).tobytes()

        def put_unsigned(key):
            from minio_tpu.utils import streams
            cli.put_object_stream("sv4", key, streams.BytesReader(payload),
                                  len(payload))

        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        scope = f"{amz_date[:8]}/{cli.creds.region}/s3/aws4_request"

        def encode_chunked(key):
            """Client-side signing/framing, done OUTSIDE the timed
            region — the server's verify cost is what we measure."""
            headers = {"Host": f"{cli.host}:{cli.port}"}
            auth = sigv4.sign_request(cli.creds, "PUT", f"/sv4/{key}", {},
                                      headers, sigv4.STREAMING_PAYLOAD,
                                      now=now)
            headers.update(auth)
            seed_sig = auth["Authorization"].rsplit("Signature=", 1)[1]
            wire = sigv4.encode_streaming_body(
                cli.creds, scope, amz_date, seed_sig, payload,
                chunk_size=1 << 20)
            headers["Content-Length"] = str(len(wire))
            return key, headers, wire

        def put_chunked(key, headers, wire):
            conn = hc.HTTPConnection(cli.host, cli.port, timeout=120)
            try:
                conn.request("PUT", f"/sv4/{key}", body=wire,
                             headers=headers)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise RuntimeError(body[:200])
            finally:
                conn.close()

        wires = [encode_chunked(f"c{i}") for i in range(n_put)]
        put_unsigned("warm-u")                          # warm both paths
        put_chunked(*encode_chunked("warm-c"))
        t0 = time.perf_counter()
        for i in range(n_put):
            put_unsigned(f"u{i}")
        dt_u = time.perf_counter() - t0
        t0 = time.perf_counter()
        for w in wires:
            put_chunked(*w)
        dt_c = time.perf_counter() - t0
        total = n_put * len(payload)
        out["digest_put_unsigned_gbps"] = round(total / dt_u / 1e9, 2)
        out["digest_sigv4_streamed_gbps"] = round(total / dt_c / 1e9, 2)
        out["digest_sigv4_overhead_pct"] = round(
            100.0 * (dt_c - dt_u) / dt_u, 1)
    finally:
        if srv is not None:
            srv.shutdown()
        shutil.rmtree(root, ignore_errors=True)
    return out


def _best_of(f, n=5):
    """Best-of-n ms timing for the stage-attribution probes."""
    f()
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        f()
        times.append(time.perf_counter() - t0)
    return min(times) * 1e3


def _get_healthy_stages(es12) -> dict:
    """Per-stage attribution of the HEALTHY GET fast path over one
    16-block (16 MiB) segment of the 8+4 object: verdict-only bitrot
    verify (native/ecio.cc ec_verify_frames — no decode, no gather),
    the systematic assemble (strided copy of the k data rows into the
    response buffer), the FUSED verify+gather the path actually
    dispatches (hash and copy in one pass over each frame), and the
    whole engine segment read.  Acceptance target: verify <= 1.6 ms
    per 16 MiB."""
    stages = {}
    try:
        from native import ecio_native
        from minio_tpu.engine import quorum as Q

        best = _best_of
        fi, _, _ = es12._read_metadata("bench", "mp")
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        ss = fi.erasure.shard_size
        hs = 32
        nb = 16
        path = f"mp/{fi.data_dir}/part.1"
        dist = fi.erasure.distribution
        order = Q.shuffle_by_distribution(list(range(es12.n)), dist)
        raws = [es12.drives[order[s]].read_file_view(
            "bench", path, 0, nb * (hs + ss)) for s in range(k)]

        def vf():
            _, nbad = ecio_native.verify_frames(raws, nb, ss)
            if nbad:
                raise RuntimeError("bitrot during healthy stage probe")
        stages["get_healthy_stage_verify_ms"] = best(vf)

        buf = bytearray(nb * k * ss)
        y = np.frombuffer(buf, dtype=np.uint8).reshape(nb, k, ss)
        frames = [np.frombuffer(r, np.uint8).reshape(nb, hs + ss)
                  for r in raws]

        def asm():
            for s in range(k):
                y[:, s, :] = frames[s][:, hs:]
        stages["get_healthy_stage_assemble_ms"] = best(asm)

        def fused_va():
            _, _, nbad = ecio_native.get_verify(
                raws, list(range(k)), nb, ss, k, m, [],
                out=memoryview(buf))
            if nbad:
                raise RuntimeError("bitrot during healthy stage probe")
        stages["get_healthy_fused_verify_assemble_ms"] = best(fused_va)

        def whole():
            es12._read_part("bench", "mp", fi, part_number=1, offset=0,
                            length=nb * (1 << 20), healthy=True)
        stages["get_healthy_total_16mib_ms"] = best(whole)
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        stages["get_healthy_stage_error"] = f"{type(e).__name__}: {e}"
    return {k2: round(v, 3) if isinstance(v, float) else v
            for k2, v in stages.items()}


def _get_stages(es12) -> dict:
    """Per-stage attribution of the degraded GET (2 data shards offline)
    over one 16-block segment of the 8+4 object: mmap'd shard reads,
    the fused native verify+gather+reconstruct pass, and the whole
    engine segment read (residual = quorum/metadata/iterator glue)."""
    stages = {}
    try:
        from native import ecio_native
        from minio_tpu.engine import quorum as Q

        best = _best_of
        fi, _, _ = es12._read_metadata("bench", "mp")
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        ss = fi.erasure.shard_size
        hs = 32
        nb = 16
        path = f"mp/{fi.data_dir}/part.1"
        dist = fi.erasure.distribution
        order = Q.shuffle_by_distribution(list(range(es12.n)), dist)
        sel = [s for s in range(k + m)
               if es12.drives[order[s]] is not None][:k]
        missing = [s for s in range(k) if s not in sel]
        raws = [None]

        def rd():
            raws[0] = [es12.drives[order[s]].read_file_view(
                "bench", path, 0, nb * (hs + ss)) for s in sel]
        stages["get_stage_read_ms"] = best(rd)

        def vf():
            y, ok, nbad = ecio_native.get_verify(raws[0], sel, nb, ss, k,
                                                 m, missing)
            if nbad:
                raise RuntimeError("bitrot during stage probe")
        stages["get_stage_verify_decode_ms"] = best(vf)

        def whole():
            es12._read_part("bench", "mp", fi, part_number=1, offset=0,
                            length=nb * (1 << 20))
        total = best(whole)
        stages["get_total_16mib_ms"] = total
        stages["get_stage_other_ms"] = max(
            total - stages["get_stage_read_ms"]
            - stages["get_stage_verify_decode_ms"], 0.0)
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        stages["get_stage_error"] = f"{type(e).__name__}: {e}"
    return {k2: round(v, 3) if isinstance(v, float) else v
            for k2, v in stages.items()}


def _span_attribution(es) -> dict:
    """Span-tree attribution of one traced 16 MiB PUT + GET: the
    trace-plane cross-check of _put_stages/_get_stages.  Where those
    probes re-run stages standalone and leave a put/get_stage_other_ms
    residue, the span tree decomposes the ACTUAL request into named
    engine/native/drive stages, and coverage_pct says how much of the
    root wall time the direct children account for."""
    from minio_tpu.observe import span as ospan

    tracer = ospan.TRACER
    out = {}
    try:
        rng = np.random.default_rng(11)
        data = rng.integers(0, 256, 16 << 20, dtype=np.uint8).tobytes()
        es.put_object("bench", "spanprobe", data)        # warm
        es.get_object("bench", "spanprobe")
        tracer.configure(ring=8, sample=1.0)
        with tracer.root("api.PutObject", path="/bench/spanprobe"):
            es.put_object("bench", "spanprobe", data)
        with tracer.root("api.GetObject", path="/bench/spanprobe"):
            es.get_object("bench", "spanprobe")
        put_rec, get_rec = tracer.traces()[-2:]
        for pref, rec in (("put", put_rec), ("get", get_rec)):
            out[f"{pref}_span_total_16mib_ms"] = rec["dur_ms"]
            out[f"{pref}_span_coverage_pct"] = \
                100.0 * ospan.coverage(rec)
            for name, ms in sorted(ospan.flatten(rec).items()):
                out[f"{pref}_span_{name.replace('.', '_')}_ms"] = ms
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        out["span_stage_error"] = f"{type(e).__name__}: {e}"
    finally:
        tracer.configure(ring=0)
    return {k: round(v, 3) if isinstance(v, float) else v
            for k, v in out.items()}


def _put_stages(es4, obj_bytes: bytes) -> dict:
    """Per-stage attribution of the 2+2/1 MiB PUT (VERDICT r4 next-#1:
    'a per-stage time breakdown so the remaining gap is attributed, not
    guessed').  Stages are timed standalone, best-of-5, in ms per 1 MiB
    object; put_stage_other_ms is the measured whole-PUT median minus
    the accounted stages (publish metadata, quorum glue, locks)."""
    import hashlib
    import numpy as np

    best = _best_of
    stages = {}
    stages["put_stage_md5_ms"] = best(
        lambda: hashlib.md5(obj_bytes).hexdigest())
    blocks = np.frombuffer(obj_bytes, np.uint8).reshape(1, 2, 1 << 19)
    try:
        from native import ecio_native
        framed = [None]

        def enc():
            framed[0] = [np.asarray(v) for v in
                         ecio_native.put_frame(blocks, 2, 2)]
        stages["put_stage_encode_hash_frame_ms"] = best(enc)
        import os
        import uuid
        wdir = f"{es4.drives[0].root}/.stageprobe"
        os.makedirs(wdir, exist_ok=True)

        def wr():
            tag = uuid.uuid4().hex
            for i, fr in enumerate(framed[0]):
                with open(f"{wdir}/{tag}.{i}", "wb") as f:
                    f.write(fr)
        stages["put_stage_write_ms"] = best(wr)
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        stages["put_stage_error"] = f"{type(e).__name__}: {e}"

    seq = [0]

    def put_one():
        seq[0] += 1
        es4.put_object("bench", f"stageprobe{seq[0]}", obj_bytes)
    total = best(put_one)
    stages["put_total_ms"] = total
    accounted = sum(v for k, v in stages.items()
                    if k.startswith("put_stage_") and k.endswith("_ms"))
    stages["put_stage_other_ms"] = max(total - accounted, 0.0)
    return {k: round(v, 3) if isinstance(v, float) else v
            for k, v in stages.items()}


def _select_bench(n_records: int = 300_000) -> dict:
    """S3 Select NDJSON scan: the simdjson-role native fast path vs the
    stdlib reader on the same query (VERDICT r4 #9)."""
    import json as _json

    from minio_tpu.s3select.engine import read_json_lines
    from minio_tpu.s3select.fastjson import (load, read_json_lines_fast,
                                             referenced_fields)
    from minio_tpu.s3select.sql import parse

    load()                                  # build outside the timing
    lines = []
    for i in range(n_records):
        lines.append(_json.dumps({
            "id": i, "name": f"user-{i}", "score": (i % 997) / 7.0,
            "active": bool(i % 3), "tags": ["a", "b"],
            "nested": {"x": i}, "payload": "x" * 64, "note": "plain"}))
    data = ("\n".join(lines)).encode()

    def best_of(expr, n=2):
        fields = referenced_fields(parse(expr))
        b_std = b_fast = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            read_json_lines(data)
            b_std = min(b_std, time.perf_counter() - t0)
            t0 = time.perf_counter()
            read_json_lines_fast(data, fields)
            b_fast = min(b_fast, time.perf_counter() - t0)
        return b_std, b_fast

    # the classic scan shape: aggregate over a filtered pass
    std, fast = best_of("SELECT count(*) FROM s3object s "
                        "WHERE s.score > 100")
    # multi-field projection: bounded by Python dict assembly
    std_p, fast_p = best_of("SELECT s.note FROM s3object s "
                            "WHERE s.active = true AND s.id < 100")
    return {
        "select_ndjson_fast_gbps": round(len(data) / fast / 1e9, 3),
        "select_ndjson_stdlib_gbps": round(len(data) / std / 1e9, 3),
        "select_ndjson_speedup": round(std / fast, 1),
        "select_ndjson_project_speedup": round(std_p / fast_p, 1),
    }


def _tunnel_probe() -> dict:
    """Measure the axon tunnel's dispatch RT and transfer bandwidth so
    the e2e numbers can be read against the environment's ceiling."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def triv(x):
        return x + 1

    x1 = jax.device_put(np.ones((8,), np.uint8))
    np.asarray(triv(x1))
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(triv(x1))
    rt_ms = (time.perf_counter() - t0) / 5 * 1e3

    big = np.ones((32 << 20,), np.uint8)
    jax.device_put(big).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(triv(jax.device_put(big)[:8]))
    h2d_s = (time.perf_counter() - t0) / 3

    @jax.jit
    def make16(x):
        return jnp.broadcast_to(x, (16 << 20,)).astype(jnp.uint8)

    np.asarray(make16(x1[:1]))
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(make16(x1[:1] ^ 1))
    d2h_s = (time.perf_counter() - t0) / 3
    return {
        "tunnel_rt_ms": round(rt_ms, 1),
        "tunnel_h2d_mbps": round(32 / max(h2d_s - rt_ms / 1e3, 1e-9), 1),
        "tunnel_d2h_mbps": round(16 / max(d2h_s - rt_ms / 1e3, 1e-9), 1),
    }


def main() -> None:
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops.erasure_jax import (ReedSolomonTPU,
                                           _transform_matrix_bits,
                                           _gf_matmul_blocks)
    from minio_tpu.ops.highwayhash import MAGIC_KEY

    on_tpu = jax.default_backend() == "tpu"
    dev = ReedSolomonTPU(K, M, use_pallas=on_tpu)
    rng = np.random.default_rng(0)

    def fold(*arrays):
        acc = jnp.uint8(0)
        for a in arrays:
            acc = acc ^ jax.lax.reduce(a, jnp.uint8(0), jax.lax.bitwise_xor,
                                       tuple(range(a.ndim)))
        return acc

    def make_loop(body_fn, n_iter):
        """body_fn(x, salt) with salt a (1,) int32 changing per iteration
        — the codec kernels fold it into the input in-kernel."""
        @jax.jit
        def loop(x):
            def body(i, acc):
                salt = jnp.full((1,), i, dtype=jnp.int32)
                return acc ^ body_fn(x, salt)
            return jax.lax.fori_loop(0, n_iter, body, jnp.uint8(0))
        return loop

    results = {}

    # -- encode (headline) --------------------------------------------------
    x = jax.device_put(rng.integers(0, 256, size=(BLOCKS, K, SHARD),
                                    dtype=np.uint8))
    data_bytes = BLOCKS * K * SHARD
    encode_loop = make_loop(
        lambda xi, s: fold(dev.encode_blocks(xi, salt=s)), N_ITER)
    base_loop = make_loop(
        lambda xi, s: xi[0, 0, 0] ^ s[0].astype(jnp.uint8), N_ITER)
    t_encode = _timed(encode_loop, x)
    t_base = _timed(base_loop, x)
    per_call = max((t_encode - t_base) / N_ITER, 1e-9)
    if t_encode - t_base <= 0:
        per_call = t_encode / N_ITER
    results["encode"] = data_bytes / per_call / 1e9

    # -- decode: 2 data rows lost, read 8 of the surviving rows -------------
    sources = (2, 3, 4, 5, 6, 7, 8, 9)   # rows 0,1 lost; 8 survivors read
    targets = (0, 1)
    decode_loop = make_loop(
        lambda xi, s: fold(dev.transform_blocks(xi, sources, targets,
                                                salt=s)), N_ITER)
    t_dec = _timed(decode_loop, x)
    per_call = max((t_dec - t_base) / N_ITER, t_dec / N_ITER / 10)
    results["decode_2lost"] = data_bytes / per_call / 1e9

    # -- heal: rebuild one data + one parity row (decode->re-encode pipe) ---
    heal_targets = (0, 9)
    heal_loop = make_loop(
        lambda xi, s: fold(dev.transform_blocks(xi, sources, heal_targets,
                                                salt=s)), N_ITER)
    t_heal = _timed(heal_loop, x)
    per_call = max((t_heal - t_base) / N_ITER, t_heal / N_ITER / 10)
    results["heal_2lost"] = data_bytes / per_call / 1e9

    # -- fused verify+decode (north-star config #5) -------------------------
    # Production path: mxh256 digests (the default write algorithm) fused
    # with the 2-row reconstruct. The HighwayHash variant (interop reads of
    # pre-mxh objects) is timed separately as an extra.
    xf = x[:FUSED_BLOCKS]
    fused_bytes = FUSED_BLOCKS * K * SHARD
    mat = jnp.asarray(_transform_matrix_bits(K, M, sources, targets),
                      dtype=jnp.bfloat16)

    from minio_tpu.ops.erasure_pallas import gf_matmul_blocks
    from minio_tpu.ops.highwayhash_jax import _hh256_impl
    from minio_tpu.ops.mxhash_jax import mxh256_rows

    if on_tpu:
        decode_kernel = gf_matmul_blocks
    else:
        def decode_kernel(mat, x, rows, salt=None):
            if salt is not None:
                x = x ^ salt[0].astype(jnp.uint8)
            return _gf_matmul_blocks(mat, x, rows)

    def fused_body(xi, s):
        b, kk, sh = xi.shape
        # hash consumes the salt at the jax level (fuses into its int8
        # packing); the erasure matmul takes it in-kernel
        xs = (xi.reshape(b * kk, sh) ^ s[0].astype(jnp.uint8))
        digests = mxh256_rows(xs)
        out = decode_kernel(mat, xi, len(targets), salt=s)
        return fold(digests, out)

    def fused_body_hh(xi, s):
        b, kk, sh = xi.shape
        xs = (xi.reshape(b * kk, sh) ^ s[0].astype(jnp.uint8))
        digests = _hh256_impl(xs, MAGIC_KEY)
        out = decode_kernel(mat, xi, len(targets), salt=s)
        return fold(digests, out)

    perturb_f = make_loop(
        lambda xi, s: xi[0, 0, 0] ^ s[0].astype(jnp.uint8), FUSED_ITER)
    t_fbase = _timed(perturb_f, xf, repeats=3)
    fused_loop = make_loop(fused_body, FUSED_ITER)
    t_fused = _timed(fused_loop, xf, repeats=3)
    per_call = max((t_fused - t_fbase) / FUSED_ITER, t_fused / FUSED_ITER / 10)
    results["fused_verify_decode"] = fused_bytes / per_call / 1e9

    fused_hh_loop = make_loop(fused_body_hh, FUSED_ITER)
    t_fused_hh = _timed(fused_hh_loop, xf, repeats=3)
    per_call = max((t_fused_hh - t_fbase) / FUSED_ITER,
                   t_fused_hh / FUSED_ITER / 10)
    results["fused_verify_decode_hh"] = fused_bytes / per_call / 1e9

    # HH verify as the READ PATH actually routes it (VERDICT r3 weak
    # #2): the native AVX2/AVX-512 host kernel (native/highwayhash.cc)
    # verifies HighwayHash shards; the device only reconstructs. The
    # device-fused HH number above is kept for comparison.
    try:
        from native.hh_native import hh256_rows_native, isa as hh_isa
        rows = np.random.default_rng(5).integers(
            0, 256, (K * 64, SHARD), dtype=np.uint8)   # host-resident
        hh256_rows_native(rows)                           # build+warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            hh256_rows_native(rows)
            best = min(best, time.perf_counter() - t0)
        results["hh_host_verify_gbps"] = rows.size / best / 1e9
        results["hh_host_isa"] = hh_isa()
    except Exception as e:  # noqa: BLE001
        results["hh_host_error"] = f"{type(e).__name__}: {e}"

    # -- end-to-end object-layer configs (BASELINE.json 1-4) ----------------
    # Through the REAL engine on local drives: wire framing, bitrot
    # hashing, quorum fan-out, xl.meta publish — what a client actually
    # gets, not the naked codec (VERDICT r2 item 3).
    #
    # Environment caveat: this host reaches its one TPU through a relay
    # tunnel moving ~20-50 MB/s with ~80 ms round trips (measured below)
    # — any data path that ships object bytes to the device is
    # tunnel-bound, not design-bound. So the e2e configs run in a clean
    # JAX_PLATFORMS=cpu subprocess (same engine, XLA-CPU codec, real
    # drives) for the framework's host-path numbers, and one
    # tunnel-attached TPU figure is reported alongside for transparency.
    try:
        results.update(_tunnel_probe())
    except Exception as e:  # noqa: BLE001
        results["tunnel_probe_error"] = f"{type(e).__name__}: {e}"
    try:
        import os
        import subprocess
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PYTHONPATH", None)         # axon plugin leaks transfers
        env.pop("PALLAS_AXON_POOL_IPS", None)
        here = os.path.dirname(os.path.abspath(__file__))
        res = subprocess.run(
            [sys.executable, "-c",
             "import json, sys; sys.path.insert(0, sys.argv[1]); "
             "from bench import (e2e_bench, concurrent_bench, "
             "hedge_bench, digest_bench, workers_bench, "
             "multichip_bench, decom_bench, obs_bench); "
             "r = e2e_bench(); r.update(concurrent_bench()); "
             "r.update(hedge_bench()); r.update(digest_bench()); "
             "r.update(workers_bench()); r.update(multichip_bench()); "
             "r.update(decom_bench()); r.update(obs_bench()); "
             "print(json.dumps(r))", here],
            env=env, capture_output=True, text=True, timeout=900)
        if res.returncode != 0:
            raise RuntimeError(res.stderr[-300:])
        results.update(json.loads(res.stdout.strip().splitlines()[-1]))
        # Same configs on tmpfs: the framework's own ceiling, with the
        # VM's virtio-disk journal (file creates cost 0.3-1 ms and do
        # not parallelize) taken out of the picture. This host has ONE
        # CPU core (host_cores below): the S3 MD5 ETag alone costs
        # ~1.7 ms/MiB serial, capping any 1 MiB PUT at ~0.6 GB/s
        # before the codec or a single byte of IO.
        if os.path.isdir("/dev/shm"):
            env2 = dict(env)
            env2["TMPDIR"] = "/dev/shm"
            res = subprocess.run(
                [sys.executable, "-c",
                 "import json, sys; sys.path.insert(0, sys.argv[1]); "
                 "from bench import e2e_bench; "
                 "print(json.dumps(e2e_bench()))", here],
                env=env2, capture_output=True, text=True, timeout=600)
            if res.returncode == 0:
                shm = json.loads(res.stdout.strip().splitlines()[-1])
                results.update({
                    (k.replace("_gbps", "_tmpfs_gbps")
                     if k.endswith("_gbps") else f"{k}_tmpfs"): v
                    for k, v in shm.items()})
        results["host_cores"] = os.cpu_count()
    except Exception as e:  # noqa: BLE001 — codec numbers must still print
        results["e2e_error"] = f"{type(e).__name__}: {e}"
    try:
        results.update(_select_bench())
    except Exception as e:  # noqa: BLE001 — extras are best-effort
        results["select_bench_error"] = f"{type(e).__name__}: {e}"
    try:
        tpu_e2e = e2e_bench(n_put=8, n_parts=1, part_mib=32)
        results["put_e2e_8p4_mp_tpu_tunnel_gbps"] = \
            tpu_e2e["put_e2e_8p4_mp_gbps"]
    except Exception as e:  # noqa: BLE001
        results["e2e_tpu_error"] = f"{type(e).__name__}: {e}"

    # -- measured CPU baseline (native comparator) --------------------------
    try:
        from native import rs_comparator
        cpu_gbps = rs_comparator.measure_encode_gbps(K, M, SHARD)
        cpu_isa = rs_comparator.isa()
        cpu_src = "measured"
    except Exception as e:  # noqa: BLE001 — bench must still report
        # LOUD fallback: vs_baseline is then against a previously measured
        # constant from this host, not a live measurement.
        cpu_gbps = 2.69
        cpu_isa = "unavailable"
        cpu_src = f"fallback-constant ({type(e).__name__}: {e})"

    gbps = results["encode"]
    extras = {
        "decode_2lost_gbps": round(results["decode_2lost"], 2),
        "heal_2lost_gbps": round(results["heal_2lost"], 2),
        "fused_verify_decode_gbps": round(results["fused_verify_decode"], 2),
        # The READ PATH routes HighwayHash verification to the native
        # host kernel (hh_host_verify_gbps); the device formulation is
        # kept only as a documented negative result
        # (ops/highwayhash_pallas.py) — do not read it as the HH path.
        "hh_device_fused_negative_result_gbps": round(
            results["fused_verify_decode_hh"], 2),
        "cpu_baseline_gbps": round(cpu_gbps, 2),
        "cpu_baseline_isa": cpu_isa,
        "cpu_baseline_source": cpu_src,
        "backend": jax.default_backend(),
    }
    # e2e object-layer configs + tunnel context measured above
    for k, v in results.items():
        if (k.endswith(("_gbps", "_error", "_mbps", "_ms", "_speedup",
                        "_ms_tmpfs", "_pct", "_pct_tmpfs", "_occupancy"))
                or k.startswith(("tunnel_", "digest_", "mc_", "decom_",
                                 "obs_", "hc_"))
                or k == "host_cores"):
            extras.setdefault(k, v)
    if "put_stage_md5_ms_tmpfs" in extras:
        extras["put_attribution_note"] = (
            "1-core host: the serial S3 MD5 ETag "
            f"({extras['put_stage_md5_ms_tmpfs']} ms/MiB) is the PUT "
            "wall; put_e2e_2p2_noetag_tmpfs_gbps shows the framework "
            "with a client-supplied ETag (multi-core hosts overlap the "
            "digest in the etag thread)")
    print(json.dumps({
        "metric": "ec_8p4_encode_throughput",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / cpu_gbps, 2),
        "extras": extras,
    }))
    print(f"# encode={t_encode*1e3:.1f}ms perturb={t_base*1e3:.1f}ms "
          f"decode={t_dec*1e3:.1f}ms heal={t_heal*1e3:.1f}ms "
          f"fused={t_fused*1e3:.1f}ms/{FUSED_ITER}it "
          f"data={data_bytes/2**20:.0f}MiB x{N_ITER}", file=sys.stderr)


def _multichip_main() -> None:
    """`python bench.py multichip_bench`: run the device-sharding suite
    alone and drop MULTICHIP_r06.json next to the other round files."""
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    doc = {"n_devices": 8, "rc": 0, "ok": False, "skipped": False}
    try:
        extras = multichip_bench()
        doc["ok"] = bool(extras.get("mc_heal_equal")) and all(
            extras.get(f"mc_dev{nd}_lanes_active", 0) >= 1
            for nd in (1, 2, 8))
        doc["extras"] = extras
        doc["tail"] = (
            f"multichip_bench OK on {extras.get('mc_visible_devices')} "
            f"devices: lanes active 1/2/8 -> "
            f"{extras.get('mc_dev1_lanes_active')}/"
            f"{extras.get('mc_dev2_lanes_active')}/"
            f"{extras.get('mc_dev8_lanes_active')}, heal "
            f"parallel/serial = "
            f"{extras.get('mc_heal_parallel_vs_serial')}x, "
            f"end-state equal = {extras.get('mc_heal_equal')}")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    with open(os.path.join(here, "MULTICHIP_r06.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"]:
        raise SystemExit(1)


def _hotcache_main() -> None:
    """`python bench.py hotcache_bench` — hot-tier suite alone, JSON to
    stdout and HOTCACHE_r14.json for the record."""
    import os
    r = hotcache_bench()
    doc = json.dumps(r, indent=2, sort_keys=True)
    print(doc)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "HOTCACHE_r14.json"), "w") as f:
        f.write(doc + "\n")


def _ilm_main() -> None:
    """`python bench.py ilm_bench` — data-temperature suite alone,
    JSON to stdout and ILM_r15.json for the record."""
    import os
    doc = {"rc": 0, "ok": False}
    try:
        extras = ilm_bench()
        doc["ok"] = (
            extras.get("ilm_journal_pending_after_transition") == 0
            and extras.get("ilm_journal_pending_after_restore") == 0
            and extras.get("ilm_journal_pending_after_load") == 0
            and extras.get("ilm_transitioned")
            == extras.get("ilm_objects")
            == extras.get("ilm_tier_objects")
            and extras.get("ilm_tier_objects_after_restore")
            == extras.get("ilm_tier_objects", 0)
            - extras.get("ilm_restores", 0)
            and extras.get("ilm_reexpired")
            == extras.get("ilm_temp_restores"))
        doc["extras"] = extras
        doc["tail"] = (
            f"ilm_bench {'OK' if doc['ok'] else 'VIOLATION'}: "
            f"transition {extras.get('ilm_transition_mbps')} MB/s "
            f"over {extras.get('ilm_transitioned')} objects, "
            f"restore p50 {extras.get('ilm_restore_p50_ms')} ms, "
            f"stub GET p50/p99 {extras.get('ilm_stub_p50_ms')}/"
            f"{extras.get('ilm_stub_p99_ms')} ms vs hot "
            f"{extras.get('ilm_hot_p50_ms')}/"
            f"{extras.get('ilm_hot_p99_ms')} ms, journal drained "
            f"to zero at every phase")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "ILM_r15.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"] or not doc["ok"]:
        raise SystemExit(1)


def _zerocopy_main() -> None:
    """`python bench.py zerocopy_bench` — zero-copy suite alone, JSON
    to stdout and ZEROCOPY_r16.json for the record.  Gates (ISSUE 16):
    healthy-GET and mp-PUT GB/s must not regress vs the oracle, and
    the hot-cache GET leg must cut CPU-seconds-per-GB by >= 20%."""
    import os
    doc = {"rc": 0, "ok": False}
    try:
        extras = zerocopy_bench()
        doc["ok"] = (
            extras.get("healthy_get_gbps_ratio", 0.0) >= 1.0
            and extras.get("mp_put_gbps_ratio", 0.0) >= 1.0
            and extras.get("hotcache_get_cpu_per_gb_saving", 0.0)
            >= 0.20)
        doc["extras"] = extras
        doc["tail"] = (
            f"zerocopy_bench {'OK' if doc['ok'] else 'VIOLATION'}: "
            f"hot-cache CPU-s/GB "
            f"{extras.get('hotcache_get_oracle_cpu_s_per_gb')} -> "
            f"{extras.get('hotcache_get_zc_cpu_s_per_gb')} "
            f"({extras.get('hotcache_get_cpu_per_gb_saving', 0.0):.0%}"
            f" saved), healthy-GET x"
            f"{extras.get('healthy_get_gbps_ratio')}, mp-PUT x"
            f"{extras.get('mp_put_gbps_ratio')} vs oracle; "
            f"{extras.get('zerocopy_hot_views')} view hits, "
            f"{extras.get('zerocopy_vectored_writes')} vectored writes")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "ZEROCOPY_r16.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"] or not doc["ok"]:
        raise SystemExit(1)


def _devcache_main() -> None:
    """`python bench.py devcache_bench` — device-residency suite alone,
    JSON to stdout and DEVCACHE_r17.json for the record.  Gates
    (ISSUE 17): devcache-hit GETs perform zero device_put, first-touch
    h2d bytes-per-byte ~1.0, and on the simulated 8-device mesh the
    pipelined PUT path holds GB/s >= the MTPU_H2D_PIPELINE=0 oracle
    with overlap fraction > 0."""
    import os
    doc = {"rc": 0, "ok": False}
    try:
        extras = devcache_bench()
        ratio = extras.get("dc_first_touch_h2d_bytes_per_byte", 0.0)
        doc["ok"] = (
            extras.get("dc_hit_zero_device_put", False)
            and 0.9 <= ratio <= 1.5
            and extras.get("dc_pipelined_vs_serial", 0.0) >= 1.0
            and extras.get("dc_overlap_frac", 0.0) > 0.0
            and extras.get("dc_pipeline_dispatches", 0) > 0)
        doc["extras"] = extras
        doc["tail"] = (
            f"devcache_bench {'OK' if doc['ok'] else 'VIOLATION'}: "
            f"first-touch {ratio} h2d bytes/byte over "
            f"{extras.get('dc_first_touch_h2d_dispatches')} uploads, "
            f"hit = {extras.get('dc_hit_h2d_dispatches')} device_puts; "
            f"pipelined PUT x{extras.get('dc_pipelined_vs_serial')} "
            f"vs serial oracle "
            f"({extras.get('dc_pipelined_gbps')} vs "
            f"{extras.get('dc_serial_gbps')} GB/s) with "
            f"{extras.get('dc_overlap_frac', 0.0):.0%} of host "
            f"staging overlapped across "
            f"{extras.get('dc_pipeline_dispatches')} pipelined "
            f"dispatches on {extras.get('dc_visible_devices')} devices")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "DEVCACHE_r17.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"] or not doc["ok"]:
        raise SystemExit(1)


def _overload_main() -> None:
    """`python bench.py overload_bench` — the overload-plane suite
    alone, JSON to stdout and QOS_r18.json for the record.  Gates
    (ISSUE 18): under 4x offered saturation with QoS on, total goodput
    holds >= 90% of the uncontended capacity leg, best-effort sheds
    (and sheds harder than premium), premium p99 stays under the
    deadline-derived bound, and nothing sheds in the capacity leg.
    The MTPU_QOS=0 collapse leg is recorded as contrast, not gated."""
    import os
    doc = {"rc": 0, "ok": False}
    try:
        # Sized for modest CI hosts: a 4-slot budget keeps the 4x
        # overload leg at 16 client threads.
        extras = overload_bench(slots=4)
        doc["ok"] = (
            extras.get("ol_goodput_ratio", 0.0) >= 0.9
            and extras.get("ol_cap_shed", 1) == 0
            and extras.get("ol_be_shed", 0) > 0
            and extras.get("ol_be_shed_rate", 0.0)
            > extras.get("ol_gold_shed_rate", 1.0)
            and extras.get("ol_gold_p99_ms", 1e9)
            <= extras.get("ol_gold_p99_bound_ms", 0.0)
            and extras.get("ol_over_errors", 1) == 0)
        doc["extras"] = extras
        doc["tail"] = (
            f"overload_bench {'OK' if doc['ok'] else 'VIOLATION'}: "
            f"{extras.get('ol_offered_clients')} clients vs "
            f"{extras.get('ol_slots')} slots -> goodput "
            f"x{extras.get('ol_goodput_ratio')} of capacity "
            f"({extras.get('ol_over_goodput_gbps')} vs "
            f"{extras.get('ol_cap_goodput_gbps')} GB/s), premium p99 "
            f"{extras.get('ol_gold_p99_ms')} ms (bound "
            f"{extras.get('ol_gold_p99_bound_ms')} ms, shed rate "
            f"{extras.get('ol_gold_shed_rate')}), best-effort shed "
            f"{extras.get('ol_be_shed')} "
            f"(rate {extras.get('ol_be_shed_rate')}); QoS-off "
            f"contrast p99 {extras.get('ol_off_p99_ms')} ms with "
            f"{extras.get('ol_off_shed')} sheds")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "QOS_r18.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"] or not doc["ok"]:
        raise SystemExit(1)


def _smallobj_main() -> None:
    """`python bench.py smallobj_bench` — the small-object metadata
    suite alone, JSON to stdout and SMALLOBJ_r19.json for the record.
    Gates (ISSUE 19): 4-64 KiB Zipf PUT ops/s >= 1.3x and amortized
    fsyncs/object <= 0.5x vs the MTPU_METABATCH=0 oracle under >= 8
    concurrent clients, metadata read fan-outs/request < 1 on the
    coalesced HEAD leg, and the idle-server small PUT/GET p50 within
    3% of the oracle (batching must not tax the unloaded path)."""
    import os
    doc = {"rc": 0, "ok": False}
    try:
        extras = smallobj_bench()
        doc["ok"] = (
            "disk_leg_skipped" not in extras
            and extras.get("so_clients", 0) >= 8
            and extras.get("so_put_ops_ratio", 0.0) >= 1.3
            and 0.0 < extras.get("so_fsyncs_ratio", 1.0) <= 0.5
            and 0.0 < extras.get("so_get_fanouts_per_request", 9.9)
            < 1.0
            and extras.get("so_idle_put_p50_ratio", 9.9) <= 1.03
            and extras.get("so_idle_get_p50_ratio", 9.9) <= 1.03)
        doc["extras"] = extras
        doc["tail"] = (
            f"smallobj_bench {'OK' if doc['ok'] else 'VIOLATION'}: "
            f"PUT x{extras.get('so_put_ops_ratio')} "
            f"({extras.get('so_batch_put_ops_per_s')} vs "
            f"{extras.get('so_oracle_put_ops_per_s')} ops/s), "
            f"fsyncs/object x{extras.get('so_fsyncs_ratio')} "
            f"({extras.get('so_batch_fsyncs_per_object')} vs "
            f"{extras.get('so_oracle_fsyncs_per_object')}) at batch "
            f"occupancy {extras.get('so_batch_batch_occupancy')}, "
            f"HEAD fan-outs/request "
            f"{extras.get('so_get_fanouts_per_request')}, idle p50 "
            f"x{extras.get('so_idle_put_p50_ratio')} PUT / "
            f"x{extras.get('so_idle_get_p50_ratio')} GET vs oracle "
            f"on {extras.get('so_fs_type', 'tmpfs')}")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "SMALLOBJ_r19.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"] or not doc["ok"]:
        raise SystemExit(1)


def repl_bench(n_objects: int = 96, object_kib: int = 128,
               resync_objects: int = 400,
               lag_objects: int = 24) -> dict:
    """Replication-under-fire suite (bucket/replication.py): what the
    journaled mirror costs and how fast it recovers.

    Leg 1 — steady mirror: PUT n_objects through the source's S3 front
    with replication wired to a live target (clean wire); report the
    client-visible ack rate (the journal write is on the PUT path) and
    the end-to-end mirror rate (ack through backlog drained), with a
    byte-exact sample check on the target.

    Leg 2 — resync: bulk-load resync_objects BEFORE wiring, then
    admin op=resync and time enumeration + drain to convergence — the
    "point a fresh target at an old bucket" number.

    Leg 3 — lag drain after heal: black-hole the target's wire (the
    same chaos TCP proxy the partition matrix uses), keep acking
    writes, observe the backlog and per-target lag grow, then heal and
    time the drain back to zero — partition produces lag, never loss.

    Sized for a 1-core CI host; the structure (fsync per intent, one
    copy per task, capped backoff against a dark target) is what the
    numbers price."""
    import os
    import shutil
    import tempfile

    from minio_tpu.tools.net_matrix import ReplPair

    out: dict = {"repl_objects": n_objects,
                 "repl_object_kib": object_kib}
    size = object_kib << 10

    def wait_for(pred, timeout, step=0.1):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(step)
        return False

    saved = os.environ.get("MTPU_SCANNER")
    os.environ["MTPU_SCANNER"] = "0"
    root = tempfile.mkdtemp(prefix="mtpu-replbench-")
    try:
        pair = ReplPair(root, seed=5)
        try:
            def queued():
                return int(pair.repl.stats().get("queued", 0))

            # -- leg 1: steady mirror throughput ------------------------
            pair.dcli.make_bucket("rbm-dst")
            pair.scli.make_bucket("rbm")
            pair.wire("rbm", "rbm-dst")
            rng = np.random.default_rng(20)
            body = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
            t0 = time.monotonic()
            for i in range(n_objects):
                pair.scli.put_object("rbm", f"o{i}", body)
            ack_s = time.monotonic() - t0
            if not wait_for(lambda: queued() == 0, 180):
                raise RuntimeError(
                    f"mirror backlog never drained ({queued()} left)")
            dt = time.monotonic() - t0
            for i in (0, n_objects // 2, n_objects - 1):
                if pair.dcli.get_object("rbm-dst", f"o{i}") != body:
                    raise RuntimeError(f"replica o{i} diverged")
            out["repl_ack_mbps"] = round(
                n_objects * size / ack_s / 1e6, 1)
            out["repl_mirror_s"] = round(dt, 3)
            out["repl_mirror_mbps"] = round(
                n_objects * size / dt / 1e6, 1)

            # -- leg 2: resync of a pre-existing bucket -----------------
            small = body[:16 << 10]
            pair.dcli.make_bucket("rsy-dst")
            pair.scli.make_bucket("rsy")
            for i in range(resync_objects):
                pair.scli.put_object("rsy", f"k{i:05d}", small)
            pair.wire("rsy", "rsy-dst")
            t0 = time.monotonic()
            st, _, rbody = pair.scli.request(
                "POST", "/minio/admin/v3/replication",
                body=json.dumps({"op": "resync",
                                 "bucket": "rsy"}).encode())
            if st != 200:
                raise RuntimeError(f"resync start: {st} {rbody!r}")
            done = wait_for(
                lambda: queued() == 0
                and (pair.repl.resync_status("rsy")
                     or {}).get("status") == "done", 300, step=0.25)
            out["repl_resync_objects"] = resync_objects
            out["repl_resync_done"] = done
            out["repl_resync_s"] = round(time.monotonic() - t0, 3)
            out["repl_resync_objs_per_s"] = round(
                resync_objects / max(time.monotonic() - t0, 1e-9), 1)

            # -- leg 3: partition -> lag -> heal -> drain ---------------
            pair.dcli.make_bucket("lag-dst")
            pair.scli.make_bucket("lag")
            pair.wire("lag", "lag-dst")
            pair.proxy.set_mode("blackhole")
            for i in range(lag_objects):
                pair.scli.put_object("lag", f"w{i}", small)
            wait_for(lambda: queued() >= lag_objects, 30)
            wait_for(lambda: max(
                pair.repl.stats().get("lagSeconds", {}).values()
                or [0.0]) > 0.5, 30)
            st_dark = pair.repl.stats()
            out["repl_lag_backlog"] = int(st_dark.get("queued", 0))
            out["repl_lag_peak_s"] = max(
                st_dark.get("lagSeconds", {}).values() or [0.0])
            r0 = int(st_dark.get("retries", 0))
            time.sleep(2.0)
            out["repl_dark_retries_2s"] = \
                int(pair.repl.stats().get("retries", 0)) - r0
            pair.proxy.heal()
            t0 = time.monotonic()
            drained = wait_for(lambda: queued() == 0, 120)
            out["repl_lag_drain_s"] = round(time.monotonic() - t0, 3)
            out["repl_drained_after_heal"] = drained
            if drained:
                for i in range(lag_objects):
                    if pair.dcli.get_object("lag-dst", f"w{i}") != small:
                        raise RuntimeError(
                            f"w{i} diverged after lag drain")
            fin = pair.repl.stats()
            out["repl_completed_total"] = int(fin.get("completed", 0))
            out["repl_retries_total"] = int(fin.get("retries", 0))
            out["repl_failed_total"] = int(fin.get("failed", 0))
            out["repl_dropped_total"] = int(fin.get("dropped", 0))
        finally:
            pair.close()
    finally:
        if saved is None:
            os.environ.pop("MTPU_SCANNER", None)
        else:
            os.environ["MTPU_SCANNER"] = saved
        shutil.rmtree(root, ignore_errors=True)
    return out


def _repl_main() -> None:
    """`python bench.py repl_bench` — the replication suite alone,
    JSON to stdout and REPL_r20.json for the record.  Gates (ISSUE
    20): the mirror drains and a byte-exact sample lands on the
    target, the pre-existing-bucket resync converges, and a
    black-holed target produces observable backlog + lag that drains
    to zero after heal with bounded dark-window retries and zero
    dropped intents (first-attempt FAILED stamps against the dark
    target are by design — those tasks retry and converge)."""
    import os
    doc = {"rc": 0, "ok": False}
    try:
        extras = repl_bench()
        doc["ok"] = (
            extras.get("repl_mirror_mbps", 0.0) > 0
            and extras.get("repl_resync_done", False)
            and extras.get("repl_lag_backlog", 0) > 0
            and extras.get("repl_lag_peak_s", 0.0) > 0
            and extras.get("repl_drained_after_heal", False)
            and extras.get("repl_dark_retries_2s", 10**9) <= 60
            and extras.get("repl_dropped_total", 1) == 0)
        doc["extras"] = extras
        doc["tail"] = (
            f"repl_bench {'OK' if doc['ok'] else 'VIOLATION'}: mirror "
            f"{extras.get('repl_mirror_mbps')} MB/s end-to-end "
            f"(acks {extras.get('repl_ack_mbps')} MB/s) over "
            f"{extras.get('repl_objects')}x"
            f"{extras.get('repl_object_kib')} KiB; resync of "
            f"{extras.get('repl_resync_objects')} keys in "
            f"{extras.get('repl_resync_s')} s "
            f"({extras.get('repl_resync_objs_per_s')} obj/s); "
            f"partition backlog {extras.get('repl_lag_backlog')} "
            f"(peak lag {extras.get('repl_lag_peak_s')} s, "
            f"{extras.get('repl_dark_retries_2s')} retries/2s dark) "
            f"drained in {extras.get('repl_lag_drain_s')} s after "
            f"heal with {extras.get('repl_failed_total')} first-attempt "
            f"FAILED stamps and {extras.get('repl_dropped_total')} "
            f"dropped intents")
    except Exception as e:  # noqa: BLE001 — the round file records it
        doc["rc"] = 1
        doc["tail"] = f"{type(e).__name__}: {e}"
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "REPL_r20.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc))
    if doc["rc"] or not doc["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    if sys.argv[1:2] == ["multichip_bench"]:
        _multichip_main()
    elif sys.argv[1:2] == ["hotcache_bench"]:
        _hotcache_main()
    elif sys.argv[1:2] == ["ilm_bench"]:
        _ilm_main()
    elif sys.argv[1:2] == ["zerocopy_bench"]:
        _zerocopy_main()
    elif sys.argv[1:2] == ["devcache_bench"]:
        _devcache_main()
    elif sys.argv[1:2] == ["overload_bench"]:
        _overload_main()
    elif sys.argv[1:2] == ["smallobj_bench"]:
        _smallobj_main()
    elif sys.argv[1:2] == ["repl_bench"]:
        _repl_main()
    else:
        main()
