"""Headline benchmark: EC:8+4 erasure codec throughput on TPU.

Covers the BASELINE.json config list (cf. the reference harnesses
/root/reference/cmd/erasure-encode_test.go:210, -decode_test.go:344,
-heal_test.go, bitrot-streaming verify):
  - encode           (B, 8, S) -> 4 parity rows        [headline metric]
  - decode_2lost     reconstruct 2 data rows from 8 of 12
  - heal_2lost       rebuild 1 data + 1 parity row (decode->re-encode)
  - fused_verify_decode  mxh256 bitrot digests of the 8 read rows fused
                         with the 2-row reconstruct in ONE dispatch
                         (north-star config #5; the production GET path)
  - fused_verify_decode_hh  same with HighwayHash256 (interop reads of
                         objects written before the mxh256 default)

vs_baseline divides encode throughput by a MEASURED native comparator:
native/rs_cpu.cc, the same vpshufb nibble-table algorithm the reference's
klauspost/reedsolomon assembly uses, compiled -march=native and timed on
this host at the same EC:8+4 geometry (replaces the round-1 hardcoded
constant the verdict flagged).

Timing protocol (axon tunnel): N_ITER codec calls inside ONE jitted
fori_loop; inputs xor-perturbed per iteration to defeat CSE; the full
output is xor-folded into the carry so no backend can dead-code any part;
an identical loop without the codec call is timed and subtracted.
Completion is forced by fetching the 1-byte result (block_until_ready is
unreliable through the tunnel). Median of REPEATS runs.

Prints ONE JSON line; secondary configs ride in "extras".
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K, M = 8, 4
SHARD = 131072          # 1 MiB block / 8 data shards
BLOCKS = 128            # 128 MiB data per dispatch
REPEATS = 5
N_ITER = 20
FUSED_BLOCKS = 128      # hash scan length == SHARD/32 packets regardless
FUSED_ITER = 4


def _timed(fn, x, repeats=REPEATS):
    int(fn(x))  # compile + warm (int() forces completion through tunnel)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        int(fn(x))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def main() -> None:
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops.erasure_jax import (ReedSolomonTPU,
                                           _transform_matrix_bits,
                                           _gf_matmul_blocks)
    from minio_tpu.ops.highwayhash import MAGIC_KEY

    on_tpu = jax.default_backend() == "tpu"
    dev = ReedSolomonTPU(K, M, use_pallas=on_tpu)
    rng = np.random.default_rng(0)

    def fold(*arrays):
        acc = jnp.uint8(0)
        for a in arrays:
            acc = acc ^ jax.lax.reduce(a, jnp.uint8(0), jax.lax.bitwise_xor,
                                       tuple(range(a.ndim)))
        return acc

    def make_loop(body_fn, n_iter):
        @jax.jit
        def loop(x):
            def body(i, acc):
                xi = x ^ i.astype(jnp.uint8)
                return acc ^ body_fn(xi)
            return jax.lax.fori_loop(0, n_iter, body, jnp.uint8(0))
        return loop

    results = {}

    # -- encode (headline) --------------------------------------------------
    x = jax.device_put(rng.integers(0, 256, size=(BLOCKS, K, SHARD),
                                    dtype=np.uint8))
    data_bytes = BLOCKS * K * SHARD
    encode_loop = make_loop(lambda xi: fold(dev.encode_blocks(xi)), N_ITER)
    perturb_loop = make_loop(lambda xi: xi[0, 0, 0], N_ITER)
    t_encode = _timed(encode_loop, x)
    t_base = _timed(perturb_loop, x)
    per_call = max((t_encode - t_base) / N_ITER, 1e-9)
    if t_encode - t_base <= 0:
        per_call = t_encode / N_ITER
    results["encode"] = data_bytes / per_call / 1e9

    # -- decode: 2 data rows lost, read 8 of the surviving rows -------------
    sources = (2, 3, 4, 5, 6, 7, 8, 9)   # rows 0,1 lost; 8 survivors read
    targets = (0, 1)
    decode_loop = make_loop(
        lambda xi: fold(dev.transform_blocks(xi, sources, targets)), N_ITER)
    t_dec = _timed(decode_loop, x)
    per_call = max((t_dec - t_base) / N_ITER, t_dec / N_ITER / 10)
    results["decode_2lost"] = data_bytes / per_call / 1e9

    # -- heal: rebuild one data + one parity row (decode->re-encode pipe) ---
    heal_targets = (0, 9)
    heal_loop = make_loop(
        lambda xi: fold(dev.transform_blocks(xi, sources, heal_targets)),
        N_ITER)
    t_heal = _timed(heal_loop, x)
    per_call = max((t_heal - t_base) / N_ITER, t_heal / N_ITER / 10)
    results["heal_2lost"] = data_bytes / per_call / 1e9

    # -- fused verify+decode (north-star config #5) -------------------------
    # Production path: mxh256 digests (the default write algorithm) fused
    # with the 2-row reconstruct. The HighwayHash variant (interop reads of
    # pre-mxh objects) is timed separately as an extra.
    xf = x[:FUSED_BLOCKS]
    fused_bytes = FUSED_BLOCKS * K * SHARD
    mat = jnp.asarray(_transform_matrix_bits(K, M, sources, targets),
                      dtype=jnp.bfloat16)

    from minio_tpu.ops.erasure_pallas import gf_matmul_blocks
    from minio_tpu.ops.highwayhash_jax import _hh256_impl
    from minio_tpu.ops.mxhash_jax import mxh256_rows

    decode_kernel = gf_matmul_blocks if on_tpu else _gf_matmul_blocks

    def fused_body(xi):
        b, kk, s = xi.shape
        digests = mxh256_rows(xi.reshape(b * kk, s))
        out = decode_kernel(mat, xi, len(targets))
        return fold(digests, out)

    def fused_body_hh(xi):
        b, kk, s = xi.shape
        digests = _hh256_impl(xi.reshape(b * kk, s), MAGIC_KEY)
        out = decode_kernel(mat, xi, len(targets))
        return fold(digests, out)

    perturb_f = make_loop(lambda xi: xi[0, 0, 0], FUSED_ITER)
    t_fbase = _timed(perturb_f, xf, repeats=3)
    fused_loop = make_loop(fused_body, FUSED_ITER)
    t_fused = _timed(fused_loop, xf, repeats=3)
    per_call = max((t_fused - t_fbase) / FUSED_ITER, t_fused / FUSED_ITER / 10)
    results["fused_verify_decode"] = fused_bytes / per_call / 1e9

    fused_hh_loop = make_loop(fused_body_hh, FUSED_ITER)
    t_fused_hh = _timed(fused_hh_loop, xf, repeats=3)
    per_call = max((t_fused_hh - t_fbase) / FUSED_ITER,
                   t_fused_hh / FUSED_ITER / 10)
    results["fused_verify_decode_hh"] = fused_bytes / per_call / 1e9

    # -- measured CPU baseline (native comparator) --------------------------
    try:
        from native import rs_comparator
        cpu_gbps = rs_comparator.measure_encode_gbps(K, M, SHARD)
        cpu_isa = rs_comparator.isa()
        cpu_src = "measured"
    except Exception as e:  # noqa: BLE001 — bench must still report
        # LOUD fallback: vs_baseline is then against a previously measured
        # constant from this host, not a live measurement.
        cpu_gbps = 2.69
        cpu_isa = "unavailable"
        cpu_src = f"fallback-constant ({type(e).__name__}: {e})"

    gbps = results["encode"]
    print(json.dumps({
        "metric": "ec_8p4_encode_throughput",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / cpu_gbps, 2),
        "extras": {
            "decode_2lost_gbps": round(results["decode_2lost"], 2),
            "heal_2lost_gbps": round(results["heal_2lost"], 2),
            "fused_verify_decode_gbps": round(results["fused_verify_decode"], 2),
            "fused_verify_decode_hh_gbps": round(
                results["fused_verify_decode_hh"], 2),
            "cpu_baseline_gbps": round(cpu_gbps, 2),
            "cpu_baseline_isa": cpu_isa,
            "cpu_baseline_source": cpu_src,
            "backend": jax.default_backend(),
        },
    }))
    print(f"# encode={t_encode*1e3:.1f}ms perturb={t_base*1e3:.1f}ms "
          f"decode={t_dec*1e3:.1f}ms heal={t_heal*1e3:.1f}ms "
          f"fused={t_fused*1e3:.1f}ms/{FUSED_ITER}it "
          f"data={data_bytes/2**20:.0f}MiB x{N_ITER}", file=sys.stderr)


if __name__ == "__main__":
    main()
