"""Verified shared-memory hot-object tier + single-flight GETs.

The reference ships this tier as an ObjectLayer-wrapper disk cache
(cmd/disk-cache.go, cmd/disk-cache-backend.go); ours is RAM-resident
and POOL-SHARED: the cache lives in one anonymous shared mapping
created before fork (ops/shm_arena.py discipline), so under
MTPU_WORKERS=N worker A's fill serves worker B's hit — one warm copy
of the hot set, not N cold ones.

Correctness contract (the part that makes a cache safe to ship):

* Fills come ONLY from fully-verified healthy reads — every segment
  of the object took the verify-only fast path (all k data shards
  digest-checked).  Degraded, hedged-spare, breaker-rerouted, or
  fallback-decoded reads return correct bytes but BYPASS the fill, so
  chaos-injected corruption can never seed the cache with bytes that
  skipped the full-k verify.
* Every entry is stamped with the per-bucket GENERATION read before
  the underlying engine read began.  Any mutation path that calls
  ErasureSet._mark_dirty (PUT, DELETE, multipart complete, heal,
  decommission reap, metadata update) bumps the shared generation
  slot; a stale stamp fails the lookup and the entry is reaped.
  Because the generation table lives in the shared segment, a PUT
  through worker A invalidates worker B's hits in the same store.
* Readers copy entry bytes out under an arena per-entry refcount
  (ShmArena.retain/release), so an evicting writer defers the actual
  slot reuse until the last in-flight reader finishes — no torn
  bodies.
* Only erasure sets whose drives are ALL local attach a tier
  (attach_sets): a remote peer's write cannot bump our generation
  table, so cluster-mode sets stay uncached rather than stale.

Eviction is CLOCK over a fixed entry table under one fork-shared
lock; admission is gated by size (MTPU_HOTCACHE_MAX_OBJ) and a
two-hit ghost filter (a key must MISS twice before it is admitted, so
one-pass scans do not flush the hot set).  MTPU_HOTCACHE=0 disables
the tier entirely — the byte-identical oracle; MTPU_HOTCACHE_MB
bounds the data segment.

SingleFlight is the PR 4 coalescer discipline applied to whole
objects: concurrent GETs for one (bucket, object, version) elect a
leader that performs the single engine read; followers block on the
leader's handle and slice its result (ranged GETs included), so a
thundering herd on a cold hot key costs one read, not N.

This module stays import-light on purpose (stdlib + numpy +
ops.shm_arena): the pre-fork supervisor (server/workers.py) builds
the segment before any engine/jax import happens.
"""

from __future__ import annotations

import collections
import hashlib
import os
import pickle
import threading
import weakref

import numpy as np

from ..ops.shm_arena import ArenaFull, ShmArena

#: header int64 slots: 0 hits, 1 misses, 2 fills, 3 evictions,
#: 4 bypassed, 5 stale_gen, 6 invalidations, 7 clock_hand,
#: 8 collisions, 9 ghost_defers, 10 meta_hits
_HDR = 16
#: hashed per-bucket generation slots (over-invalidation on a slot
#: collision is safe: it only forces a re-read)
_GEN_SLOTS = 512
#: direct-mapped ghost table of key hashes (two-hit admission filter)
_GHOST_SLOTS = 4096
#: entry fields: 0 used, 1 keyhash, 2 gen, 3 off, 4 total,
#: 5 clockbit, 6 hits, 7 body_len
_EFIELDS = 8

#: blob layout inside the arena:
#: [u32 klen][u32 filen][key utf8][fi pickle][body]
_BLOB_HDR = 8


def hot_enabled() -> bool:
    return os.environ.get("MTPU_HOTCACHE", "1") != "0"


def hot_bytes() -> int:
    try:
        mb = int(os.environ.get("MTPU_HOTCACHE_MB", "64"))
    except ValueError:
        mb = 64
    return max(8, mb) << 20


def hot_max_obj() -> int:
    try:
        return max(1, int(os.environ.get("MTPU_HOTCACHE_MAX_OBJ",
                                         str(4 << 20))))
    except ValueError:
        return 4 << 20


def _key_bytes(bucket: str, obj: str, version_id: str) -> bytes:
    return f"{bucket}\x00{obj}\x00{version_id}".encode()


def _key_hash(key: bytes) -> int:
    d = hashlib.blake2b(key, digest_size=8).digest()
    return int.from_bytes(d, "little", signed=True)


def _bucket_slot(bucket: str) -> int:
    d = hashlib.blake2b(bucket.encode(), digest_size=8).digest()
    return int.from_bytes(d, "little") % _GEN_SLOTS


class _Flight:
    """One in-flight leader read; followers wait on the event."""

    __slots__ = ("ev", "result")

    def __init__(self):
        self.ev = threading.Event()
        self.result = None          # (fi, body) | None (leader failed)

    def resolve(self, result) -> None:
        self.result = result
        self.ev.set()

    def wait(self, timeout: float = 30.0):
        if not self.ev.wait(timeout):
            return None             # wedged leader: caller reads direct
        return self.result


class SingleFlight:
    """Per-process GET deduplication keyed by (bucket, obj, version).

    begin() returns (flight, leader); exactly one caller per key gets
    leader=True and MUST resolve + end() the flight (followers fall
    back to a direct read when the leader resolves None or fails)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._flights: dict[tuple, _Flight] = {}

    def begin(self, key: tuple) -> tuple[_Flight, bool]:
        with self._mu:
            f = self._flights.get(key)
            if f is not None:
                return f, False
            f = _Flight()
            self._flights[key] = f
            return f, True

    def end(self, key: tuple) -> None:
        with self._mu:
            f = self._flights.pop(key, None)
            if f is not None and not f.ev.is_set():
                f.resolve(None)     # never leave followers hanging


class HotObjectCache:
    """The shared hot tier: entry table + generation table + ghost
    filter in one anonymous shared mapping, bodies in a ShmArena.

    Create BEFORE fork (WorkerPlane does); every worker operates on
    its inherited copy — all state that matters lives in the two
    mappings and the fork-shared lock.
    """

    def __init__(self, total_bytes: int | None = None,
                 max_obj: int | None = None,
                 n_entries: int | None = None):
        import mmap
        import multiprocessing
        total_bytes = int(total_bytes or hot_bytes())
        self.max_obj = int(max_obj or hot_max_obj())
        # 64 KiB slots: small hot objects waste little; a 1 MiB object
        # is a 17-slot run (first-fit over a few thousand slots).
        self.arena = ShmArena(total_bytes, slot_bytes=64 << 10)
        if n_entries is None:
            n_entries = min(4096, max(64, self.arena.nslots))
        self.n_entries = int(n_entries)
        words = _HDR + _GEN_SLOTS + _GHOST_SLOTS \
            + self.n_entries * _EFIELDS
        self._mm = mmap.mmap(-1, words * 8)
        a = np.frombuffer(self._mm, dtype=np.int64)
        self._hdr = a[:_HDR]
        self._gens = a[_HDR:_HDR + _GEN_SLOTS]
        self._ghost = a[_HDR + _GEN_SLOTS:
                        _HDR + _GEN_SLOTS + _GHOST_SLOTS]
        self._ent = a[_HDR + _GEN_SLOTS + _GHOST_SLOTS:].reshape(
            self.n_entries, _EFIELDS)
        ctx = multiprocessing.get_context("fork")
        self._mu = ctx.RLock()
        self.flights = SingleFlight()
        #: arena offsets whose zero-copy view died (weakref.finalize)
        #: — released on the next cache operation, NOT in the GC
        #: callback: release() takes the arena's non-reentrant
        #: fork-shared lock, and cyclic GC can run while this thread
        #: already holds it.  deque append/popleft are atomic.
        self._dead_views: collections.deque = collections.deque()
        #: optional per-process observer — pool workers point this at
        #: their SharedState slab slot (hit/miss per worker).
        self.on_lookup = None

    #: the tier object itself is only built when enabled, but tests
    #: flip MTPU_HOTCACHE at runtime — honor the kill switch per call.
    @property
    def enabled(self) -> bool:
        return hot_enabled()

    # -- generations ---------------------------------------------------------

    def generation(self, bucket: str) -> int:
        with self._mu:
            return int(self._gens[_bucket_slot(bucket)])

    def note_mutation(self, bucket: str) -> None:
        """One atomic generation bump invalidates every cached entry
        of the bucket — wired into ErasureSet._mark_dirty, so each
        PUT/DELETE/heal/decom write-path already reaches it."""
        with self._mu:
            self._gens[_bucket_slot(bucket)] += 1
            self._hdr[6] += 1

    # -- lookup --------------------------------------------------------------

    def _find_locked(self, h: int) -> list[int]:
        m = (self._ent[:, 0] == 1) & (self._ent[:, 1] == h)
        return np.nonzero(m)[0].tolist()

    def _remove_locked(self, idx: int) -> None:
        off, total = int(self._ent[idx, 3]), int(self._ent[idx, 4])
        self._ent[idx, 0] = 0
        self.arena.free(off, total)     # deferred while readers hold it

    def _pin_locked(self, bucket: str, h: int) -> tuple[int, int] | None:
        """Find a fresh entry for key hash h, retain its arena run, and
        return (off, total) — or None (miss).  Stale entries are reaped
        in passing."""
        for idx in self._find_locked(h):
            if int(self._ent[idx, 2]) != \
                    int(self._gens[_bucket_slot(bucket)]):
                self._hdr[5] += 1       # stale generation
                self._remove_locked(idx)
                continue
            off, total = int(self._ent[idx, 3]), int(self._ent[idx, 4])
            self.arena.retain(off)
            self._ent[idx, 5] = 1       # CLOCK reference bit
            self._ent[idx, 6] += 1
            return off, total
        return None

    def _parse(self, off: int, total: int, key: bytes,
               want_body: bool):
        """Copy + parse a pinned blob; returns (fi, body|None) or None
        on a key-hash collision."""
        try:
            head = bytes(self.arena.view(off, _BLOB_HDR))
            klen = int.from_bytes(head[:4], "little")
            filen = int.from_bytes(head[4:8], "little")
            meta_end = _BLOB_HDR + klen + filen
            raw = bytes(self.arena.view(
                off, total if want_body else meta_end))
            if raw[_BLOB_HDR:_BLOB_HDR + klen] != key:
                return None             # 64-bit hash collision
            fi = pickle.loads(raw[_BLOB_HDR + klen:meta_end])
            return fi, (raw[meta_end:] if want_body else None)
        finally:
            self.arena.release(off)

    def lookup(self, bucket: str, obj: str, version_id: str):
        """Full hit: (fi, body bytes) or None.  The returned FileInfo
        is a fresh unpickle — callers may mutate it freely."""
        self.drain_released_views()
        key = _key_bytes(bucket, obj, version_id)
        h = _key_hash(key)
        with self._mu:
            pinned = self._pin_locked(bucket, h)
            if pinned is None:
                self._hdr[1] += 1
            else:
                self._hdr[0] += 1
        if pinned is not None:
            got = self._parse(*pinned, key, want_body=True)
            if got is not None:
                if self.on_lookup is not None:
                    self.on_lookup(True)
                return got
            with self._mu:              # collision: a miss after all
                self._hdr[0] -= 1
                self._hdr[1] += 1
                self._hdr[8] += 1
        if self.on_lookup is not None:
            self.on_lookup(False)
        return None

    def drain_released_views(self) -> None:
        """Release the arena pins of dead lookup_view results (queued
        by their finalizers); called at the top of every cache
        operation and exposed for tests that assert pin counts."""
        dq = self._dead_views
        while dq:
            try:
                off = dq.popleft()
            except IndexError:
                break
            self.arena.release(off)

    def lookup_view(self, bucket: str, obj: str, version_id: str):
        """Zero-copy full hit: (fi, body) with the body a uint8 ndarray
        view STRAIGHT OVER the arena run — no bytes() copy, no slice
        copy (the MTPU_ZEROCOPY serve path; lookup() is the copying
        oracle).

        The run stays retained until the view's base array dies
        (weakref.finalize queues the release), so the caller can hand
        the view — or any slice of it, slices keep the base alive — to
        sendmsg and simply drop it.  Eviction while pinned only DEFERS
        the arena free (ShmArena pending-free), so the bytes under the
        view can never be reused mid-send: torn bodies stay impossible.
        """
        self.drain_released_views()
        key = _key_bytes(bucket, obj, version_id)
        h = _key_hash(key)
        with self._mu:
            pinned = self._pin_locked(bucket, h)
            if pinned is None:
                self._hdr[1] += 1
            else:
                self._hdr[0] += 1
        if pinned is not None:
            off, total = pinned
            base = self.arena.view(off, total)
            try:
                klen = int.from_bytes(base[:4].tobytes(), "little")
                filen = int.from_bytes(base[4:8].tobytes(), "little")
                meta_end = _BLOB_HDR + klen + filen
                if base[_BLOB_HDR:_BLOB_HDR + klen].tobytes() != key:
                    raise KeyError      # 64-bit hash collision
                fi = pickle.loads(
                    base[_BLOB_HDR + klen:meta_end].tobytes())
            except Exception:  # noqa: BLE001 — collision/corrupt blob
                self.arena.release(off)
                with self._mu:          # a miss after all
                    self._hdr[0] -= 1
                    self._hdr[1] += 1
                    self._hdr[8] += 1
                if self.on_lookup is not None:
                    self.on_lookup(False)
                return None
            weakref.finalize(
                base, self._dead_views.append, off)
            if self.on_lookup is not None:
                self.on_lookup(True)
            return fi, base[meta_end:]
        if self.on_lookup is not None:
            self.on_lookup(False)
        return None

    def lookup_meta(self, bucket: str, obj: str, version_id: str):
        """Metadata-only hit (HEAD / conditional-GET precheck): the
        FileInfo without copying the body.  Counted separately so HEAD
        traffic does not skew the body hit ratio."""
        key = _key_bytes(bucket, obj, version_id)
        h = _key_hash(key)
        with self._mu:
            pinned = self._pin_locked(bucket, h)
            if pinned is None:
                return None
            self._hdr[10] += 1
        got = self._parse(*pinned, key, want_body=False)
        return None if got is None else got[0]

    # -- fill / eviction -----------------------------------------------------

    def note_bypass(self) -> None:
        with self._mu:
            self._hdr[4] += 1

    def _evict_one_locked(self) -> bool:
        """One CLOCK sweep step chain: clear reference bits until an
        unreferenced entry falls out; False when the table is empty."""
        n = self.n_entries
        hand = int(self._hdr[7])
        for _ in range(2 * n):
            idx = hand % n
            hand += 1
            if not self._ent[idx, 0]:
                continue
            if self._ent[idx, 5]:
                self._ent[idx, 5] = 0
                continue
            self._remove_locked(idx)
            self._hdr[3] += 1
            self._hdr[7] = hand
            return True
        self._hdr[7] = hand
        return False

    def fill(self, bucket: str, obj: str, version_id: str, fi,
             body: bytes, gen: int) -> bool:
        """Admit one verified read.  `gen` is the bucket generation
        captured BEFORE the engine read started — if a write raced the
        read, the stamp mismatches and the fill is dropped (a cached
        entry may never outlive the bytes it was read from)."""
        self.drain_released_views()
        blen = len(body)
        if blen == 0 or blen > self.max_obj:
            self.note_bypass()
            return False
        key = _key_bytes(bucket, obj, version_id)
        h = _key_hash(key)
        try:
            fi_raw = pickle.dumps(fi, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:  # noqa: BLE001 — unpicklable fi: skip fill
            self.note_bypass()
            return False
        total = _BLOB_HDR + len(key) + len(fi_raw) + blen
        with self._mu:
            if int(self._gens[_bucket_slot(bucket)]) != int(gen):
                self._hdr[5] += 1
                return False
            # Two-hit ghost filter: first miss plants the key hash,
            # second admits (scans touch each key once — never admitted).
            gi = h % _GHOST_SLOTS
            if int(self._ghost[gi]) != h:
                self._ghost[gi] = h
                self._hdr[9] += 1
                return False
            if any(int(self._ent[i, 2])
                   == int(self._gens[_bucket_slot(bucket)])
                   for i in self._find_locked(h)):
                return False            # another worker beat us to it
            # Entry slot: first free, else CLOCK-evict one.
            free = np.nonzero(self._ent[:, 0] == 0)[0]
            if free.size == 0:
                if not self._evict_one_locked():
                    self.note_bypass()
                    return False
                free = np.nonzero(self._ent[:, 0] == 0)[0]
            idx = int(free[0])
            # Arena space: evict until the run fits (bounded by the
            # table size; pinned runs free lazily so give up rather
            # than spin).
            off = None
            for _ in range(self.n_entries + 1):
                try:
                    off = self.arena.alloc(total, timeout=0)
                    break
                except ArenaFull:
                    if not self._evict_one_locked():
                        break
            if off is None:
                self._hdr[4] += 1
                return False
            view = self.arena.view(off, total)
            view[:4] = np.frombuffer(
                len(key).to_bytes(4, "little"), dtype=np.uint8)
            view[4:8] = np.frombuffer(
                len(fi_raw).to_bytes(4, "little"), dtype=np.uint8)
            view[_BLOB_HDR:_BLOB_HDR + len(key)] = np.frombuffer(
                key, dtype=np.uint8)
            view[_BLOB_HDR + len(key):_BLOB_HDR + len(key)
                 + len(fi_raw)] = np.frombuffer(fi_raw, dtype=np.uint8)
            view[_BLOB_HDR + len(key) + len(fi_raw):] = np.frombuffer(
                body, dtype=np.uint8)
            self._ent[idx] = (1, h, gen, off, total, 1, 0, blen)
            self._hdr[2] += 1
            return True

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            h = self._hdr
            entries = int(np.count_nonzero(self._ent[:, 0]))
            cached_bytes = int(self._ent[self._ent[:, 0] == 1, 7].sum())
            hits, misses = int(h[0]), int(h[1])
        a = self.arena.stats()
        total = hits + misses
        return {
            "hits": hits, "misses": misses,
            "meta_hits": int(h[10]),
            "hit_ratio": (hits / total) if total else 0.0,
            "fills": int(h[2]), "evictions": int(h[3]),
            "bypassed": int(h[4]), "stale_gen": int(h[5]),
            "invalidations": int(h[6]), "collisions": int(h[8]),
            "ghost_defers": int(h[9]),
            "entries": entries, "cached_bytes": cached_bytes,
            "segment_bytes": a["arena_bytes"],
            "in_use_bytes": a["in_use_bytes"],
            "max_obj_bytes": self.max_obj,
        }


# -- attachment ---------------------------------------------------------------

def _all_local(es) -> bool:
    """A tier can only trust its generation table when every mutation
    in the deployment runs through THIS process tree's _mark_dirty —
    i.e. every drive is local (HealthWrappedDrive is isinstance-
    transparent).  Offline slots (None) are fine."""
    from ..storage.drive import LocalDrive
    return all(d is None or isinstance(d, LocalDrive)
               for d in es.drives)


def attach_sets(sets, tier: HotObjectCache) -> int:
    """Attach `tier` to every all-local ErasureSet of one ErasureSets
    stack; returns how many sets attached."""
    n = 0
    for es in getattr(sets, "sets", [sets]):
        if _all_local(es):
            es.hot_tier = tier
            n += 1
    return n


def attach_pools(pools, tier: HotObjectCache | None = None):
    """Build (unless given the pre-fork one) and attach the hot tier
    across every pool; remembers it as pools.hot_tier for metrics/
    healthinfo and for add_pool propagation.  Returns the tier or None
    when disabled / nothing attached."""
    if not hot_enabled():
        return None
    if tier is None:
        tier = HotObjectCache()
    n = 0
    for p in pools.pools:
        n += attach_sets(p, tier)
    if n == 0:
        return None
    pools.hot_tier = tier
    return tier


def maybe_tier() -> HotObjectCache | None:
    """Pre-fork constructor used by WorkerPlane: the segment must
    exist before the first fork so every worker inherits ONE cache."""
    return HotObjectCache() if hot_enabled() else None
