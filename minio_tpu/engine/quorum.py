"""Quorum primitives: error reduction, metadata election, shard placement.

These are the subtle-bug reservoir of the reference (SURVEY.md §7 hard-part
#4): reduceErrs / findFileInfoInQuorum / hashOrder, cf.
/root/reference/cmd/erasure-metadata-utils.go and cmd/erasure-metadata.go.
"""

from __future__ import annotations

import binascii

from ..storage.errors import (ErrDiskNotFound, ErrErasureReadQuorum,
                              ErrErasureWriteQuorum, ErrFileNotFound,
                              ErrFileVersionNotFound, StorageError)
from ..storage.xlmeta import FileInfo


def hash_order(key: str, cardinality: int) -> list[int]:
    """Deterministic shard rotation for an object key: returns a permutation
    of 1..cardinality (cf. hashOrder, /root/reference/cmd/erasure-metadata.go).

    distribution[i] is the 1-based shard index stored on drive position i.
    """
    if cardinality <= 0:
        return []
    crc = binascii.crc32(key.encode("utf-8")) & 0xFFFFFFFF
    start = crc % cardinality
    return [1 + (start + i) % cardinality for i in range(cardinality)]


def reduce_errs(errs: list[Exception | None],
                ignored: tuple[type, ...] = ()) -> tuple[Exception | None, int]:
    """Return (most common error, count), treating None as success.

    Errors of `ignored` types are skipped entirely (cf. reduceErrs,
    /root/reference/cmd/erasure-metadata-utils.go:116).
    """
    counts: dict[str, int] = {}
    samples: dict[str, Exception | None] = {}
    for e in errs:
        if e is not None and isinstance(e, ignored):
            continue
        key = "" if e is None else f"{type(e).__name__}:{e}"
        counts[key] = counts.get(key, 0) + 1
        samples[key] = e
    if not counts:
        return None, 0
    key = max(counts, key=lambda k: (counts[k], k == ""))
    return samples[key], counts[key]


def reduce_quorum_errs(errs: list[Exception | None], quorum: int,
                       quorum_err: StorageError,
                       ignored: tuple[type, ...] = ()) -> Exception | None:
    """The max-count error if it reaches quorum, else `quorum_err`.

    None (success) reaching quorum returns None.
    """
    err, count = reduce_errs(errs, ignored)
    if count >= quorum:
        return err
    return quorum_err


def reduce_write_quorum_errs(errs, quorum, ignored=()):
    return reduce_quorum_errs(errs, quorum, ErrErasureWriteQuorum(), ignored)


def reduce_read_quorum_errs(errs, quorum, ignored=()):
    return reduce_quorum_errs(errs, quorum, ErrErasureReadQuorum(), ignored)


def _fi_key(fi: FileInfo) -> tuple:
    """Version identity for quorum grouping: same logical write."""
    ec = fi.erasure
    return (fi.version_id, fi.mod_time_ns, fi.data_dir, fi.deleted,
            fi.size, None if ec is None else (ec.data_blocks,
                                              ec.parity_blocks))


def find_file_info_in_quorum(metas: list[FileInfo | None],
                             quorum: int) -> FileInfo:
    """Elect the version that at least `quorum` drives agree on
    (cf. findFileInfoInQuorum, /root/reference/cmd/erasure-metadata.go).

    Among agreeing groups prefers the newest mod time.
    """
    groups: dict[tuple, list[FileInfo]] = {}
    for fi in metas:
        if fi is None:
            continue
        groups.setdefault(_fi_key(fi), []).append(fi)
    best = None
    for key, group in groups.items():
        if len(group) >= quorum:
            if best is None or group[0].mod_time_ns > best[0].mod_time_ns:
                best = group
    if best is None:
        raise ErrErasureReadQuorum(
            f"no version reaches quorum {quorum} "
            f"({len([m for m in metas if m])} readable)")
    return best[0]


def object_quorum_from_meta(metas: list[FileInfo | None], n_drives: int,
                            default_parity: int) -> tuple[int, int]:
    """(read_quorum, write_quorum) from the elected metadata's parity
    (cf. objectQuorumFromMeta, /root/reference/cmd/erasure-metadata.go:339)."""
    # Most-common parity across metas (cf. commonParity in the reference):
    # with per-object parity upgrade, mixed-parity metas are an expected
    # state, and trusting the first one could legitimize a torn write.
    counts: dict[int, int] = {}
    for fi in metas:
        if fi is not None and fi.erasure is not None:
            p = fi.erasure.parity_blocks
            counts[p] = counts.get(p, 0) + 1
    parity = (max(counts, key=lambda p: counts[p]) if counts
              else default_parity)
    data = n_drives - parity
    write_quorum = data
    if data == parity:
        write_quorum += 1
    return data, write_quorum


def shuffle_by_distribution(items: list, distribution: list[int]) -> list:
    """Reorder drive-position-ordered `items` into shard-index order:
    out[shard] = items[drive holding that shard]
    (cf. shuffleDisks, /root/reference/cmd/erasure-metadata-utils.go)."""
    out = [None] * len(items)
    for drive_pos, shard_1b in enumerate(distribution):
        out[shard_1b - 1] = items[drive_pos]
    return out


def unshuffle_to_drives(shard_items: list, distribution: list[int]) -> list:
    """Inverse: out[drive_pos] = shard_items[distribution[drive_pos]-1]."""
    return [shard_items[s - 1] for s in distribution]
