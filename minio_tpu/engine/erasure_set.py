"""One erasure set: quorum CRUD over a stripe of N drives.

The erasureObjects equivalent (/root/reference/cmd/erasure-object.go:748) with
the streaming encode/decode drivers (/root/reference/cmd/erasure-encode.go:36,
cmd/erasure-decode.go:101) redesigned TPU-first:

- data is staged in batches of 1 MiB blocks and erasure-coded as ONE batched
  device dispatch per batch — (B, K, S) uint8 through the bit-plane MXU
  matmul — instead of the reference's per-block synchronous SIMD calls
  (SURVEY.md §5: blocks are the natural batch dimension);
- shard fan-out to drives runs on a thread pool with write-quorum reduce
  (the parallelWriter analogue);
- reads fetch exactly K shards, verify bitrot frames, trigger spare reads
  on failure (the parallelReader analogue), and reconstruct missing rows
  with the same device matmul;
- small objects (<= 128 KiB) inline their framed shards into xl.meta and
  bypass the device (SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

import hashlib
import os
import queue as _queuemod
import time
import uuid
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..cluster.dynamic_timeout import DynamicTimeout
from ..observe import span as ospan
from ..observe.metrics import DATA_PATH
from ..ops import coalesce, fused, metalanes
from ..ops import devcache as devcache_mod
from ..ops import devices as devices_mod
from ..ops import zerocopy as zc
from ..ops.erasure_cpu import ReedSolomonCPU
from ..ops.erasure_jax import ReedSolomonTPU
from ..parallel import pipeline as pl
from ..storage import bitrot_io
from ..storage.drive import (SMALL_FILE_THRESHOLD, SYS_VOL, TMP_DIR,
                             LocalDrive)
from ..storage.errors import (ErrBucketExists, ErrBucketNotFound,
                              ErrDiskNotFound, ErrErasureReadQuorum,
                              ErrErasureWriteQuorum, ErrFileCorrupt,
                              ErrFileNotFound, ErrFileVersionNotFound,
                              ErrObjectNotFound, ErrVersionNotFound,
                              ErrVolumeExists, ErrVolumeNotFound,
                              StorageError)
from ..storage.health_wrap import drive_available
from ..storage.xlmeta import (ErasureInfo, FileInfo, ObjectPartInfo, XLMeta,
                              new_uuid, normalize_version_id)
from ..utils import streams
from ..utils.crashpoints import crash_point
from . import quorum as Q

BLOCK_SIZE = 1 << 20          # blockSizeV2, cmd/object-api-common.go:40
BATCH_BLOCKS = 32             # 1 MiB blocks per device dispatch (32 MiB data)

# Lazily resolved once: whether this process has a real TPU (see
# ErasureSet._use_device).  Tests can reset to force a path.
_USE_DEVICE: bool | None = None

# Whether the native host codec built + loaded (None = untried).
_NATIVE_OK: bool | None = None

# Fused host erasure-IO kernel (native/ecio.cc): encode+hash+frame /
# verify+gather+reconstruct in one C pass (None = untried, False = n/a).
_ECIO = None


def _ecio_mod():
    global _ECIO
    if _ECIO is None:
        try:
            from native import ecio_native
            ecio_native.load()
            _ECIO = ecio_native
        except Exception:  # noqa: BLE001 — no g++/ISA: numpy paths serve
            _ECIO = False
    return _ECIO or None

# Process-wide mesh for multi-device codec placement (built lazily).
_MESH = None

# Per-thread pair of alternating fused-encode output buffers for the
# double-buffered pipeline (same page-fault economics as ecio_native's
# single _arena_buf: a fresh 2x ~50 MB allocation per multipart part
# would cost more in faults than the overlap saves).  One pipelined
# encode per thread at a time, and StagePipeline joins its in-flight
# write before returning, so reuse across calls is safe.
_DB_ARENAS = __import__("threading").local()


def _db_arenas(nbytes: int) -> list:
    pair = getattr(_DB_ARENAS, "pair", None)
    if pair is None or pair[0].size < nbytes:
        pair = [np.empty(nbytes, dtype=np.uint8) for _ in range(2)]
        _DB_ARENAS.pair = pair
    return pair


def _mesh_mode() -> bool:
    """Whether the engine places codec work on a multi-device mesh.

    Auto (MTPU_MESH unset): on when >1 jax device is attached — the
    WithAutoGoroutines role (cmd/erasure-coding.go:63), scaling the
    shard math across chips without configuration.  MTPU_MESH=1/0
    forces (tests use 1 to exercise the SPMD path on the virtual CPU
    mesh, where auto would stay off for speed)."""
    import os
    v = os.environ.get("MTPU_MESH", "")
    if v == "1":
        return True
    if v == "0":
        return False
    import jax
    return jax.default_backend() == "tpu" and len(jax.devices()) > 1


def _get_fastpath() -> bool:
    """Healthy-read verify-only fast path gate (MTPU_GET_FASTPATH).

    Default on: when all k data shards are present, `_read_part`
    dispatches a batched verify-only bitrot check and assembles the
    object from systematic shard slices with zero GF(2^8) work.
    MTPU_GET_FASTPATH=0 forces the fused verify+decode path — the
    oracle the equivalence tests diff against (read per call so tests
    can flip it without re-importing)."""
    return os.environ.get("MTPU_GET_FASTPATH", "1") != "0"


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


#: Drive-pool thread tag (see ErasureSet.__init__): lets fan-out helpers
#: detect they are ALREADY on this set's drive pool and run inline
#: instead of nested-submitting — a task queued behind its own parent is
#: the one thread-pool deadlock shape this engine can produce.
def _hedge_enabled() -> bool:
    """Hedged shard-read gate (MTPU_HEDGE, default on).

    The Tail-at-Scale move: when a stripe read's stragglers outlive an
    adaptive delay, speculatively read parity spares and take whichever
    k distinct shards answer first — erasure coding makes the hedge
    nearly free since any k of k+m reconstruct.  MTPU_HEDGE=0 is the
    wait-for-your-shard oracle (read per call so tests flip it live)."""
    return os.environ.get("MTPU_HEDGE", "1") != "0"


def _hedge_fixed_ms() -> float | None:
    """MTPU_HEDGE_MS pins the hedge delay (tests/benchmarks); unset
    means the per-set DynamicTimeout adapts it from observed reads."""
    v = os.environ.get("MTPU_HEDGE_MS", "")
    try:
        return float(v) if v else None
    except ValueError:
        return None


_POOL_LOCAL = __import__("threading").local()


def _tag_pool_thread(tag: str) -> None:
    _POOL_LOCAL.tag = tag


def _now_ns() -> int:
    return time.time_ns()


class ErasureSet:
    """Object CRUD on one stripe of `n` drives (entries may be None when a
    drive is offline)."""

    def __init__(self, drives: list[LocalDrive | None],
                 default_parity: int | None = None,
                 set_index: int = 0, nslock=None):
        self.drives = list(drives)
        self.n = len(drives)
        if self.n < 2:
            raise ValueError("an erasure set needs >= 2 drives")
        self.default_parity = (self.n // 2 if default_parity is None
                               else default_parity)
        self.set_index = set_index
        # Pool-nesting invariant: work running ON self.pool must never
        # block on another self.pool future.  Two mechanisms enforce it:
        # (1) layered executors — prefetch tasks (get_object_iter
        # segments) WAIT on self.pool leaf tasks, so they get their own
        # _iter_pool; coalesced-dispatch futures resolve on the
        # coalescer's dedicated thread, never this pool; and (2) the
        # initializer tags every pool thread so fan-out helpers
        # (_map_drives, _map_drives_positions, _hash_shard_frames, the
        # read-shard fan-outs) detect re-entry and run inline instead
        # of nested-submitting behind their own parent task.
        self._pool_tag = f"drive-pool-{set_index}-{id(self)}"
        self.pool = ThreadPoolExecutor(max_workers=max(self.n, 4),
                                       initializer=_tag_pool_thread,
                                       initargs=(self._pool_tag,))
        self._iter_pool = ThreadPoolExecutor(max_workers=8)
        self._codec_cache: dict[tuple[int, int], ReedSolomonTPU] = {}
        self._cpu_cache: dict[tuple[int, int], ReedSolomonCPU] = {}
        self._native_cache: dict[tuple[int, int], object] = {}
        # Namespace locks guard object mutations (cf. NSLock use at
        # cmd/erasure-object.go:930). Standalone default: in-process RW
        # locks; a distributed deployment injects an NSLockMap over the
        # set's (local+remote) lockers (cluster/nslock.py).
        if nslock is None:
            from ..cluster.nslock import NSLockMap
            nslock = NSLockMap()
        self.nslock = nslock
        # Optional background-subsystem hooks: an MRF queue receives
        # partial-write failures; the dirty tracker feeds the scanner's
        # changed-bucket skip logic (background/usage.py).
        self.mrf = None
        self._dirty_tracker = None
        self._bucket_cache: dict[str, float] = {}
        # Parsed-quorum FileInfo cache for the GET fan-out: a ranged GET
        # split into N segment requests must not re-read and re-elect
        # xl.meta N times.  Entries are (bucket generation, stamp, fi,
        # metas, errs); any write path bumps the bucket's generation via
        # _mark_dirty, and a short TTL bounds cross-process staleness
        # exactly like the bucket-existence cache above.
        self._fi_cache: dict[tuple, tuple] = {}
        self._fi_gen: dict[str, int] = {}
        # Optional RAM hot-object tier (engine/hotcache.py): attached
        # by attach_pools/attach_sets only when every drive is local.
        # Invalidation piggybacks on _mark_dirty — same generation
        # discipline as the FileInfo cache, but in shared memory so a
        # pool sibling's PUT invalidates this process's hits too.
        self.hot_tier = None
        # Hedged-read state: the hedge delay adapts like a lock deadline
        # (log_timeout when the timer fires, log_success when the
        # slowest needed shard beat it), and per-drive-position read
        # EWMAs let the 1-core serial host decide when fanning out is
        # worth the thread hops (a known-slow drive) vs. pure overhead
        # (every drive fast).  Lock-free float updates: a lost race
        # skews a hint, nothing more.
        self._hedge_dyn = DynamicTimeout(0.05, 0.002, 2.0)
        self._read_ewma_ms = [0.0] * self.n
        # Device-resident shard cache identity (ops/devcache.py): a
        # fresh per-process owner token per ErasureSet instance, so a
        # reopened set (crash recovery, decom re-attach) can never see
        # entries filled by a previous incarnation.
        self._devcache_owner = devcache_mod.next_owner()
        from .metacache import Metacache
        self.metacache = Metacache(self)

    #: FileInfo-cache tuning: TTL matches the bucket-existence cache
    #: window; the size cap only matters for pathological key churn
    #: (clearing wholesale is fine — it is a latency cache, not state).
    _FI_CACHE_TTL = 2.0
    _FI_CACHE_MAX = 512

    def _mark_dirty(self, bucket: str) -> None:
        if self._dirty_tracker is not None:
            self._dirty_tracker.mark(bucket)
        self._fi_gen[bucket] = self._fi_gen.get(bucket, 0) + 1
        self.metacache.bump(bucket)
        if self.hot_tier is not None:
            self.hot_tier.note_mutation(bucket)
        # Always recorded, even with MTPU_DEVCACHE=0 — a mutation made
        # while the cache is disabled must still invalidate entries a
        # later re-enable would otherwise resurrect.
        devcache_mod.get().note_mutation(self._devcache_owner, bucket)

    # -- codec helpers -------------------------------------------------------

    @property
    def device_idx(self) -> int:
        """The coalescer-lane device this set's kernel traffic rides
        (PR 10): `set_index % n_devices` — the same deterministic index
        as the set's sipHashMod placement, one layer down, so affinity
        is stable across boots and identical in every process.
        Resolved per call: tests flip MTPU_DEVICES at runtime."""
        return devices_mod.device_for_set(self.set_index)

    @property
    def _use_device(self) -> bool:
        """Device codec on a real TPU; native AVX codec otherwise.

        Off-TPU (tests, FS-like hosts, device loss) the XLA-CPU
        bit-plane path would be the bottleneck; the native nibble-table
        codec (ops/erasure_native.py) is the same code the reference's
        assembly computes.  The TPU decision is made once per process.
        """
        global _USE_DEVICE
        if _USE_DEVICE is None:
            import jax
            _USE_DEVICE = jax.default_backend() == "tpu"
        return _USE_DEVICE

    def _native(self, k: int, m: int):
        """Host codec, degrading gracefully: native AVX kernel if the
        toolchain builds it, else the portable XLA path — a missing g++
        must slow the data path down, not break it."""
        global _NATIVE_OK
        key = (k, m)
        if key in self._native_cache:
            return self._native_cache[key]
        if _NATIVE_OK is None:
            try:
                from native import rs_comparator
                rs_comparator.load()
                _NATIVE_OK = True
            except Exception:  # noqa: BLE001 — no g++/ISA
                _NATIVE_OK = False
        if _NATIVE_OK:
            from ..ops.erasure_native import ReedSolomonNative
            codec = ReedSolomonNative(k, m)
        else:
            codec = self._codec(k, m)
        self._native_cache[key] = codec
        return codec

    def _sharded(self, k: int, m: int):
        """Mesh codec (parallel/sharded.py) cached per geometry over the
        process-wide device mesh."""
        global _MESH
        key = ("sharded", k, m)
        if key not in self._native_cache:
            from ..parallel.sharded import ShardedCodec, make_mesh
            if _MESH is None:
                _MESH = make_mesh()
            self._native_cache[key] = ShardedCodec(k, m, _MESH)
        return self._native_cache[key]

    def _mesh_encode(self, k: int, m: int, blocks) -> np.ndarray | None:
        """Mesh-placed encode, or None when the geometry doesn't tile
        (caller falls back to the single-device path)."""
        sc = self._sharded(k, m)
        baxis = sc.mesh.shape["blocks"]
        lanes = sc.mesh.shape["lanes"]
        blocks = np.asarray(blocks)
        nb, kk, s = blocks.shape
        if s % lanes:
            return None
        pad = (-nb) % baxis
        if pad:
            blocks = np.concatenate(
                [blocks, np.zeros((pad, kk, s), np.uint8)])
        return np.asarray(sc.encode_blocks(blocks))[:nb]

    def _mesh_transform(self, k: int, m: int, x, sources,
                        targets) -> np.ndarray | None:
        sc = self._sharded(k, m)
        baxis = sc.mesh.shape["blocks"]
        lanes = sc.mesh.shape["lanes"]
        x = np.asarray(x)
        nb, rows, s = x.shape
        if rows % lanes:
            return None                     # drive rows don't tile
        pad = (-nb) % baxis
        if pad:
            x = np.concatenate([x, np.zeros((pad, rows, s), np.uint8)])
        out = np.asarray(sc.reconstruct_blocks(x, tuple(sources),
                                               tuple(targets)))
        return out[:nb]

    def _transform(self, k: int, m: int, x, sources, targets) -> np.ndarray:
        """Backend-picking transform: (B, K, S) -> (B, T, S) numpy."""
        if _mesh_mode():
            out = self._mesh_transform(k, m, x, sources, targets)
            if out is not None:
                return out
        if self._use_device:
            return np.asarray(self._codec(k, m).transform_blocks(
                x, tuple(sources), tuple(targets)))
        return np.asarray(self._native(k, m).transform_blocks(
            np.asarray(x), tuple(sources), tuple(targets)))

    def _codec(self, k: int, m: int) -> ReedSolomonTPU:
        if (k, m) not in self._codec_cache:
            self._codec_cache[k, m] = ReedSolomonTPU(k, m)
        return self._codec_cache[k, m]

    def _cpu(self, k: int, m: int) -> ReedSolomonCPU:
        if (k, m) not in self._cpu_cache:
            self._cpu_cache[k, m] = ReedSolomonCPU(k, m)
        return self._cpu_cache[k, m]

    # -- drive fan-out helpers ----------------------------------------------

    def _map_drives(self, fn, drives=None) -> list:
        """Run fn(drive) on every drive in parallel; exceptions captured.

        Returns list of (result, error) per drive position.
        """
        drives = self.drives if drives is None else drives

        def call(d):
            if d is None:
                return None, ErrDiskNotFound("offline")
            try:
                return fn(d), None
            except Exception as e:  # noqa: BLE001 — quorum layer classifies
                return None, e

        if self._serial_local(drives) or self._on_drive_pool():
            return [call(d) for d in drives]
        # wrap_ctx: per-drive spans born in pool threads still attach
        # to the traced request (no-op when untraced).
        return list(self.pool.map(ospan.wrap_ctx(call), drives))

    # -- bucket ops ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        res = self._map_drives(lambda d: d.make_volume(bucket))
        errs = [e for _, e in res]
        # Already present on every drive -> the bucket truly exists.
        if errs and all(isinstance(e, ErrVolumeExists) for e in errs):
            raise ErrBucketExists(bucket)
        # Partial existence is the heal case: treat as success.
        errs = [None if isinstance(e, ErrVolumeExists) else e for e in errs]
        err = Q.reduce_write_quorum_errs(errs, self.n // 2 + 1)
        if err is not None:
            raise err

    def bucket_exists(self, bucket: str, cached: bool = False) -> bool:
        # cached=True serves the WRITE hot path's pre-check (put_object
        # probes existence on every call): a stale positive there is
        # backstopped by the per-drive ErrVolumeNotFound the write
        # itself surfaces. Reads and explicit existence queries
        # (HeadBucket, error classification) always stat — a cluster
        # peer's delete must be visible immediately, not after a TTL.
        now = time.monotonic()
        if cached:
            hit = self._bucket_cache.get(bucket)
            if hit is not None and now - hit < 2.0:
                return True
        res = self._map_drives(lambda d: d.stat_volume(bucket))
        ok = sum(1 for _, e in res if e is None)
        exists = ok >= self._live_quorum()
        if exists:
            self._bucket_cache[bucket] = now
        else:
            self._bucket_cache.pop(bucket, None)
        return exists

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._bucket_cache.pop(bucket, None)
        res = self._map_drives(lambda d: d.delete_volume(bucket, force=force))
        errs = [e for _, e in res]
        if errs and all(isinstance(e, ErrVolumeNotFound) for e in errs):
            raise ErrBucketNotFound(bucket)
        errs = [None if isinstance(e, ErrVolumeNotFound) else e for e in errs]
        err = Q.reduce_write_quorum_errs(errs, self.n // 2 + 1)
        if err is not None:
            raise err
        # Recreating the bucket must not resurrect pre-delete cache
        # entries (FileInfo cache or hot tier).
        self._mark_dirty(bucket)

    def list_buckets(self) -> list[str]:
        res = self._map_drives(lambda d: d.list_volumes())
        counts: dict[str, int] = {}
        for vols, e in res:
            if e is None:
                for v in vols:
                    counts[v] = counts.get(v, 0) + 1
        quorum = self._live_quorum()
        return sorted(v for v, c in counts.items() if c >= quorum)

    def _live_quorum(self) -> int:
        live = sum(1 for d in self.drives if d is not None)
        return max(1, live // 2)

    # -- put -----------------------------------------------------------------

    def put_object(self, bucket: str, obj: str, data, *,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity: int | None = None,
                   version_id: str | None = None,
                   mod_time_ns: int | None = None) -> FileInfo:
        """Erasure-code and store one object (single part).

        `data` is bytes or a reader (.read(n)); a reader streams through
        encode in O(BATCH_BLOCKS x BLOCK_SIZE) memory — the role of the
        reference's blockwise streaming Encode
        (/root/reference/cmd/erasure-encode.go:73).

        `version_id`/`mod_time_ns` override the generated identity —
        the decommission mover re-PUTs a drained pool's versions through
        this path and must preserve each version's id and timestamp or
        the moved history would reorder (a moved OLD version would
        eclipse a client write that raced the drain).

        cf. erasureObjects.putObject, /root/reference/cmd/erasure-object.go:748.
        """
        with ospan.span("engine.bucket_check"):
            if not self.bucket_exists(bucket, cached=True):
                raise ErrBucketNotFound(bucket)
        with self.nslock.write_locked(bucket, obj):
            fi = self._put_object_locked(bucket, obj, data,
                                         metadata=metadata,
                                         versioned=versioned,
                                         parity=parity,
                                         version_id=version_id,
                                         mod_time_ns=mod_time_ns)
        self._mark_dirty(bucket)
        return fi

    def clamp_parity(self, parity: int | None) -> int:
        """Request-supplied parity (storage-class plumbing) clamped to
        the stripe's sane range — EC:N beyond n/2 would starve data
        shards (the reference validates SC parity the same way,
        internal/config/storageclass/storage-class.go)."""
        if parity is None:
            return self.default_parity
        return max(0, min(int(parity), self.n // 2))

    def _put_object_locked(self, bucket, obj, data, *, metadata,
                           versioned, parity, version_id=None,
                           mod_time_ns=None) -> FileInfo:
        parity = self.clamp_parity(parity)
        # Parity upgrade: offline drives become parity so the write keeps
        # full reconstruction capability (cf. erasure-object.go:766-800).
        # Breaker-OFFLINE drives count too — their writes fail fast, so
        # the stripe needs the same extra parity as a physical hole.
        offline = sum(1 for d in self.drives if not drive_available(d))
        upgraded = False
        if offline and parity < self.n // 2:
            parity = min(parity + offline, self.n // 2)
            upgraded = True
        k = self.n - parity
        write_quorum = k + (1 if k == parity else 0)

        # A streamed body: peek enough to decide inline-vs-streaming;
        # small bodies collapse to the bytes path.
        stream = None
        if streams.is_reader(data):
            stream = data
            # Loop: a reader may legally return short reads before EOF.
            head = bytearray()
            while len(head) <= SMALL_FILE_THRESHOLD:
                piece = stream.read(SMALL_FILE_THRESHOLD + 1 - len(head))
                if not piece:
                    break
                head += piece
            head = bytes(head)
            if len(head) <= SMALL_FILE_THRESHOLD:
                data, stream = head, None
            else:
                data = head

        distribution = Q.hash_order(f"{bucket}/{obj}", self.n)
        meta = dict(metadata or {})
        # Overlap the MD5 etag with encode+write: the body is queued to
        # a digest worker in 1 MiB views and hashed WHILE the shard
        # pipeline encodes/writes (hashlib, the codec kernels, and file
        # IO all release the GIL, so the overlap is real even on the
        # 1-core host, where the up-front digest was the measured PUT
        # wall).  Resolved before publish; byte-identical ETags.
        etag_md5 = None
        if stream is None and "etag" not in meta:
            etag_md5 = streams.PipelinedMD5()
            etag_md5.feed(data)
        if upgraded:
            meta["x-mtpu-internal-erasure-upgraded"] = f"{offline}-offline"
        if version_id is None:
            version_id = new_uuid() if versioned else ""
        mod_time = mod_time_ns if mod_time_ns is not None else _now_ns()
        if mod_time_ns is not None:
            # A preserved-timestamp write (the decommission mover) must
            # never clobber a NEWER racing client write: the mover's
            # copy of a drained version is stale the instant a client
            # overwrites or deletes the object mid-drain, and last-
            # write-wins on the xl.meta slot would silently resurrect
            # the old bytes.  Under the namespace write lock the check
            # is race-free.
            try:
                cur = self._read_metadata(bucket, obj, version_id)[0]
                if cur.mod_time_ns >= mod_time:
                    return cur
            except StorageError:
                pass

        algo = bitrot_io.write_algo()
        ec_base = ErasureInfo(
            data_blocks=k, parity_blocks=parity, block_size=BLOCK_SIZE,
            index=0, distribution=distribution,
            checksums=[{"part": 1, "algo": algo, "hash": b""}])
        # Object size: known up front for bytes, discovered at EOF for a
        # stream — fi_for reads it at publish time (after the stream).
        sizeref = {"size": len(data) if stream is None else None}

        def fi_for(drive_pos: int, data_dir: str,
                   inline: bytes | None) -> FileInfo:
            size = sizeref["size"]
            ec = ErasureInfo(
                data_blocks=k, parity_blocks=parity, block_size=BLOCK_SIZE,
                index=distribution[drive_pos], distribution=distribution,
                checksums=ec_base.checksums)
            return FileInfo(
                volume=bucket, name=obj, version_id=version_id,
                data_dir=data_dir, mod_time_ns=mod_time, size=size,
                metadata=meta,
                parts=[ObjectPartInfo(1, size, size)],
                erasure=ec, inline_data=inline)

        if stream is None and len(data) <= SMALL_FILE_THRESHOLD:
            if etag_md5 is not None:
                with ospan.span("engine.etag"):
                    meta.setdefault("etag", etag_md5.hexdigest())
            return self._put_inline(bucket, obj, data, fi_for, k, parity,
                                    distribution, write_quorum, algo)

        # Streaming path: encode batches of blocks on device, append framed
        # shards to per-drive staging files, publish with rename_data.
        data_dir = new_uuid()
        tmp_id = f"put-{uuid.uuid4().hex}"
        failed = [d is None for d in self.drives]

        # Streamed bodies pipeline their digest too: each pulled chunk
        # is queued to the digest worker and hashes under the NEXT
        # chunk's read+encode instead of serially before it.
        md5 = streams.PipelinedMD5() if stream is not None \
            else hashlib.md5()
        total = 0

        def counted_chunks():
            nonlocal total
            for chunk, is_last in streams.batched_chunks(
                    data, stream, BATCH_BLOCKS * BLOCK_SIZE):
                if stream is not None:
                    md5.update(chunk)    # bytes path already has its etag
                total += len(chunk)
                yield chunk, is_last

        # Fast path: the whole object fits in one encode dispatch
        # (bytes body <= one batch). Encode, then ONE fan-out per
        # drive doing write+publish together — the generic path costs
        # two thread-pool round-trips per batch plus an all-drive
        # cleanup sweep, which dominates small-object latency (the
        # parallelWriter+RenameData pair in the reference is likewise
        # one connection round per drive, cmd/erasure-object.go:1200).
        if stream is None and len(data) <= BATCH_BLOCKS * BLOCK_SIZE:
            try:
                with ospan.span("engine.encode"):
                    batches = list(self._encode_chunks(
                        [(data, True)], k, parity, algo))
            finally:
                if etag_md5 is not None:
                    etag_md5.close()     # worker drains what's queued
            if etag_md5 is not None:
                with ospan.span("engine.etag"):
                    meta.setdefault("etag", etag_md5.hexdigest())
            per_drive = [Q.unshuffle_to_drives(b, distribution)
                         for b in batches]

            def stage(pos):
                d = self.drives[pos]
                if d is None:
                    raise ErrDiskNotFound("offline")
                bufs = [pdc[pos] for pdc in per_drive]
                # Vectored staging: the whole per-drive fan-out is one
                # open + fallocate + pwritev instead of one
                # open/write/close per batch.  Feature-detected so
                # RPC/remote drives (no write_file_batches) keep the
                # append loop; MTPU_ZEROCOPY=0 is the oracle.
                wfb = (getattr(d, "write_file_batches", None)
                       if zc.zerocopy_enabled() else None)
                if wfb is not None:
                    wfb(SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.1", bufs)
                    return
                for buf in bufs:
                    d.append_file(SYS_VOL,
                                  f"{TMP_DIR}/{tmp_id}/part.1", buf)

            # Quorum gate BETWEEN staging and publish: nothing becomes
            # visible unless enough drives staged — a failed PUT must
            # not leave committed versions on the survivors (the
            # reference likewise aborts before RenameData,
            # cmd/erasure-object.go:1200).
            with ospan.span("engine.stage"):
                res = self._map_drives_positions(stage)
            stage_errs = [e for _, e in res]
            err = Q.reduce_write_quorum_errs(stage_errs, write_quorum)
            if err is not None:
                self._cleanup_tmp(tmp_id)
                raise err

            def publish(pos):
                if stage_errs[pos] is not None:
                    raise ErrDiskNotFound("stage failed")
                self.drives[pos].rename_data(
                    SYS_VOL, f"{TMP_DIR}/{tmp_id}",
                    fi_for(pos, data_dir, None), bucket, obj)

            with ospan.span("engine.publish"):
                res = self._map_drives_positions(publish)
            errs = [e for _, e in res]
            err = Q.reduce_write_quorum_errs(errs, write_quorum)
            if err is not None:
                self._undo_publish(bucket, obj,
                                   fi_for(0, data_dir, None), errs)
                self._cleanup_tmp(tmp_id)
                raise err
            crash_point("put.post_publish")
            if any(errs):
                # Only failed drives can still hold staging files —
                # successful publishes renamed theirs away.
                self._cleanup_tmp(tmp_id)
            fi = fi_for(0, data_dir, None)
            if self.mrf is not None and any(errs):
                self.mrf.enqueue(bucket, obj, fi.version_id)
            return fi

        # try/finally: a reader that raises mid-stream (client
        # disconnect, truncated body, hash mismatch at EOF) must not
        # leak per-drive staging files — they only get swept again at
        # drive startup.
        try:
            for batch_shards in ospan.timed_iter(
                    self._encode_chunks(counted_chunks(), k, parity, algo),
                    "engine.encode"):
                # batch_shards: n framed byte strings in SHARD order.
                per_drive = Q.unshuffle_to_drives(batch_shards,
                                                  distribution)

                def write_one(pos):
                    d = self.drives[pos]
                    if d is None or failed[pos]:
                        return
                    # Streaming batches ride the vectored writer too (a
                    # one-element iovec): same single open per batch,
                    # but with fallocate extension and the
                    # O_DIRECT-when-aligned path for bulk shards.
                    wfb = (getattr(d, "write_file_batches", None)
                           if zc.zerocopy_enabled() else None)
                    if wfb is not None:
                        wfb(SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.1",
                            [per_drive[pos]])
                        return
                    d.append_file(SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.1",
                                  per_drive[pos])

                with ospan.span("engine.write"):
                    res = self._map_drives_positions(write_one)
                for pos, (_, e) in enumerate(res):
                    if e is not None:
                        failed[pos] = True
                if sum(1 for f in failed if not f) < write_quorum:
                    raise ErrErasureWriteQuorum(
                        f"{self.n - sum(failed)} < {write_quorum}")

            if stream is not None:
                sizeref["size"] = total
                with ospan.span("engine.etag"):
                    meta.setdefault("etag", md5.hexdigest())
            elif etag_md5 is not None:
                with ospan.span("engine.etag"):
                    meta.setdefault("etag", etag_md5.hexdigest())

            def publish(pos):
                d = self.drives[pos]
                if d is None or failed[pos]:
                    raise ErrDiskNotFound("offline/failed")
                d.rename_data(SYS_VOL, f"{TMP_DIR}/{tmp_id}",
                              fi_for(pos, data_dir, None), bucket, obj)

            with ospan.span("engine.publish"):
                res = self._map_drives_positions(publish)
            errs = [e for _, e in res]
            err = Q.reduce_write_quorum_errs(errs, write_quorum)
            if err is not None:
                self._undo_publish(bucket, obj,
                                   fi_for(0, data_dir, None), errs)
                raise err
            crash_point("put.post_publish")
        finally:
            # Always sweep staging: publish renames the winners away;
            # failed/partial drives still hold tmp shard files.  The
            # digest workers must be released too — an abandoned one
            # would hold its slot until the idle backstop.
            if etag_md5 is not None:
                etag_md5.close()
            if isinstance(md5, streams.PipelinedMD5):
                md5.close()
            self._cleanup_tmp(tmp_id)
        fi = fi_for(0, data_dir, None)
        # Partial success (quorum met, some drives failed): queue for MRF
        # heal so the stripe returns to full width without waiting for
        # the scanner (cf. enqueue at cmd/erasure-object.go:1403).
        if self.mrf is not None and (any(failed) or any(errs)):
            self.mrf.enqueue(bucket, obj, fi.version_id)
        return fi

    def _put_inline(self, bucket, obj, data, fi_for, k, parity,
                    distribution, write_quorum, algo: str) -> FileInfo:
        """Small objects: framed shards live inline in each drive's xl.meta
        (cf. inline data, /root/reference/cmd/xl-storage.go:1183)."""
        with ospan.span("engine.encode"):
            shards = self._encode_full(data, k, parity, algo)  # n framed
        per_drive = Q.unshuffle_to_drives(shards, distribution)

        def write_one(pos):
            d = self.drives[pos]
            if d is None:
                raise ErrDiskNotFound("offline")
            d.write_metadata(bucket, obj, fi_for(pos, "", per_drive[pos]))

        # Publish routing: a lone request takes the exact solo fan-out
        # (one fsynced write_metadata per drive — oracle latency and
        # oracle durability mechanics); once the request-level inflight
        # counter or a busy lane proves concurrency, publishes route
        # through the per-drive metadata lanes where same-drive
        # batch-mates share ONE journal fsync (group commit).
        use_lanes = False
        mb = None
        if metalanes.enabled():
            mb = metalanes.get()
            mb.note_put(1)
            use_lanes = mb.put_hot() or metalanes.solo_forced()
        try:
            with ospan.span("engine.write"):
                if use_lanes:
                    res = self._put_inline_lanes(
                        bucket, obj, fi_for, per_drive, mb)
                else:
                    res = self._map_drives_positions(write_one)
        finally:
            if mb is not None:
                mb.note_put(-1)
        errs = [e for _, e in res]
        err = Q.reduce_write_quorum_errs(errs, write_quorum)
        if err is not None:
            self._undo_publish(bucket, obj, fi_for(0, "", None), errs)
            raise err
        crash_point("put.inline.post_meta")
        fi = fi_for(0, "", None)
        if self.mrf is not None and any(errs):
            # Same partial-success rule as the streaming path.
            self.mrf.enqueue(bucket, obj, fi.version_id)
        return fi

    def _put_inline_lanes(self, bucket, obj, fi_for, per_drive,
                          mb) -> list:
        """Submit one xl.meta publish per position to its drive's
        write lane and collect the handles into the same
        ``[(result, error)]`` shape `_map_drives_positions` returns.
        Submission never touches the drive pool (the lanes own their
        dispatcher threads), so this path composes with nested
        fan-outs without deadlock."""
        handles: list = []
        for pos in range(self.n):
            d = self.drives[pos]
            if d is None:
                handles.append(None)
                continue
            try:
                handles.append(mb.submit_write(
                    d, bucket, obj, fi_for(pos, "", per_drive[pos])))
            except Exception as e:  # noqa: BLE001 — quorum classifies
                handles.append(e)
        out = []
        for h in handles:
            if h is None:
                out.append((None, ErrDiskNotFound("offline")))
            elif isinstance(h, Exception):
                out.append((None, h))
            else:
                try:
                    out.append((h.result(), None))
                except Exception as e:  # noqa: BLE001 — quorum classifies
                    out.append((None, e))
        return out

    #: One-core hosts (this bench VM) gain nothing from a thread pool —
    #: the per-drive work is GIL-bound glue plus page-cache writes, and
    #: pool coordination costs ~0.5 ms/call. Multi-core hosts keep the
    #: parallel fan-out (real deployments: one thread per drive, like
    #: the reference's per-disk goroutines). Remote drives always fan
    #: out — network round-trips overlap even with one core.
    _SERIAL_FANOUT = (os.cpu_count() or 2) == 1

    def _serial_local(self, drives=None) -> bool:
        """One policy, three dispatch sites: serial per-drive calls
        only on a 1-core host whose drives are all in-process."""
        return self._SERIAL_FANOUT and all(
            isinstance(d, (LocalDrive, type(None)))
            for d in (self.drives if drives is None else drives))

    def _on_drive_pool(self) -> bool:
        """True when the calling thread IS one of this set's drive-pool
        workers: a nested fan-out must run inline — submitting to the
        pool it occupies and blocking on the result can deadlock once
        every worker does the same (the hazard the prefetch _iter_pool
        comment in __init__ guards the iterator path against)."""
        return getattr(_POOL_LOCAL, "tag", None) == self._pool_tag

    # -- hedged shard reads --------------------------------------------------

    def _note_read_ms(self, pos: int, ms: float) -> None:
        cur = self._read_ewma_ms[pos]
        self._read_ewma_ms[pos] = ms if cur == 0.0 else 0.25 * ms + 0.75 * cur

    def _hedge_delay_s(self) -> float:
        fixed = _hedge_fixed_ms()
        if fixed is not None:
            return fixed / 1e3
        return self._hedge_dyn.timeout()

    def _hedge_worthwhile(self, positions: list[int]) -> bool:
        """Serial-host hedge ignition: fanning k reads across threads
        costs real milliseconds on a 1-core box, so only do it when the
        per-position EWMAs actually show a straggler — one position
        markedly slower than the fastest known (or >5 ms absolute)."""
        known = [self._read_ewma_ms[p] for p in positions
                 if self._read_ewma_ms[p] > 0.0]
        if not known:
            return False
        return max(known) > max(5.0, 4.0 * min(known))

    def _hedged_fetch(self, read_shard, order, rows, tried, want,
                      spares, k: int) -> set[int]:
        """First-k-wins gather.  Launch `want` shard reads concurrently;
        if stragglers outlive the adaptive hedge delay, launch parity
        `spares` to cover them; a FAILED read promotes a spare
        immediately (no timer).  Fills `rows` until k distinct shards
        answered (or everything failed) and returns the shard indices
        still in flight — abandoned losers whose results are ignored.
        The caller must un-`tried` those so a later retry round may
        re-read them.  Slow drives need no explicit demerit here: their
        in-flight wrapper call is still timing, so the breaker's latency
        ledger sees every straggle.
        """
        q: _queuemod.Queue = _queuemod.Queue()
        inflight: set[int] = set()

        def launch(s):
            tried.add(s)
            inflight.add(s)
            pos = order[s]

            def run():
                try:
                    q.put((s, read_shard(pos), None))
                except BaseException as e:  # noqa: BLE001 — marshalled
                    q.put((s, None, e))
            self.pool.submit(ospan.wrap_ctx(run))

        for s in want:
            launch(s)
        spares = list(spares)
        t0 = time.monotonic()
        deadline = t0 + self._hedge_delay_s()
        fired = False
        hedged: set[int] = set()
        n_spares = wins = 0
        while len(rows) < k and inflight:
            if not fired and spares:
                left = deadline - time.monotonic()
                if left <= 0:
                    # Timer: cover every straggler with a spare at once
                    # (k-len(rows) are missing; that many spares close
                    # the read if every straggler is truly stuck).
                    for _ in range(min(len(spares), k - len(rows))):
                        s = spares.pop(0)
                        hedged.add(s)
                        launch(s)
                        n_spares += 1
                    fired = True
                    self._hedge_dyn.log_timeout()
                    continue
                try:
                    item = q.get(timeout=left)
                except _queuemod.Empty:
                    continue
            else:
                # Every launched read puts exactly one item — blocking
                # without a timeout cannot hang while inflight is
                # non-empty.
                item = q.get()
            s, r, err = item
            inflight.discard(s)
            if err is None:
                rows[s] = r
                if s in hedged:
                    wins += 1
            elif spares:
                sp = spares.pop(0)
                launch(sp)
                n_spares += 1
        if not fired:
            self._hedge_dyn.log_success(time.monotonic() - t0)
        DATA_PATH.record_hedge(fired=fired, spares=n_spares, wins=wins)
        return inflight

    def _map_drives_positions(self, fn, parallel: bool = False) -> list:
        """Like _map_drives but fn gets the drive *position*.

        ``parallel=True`` forces the pool fan-out even on the 1-core
        host — for syscall-heavy per-drive work (multipart complete's
        publish: per-part stat + meta read + renames) where the GIL is
        released in the kernel and overlap beats pool overhead."""
        if (not parallel and self._serial_local()) \
                or self._on_drive_pool():
            out = []
            for pos in range(self.n):
                try:
                    out.append((fn(pos), None))
                except Exception as e:  # noqa: BLE001
                    out.append((None, e))
            return out

        def call(pos):
            try:
                return fn(pos), None
            except Exception as e:  # noqa: BLE001
                return None, e
        return list(self.pool.map(ospan.wrap_ctx(call), range(self.n)))

    # -- encode drivers ------------------------------------------------------

    def _encode_full(self, data: bytes, k: int, m: int,
                     algo: str) -> list[bytes]:
        """Encode a small object in one shot; returns n framed shard files."""
        out = [bytearray() for _ in range(k + m)]
        for framed in self._encode_stream(data, k, m, algo):
            for i, b in enumerate(framed):
                # Frames arrive as ndarray views (fused kernel) or bytes
                # (CPU tail); bytearray += needs a buffer, not an array.
                out[i] += memoryview(b) if isinstance(b, np.ndarray) else b
        return [bytes(b) for b in out]

    def _encode_stream(self, data: bytes, k: int, m: int,
                       algo: str | None = None):
        """Yield lists of n framed shard-chunks per batch of blocks
        from an in-memory object (small/compat path)."""
        chunks = streams.batched_chunks(data, None,
                                        BATCH_BLOCKS * BLOCK_SIZE)
        yield from self._encode_chunks(chunks, k, m, algo)

    # -- coalesced-dispatch kernels (ops/coalesce.py) ------------------------
    #
    # Each factory returns an fn(stacked, spans, ctx) closure computing
    # one coalesced batch; the coalescer key carries every parameter the
    # closure captures, so items from different requests (and different
    # ErasureSet instances of the same geometry — the kernels are pure
    # functions of (k, m, algo, S)) stack along the block axis.

    def _pf_kernel(self, k: int, m: int, shard_size: int):
        """Fused host encode (ecio put_frame): parity + digests + frame
        layout in one C pass over the stacked blocks.  Output goes into
        a pooled per-dispatch buffer (fresh mmap-sized allocations per
        dispatch would pay ~0.5 ms/MiB in page faults — the reason the
        direct path uses a per-thread arena, which a cross-request
        result cannot safely alias); shard i's frames are contiguous,
        so item j's framed views are plain slices."""
        fused_host = _ecio_mod()
        frame_len = bitrot_io.digest_size("mxh256") + shard_size

        def kernel(stacked, spans, ctx):
            nb = stacked.shape[0]
            per = nb * frame_len
            buf = ctx.rent((k + m) * per)
            outs = [buf[i * per:(i + 1) * per] for i in range(k + m)]
            fused_host.put_frame(stacked, k, m, outs=outs)
            return [[o[lo * frame_len:hi * frame_len] for o in outs]
                    for lo, hi in spans]

        return kernel

    def _enc_kernel(self, k: int, m: int, algo: str, fused_dev: bool,
                    device: int | None = None):
        """Device/native encode over the stacked blocks; device shapes
        are padded to BATCH_BLOCKS buckets so coalesced batch sizes
        don't multiply jit compiles.  Returns (parity, digests) per
        span — the same pair the direct dispatch produces, so the
        framing path downstream is shared.  `device` is the lane the
        batch is placed on (the submitting set's affinity)."""

        def kernel(stacked, spans, ctx):
            if fused_dev:
                x, n = coalesce.pad_batch(stacked, BATCH_BLOCKS)
                parity, digests = fused.encode_and_hash(x, k, m,
                                                        algo=algo,
                                                        device=device)
                parity = np.asarray(parity)[:n]
                digests = np.asarray(digests)[:, :n]
                return [(parity[lo:hi], digests[:, lo:hi])
                        for lo, hi in spans]
            if self._use_device:
                x, n = coalesce.pad_batch(stacked, BATCH_BLOCKS)
                parity = np.asarray(
                    self._codec(k, m).encode_blocks(
                        devices_mod.put(x, device)))[:n]
            else:
                parity = np.asarray(
                    self._native(k, m).encode_blocks(stacked))
            return [(parity[lo:hi], None) for lo, hi in spans]

        if fused_dev or self._use_device:
            def launch(x, n, spans, ctx):
                # Pipeline form: `x` arrives staged on the lane's
                # device, padded to BATCH_BLOCKS.  Encode inputs are
                # placement-owned (nothing retains them), so the fused
                # dispatch donates the buffer — XLA reuses the device
                # allocation instead of growing one per batch.
                if fused_dev:
                    parity_d, digests_d = fused.encode_and_hash(
                        x, k, m, algo=algo, device=device, donate=True)

                    def resolve():
                        parity = np.asarray(parity_d)[:n]
                        digests = np.asarray(digests_d)[:, :n]
                        return [(parity[lo:hi], digests[:, lo:hi])
                                for lo, hi in spans]

                    return resolve
                if not self._use_device:
                    raise RuntimeError("device codec unavailable")
                parity_d = self._codec(k, m).encode_blocks(
                    devices_mod.put(x, device))

                def resolve():
                    parity = np.asarray(parity_d)[:n]
                    return [(parity[lo:hi], None) for lo, hi in spans]

                return resolve

            kernel.launch = launch
            kernel.pad_rows = BATCH_BLOCKS
        return kernel

    def _direct_encode(self, blocks, k: int, m: int, algo: str):
        """The no-coalescer encode for one (nb, K, S) batch — the same
        (parity, digests) pair `_enc_kernel` produces.  Used as the
        per-request fallback when a coalesced handle fails (poisoned
        batch neighbor / dead dispatcher)."""
        fused_dev = (algo in fused.DEVICE_ALGOS and self._use_device
                     and bitrot_io.device_preferred(algo))
        if fused_dev:
            return fused.encode_and_hash(blocks, k, m, algo=algo,
                                         device=self.device_idx)
        if self._use_device:
            return self._codec(k, m).encode_blocks(
                devices_mod.put(blocks, self.device_idx)), None
        return self._native(k, m).encode_blocks(blocks), None

    def _vt_kernel(self, k: int, m: int, sources: tuple, targets: tuple,
                   algo: str, device: int | None = None):
        """Fused device verify(+reconstruct) over stacked (B, K, S)
        gathers — the healthy-verify / degraded-decode / heal work
        item.  Digest layout is (B, K, hs): axis 0 is the concat axis
        for both outputs.  `device` places the dispatch on the
        submitting set's affine lane."""

        def kernel(stacked, spans, ctx):
            x, n = coalesce.pad_batch(stacked, BATCH_BLOCKS)
            digests, out = fused.verify_and_transform(
                x, k, m, sources, targets, algo=algo, device=device)
            digests = np.asarray(digests)[:n]
            out = np.asarray(out)[:n] if targets else None
            return [(digests[lo:hi],
                     out[lo:hi] if out is not None else None)
                    for lo, hi in spans]

        def launch(x, n, spans, ctx):
            # Pipeline form (ops/coalesce.py): `x` is the lane's staged
            # device array, already padded to BATCH_BLOCKS and counted
            # at its upload — the sync moves to resolve(), one dispatch
            # behind.
            digests_d, out_d = fused.verify_and_transform(
                x, k, m, sources, targets, algo=algo, device=device)

            def resolve():
                digests = np.asarray(digests_d)[:n]
                out = np.asarray(out_d)[:n] if targets else None
                return [(digests[lo:hi],
                         out[lo:hi] if out is not None else None)
                        for lo, hi in spans]

            return resolve

        kernel.launch = launch
        kernel.pad_rows = BATCH_BLOCKS
        return kernel

    def _encode_chunks(self, chunks, k: int, m: int,
                       algo: str | None = None,
                       double_buffer: bool = False):
        """Encode an iterator of (chunk, is_last) pairs — every chunk a
        multiple of BLOCK_SIZE except the final one — yielding lists of
        n framed shard-chunks.  Memory is O(chunk), never O(object).

        Full 1 MiB blocks are encoded as one batched device dispatch
        ((B, K, S) uint8); the partial tail block goes through the CPU
        oracle codec (tiny, not worth a dispatch).

        ``double_buffer=True`` makes every yielded batch safe to consume
        asynchronously while the NEXT batch encodes: the fused host
        kernel normally writes into one reused per-thread arena (valid
        only until the next put_frame on that thread), so a pipelined
        caller that overlaps shard writes of batch *i* with the encode
        of batch *i+1* must get alternating buffers.  The device/mesh/
        numpy paths allocate fresh frames per batch and need no copy.
        """
        if algo is None:
            algo = bitrot_io.write_algo()
        shard_size = -(-BLOCK_SIZE // k)
        # Host fast path: ONE native pass per batch does parity + bitrot
        # digests + frame layout (native/ecio.cc) — no device, so there
        # is no dispatch to pipeline behind. Width-gated: the C kernels
        # hold at most 64 row pointers on the stack.
        fused_host = None
        if (not self._use_device and algo == "mxh256"
                and not _mesh_mode() and k + m <= 64):
            fused_host = _ecio_mod()

        def frame(blocks, parity, digests):
            # np.asarray here is the device sync point; by the time we
            # take it, the NEXT batch's dispatch is already in flight.
            # frame_shard_views fills the framed layout in one pass and
            # returns zero-copy per-shard views (the previous concat +
            # transpose + tobytes chain copied the batch three times).
            if digests is not None:
                digests = np.asarray(digests)
            return bitrot_io.frame_shard_views(
                blocks, np.asarray(parity), digests, algo)

        # Cross-request coalescing (MTPU_COALESCE, ops/coalesce.py):
        # instead of dispatching this request's batch directly, submit
        # it to the shared coalescer — concurrent requests' compatible
        # batches stack into ONE kernel launch and each request gets
        # its slice back through a future.  The future slots into the
        # same one-deep `pending` pipeline the direct device path uses,
        # so in-request overlap is preserved while cross-request
        # batching happens underneath.
        co = coalesce.get() if coalesce.enabled() else None

        # Double-buffered pipeline: dispatch batch i, then frame/yield
        # batch i-1 while the device works — hides dispatch+transfer
        # latency (large through the axon tunnel) behind host framing
        # and the caller's disk writes, the role of the reference's
        # in-flight parallelWriter (cmd/erasure-encode.go:36).
        pending = None
        arenas = None       # two alternating fused-output buffers
        flip = 0
        # Retired coalesced put_frame handles: their results alias a
        # POOLED dispatch buffer, and a pipelined consumer may still be
        # writing batch i when batch i+1 is pulled — so a buffer is
        # only recycled two yields after its batch was handed out.
        retired: list = []

        def flush(p):
            # Coalesced handles can FAIL (a poisoned batch neighbor, a
            # dead dispatcher): each tag recomputes its span through the
            # direct reference path — this request's bytes, this
            # request's kernels, nobody else's fault surface.
            tag = p[0]
            if tag == "pf":
                try:
                    framed = p[1].result()
                except Exception:  # noqa: BLE001 — direct fallback
                    DATA_PATH.record_co_fallback()
                    return fused_host.put_frame(p[2], k, m)
                retired.append(p[1])
                if len(retired) > 2:
                    retired.pop(0).release()
                return framed
            if tag == "co":
                try:
                    parity, digests = p[2].result()
                    p[2].release()   # fresh arrays — nothing pooled
                except Exception:  # noqa: BLE001 — direct fallback
                    DATA_PATH.record_co_fallback()
                    parity, digests = self._direct_encode(p[1], k, m, algo)
                return frame(p[1], parity, digests)
            return frame(p[1], p[2], p[3])

        frame_len = bitrot_io.digest_size("mxh256") + shard_size
        for chunk, is_last in chunks:
            buf = np.frombuffer(chunk, dtype=np.uint8)
            n_full = buf.size // BLOCK_SIZE
            for start in range(0, n_full, BATCH_BLOCKS):
                nb = min(BATCH_BLOCKS, n_full - start)
                batch = buf[start * BLOCK_SIZE:(start + nb) * BLOCK_SIZE]
                if BLOCK_SIZE % k == 0:
                    blocks = batch.reshape(nb, k, shard_size)
                else:
                    # Non-power-of-two K: each block zero-pads to
                    # K*shard_size (split padding rule,
                    # cf. erasure-coding.go:81).
                    blocks = np.zeros((nb, k * shard_size), dtype=np.uint8)
                    blocks[:, :BLOCK_SIZE] = batch.reshape(nb, BLOCK_SIZE)
                    blocks = blocks.reshape(nb, k, shard_size)
                if fused_host is not None:
                    if co is not None:
                        h = co.submit(
                            ("pf", k, m, shard_size), blocks,
                            self._pf_kernel(k, m, shard_size), weight=nb,
                            device=self.device_idx)
                        if pending is not None:
                            yield flush(pending)
                        pending = ("pf", h, blocks)
                    elif double_buffer:
                        per = BATCH_BLOCKS * frame_len
                        if arenas is None:
                            arenas = _db_arenas((k + m) * per)
                        a = arenas[flip]
                        flip ^= 1
                        outs = [a[i * per:i * per + nb * frame_len]
                                for i in range(k + m)]
                        yield fused_host.put_frame(blocks, k, m, outs=outs)
                    else:
                        yield fused_host.put_frame(blocks, k, m)
                    continue
                # Parity AND bitrot digests in ONE device dispatch
                # (north-star config #5 PUT side, ops/fused.py); framing
                # is then pure byte interleaving on the host.
                parity = digests = None
                if _mesh_mode():
                    # Multi-device: place the shard matmul on the mesh
                    # (blocks x lanes SPMD); digests hash on host.
                    # Mesh placement stays direct — SPMD shapes don't
                    # stack across requests.
                    parity = self._mesh_encode(k, m, blocks)
                if parity is not None:
                    if pending is not None:
                        yield flush(pending)
                    pending = ("arr", blocks, parity, None)
                    continue
                fused_dev = (algo in fused.DEVICE_ALGOS
                             and self._use_device
                             and bitrot_io.device_preferred(algo))
                if co is not None:
                    tag = ("fd" if fused_dev
                           else "dev" if self._use_device else "nat")
                    h = co.submit(
                        ("enc", tag, k, m, algo, shard_size), blocks,
                        self._enc_kernel(k, m, algo, fused_dev,
                                         device=self.device_idx),
                        weight=nb, device=self.device_idx)
                    if pending is not None:
                        yield flush(pending)
                    pending = ("co", blocks, h)
                    continue
                if fused_dev:
                    parity, digests = fused.encode_and_hash(blocks, k, m,
                                                            algo=algo)
                elif self._use_device:
                    # Host-hashed algorithms (sha256, or HighwayHash
                    # with its faster native host kernel): device
                    # encodes, the framing pass hashes.
                    parity, digests = \
                        self._codec(k, m).encode_blocks(blocks), None
                else:
                    # No TPU: native AVX codec; frame_shards_batch
                    # hashes on the host.
                    parity, digests = \
                        self._native(k, m).encode_blocks(blocks), None
                if pending is not None:
                    yield flush(pending)
                pending = ("arr", blocks, parity, digests)

            tail = buf[n_full * BLOCK_SIZE:]
            if is_last:
                if pending is not None:
                    yield flush(pending)
                    pending = None
                if tail.size:
                    cpu = self._cpu(k, m)
                    shards = cpu.encode_data(tail.tobytes())  # k+m arrays
                    tail_shard = shards[0].size
                    yield [bitrot_io.frame_shard(s, tail_shard, algo)
                           for s in shards]
            if not is_last and tail.size:
                raise ValueError("non-final chunk not BLOCK_SIZE aligned")

    # -- get -----------------------------------------------------------------

    def get_object(self, bucket: str, obj: str, offset: int = 0,
                   length: int = -1, version_id: str = "") -> tuple[FileInfo, bytes]:
        """Read [offset, offset+length) of an object, verifying bitrot and
        reconstructing up to `parity` missing/corrupt shards.

        With a hot tier attached, the read is served from the shared
        RAM cache when fresh, and cold reads of cacheable objects run
        single-flight: one leader does the engine read (and fills the
        cache if every segment passed the full-k fast-path verify),
        concurrent followers slice the leader's result.

        cf. GetObjectNInfo → getObjectWithFileInfo,
        /root/reference/cmd/erasure-object.go:221.
        """
        if self.hot_tier is not None and self.hot_tier.enabled:
            got = self._get_object_hot(bucket, obj, offset, length,
                                       version_id)
            if got is not None:
                return got
        return self._get_object_direct(bucket, obj, offset, length,
                                       version_id)

    def _get_object_direct(self, bucket: str, obj: str, offset: int = 0,
                           length: int = -1, version_id: str = "",
                           report: dict | None = None
                           ) -> tuple[FileInfo, bytes]:
        """The uncached engine read: segment reads assemble straight
        into ONE preallocated bytearray (each `_read_part` gathers into
        its slice of the final buffer), so the object is never joined
        through an extra full-size copy; the return is that
        memoryview-backed bytearray (bytes-compatible for
        hashing/slicing/IO).

        `report` (hot-tier fill eligibility) collects per-read
        evidence: segs = segment count, fast = segments served by the
        full-k verify-only fast path, taint = any decode/reconstruct
        involvement.  Fill requires fast == segs and no taint.
        """
        fi, metas, offset, length = self._plan_read(bucket, obj, offset,
                                                    length, version_id)
        if length == 0:
            return fi, b""
        data = self._read_whole_small(bucket, obj, fi, metas, version_id)
        if data is not None:
            if offset == 0 and length == len(data):
                return fi, data
            # Ranged inline/v1 reads serve a memoryview SLICE of the
            # already-materialized body: every consumer (socket writer,
            # hashing, bytes()) takes any buffer, so the per-request
            # copy was pure CPU tax.  MTPU_ZEROCOPY=0 keeps the copying
            # bytes slice as the byte-identical oracle.
            if zc.zerocopy_enabled():
                return fi, memoryview(data)[offset:offset + length]
            return fi, data[offset:offset + length]

        # The zeroed destination buffer is real time at 10s of MiB
        # (~0.3 ms/MiB of page faults) — price it as its own stage.
        with ospan.span("engine.alloc"):
            buf = bytearray(length)
        mv = memoryview(buf)
        segs = self._plan_segments(fi, offset, length)
        offs = []
        o = 0
        for _, _, ln in segs:
            offs.append(o)
            o += ln
        degraded = (any(d is None for d in self.drives)
                    or any(m is None for m in metas))
        if report is not None:
            report["segs"] = len(segs)
            if degraded:
                report["taint"] = True

        def read_seg(i):
            pn, off, ln = segs[i]
            with ospan.span("engine.read_part"):
                self._read_part(bucket, obj, fi, part_number=pn,
                                offset=off, length=ln,
                                dst=mv[offs[i]:offs[i] + ln],
                                healthy=not degraded, report=report)
        if self._serial_local() and not degraded:
            for i in range(len(segs)):
                read_seg(i)
        else:
            for _ in pl.prefetch_map(ospan.wrap_ctx(read_seg),
                                     range(len(segs)),
                                     self._iter_pool, depth=1):
                pass
        return fi, buf

    # -- hot tier ------------------------------------------------------------

    @staticmethod
    def _hot_range(fi, body, offset: int, length: int):
        """Slice a cached/leader whole-object body with _plan_read's
        exact range-validation semantics, so a cache hit raises the
        same errors a direct read would."""
        size = fi.size
        if offset < 0 or offset > size:
            raise StorageError(
                f"offset {offset} outside object of size {size}")
        if length < 0:
            length = size - offset
        if offset + length > size:
            raise StorageError(f"range [{offset}, {offset + length}) "
                               f"outside object of size {size}")
        if offset == 0 and length == len(body):
            return body
        return body[offset:offset + length]

    def _hot_cacheable(self, fi) -> bool:
        """Only healthy streaming-layout objects within the size gate
        enter the cache: inline/v1 small objects are already a single
        cheap read, and zero-byte bodies carry no payload to cache."""
        from ..storage import xlmeta_v1
        if fi.deleted or fi.size <= 0 \
                or fi.size > self.hot_tier.max_obj:
            return False
        if fi.inline_data is not None or (fi.parts and not fi.data_dir):
            return False
        return not xlmeta_v1.is_v1(fi)

    def _get_object_hot(self, bucket: str, obj: str, offset: int,
                        length: int, version_id: str,
                        skip_lookup: bool = False):
        """Hot-tier GET: cache hit, else single-flight engine read with
        a verified fill.  Returns (fi, body) or None — None means
        \"bypass: caller must do the direct read\"."""
        tier = self.hot_tier
        if not skip_lookup:
            got = tier.lookup(bucket, obj, version_id)
            if got is not None:
                fi, body = got
                return fi, self._hot_range(fi, body, offset, length)
        key = (id(self), bucket, obj, version_id)
        flight, leader = tier.flights.begin(key)
        if not leader:
            res = flight.wait()
            if res is None:
                return None         # leader failed/bypassed: go direct
            fi, body = res
            return fi, self._hot_range(fi, body, offset, length)
        ok = False
        try:
            # Capture the bucket generation BEFORE the read: a write
            # landing mid-read bumps it and the fill is discarded.
            gen0 = tier.generation(bucket)
            fi, metas, _, _ = self._plan_read(bucket, obj, 0, -1,
                                              version_id)
            if not self._hot_cacheable(fi):
                tier.note_bypass()
                return None
            report: dict = {}
            fi, data = self._get_object_direct(bucket, obj, 0, -1,
                                               version_id,
                                               report=report)
            body = bytes(data)
            if report.get("segs") and not report.get("taint") \
                    and report.get("fast", 0) == report["segs"]:
                tier.fill(bucket, obj, version_id, fi, body, gen0)
            else:
                tier.note_bypass()
            flight.resolve((fi, body))
            ok = True
            return fi, self._hot_range(fi, body, offset, length)
        finally:
            if not ok:
                flight.resolve(None)
            tier.flights.end(key)

    def _plan_read(self, bucket, obj, offset, length, version_id):
        """Shared GET front half: cached metadata election + range
        validation.  Returns (fi, metas, offset, resolved_length)."""
        fi, metas, errs = self._read_metadata_cached(bucket, obj,
                                                     version_id)
        if fi.deleted:
            raise ErrObjectNotFound(f"{bucket}/{obj} (delete marker)")
        size = fi.size
        if offset < 0 or offset > size:
            raise StorageError(f"offset {offset} outside object of size {size}")
        if length < 0:
            length = size - offset
        if offset + length > size:
            raise StorageError(f"range [{offset}, {offset + length}) "
                               f"outside object of size {size}")
        if size == 0:
            length = 0
        return fi, metas, offset, length

    def _read_whole_small(self, bucket, obj, fi, metas, version_id):
        """Inline / legacy-v1 whole-object read, or None for the
        streaming erasure layout."""
        if fi.inline_data is not None or (fi.parts and not fi.data_dir):
            return self._read_inline(bucket, obj, fi, metas, version_id)
        from ..storage import xlmeta_v1
        if xlmeta_v1.is_v1(fi):
            # Legacy format-v1 object: unframed shard files with
            # whole-file bitrot, 10 MiB blocks (migration read path,
            # cmd/xl-storage-format-v1.go + cmd/bitrot-whole.go).
            return self._read_v1_object(bucket, obj, fi)
        return None

    def _plan_segments(self, fi, offset: int,
                       length: int) -> list[tuple[int, int, int]]:
        """Map an object byte range onto batch-aligned per-part segments.

        Segment size: one bounded device dispatch per segment on TPU; on
        the host path, 16 MiB keeps the gather buffer under glibc's
        mmap threshold so successive segments recycle the same pages
        (a fresh 32 MiB allocation pays ~0.5 ms/MiB in page faults).
        Each part is an independent EC stream (cf. ObjectToPartOffset,
        cmd/erasure-metadata.go)."""
        batch_bytes = (BATCH_BLOCKS if self._use_device
                       else BATCH_BLOCKS // 2) * BLOCK_SIZE
        segs: list[tuple[int, int, int]] = []   # (part_number, off, len)
        part_start = 0
        remaining = length
        pos = offset
        for part in fi.parts:
            part_end = part_start + part.size
            if remaining <= 0:
                break
            if pos < part_end:
                in_off = pos - part_start
                in_len = min(remaining, part.size - in_off)
                seg = in_off
                stop = in_off + in_len
                while seg < stop:
                    # segment ends at the next batch boundary so each
                    # yield is one bounded device dispatch
                    boundary = (seg // batch_bytes + 1) * batch_bytes
                    seg_end = min(stop, boundary)
                    segs.append((part.number, seg, seg_end - seg))
                    seg = seg_end
                pos += in_len
                remaining -= in_len
            part_start = part_end
        return segs

    def get_object_iter(self, bucket: str, obj: str, offset: int = 0,
                        length: int = -1, version_id: str = ""):
        """Streaming read: returns (fi, iterator of assembled byte
        chunks), each chunk one device batch (<= BATCH_BLOCKS blocks) of
        verified+decoded data — memory is O(batch), never O(object)
        (the GetObjectReader role, cmd/object-api-utils.go:392-528)."""
        if self.hot_tier is not None and self.hot_tier.enabled:
            tier = self.hot_tier
            if zc.zerocopy_enabled() \
                    and hasattr(tier, "lookup_view"):
                # Zero-copy hit: the chunk is an ndarray view pinned
                # over the shared arena (release rides the view's GC;
                # eviction under the pin only defers slot reuse).  The
                # socket writer sends it via sendmsg without any
                # bytes() materialization — ranged GETs slice the view,
                # not copy it.
                got = tier.lookup_view(bucket, obj, version_id)
                if got is not None:
                    hfi, body = got
                    chunk = self._hot_range(hfi, body, offset, length)
                    DATA_PATH.record_zerocopy_hot_view(len(chunk))
                    return hfi, (iter(()) if len(chunk) == 0
                                 else iter((chunk,)))
            else:
                got = tier.lookup(bucket, obj, version_id)
                if got is not None:
                    hfi, body = got
                    chunk = self._hot_range(hfi, memoryview(body),
                                            offset, length)
                    return hfi, (iter(()) if len(chunk) == 0
                                 else iter((chunk,)))
            got = None
            # Cold cacheable object: delegate to the single-flight
            # whole-read (fills the cache; O(max_obj) memory is the
            # admission bound, so streaming degrades to nothing).
            # skip_lookup — the miss was already counted above.
            try:
                peek, _, _, _ = self._plan_read(bucket, obj, 0, -1,
                                                version_id)
            except StorageError:
                peek = None
            if peek is not None and self._hot_cacheable(peek):
                got = self._get_object_hot(bucket, obj, offset, length,
                                           version_id, skip_lookup=True)
                if got is not None:
                    hfi, body = got
                    return hfi, (iter(()) if len(body) == 0
                                 else iter((body,)))
            elif peek is not None:
                tier.note_bypass()
        fi, metas, offset, length = self._plan_read(bucket, obj, offset,
                                                    length, version_id)
        if length == 0:
            return fi, iter(())

        data = self._read_whole_small(bucket, obj, fi, metas, version_id)
        if data is not None:
            if offset == 0 and length == len(data):
                return fi, iter((data,))
            # Zero-copy range: the consumer (socket writer) takes any
            # buffer, so slice through a memoryview instead of copying.
            return fi, iter((memoryview(data)[offset:offset + length],))

        segs = self._plan_segments(fi, offset, length)

        # One-segment prefetch: segment i+1's drive reads + fused
        # verify/decode dispatch run while segment i drains to the
        # caller — hides device round-trips (large via the axon
        # tunnel) behind socket writes.  On a 1-core host with local
        # drives a HEALTHY read has nothing to overlap — prefetch is
        # pure executor overhead, so segments run inline.  A DEGRADED
        # read is different even there: reconstruction is native
        # GIL-releasing kernel work, so segment i+1's shard reads run
        # under segment i's decode (the reconstruct-pipeline shape
        # heal uses, parallel/pipeline.py).
        degraded = (any(d is None for d in self.drives)
                    or any(m is None for m in metas))
        pool = (None if self._serial_local() and not degraded
                else self._iter_pool)

        def read_seg(seg):
            pn, off, ln = seg
            with ospan.span("engine.read_part"):
                return self._read_part(bucket, obj, fi, part_number=pn,
                                       offset=off, length=ln,
                                       healthy=not degraded)
        return fi, pl.prefetch_map(ospan.wrap_ctx(read_seg), segs, pool,
                                   depth=1)

    def sendfile_plan(self, bucket: str, obj: str, offset: int = 0,
                      length: int = -1, version_id: str = ""):
        """Kernel-send plan for a whole healthy GET, or None.

        When the object's framing allows it — k=1 layout, so each
        part's single data shard IS the plaintext interleaved with
        bitrot frames — the response body can leave via os.sendfile of
        the data runs: the bytes go page cache -> socket without ever
        entering the process.  Returns (fi, [FilePlan, ...]) with the
        shard files ALREADY digest-verified through an mmap over the
        same fds the sends will use (a racing delete only unlinks the
        name), or None when any gate fails — the caller then takes the
        normal engine read, so this is a pure opportunistic overlay.

        Gates: MTPU_ZEROCOPY on; whole object (offset 0, full length);
        k=1 streaming layout (not inline, not legacy v1); nothing
        degraded; the shard drive is a healthy LocalDrive; and the
        object is NOT hot-cacheable when the RAM tier is on (the tier
        owns the small hot set — sendfile serves what the cache
        can't)."""
        if not zc.zerocopy_enabled():
            return None
        try:
            fi, metas, offset, length = self._plan_read(
                bucket, obj, offset, length, version_id)
        except StorageError:
            return None          # normal path surfaces the real error
        if offset != 0 or length != fi.size or fi.size <= 0:
            return None
        if fi.erasure.data_blocks != 1:
            return None
        if fi.inline_data is not None or not fi.parts \
                or not fi.data_dir:
            return None
        from ..storage import xlmeta_v1
        if xlmeta_v1.is_v1(fi):
            return None
        if self.hot_tier is not None and self.hot_tier.enabled \
                and self._hot_cacheable(fi):
            return None
        if any(m is None for m in metas) \
                or any(d is None for d in self.drives):
            return None
        order = Q.shuffle_by_distribution(list(range(self.n)),
                                          fi.erasure.distribution)
        d = self.drives[order[0]]
        if not isinstance(d, LocalDrive) or not drive_available(d):
            return None
        import mmap as _mmap
        shard_size = fi.erasure.shard_size
        plans: list[zc.FilePlan] = []
        try:
            for part in fi.parts:
                algo = fi.erasure.bitrot_algo(part.number)
                hs = bitrot_io.digest_size(algo)
                frame = hs + shard_size
                fd = d.open_read_fd(
                    bucket, f"{obj}/{fi.data_dir}/part.{part.number}")
                full = part.size // shard_size
                tail = part.size - full * shard_size
                runs = [(b * frame + hs, shard_size)
                        for b in range(full)]
                if tail:
                    runs.append((full * frame + hs, tail))
                # FilePlan owns the fd from here (closes on any bail).
                plan = zc.FilePlan(fd, runs, part.size)
                plans.append(plan)
                want = bitrot_io.bitrot_shard_file_size(
                    part.size, shard_size, algo)
                if os.fstat(fd).st_size != want:
                    raise ErrFileCorrupt("sendfile plan size mismatch")
                # Verify the framed shard through the SAME fd the sends
                # will use.  The mmap is dropped, not closed: numpy may
                # still export its buffer and GC unmaps it safely.
                mm = _mmap.mmap(fd, want, prot=_mmap.PROT_READ)
                bitrot_io.unframe_shard(memoryview(mm), shard_size,
                                        verify=True,
                                        logical_size=part.size,
                                        algo=algo)
        except (StorageError, OSError, ValueError):
            for p in plans:
                p.close()
            return None
        return fi, plans

    def _read_v1_object(self, bucket, obj, fi) -> bytes:
        """Whole-object read of a legacy (xl.json) object: per-drive
        UNFRAMED part files verified by whole-file digest, per-block
        reconstruction via the CPU oracle (v1 is a migration path, not
        a hot path)."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        bs = fi.erasure.block_size
        dist = fi.erasure.distribution
        out = bytearray()
        from ..storage import xlmeta_v1
        # v1 checksums are per-drive: each drive's xl.json carries the
        # whole-file hash of ITS shard — parse once per drive, not once
        # per (drive, part).
        own_sums: list[list[dict] | None] = []
        for d in self.drives:
            if d is None:
                own_sums.append(None)
                continue
            try:
                own = xlmeta_v1.parse_xl_json(
                    d.read_all(bucket, f"{obj}/{xlmeta_v1.XL_JSON}"),
                    bucket, obj)
                own_sums.append(own.erasure.checksums)
            except StorageError:
                # No readable xl.json = no digest to verify against:
                # treat the drive's shards as MISSING and reconstruct
                # around them — serving unverifiable bytes risks silent
                # corruption (the drive most likely to have lost its
                # metadata is the damaged one).
                own_sums.append(None)

        for part in fi.parts:

            def read_row(pos: int):
                d = self.drives[pos]
                if d is None or own_sums[pos] is None:
                    return None                   # offline/unverifiable
                try:
                    raw = d.read_file(bucket,
                                      f"{obj}/part.{part.number}")
                except StorageError:
                    return None
                # A shard we cannot verify is a shard we must not
                # trust: a part with no (or an empty) recorded digest
                # is treated like a missing xl.json above — return
                # None and reconstruct around it.
                for c in own_sums[pos]:
                    if c.get("name") == f"part.{part.number}" \
                            and c.get("hash"):
                        algo = c.get("algo", "highwayhash256")
                        if bitrot_io.whole_file_digest(
                                raw, algo) != c["hash"]:
                            return None           # corrupt shard
                        return raw
                return None                       # unverifiable shard

            rows: list[bytes | None] = [None] * (k + m)
            for pos in range(self.n):
                if pos < len(dist):
                    raw = read_row(pos)
                    if raw is not None:
                        rows[dist[pos] - 1] = raw
            if sum(1 for r in rows if r is not None) < k:
                raise ErrErasureReadQuorum(
                    f"{bucket}/{obj} part {part.number} (v1)")
            # Per-block chunks: v1 sizes each block's shard as
            # ceil(cur_block/k) with the final block shorter.
            remaining = part.size
            offs = [0] * (k + m)
            while remaining > 0:
                cur = min(bs, remaining)
                chunk = -(-cur // k)
                block_rows: list[np.ndarray | None] = []
                for s, r in enumerate(rows):
                    if r is None:
                        block_rows.append(None)
                        continue
                    block_rows.append(np.frombuffer(
                        r[offs[s]:offs[s] + chunk], dtype=np.uint8))
                    offs[s] += chunk
                missing = [s for s in range(k) if block_rows[s] is None]
                if missing:
                    rec = self._cpu(k, m).reconstruct(block_rows,
                                                      data_only=True)
                    for s in missing:
                        block_rows[s] = rec[s]
                blk = np.concatenate(block_rows[:k])[:cur]
                out += blk.tobytes()
                remaining -= cur
        return bytes(out)

    def _read_metadata(self, bucket, obj, version_id=""):
        version_id = normalize_version_id(version_id)
        DATA_PATH.record_meta_read_request()
        mb = metalanes.get() if metalanes.enabled() else None
        if mb is not None:
            mb.note_read(1)
        try:
            with ospan.span("engine.quorum"):
                res = self._read_version_fanout(
                    bucket, obj, version_id, mb)
        finally:
            if mb is not None:
                mb.note_read(-1)
        metas = [fi for fi, _ in res]
        errs = [e for _, e in res]
        n_found = sum(1 for f in metas if f is not None)
        if n_found == 0:
            err, count = Q.reduce_errs(errs, ignored=(ErrDiskNotFound,))
            if isinstance(err, (ErrFileNotFound, ErrVolumeNotFound)):
                if not self.bucket_exists(bucket):
                    raise ErrBucketNotFound(bucket)
                raise ErrObjectNotFound(f"{bucket}/{obj}")
            if isinstance(err, ErrFileVersionNotFound):
                raise ErrVersionNotFound(f"{bucket}/{obj}@{version_id}")
            raise ErrErasureReadQuorum(f"{bucket}/{obj}: {err}")
        read_quorum, _ = Q.object_quorum_from_meta(
            metas, self.n, self.default_parity)
        fi = Q.find_file_info_in_quorum(metas, read_quorum)
        return fi, metas, errs

    def _read_positions(self, bucket, obj, version_id,
                        positions, mb) -> list:
        """read_version over a subset of drive positions, returning
        one (FileInfo|None, error|None) per position in order.  Routes
        through the per-drive read lanes when concurrent metadata
        traffic is in flight (distinct keys' fan-outs then merge into
        one read_version_many round per drive); otherwise the exact
        oracle per-drive dispatch."""
        if mb is not None and mb.read_hot():
            handles = []
            for pos in positions:
                d = self.drives[pos]
                if d is None:
                    handles.append(None)
                    continue
                try:
                    handles.append(
                        mb.submit_read(d, bucket, obj, version_id))
                except Exception as e:  # noqa: BLE001 — quorum classifies
                    handles.append(e)
            out = []
            for h in handles:
                if h is None:
                    out.append((None, ErrDiskNotFound("offline")))
                elif isinstance(h, Exception):
                    out.append((None, h))
                else:
                    try:
                        out.append((h.result(), None))
                    except Exception as e:  # noqa: BLE001
                        out.append((None, e))
            return out
        res = self._map_drives(
            lambda d: d.read_version(bucket, obj, version_id),
            drives=[self.drives[p] for p in positions])
        DATA_PATH.record_meta_read_round(len(positions), len(positions))
        return res

    def _read_version_fanout(self, bucket, obj, version_id, mb) -> list:
        """The metadata read fan-out with the K+1 trim: read K+1
        drives first; accept only a unanimous, quorate, inline-object
        answer (streaming objects must see all N metas — the healthy
        read fast path keys off `any(m is None)`); otherwise read the
        REMAINING drives and merge, so every drive is still read
        exactly once and quorum/error classification matches the all-N
        oracle.  Unread positions are padded (None, None) — a shape no
        real drive outcome produces (failures always carry an error).

        Trim trades Python acceptance checks for one skipped drive
        read — a win only when the read plane is hot (rounds are
        shared and queued across requests).  On an idle server the
        serial page-cached read is cheaper than the checks, and idle
        single-request latency must match the oracle, so a cold plane
        takes the full fan-out."""
        k1 = (self.n - self.default_parity) + 1
        if (not metalanes.trim_enabled() or k1 >= self.n
                or mb is None or not mb.read_hot()):
            return self._read_positions(bucket, obj, version_id,
                                        list(range(self.n)), mb)
        first = list(range(k1))
        res1 = self._read_positions(bucket, obj, version_id, first, mb)
        if self._trim_acceptable(res1):
            DATA_PATH.record_meta_trim(True)
            full: list = [(None, None)] * self.n
            for pos, r in zip(first, res1):
                full[pos] = r
            return full
        DATA_PATH.record_meta_trim(False)
        rest = list(range(k1, self.n))
        res2 = self._read_positions(bucket, obj, version_id, rest, mb)
        full = [None] * self.n
        for pos, r in zip(first, res1):
            full[pos] = r
        for pos, r in zip(rest, res2):
            full[pos] = r
        return full

    def _trim_acceptable(self, res) -> bool:
        """A trimmed first round stands only when nothing about it
        could change with more drives: every read succeeded, all agree
        on one version (unanimity — a single dissenter might be the
        majority among the unread), the agreeing count already meets
        the object's own read quorum (guards per-object parity lower
        than the set default), and the elected version never touches
        shard files (inline/deleted) so no downstream path needs the
        full per-drive meta picture."""
        metas = [fi for fi, _ in res]
        if any(e is not None for _, e in res):
            return False
        if any(m is None for m in metas):
            return False
        keys = {Q._fi_key(m) for m in metas}
        if len(keys) != 1:
            return False
        read_quorum, _ = Q.object_quorum_from_meta(
            metas, self.n, self.default_parity)
        if len(metas) < read_quorum:
            return False
        fi = metas[0]
        return (fi.deleted or fi.inline_data is not None
                or bool(fi.parts and not fi.data_dir))

    def _fi_cache_store(self, bucket, obj, version_id, entry) -> None:
        # Bounded LRU: evict oldest-touched entries one at a time
        # (dict preserves insertion order; _read_metadata_cached
        # reinserts on hit, so iteration order IS recency order).  The
        # previous clear()-at-capacity wiped every hot entry whenever
        # a key scan overflowed the cache, zeroing the hit ratio.
        cache = self._fi_cache
        key = (bucket, obj, normalize_version_id(version_id))
        # Pop first: overwriting an existing dict key keeps its OLD
        # insertion slot, which would pin a re-stored hot entry at the
        # LRU end forever.
        cache.pop(key, None)
        while len(cache) >= self._FI_CACHE_MAX:
            try:
                cache.pop(next(iter(cache)))
            except (StopIteration, KeyError, RuntimeError):
                break  # racing eviction/clear — capacity is advisory
        cache[key] = (self._fi_gen.get(bucket, 0),
                      time.monotonic(), *entry)

    def _read_metadata_cached(self, bucket, obj, version_id=""):
        """GET-path metadata election with the parsed-quorum cache: a
        ranged GET fanned out as N segment requests (or HEAD followed by
        GET in the same request) elects xl.meta once, not N times.  Any
        write through this set bumps the bucket generation (_mark_dirty)
        and invalidates immediately; a short TTL bounds what another
        process's write can leave stale, same policy as bucket_exists."""
        key = (bucket, obj, normalize_version_id(version_id))
        hit = self._fi_cache.pop(key, None)
        if hit is not None:
            gen, stamp, fi, metas, errs = hit
            if (gen == self._fi_gen.get(bucket, 0)
                    and time.monotonic() - stamp < self._FI_CACHE_TTL):
                # Reinsert: a hit moves the entry to the MRU end so
                # LRU eviction tracks touch order, not insert order.
                self._fi_cache[key] = hit
                return fi, metas, errs
        entry = self._read_metadata(bucket, obj, version_id)
        self._fi_cache_store(bucket, obj, version_id, entry)
        return entry

    def _read_inline(self, bucket, obj, fi, metas, version_id) -> bytes:
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        dist = fi.erasure.distribution
        # Gather each drive's inline shard (already framed).
        shard_bytes: list[bytes | None] = [None] * (k + m)
        want_key = Q._fi_key(fi)
        for pos, meta in enumerate(metas):
            # Only trust shards from drives whose metadata matches the
            # elected version — a stale drive's inline shard is internally
            # consistent and would silently corrupt the read.
            if (meta is not None and meta.inline_data is not None
                    and Q._fi_key(meta) == want_key):
                shard_bytes[dist[pos] - 1] = meta.inline_data
        return self._decode_shard_files(shard_bytes, fi, fi.size)

    def _read_part(self, bucket, obj, fi, part_number, offset, length,
                   dst=None, healthy=None, report=None):
        """Ranged read of one part: fetch only the frames covering the
        block range, then run bitrot verify + reconstruction of missing
        rows as ONE fused device dispatch (north-star config #5; the
        parallelReader analogue of cmd/erasure-decode.go:101 with the
        verifying ReadAt of cmd/bitrot-streaming.go:142 moved on-device).

        HEALTHY reads (all k data shards present, metas agreed) take the
        verify-only fast path instead: batched bitrot VERDICTS (fused
        host kernel / device digests / pooled HighwayHash) plus a
        systematic gather — zero GF(2^8) work, since the data shards of
        a systematic code already are the plaintext.  Any verify or read
        failure falls back to the decode path below, which is also the
        byte-exactness oracle (MTPU_GET_FASTPATH=0).

        `dst`: optional writable memoryview of exactly `length` bytes;
        when given, the result is assembled straight into it (the
        get_object zero-copy assembly) and None is returned.  `healthy`:
        tri-state hint from the caller's metadata election (False =
        metas disagreed somewhere, skip the fast path).

        A digest mismatch is handled exactly like an I/O failure: the
        corrupt row is dropped and a spare shard is fetched.
        """
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        dist = fi.erasure.distribution
        part_size = fi.parts[part_number - 1].size
        shard_size = fi.erasure.shard_size
        algo = fi.erasure.bitrot_algo(part_number)
        hs = bitrot_io.digest_size(algo)
        b0 = offset // BLOCK_SIZE
        b1 = -(-(offset + length) // BLOCK_SIZE)
        frame = hs + shard_size
        path = f"{obj}/{fi.data_dir}/part.{part_number}"
        geo = self._range_geometry(fi, part_size, b0, b1)
        nb = geo["nb_full"]
        has_tail, tail_shard = geo["has_tail"], geo["tail_shard"]
        # Host fast path: shard files mmap'd straight into the fused
        # native verify+gather+reconstruct kernel — object bytes are
        # never copied by Python and never cross read() (north-star
        # config #5, host edition). Width-gated like the PUT side.
        fused_host = None
        if (not self._use_device and algo == "mxh256"
                and not _mesh_mode() and k + m <= 64):
            fused_host = _ecio_mod()
        co = coalesce.get() if coalesce.enabled() else None
        # Device-resident shard cache (ops/devcache.py): generation is
        # captured BEFORE any shard read so a racing write invalidates
        # the fill rather than the fill masking the write.  Only fully
        # verified fast-path reads fill; hits serve the verified host
        # copy with zero disk reads, zero uploads, zero dispatches.
        dcache = devcache_mod.get() if devcache_mod.enabled() else None
        dc_gen0 = (dcache.current_gen(self._devcache_owner, bucket)
                   if dcache is not None else 0)

        def read_shard(pos: int):
            """Fetch + structurally parse one shard's frame range.

            Returns (hashes (nb, 32), blocks (nb, S), tail or None, raw);
            full blocks are NOT hash-verified here — that happens batched
            on device (or in the fused native pass, which consumes `raw`).
            The (tiny) tail fragment verifies on host immediately.
            Successful reads feed the per-position EWMA that drives
            hedge ignition on serial hosts (failures don't: a fast
            error must not make a drive look fast).
            """
            t_rs = time.monotonic()
            d = self.drives[pos]
            if d is None:
                raise ErrDiskNotFound("offline")
            if fused_host is not None and isinstance(d, LocalDrive):
                raw = d.read_file_view(bucket, path, b0 * frame,
                                       (b1 - b0) * frame)
            else:
                raw = d.read_file(bucket, path, b0 * frame,
                                  (b1 - b0) * frame)
            buf = np.frombuffer(raw, dtype=np.uint8)
            expect = nb * frame + ((hs + tail_shard) if has_tail else 0)
            if buf.size != expect:
                raise ErrFileCorrupt(
                    f"shard segment {buf.size} != expected {expect}")
            frames = buf[:nb * frame].reshape(nb, frame)
            tail = None
            if has_tail:
                tail = bitrot_io.unframe_shard(
                    buf[nb * frame:].tobytes(), tail_shard, verify=True,
                    algo=algo)
            # Views, no copy: the selected rows are gathered into one
            # contiguous (nb, K, S) buffer in a single strided pass
            # below — copying here would double the memory traffic.
            self._note_read_ms(pos, (time.monotonic() - t_rs) * 1e3)
            return frames[:, :hs], frames[:, hs:], tail, buf[:nb * frame]

        order = Q.shuffle_by_distribution(list(range(self.n)), dist)
        # order[s] = drive position holding shard s. Data shards first,
        # parity as spares (cf. preferReaders, cmd/erasure-decode.go:101).
        rows: dict[int, tuple] = {}
        tried: set[int] = set()
        # Offline drives — physical holes AND breaker-open circuits —
        # can never yield a shard: skipping them up front means a
        # degraded read goes straight to the parity spares instead of
        # burning a retry round per dead position.
        candidates = [s for s in range(k + m)
                      if drive_available(self.drives[order[s]])]
        degraded = any(s < k for s in range(k + m) if s not in candidates)
        t_deg = time.monotonic() if degraded else 0.0
        lo = offset - b0 * BLOCK_SIZE

        def fast_path():
            """Verify-only healthy read.  Returns (res,) on success or
            None to fall back (bad rows already dropped from `rows` so
            the decode loop goes straight to the parity spares)."""
            t0 = time.monotonic()
            want = [s for s in range(k) if s not in rows]
            # Hedge gate: pool fan-out hosts hedge by default; the
            # 1-core serial host ignites only when the EWMAs show a
            # straggler (otherwise serial page-cache reads win).
            use_hedge = (
                _hedge_enabled() and want and not self._on_drive_pool()
                and (not self._serial_local()
                     or self._hedge_worthwhile([order[s] for s in want])))
            if use_hedge:
                spares = [s for s in candidates
                          if s >= k and s not in rows]
                abandoned = self._hedged_fetch(
                    read_shard, order, rows, tried, want, spares, k)
                for s in abandoned:
                    tried.discard(s)
                if any(s not in rows for s in range(k)):
                    # A parity spare won the race (or a data read
                    # failed): the row set isn't purely systematic, so
                    # the decode loop below reconstructs from these
                    # rows — no re-read, just GF work for the holes.
                    return None
            elif self._serial_local() or self._on_drive_pool():
                tried.update(want)
                for s in want:
                    rows[s] = read_shard(order[s])
            else:
                tried.update(want)
                rs = ospan.wrap_ctx(read_shard)
                futs = {s: self.pool.submit(rs, order[s])
                        for s in want}
                first_err = None
                for s, fut in futs.items():
                    try:
                        rows[s] = fut.result()
                    except Exception as e:  # noqa: BLE001
                        first_err = first_err or e
                if first_err is not None:
                    raise first_err
            full_bytes = nb * k * shard_size       # == nb * BLOCK_SIZE
            aligned = (dst is not None and lo == 0
                       and length >= full_bytes)
            body = dst[:full_bytes] if aligned else None
            t_read = time.monotonic()
            asm_s = 0.0
            y = None
            # Verify routing: under concurrent traffic (coalescer hot —
            # work queued/dispatching, recent occupancy >1, or another
            # read in flight) the bitrot digest rides the shared
            # dispatcher so many GETs verify in one kernel launch; a
            # lone stream keeps the direct fused path — no thread
            # handoff on the single-client latency path.  Byte-exact
            # either way (same digests, same comparisons).
            use_co = (co is not None and nb > 0
                      and (self._use_device
                           or co.hot(self.device_idx)))
            if nb and fused_host is not None and not use_co:
                # mxh256 host: ONE C pass verifies every frame AND
                # gathers the systematic rows straight into the final
                # object buffer — targets=[] means the GF unit is never
                # entered (verify time below includes that gather).
                y, okf, nbad = fused_host.get_verify(
                    [rows[s][3] for s in range(k)], list(range(k)),
                    nb, shard_size, k, m, [], out=body)
                if nbad:
                    for j in range(k):
                        if not okf[j]:
                            del rows[j]
                    return None
            elif nb:
                # Gather first (it IS the assembly either way), then
                # hash-verify: the device kernel returns verdict
                # digests only — no decoded blocks cross back — and
                # HighwayHash/host algos digest the mmap'd frames in
                # place via the strided kernel on the worker pool.
                tg = time.monotonic()
                if body is not None:
                    y = np.frombuffer(body, dtype=np.uint8).reshape(
                        nb, k, shard_size)
                else:
                    y = np.empty((nb, k, shard_size), dtype=np.uint8)
                for s in range(k):
                    y[:, s, :] = rows[s][1]
                asm_s += time.monotonic() - tg
                if use_co:
                    # Coalesced digest over the already-gathered rows
                    # (the gather IS the assembly, so this adds no
                    # copy): stacked with other requests' verify/encode
                    # digest work into one batched hash kernel.
                    h = co.submit(
                        ("digest", algo, shard_size),
                        y.reshape(nb * k, shard_size),
                        coalesce.make_digest_kernel(
                            algo, BATCH_BLOCKS * k if self._use_device
                            else 0),
                        weight=nb, device=self.device_idx)
                    try:
                        digests = h.result().reshape(nb, k, hs)
                        h.release()
                    except Exception:  # noqa: BLE001 — direct fallback
                        DATA_PATH.record_co_fallback()
                        digests = bitrot_io._hash_batch(
                            y.reshape(nb * k, shard_size),
                            algo).reshape(nb, k, hs)
                    got = [digests[:, s] for s in range(k)]
                elif algo in fused.DEVICE_ALGOS and self._use_device \
                        and bitrot_io.device_preferred(algo) \
                        and not _mesh_mode():
                    digests = np.asarray(fused.verify_and_transform(
                        y, k, m, tuple(range(k)), (), algo=algo,
                        device=self.device_idx)[0])
                    got = [digests[:, s] for s in range(k)]
                else:
                    got = self._hash_shard_frames(
                        [rows[s][3] for s in range(k)], nb, shard_size,
                        hs, algo)
                bad = [s for s in range(k)
                       if not np.array_equal(got[s], rows[s][0])]
                if bad:
                    for s in bad:
                        del rows[s]
                    return None
            t_verify = time.monotonic()
            ta = t_verify
            tail_np = None
            if has_tail:
                tail_np = np.concatenate(
                    [rows[s][2] for s in range(k)])[:geo["tail_len"]]
            if aligned:
                if tail_np is not None and length > full_bytes:
                    dst[full_bytes:length] = memoryview(
                        np.ascontiguousarray(
                            tail_np[:length - full_bytes]))
                res = None
            else:
                flat = (y.reshape(-1) if nb
                        else np.zeros(0, dtype=np.uint8))
                data = (np.concatenate([flat, tail_np])
                        if tail_np is not None else flat)
                view = data[lo:lo + length]
                if dst is not None:
                    dst[:length] = memoryview(np.ascontiguousarray(view))
                    res = None
                elif view.size == data.size:
                    res = memoryview(view)
                else:
                    res = view.tobytes()
            done = time.monotonic()
            DATA_PATH.record_healthy_read(
                length, read_s=t_read - t0, verify_s=t_verify - t_read,
                assemble_s=asm_s + (done - ta))
            ospan.record("engine.read", t_read - t0)
            ospan.record("engine.verify", t_verify - t_read)
            ospan.record("engine.assemble", asm_s + (done - ta))
            if report is not None:
                # Hot-tier evidence: this segment was served purely by
                # the full-k verify (dict ops are GIL-atomic enough for
                # the prefetch pool's one-writer-per-segment pattern).
                report["fast"] = report.get("fast", 0) + 1
            if dcache is not None and nb and y is not None:
                # Fill with private copies: `y` may view the caller's
                # dst buffer or a fused-host arena, and `tail_np` the
                # mmap'd frames — the cache must own its bytes.
                dcache.fill(
                    (self._devcache_owner, bucket, obj, part_number,
                     fi.data_dir, b0, b1, algo),
                    dc_gen0, np.array(y, copy=True),
                    tail=(np.array(tail_np, copy=True)
                          if tail_np is not None else None),
                    device=self.device_idx)
            return (res,)

        def devcache_hit(e, boff):
            """Assemble the read from a resident verified entry — the
            exact fast_path assembly over cached rows, no disk, no
            device, no dispatch.  Returns (res,) or None (entry lacks
            the tail fragment this range needs)."""
            t0 = time.monotonic()
            if has_tail and e.tail is None:
                return None
            y = e.host[boff:boff + nb] if nb else None
            tail_np = e.tail[:geo["tail_len"]] if has_tail else None
            full_bytes = nb * k * shard_size
            aligned = (dst is not None and lo == 0
                       and length >= full_bytes)
            if aligned:
                if nb:
                    dst[:full_bytes] = memoryview(y.reshape(-1))
                if tail_np is not None and length > full_bytes:
                    dst[full_bytes:length] = memoryview(
                        np.ascontiguousarray(
                            tail_np[:length - full_bytes]))
                res = None
            else:
                flat = (y.reshape(-1) if nb
                        else np.zeros(0, dtype=np.uint8))
                data = (np.concatenate([flat, tail_np])
                        if tail_np is not None else flat)
                view = data[lo:lo + length]
                if dst is not None:
                    dst[:length] = memoryview(np.ascontiguousarray(view))
                    res = None
                elif view.size == data.size:
                    res = memoryview(view)
                else:
                    res = view.tobytes()
            done = time.monotonic()
            DATA_PATH.record_healthy_read(
                length, read_s=0.0, verify_s=0.0, assemble_s=done - t0)
            ospan.record("engine.assemble", done - t0)
            if report is not None:
                report["fast"] = report.get("fast", 0) + 1
            return (res,)

        # BLOCK_SIZE % k gate: the padded (non-dividing k) layout needs
        # per-block trimming, which the generic assembly already does.
        if (_get_fastpath() and healthy is not False and not degraded
                and BLOCK_SIZE % k == 0
                and all(s in candidates for s in range(k))):
            if dcache is not None:
                found = dcache.lookup_range(
                    self._devcache_owner, bucket, obj, part_number,
                    fi.data_dir, algo, b0, b1)
                if found is not None:
                    got = devcache_hit(*found)
                    if got is not None:
                        return got[0]
            # Inflight-read signal: a GET-only storm queues no encode
            # work, so concurrency is only visible to hot() through
            # this counter.
            if co is not None:
                co.note_read(1, device=self.device_idx)
            try:
                got = fast_path()
            except (StorageError, OSError):
                got = None
            finally:
                if co is not None:
                    co.note_read(-1, device=self.device_idx)
            if got is not None:
                return got[0]
            DATA_PATH.record_fastpath_fallback()

        if report is not None:
            # Decode/reconstruct involvement (fallback, degraded, or
            # fast path disabled): correct bytes, but not the full-k
            # verify-only read the hot tier requires for a fill.
            report["taint"] = True
        sel: list[int] = []
        missing: list[int] = []
        out = None
        y_fused = None
        while True:
            active = [s for s in candidates
                      if s not in tried and s not in rows][:max(k - len(rows), 0)]
            if len(rows) < k and not active:
                raise ErrErasureReadQuorum(
                    f"{bucket}/{obj}: only {len(rows)}/{k} shards readable")
            # A degraded read always fans out: the surviving-shard
            # fetches are mmap/pread + native digest work that release
            # the GIL, so overlapping them pays even on the 1-core host
            # (unlike the healthy path, where the K reads are page-cache
            # hits and pool hops only add latency).
            with ospan.span("engine.read"):
                if (self._serial_local() and not degraded) \
                        or self._on_drive_pool():
                    for s in active:
                        tried.add(s)
                        try:
                            rows[s] = read_shard(order[s])
                        except Exception:  # noqa: BLE001 — spare read
                            pass
                elif _hedge_enabled():
                    # Hedged degraded fan-out: instead of a barrier on
                    # ALL active futures (one tail-slow survivor stalls
                    # the stripe), take the first k arrivals and cover
                    # stragglers/failures from the remaining spares.
                    remaining = [s for s in candidates
                                 if s not in tried and s not in rows
                                 and s not in active]
                    abandoned = self._hedged_fetch(
                        read_shard, order, rows, tried, active,
                        remaining, k)
                    for s in abandoned:
                        tried.discard(s)
                else:
                    rs = ospan.wrap_ctx(read_shard)
                    futs = {}
                    for s in active:
                        tried.add(s)
                        futs[s] = self.pool.submit(rs, order[s])
                    for s, fut in futs.items():
                        try:
                            rows[s] = fut.result()
                        except Exception:  # noqa: BLE001 — spare read
                            pass
            if len(rows) < k:
                continue
            sel = sorted(rows)[:k]
            missing = [s for s in range(k) if s not in sel]
            if not nb:
                break
            if fused_host is not None:
                # ONE native pass over the mmap'd segments: digest every
                # chosen row, gather data rows, reconstruct the missing
                # ones. A digest mismatch surfaces exactly like an I/O
                # failure: drop the row, fetch a spare, run again.
                with ospan.span("engine.verify_decode"):
                    y_fused, okf, nbad = fused_host.get_verify(
                        [rows[s][3] for s in sel], sel, nb, shard_size,
                        k, m, missing)
                if nbad:
                    for j, s in enumerate(sel):
                        if not okf[j]:
                            del rows[s]
                    y_fused = None
                    continue
                break
            # ONE dispatch: digests of the K chosen rows + reconstruction
            # of the missing data rows from those same HBM-resident bytes.
            x = np.empty((nb, k, shard_size), dtype=np.uint8)
            for i, s in enumerate(sel):
                x[:, i, :] = rows[s][1]                      # (nb, K, S)
            with ospan.span("engine.verify_decode"):
                if algo in fused.DEVICE_ALGOS and self._use_device \
                        and bitrot_io.device_preferred(algo) \
                        and not _mesh_mode():
                    if co is not None:
                        # Coalesced fused verify(+reconstruct): the
                        # same (sel, missing) geometry from concurrent
                        # degraded reads shares one device launch.
                        h = co.submit(
                            ("vt", k, m, tuple(sel), tuple(missing),
                             algo, shard_size), x,
                            self._vt_kernel(k, m, tuple(sel),
                                            tuple(missing), algo,
                                            device=self.device_idx),
                            weight=nb, device=self.device_idx)
                        try:
                            digests, dev_out = h.result()
                            h.release()
                        except Exception:  # noqa: BLE001 — fallback
                            DATA_PATH.record_co_fallback()
                            digests, dev_out = fused.verify_and_transform(
                                x, k, m, tuple(sel), tuple(missing),
                                algo=algo, device=self.device_idx)
                            digests = np.asarray(digests)
                    else:
                        digests, dev_out = fused.verify_and_transform(
                            x, k, m, tuple(sel), tuple(missing),
                            algo=algo, device=self.device_idx)
                        digests = np.asarray(digests)
                else:
                    # Host path (host-hashed algorithm, no TPU, or an
                    # algo whose native host kernel beats its device
                    # verify — bitrot_io.device_preferred): digest on
                    # host, reconstruct via the backend picker only if
                    # rows are missing.
                    flat = x.reshape(nb * k, shard_size)
                    if co is not None and co.hot(self.device_idx):
                        h = co.submit(
                            ("digest", algo, shard_size), flat,
                            coalesce.make_digest_kernel(algo),
                            weight=nb, device=self.device_idx)
                        try:
                            digests = h.result().reshape(nb, k, hs)
                            h.release()
                        except Exception:  # noqa: BLE001 — fallback
                            DATA_PATH.record_co_fallback()
                            digests = bitrot_io._hash_batch(
                                flat, algo).reshape(nb, k, hs)
                    else:
                        digests = bitrot_io._hash_batch(
                            flat, algo).reshape(nb, k, hs)
                    dev_out = self._transform(
                        k, m, x, tuple(sel), tuple(missing)) if missing \
                        else None
            bad = [sel[i] for i in range(k)
                   if not np.array_equal(digests[:, i], rows[sel[i]][0])]
            if not bad:
                out = np.asarray(dev_out) if missing else None
                break
            for s in bad:
                del rows[s]

        # Gather the K data rows in shard order. When nothing is
        # missing, sel IS [0..k), so x already holds them — the full
        # blocks then flow to the caller with no further copy (when
        # BLOCK_SIZE divides evenly, x's natural layout IS the data).
        ta_asm = time.monotonic()
        y = None
        if nb:
            if y_fused is not None:
                y = y_fused
            elif not missing:
                y = x
            else:
                y = np.empty((nb, k, shard_size), dtype=np.uint8)
                for s in range(k):
                    if s in sel:
                        y[:, s] = x[:, sel.index(s)]
                    else:
                        y[:, s] = out[:, missing.index(s)]

        # Tail fragment: reconstruct missing rows via the CPU oracle codec
        # (a partial block is tiny — not worth a device dispatch).
        tails: dict[int, np.ndarray] = {}
        if has_tail:
            tails = {s: rows[s][2] for s in sel}
            t_missing = [s for s in range(k) if s not in tails]
            if t_missing:
                shards_in = [tails.get(s) for s in range(k + m)]
                rec = self._cpu(k, m).reconstruct(shards_in, data_only=True)
                for s in t_missing:
                    tails[s] = rec[s]

        pieces = []
        if nb:
            if BLOCK_SIZE % k == 0:
                # k*shard_size == BLOCK_SIZE: zero-pad-free layout,
                # the whole full-block range is one contiguous view.
                pieces.append(y.reshape(-1))
            else:
                flat = y.reshape(nb, k * shard_size)
                for bi in range(nb):
                    pieces.append(flat[bi, :BLOCK_SIZE])
        if has_tail:
            tail_block = np.concatenate([tails[s] for s in range(k)])
            pieces.append(tail_block[:geo["tail_len"]])
        if not pieces:
            res: bytes | memoryview = b""
        elif len(pieces) == 1:
            view = pieces[0][lo:lo + length]
            # Full aligned segment: hand the caller a view of the
            # gather buffer (freshly allocated per call, never reused)
            # — skipping the final tobytes copy, ~25% of a cached GET.
            if view.size == pieces[0].size:
                res = memoryview(view)
            else:
                res = view.tobytes()
        elif lo == 0 and sum(p.size for p in pieces) == length:
            res = b"".join(memoryview(np.ascontiguousarray(p))
                           for p in pieces)
        else:
            data = np.concatenate(pieces)
            res = data[lo:lo + length].tobytes()
        ospan.record("engine.assemble", time.monotonic() - ta_asm)
        if degraded:
            DATA_PATH.record_degraded_read(length,
                                           time.monotonic() - t_deg)
        if dst is not None:
            # Fallback/decode result lands in the caller's buffer too —
            # one copy, same as the join it replaces.
            dst[:length] = res
            return None
        return res

    def _hash_shard_frames(self, bufs: list, nb: int, shard_size: int,
                           hs: int, algo: str) -> list[np.ndarray]:
        """Per-shard frame digests for the verify-only fast path.

        bufs[s] holds shard s's nb frames of (hs | shard_size).
        HighwayHash goes through the strided native kernel (digesting
        the frame data regions in place, no gather copy); other host
        algorithms hash via the batch hasher.  On multi-core hosts each
        shard is one worker-pool task — the native hash releases the
        GIL, so k shards verify concurrently; the 1-core bench host
        keeps the serial policy every other fan-out uses."""
        frame = hs + shard_size

        if algo.startswith("highwayhash") and bitrot_io._hh_native():
            from native.hh_native import hh256_frames_native

            def one(buf):
                return hh256_frames_native(buf, nb, frame, hs,
                                           shard_size)
        else:
            def one(buf):
                rows = np.ascontiguousarray(
                    np.frombuffer(buf, dtype=np.uint8).reshape(
                        nb, frame)[:, hs:])
                return bitrot_io._hash_batch(rows, algo)
        if self._serial_local() or self._on_drive_pool():
            return [one(b) for b in bufs]
        return list(self.pool.map(one, bufs))

    @staticmethod
    def _range_geometry(fi, part_size: int, b0: int, b1: int) -> dict:
        k = fi.erasure.data_blocks
        n_full_blocks = part_size // BLOCK_SIZE
        tail_len = part_size % BLOCK_SIZE
        tail_shard = -(-tail_len // k) if tail_len else 0
        has_tail = b1 > n_full_blocks
        nb_full = min(b1, n_full_blocks) - b0
        return {"nb_full": nb_full, "has_tail": has_tail,
                "tail_len": tail_len, "tail_shard": tail_shard,
                "expect": nb_full * fi.erasure.shard_size
                          + (tail_shard if has_tail else 0)}

    def _parse_shard_segment(self, raw: bytes, fi, geo: dict) -> np.ndarray:
        """Unframe + bitrot-verify one shard's frame range; enforce the
        exact expected logical length (short/corrupt => ErrFileCorrupt)."""
        row = bitrot_io.unframe_shard(raw, fi.erasure.shard_size,
                                      verify=True,
                                      algo=fi.erasure.bitrot_algo())
        if row.size != geo["expect"]:
            raise ErrFileCorrupt(
                f"shard segment {row.size} != expected {geo['expect']}")
        return row

    def _decode_shard_files(self, shard_bytes, fi, part_size) -> bytes:
        """Whole-object decode from full framed shard files (inline path):
        parse+verify what's present, then assemble."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        b1 = -(-part_size // BLOCK_SIZE)
        geo = self._range_geometry(fi, part_size, 0, b1)
        rows: list[np.ndarray | None] = [None] * (k + m)
        for s, data in enumerate(shard_bytes):
            if data is None:
                continue
            try:
                rows[s] = self._parse_shard_segment(data, fi, geo)
            except ErrFileCorrupt:
                rows[s] = None
        return self._assemble(rows, fi, part_size, 0, 0, part_size)

    def _assemble(self, rows, fi, part_size, b0=0, offset=0,
                  length=None) -> bytes:
        """Reconstruct missing rows (device batched matmul) and assemble
        the requested byte range from verified shard segments."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        shard_size = fi.erasure.shard_size
        if length is None:
            length = part_size - offset
        b1 = -(-(offset + length) // BLOCK_SIZE)
        geo = self._range_geometry(fi, part_size, b0, b1)
        nb_full, has_tail = geo["nb_full"], geo["has_tail"]
        tail_len, tail_shard = geo["tail_len"], geo["tail_shard"]

        if sum(1 for r in rows if r is not None) < k:
            raise ErrErasureReadQuorum("too many missing/corrupt shards")

        # Split rows into the full-block matrix and the tail segment.
        full_mat: list[np.ndarray | None] = [None] * (k + m)
        tails: list[np.ndarray | None] = [None] * (k + m)
        expect_full = nb_full * shard_size
        for s, r in enumerate(rows):
            if r is None:
                continue
            full_mat[s] = r[:expect_full].reshape(nb_full, shard_size) \
                if nb_full else np.zeros((0, shard_size), np.uint8)
            tails[s] = r[expect_full:] if has_tail else None

        # Reconstruct missing data rows (device batched matmul).
        missing = [s for s in range(k) if full_mat[s] is None]
        if missing and nb_full:
            avail = [s for s in range(k + m) if full_mat[s] is not None][:k]
            x = np.stack([full_mat[s] for s in avail], axis=1)  # (B, K, S)
            out = self._transform(k, m, x, tuple(avail), tuple(missing))
            for j, s in enumerate(missing):
                full_mat[s] = out[:, j, :]
        if has_tail:
            t_missing = [s for s in range(k) if tails[s] is None]
            if t_missing:
                t_avail = [s for s in range(k + m) if tails[s] is not None]
                cpu = self._cpu(k, m)
                shards_in = [tails[s] if s in t_avail else None
                             for s in range(k + m)]
                rec = cpu.reconstruct(shards_in, data_only=True)
                for s in t_missing:
                    tails[s] = rec[s]

        # Assemble: per block, concat K data segments, trim to block len.
        pieces = []
        for bi in range(nb_full):
            block = np.concatenate([full_mat[s][bi] for s in range(k)])
            pieces.append(block[:BLOCK_SIZE])
        if has_tail:
            tail_block = np.concatenate([tails[s] for s in range(k)])
            pieces.append(tail_block[:tail_len])
        data = np.concatenate(pieces) if pieces else np.zeros(0, np.uint8)
        lo = offset - b0 * BLOCK_SIZE
        return data[lo:lo + length].tobytes()

    # -- head / delete -------------------------------------------------------

    def update_object_metadata(self, bucket: str, obj: str,
                               fi: FileInfo) -> None:
        """Merge fi.metadata onto every drive's OWN copy of the
        version (updateObjectMetadata, cmd/erasure-object.go:1513).

        Each drive's xl.meta carries that drive's erasure index and —
        for small objects — that drive's inline SHARD; writing one
        drive's FileInfo to all of them would overwrite every inline
        shard with the same bytes and destroy the stripe. So the
        update is per drive: read its own version, replace only the
        metadata, write back."""
        def upd(d):
            own = d.read_version(bucket, obj, fi.version_id,
                                 read_data=True)
            own.metadata = dict(fi.metadata)
            d.update_metadata(bucket, obj, own)
        res = self._map_drives(upd)
        # Same write quorum every other mutation enforces: a stamp
        # landing on a minority would lose the quorum-merged read
        # election while reading as acknowledged.
        ok = sum(1 for _, e in res if e is None)
        if ok < self.n // 2 + 1:
            errs = [e for _, e in res if e is not None]
            raise errs[0] if errs else ErrObjectNotFound(
                f"{bucket}/{obj}")
        # The stamp changed the served metadata: cached FileInfos (and
        # hot-tier entries, which carry the FileInfo) are now stale.
        self._mark_dirty(bucket)

    def head_object(self, bucket: str, obj: str,
                    version_id: str = "") -> FileInfo:
        # Hot-tier metadata hit: a fresh-generation entry proves the
        # version is current (every mutation bumps the bucket
        # generation), so HEAD skips the drive stat fan-out.
        if self.hot_tier is not None and self.hot_tier.enabled:
            hfi = self.hot_tier.lookup_meta(bucket, obj, version_id)
            if hfi is not None:
                return hfi
        # HEAD always stats (a peer's write must be visible immediately)
        # but WRITES THROUGH the FileInfo cache: the common HEAD-then-GET
        # of one server request elects xl.meta once.
        entry = self._read_metadata(bucket, obj, version_id)
        self._fi_cache_store(bucket, obj, version_id, entry)
        fi = entry[0]
        if fi.deleted and not version_id:
            raise ErrObjectNotFound(f"{bucket}/{obj} (delete marker)")
        return fi

    def delete_object(self, bucket: str, obj: str, version_id: str = "",
                      versioned: bool = False) -> FileInfo | None:
        """Delete a version, or write a delete marker when the bucket is
        versioned and no explicit version was named
        (cf. DeleteObject, /root/reference/cmd/erasure-object.go:1038)."""
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        with self.nslock.write_locked(bucket, obj):
            return self._delete_object_locked(bucket, obj, version_id,
                                              versioned)

    def _delete_object_locked(self, bucket, obj, version_id="",
                              versioned=False) -> FileInfo | None:
        write_quorum = self.n // 2 + 1
        if versioned and version_id == "":
            dm = FileInfo(volume=bucket, name=obj, version_id=new_uuid(),
                          mod_time_ns=_now_ns(), deleted=True)

            def mark(d):
                try:
                    d.delete_version(bucket, obj, mark_delete=True, fi=dm)
                except ErrFileNotFound:
                    # Delete marker on a nonexistent object is still legal.
                    d.write_metadata(bucket, obj, dm)

            res = self._map_drives(mark)
            err = Q.reduce_write_quorum_errs([e for _, e in res],
                                             write_quorum)
            if err is not None:
                raise err
            self._mark_dirty(bucket)
            return dm

        vid = normalize_version_id(version_id)
        res = self._map_drives(lambda d: d.delete_version(bucket, obj, vid))
        errs = [e for _, e in res]
        nf = (ErrFileNotFound, ErrFileVersionNotFound)
        if errs and all(isinstance(e, nf) for e in errs):
            if any(isinstance(e, ErrFileVersionNotFound) for e in errs):
                raise ErrVersionNotFound(f"{bucket}/{obj}@{version_id}")
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        # A drive that never had the version counts as success.
        errs = [None if isinstance(e, nf) else e for e in errs]
        err = Q.reduce_write_quorum_errs(errs, write_quorum)
        if err is not None:
            raise err
        self._mark_dirty(bucket)
        return None

    # -- listing (walk-based; metacache comes later) -------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 10000,
                     marker: str = "") -> list[FileInfo]:
        """Quorum-merged listing through the metacache: the parallel
        drive walk + per-object quorum election runs once and is cached
        (memory + persisted) until a write to the bucket invalidates it
        (cf. /root/reference/cmd/metacache-server-pool.go:59)."""
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        return self.metacache.list(bucket, prefix, marker, max_keys)

    def list_object_names(self, bucket: str,
                          prefix: str = "") -> list[str]:
        """All object names with ANY version present (delete-marked
        included) — the versions-listing walk needs names the
        latest-version listing filters out."""
        names: set[str] = set()
        res = self._map_drives(
            lambda d: [n for n, _ in d.walk_dir(bucket, prefix)])
        for entries, e in res:
            if e is None:
                names.update(entries)
        return sorted(names)

    def list_object_versions(self, bucket: str, obj: str) -> list[FileInfo]:
        """Quorum-elected version history: every drive's xl.meta is
        read and each version must be agreed on by a majority of the
        responding drives — a stale drive must not serve a stale (or
        resurrect a deleted) version history (cf. readAllFileInfo +
        findFileInfoInQuorum, cmd/erasure-metadata-utils.go)."""
        res = self._map_drives(
            lambda d: d.read_all(bucket, f"{obj}/xl.meta"))
        lists: list[list[FileInfo]] = []
        for raw, err in res:
            if err is not None or raw is None:
                continue
            try:
                lists.append(
                    XLMeta.from_bytes(raw).list_versions(bucket, obj))
            except StorageError:
                continue
        if not lists:
            # legacy xl.json objects: one unversioned entry per drive
            from ..storage import xlmeta_v1
            res = self._map_drives(
                lambda d: d.read_all(bucket,
                                     f"{obj}/{xlmeta_v1.XL_JSON}"))
            for raw, err in res:
                if err is not None or raw is None:
                    continue
                try:
                    fi = xlmeta_v1.parse_xl_json(raw, bucket, obj)
                    fi.is_latest = True
                    lists.append([fi])
                except StorageError:
                    continue
        if not lists:
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        counts: dict[tuple, int] = {}
        keep: dict[tuple, FileInfo] = {}
        for lst in lists:
            for fi in lst:
                key = (fi.version_id, fi.mod_time_ns, fi.data_dir,
                       fi.size, fi.deleted, fi.metadata.get("etag", ""))
                counts[key] = counts.get(key, 0) + 1
                keep.setdefault(key, fi)
        # Read quorum = the erasure geometry's data_blocks, taken from
        # the LATEST erasure-bearing version and applied to every
        # version — matching objectQuorumFromMeta
        # (cf. /root/reference/cmd/erasure-metadata.go:389-417, which
        # derives ONE read quorum from the latest FileInfo; the k==m
        # "+1" there applies to WRITE quorum only). A version readable
        # at k shards must stay listable with only k metadata copies
        # reachable — lifecycle/replication iterating versions must
        # not skip durable objects. Objects with no erasure-bearing
        # version (pure delete-marker history) fall back to a simple
        # majority.
        # ... but only a latest FileInfo that is ITSELF present on at
        # least half the drives may set the quorum (getLatestFileInfo,
        # cmd/erasure-healing-common.go:196) — unquorate metadata from
        # one stale/corrupt drive must not become its own majority.
        quorum = self.n // 2 + 1
        trust_floor = max(self.n // 2, 1)
        for key, fi in sorted(keep.items(),
                              key=lambda kv: -kv[1].mod_time_ns):
            if fi.erasure is not None and counts[key] >= trust_floor:
                quorum = fi.erasure.data_blocks
                break
        if len(lists) < quorum:
            raise ErrErasureReadQuorum(
                f"{bucket}/{obj}: {len(lists)}/{self.n} version lists")
        out = [keep[k] for k, c in counts.items() if c >= quorum]
        if not out:
            raise ErrObjectNotFound(f"{bucket}/{obj} (no version in "
                                    "quorum)")
        out.sort(key=lambda fi: (-fi.mod_time_ns, fi.version_id))
        return out

    # -- internals -----------------------------------------------------------

    def _cleanup_tmp(self, tmp_id: str) -> None:
        def rm(d):
            d.delete(SYS_VOL, f"{TMP_DIR}/{tmp_id}", recursive=True)
        self._map_drives(rm)

    def _undo_publish(self, bucket, obj, fi, errs) -> None:
        """Roll back a publish fan-out that missed write quorum: drives
        that already renamed the version in must not keep it, or a
        REJECTED PUT becomes readable whenever the successes still
        reach READ quorum (read < write).  Best-effort — a drive that
        also fails the undo is left for dangling-object cleanup."""
        def undo(pos):
            if errs[pos] is not None or self.drives[pos] is None:
                return
            try:
                self.drives[pos].delete_version(bucket, obj,
                                                fi.version_id)
            except StorageError:
                pass
        self._map_drives_positions(undo)
