"""erasureServerPools equivalent: the top-level ObjectLayer.

Pools are independent ErasureSets stacks added over time for capacity.
Writes go to the pool already holding the object, else the pool with the
most free space; reads/deletes probe pools in order (cf.
erasureServerPools.getPoolIdx, /root/reference/cmd/erasure-server-pool.go:373,
PutObject :812, GetObjectNInfo :661).
"""

from __future__ import annotations

from ..storage.errors import (ErrBucketExists, ErrBucketNotFound,
                              ErrObjectNotFound, ErrVersionNotFound,
                              StorageError)
from ..storage.xlmeta import FileInfo
from .sets import ErasureSets


class ServerPools:
    """The ObjectLayer facade over one or more pools."""

    def __init__(self, pools: list[ErasureSets]):
        if not pools:
            raise ValueError("need at least one pool")
        self.pools = pools
        self.deployment_id = pools[0].deployment_id
        # Pool indices excluded from NEW placement (decommission drain):
        # reads/deletes keep probing them, writes route elsewhere.
        self.draining: set[int] = set()
        # pool idx -> background.decom.Decommissioner (admin status).
        self.decommissions: dict[int, object] = {}
        # Pool-sticky multipart ids relocated off a drained pool:
        # old full upload id -> new full upload id (see background/decom).
        self.upload_relocations: dict[str, str] = {}

    # -- pool placement ------------------------------------------------------

    def set_draining(self, idx: int, flag: bool = True) -> None:
        if not 0 <= idx < len(self.pools):
            raise ValueError(f"no pool {idx}")
        if flag:
            if len(self.placement_pools()) <= 1 \
                    and idx not in self.draining:
                raise ValueError(
                    "cannot drain the last placement-eligible pool")
            self.draining.add(idx)
        else:
            self.draining.discard(idx)

    def placement_pools(self) -> list[int]:
        """Pool indices new writes may land on (draining excluded)."""
        out = [i for i in range(len(self.pools)) if i not in self.draining]
        return out or list(range(len(self.pools)))

    def _pool_with_object(self, bucket: str, obj: str,
                          version_id: str = "") -> int | None:
        for i, p in enumerate(self.pools):
            try:
                p.head_object(bucket, obj, version_id)
                return i
            except (ErrObjectNotFound, ErrVersionNotFound,
                    ErrBucketNotFound):
                continue
            # Anything else (e.g. read-quorum loss) must propagate: treating
            # a degraded pool as "object not here" would place an overwrite
            # PUT on another pool and leave a permanently stale duplicate.
        return None

    def get_pool_idx(self, bucket: str, obj: str) -> int:
        """Existing pool wins; else most free space, ties broken by the
        LOWEST pool index (cf. getPoolIdx, erasure-server-pool.go:373 —
        the deterministic tie-break keeps placement stable across
        restarts: equal-capacity pools must not flip-flop an object
        between pools on re-PUT).

        A sole candidate short-circuits BEFORE the existence probe (the
        reference's SinglePool() fast path): the probe needs read
        quorum, and a key whose last write died mid-publish (one drive
        holds the version — below quorum) would otherwise 503 every
        overwrite PUT forever.  With one eligible pool there is no
        placement decision to protect, so the write must always
        proceed.  Draining pools are excluded outright: an existing
        copy there must NOT attract the write (the decommission mover
        owns that copy), so the overwrite re-places by free space."""
        cands = self.placement_pools()
        if len(cands) == 1:
            return cands[0]
        existing = self._pool_with_object(bucket, obj)
        if existing is not None and existing not in self.draining:
            return existing
        frees = {i: self.pools[i].disk_usage()["free"] for i in cands}
        best = max(frees.values())
        return min(i for i in cands if frees[i] == best)

    # -- pool lifecycle ------------------------------------------------------

    def add_pool(self, es: ErasureSets) -> int:
        """Attach a freshly-formatted pool to a RUNNING deployment
        (cf. the reference's restart-time capacity expansion — here it
        is live, via the admin pool/add API).  The bucket set is
        replicated onto the new pool BEFORE it becomes placement-
        eligible, so a write routed there the instant it appears can
        never hit ErrBucketNotFound."""
        if es.deployment_id != self.deployment_id:
            raise ValueError(
                f"pool deployment id {es.deployment_id} != "
                f"{self.deployment_id}")
        for b in self.list_buckets():
            try:
                es.make_bucket(b)
            except ErrBucketExists:
                pass
        self.pools.append(es)
        # A pool adopted at runtime joins the shared hot tier the
        # original pools attached at boot (all-local sets only).
        tier = getattr(self, "hot_tier", None)
        if tier is not None:
            from .hotcache import attach_sets
            attach_sets(es, tier)
        return len(self.pools) - 1

    # -- bucket ops ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        """Fan out to ALL pools atomically: a hard failure on any pool
        rolls back the copies THIS call created (pre-existing copies
        stay), so the bucket never half-exists across pools."""
        created: list[int] = []
        errs = []
        for i, p in enumerate(self.pools):
            try:
                p.make_bucket(bucket)
                created.append(i)
                errs.append(None)
            except ErrBucketExists as e:
                errs.append(e)
            except StorageError:
                for j in created:
                    try:
                        self.pools[j].delete_bucket(bucket)
                    except StorageError:
                        pass        # best-effort unwind; state converges
                raise
        if errs and all(isinstance(e, ErrBucketExists) for e in errs):
            raise ErrBucketExists(bucket)

    def bucket_exists(self, bucket: str, cached: bool = False) -> bool:
        # cached=True is the write hot path's pre-check (see
        # ErasureSet.bucket_exists); explicit queries always stat.
        return any(p.bucket_exists(bucket, cached=cached)
                   for p in self.pools)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        """Fan out to ALL pools atomically: a hard failure partway (the
        classic case — force=False and one pool still holds objects)
        re-creates the bucket on the pools already deleted from, so
        existence state converges instead of diverging (the old code
        deleted the empty pools' copies and then raised, leaving the
        bucket visible on some pools and gone on others)."""
        deleted: list[int] = []
        errs = []
        for i, p in enumerate(self.pools):
            try:
                p.delete_bucket(bucket, force=force)
                deleted.append(i)
                errs.append(None)
            except ErrBucketNotFound as e:
                errs.append(e)
            except StorageError:
                for j in deleted:
                    try:
                        self.pools[j].make_bucket(bucket)
                    except StorageError:
                        pass        # best-effort unwind; state converges
                raise
        if errs and all(isinstance(e, ErrBucketNotFound) for e in errs):
            raise ErrBucketNotFound(bucket)

    def list_buckets(self) -> list[str]:
        names: set[str] = set()
        for p in self.pools:
            names.update(p.list_buckets())
        return sorted(names)

    # -- object ops ----------------------------------------------------------

    def put_object(self, bucket: str, obj: str, data: bytes,
                   **kw) -> FileInfo:
        if not self.bucket_exists(bucket, cached=True):
            raise ErrBucketNotFound(bucket)
        idx = self.get_pool_idx(bucket, obj)
        fi = self.pools[idx].put_object(bucket, obj, data, **kw)
        try:
            # Placement tag for observability (the x-mtpu-pool response
            # header + loadgen's placement-skew histogram); never stored.
            fi.pool_idx = idx
        except (AttributeError, TypeError):
            pass
        return fi

    def _read_pool_idx(self, bucket: str, obj: str,
                       version_id: str = "") -> int | None:
        """Pool a read should serve from.  Normally first-hit probe
        order (placement guarantees at most one copy); while a drain is
        active the mover's copy-then-delete window can briefly hold the
        SAME object on two pools — and an overwrite during the drain
        lands on a non-draining pool while the stale source still
        shadows it in probe order — so reads become latest-wins
        (compare mod_time_ns across every pool that answers).  Named
        versions stay first-hit: version ids are unique."""
        if not self.draining or version_id:
            return self._pool_with_object(bucket, obj, version_id)
        best: tuple[int, int] | None = None    # (mod_time_ns, idx)
        for i, p in enumerate(self.pools):
            try:
                fi = p.head_object(bucket, obj, version_id)
            except (ErrObjectNotFound, ErrVersionNotFound,
                    ErrBucketNotFound):
                continue
            if best is None or fi.mod_time_ns > best[0]:
                best = (fi.mod_time_ns, i)
        return None if best is None else best[1]

    def get_object(self, bucket: str, obj: str, offset: int = 0,
                   length: int = -1, version_id: str = ""):
        last: StorageError | None = None
        if self.draining and not version_id:
            idx = self._read_pool_idx(bucket, obj)
            if idx is not None:
                return self.pools[idx].get_object(bucket, obj, offset,
                                                  length, version_id)
        else:
            for p in self.pools:
                try:
                    return p.get_object(bucket, obj, offset, length,
                                        version_id)
                except (ErrObjectNotFound, ErrVersionNotFound) as e:
                    last = e
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        raise last or ErrObjectNotFound(f"{bucket}/{obj}")

    def get_object_iter(self, bucket: str, obj: str, offset: int = 0,
                        length: int = -1, version_id: str = ""):
        """Streaming read: (fi, chunk iterator); falls back to a whole-
        object read on backends without a streaming path."""
        last: StorageError | None = None
        order = list(self.pools)
        if self.draining and not version_id:
            idx = self._read_pool_idx(bucket, obj)
            order = [self.pools[idx]] if idx is not None else []
        for p in order:
            try:
                if hasattr(p, "get_object_iter"):
                    return p.get_object_iter(bucket, obj, offset, length,
                                             version_id)
                fi, data = p.get_object(bucket, obj, offset, length,
                                        version_id)
                return fi, iter((data,))
            except (ErrObjectNotFound, ErrVersionNotFound) as e:
                last = e
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        raise last or ErrObjectNotFound(f"{bucket}/{obj}")

    def sendfile_plan(self, bucket: str, obj: str, offset: int = 0,
                      length: int = -1, version_id: str = ""):
        """Kernel-send plan (fi, [FilePlan...]) from the pool that owns
        the object, or None — never raises; the normal read path is the
        error oracle."""
        order = list(self.pools)
        if self.draining and not version_id:
            idx = self._read_pool_idx(bucket, obj)
            order = [self.pools[idx]] if idx is not None else []
        for p in order:
            sp = getattr(p, "sendfile_plan", None)
            if sp is None:
                continue
            try:
                got = sp(bucket, obj, offset, length, version_id)
            except StorageError:
                return None
            if got is not None:
                return got
        return None

    def head_object(self, bucket: str, obj: str,
                    version_id: str = "") -> FileInfo:
        last: StorageError | None = None
        if self.draining and not version_id:
            idx = self._read_pool_idx(bucket, obj)
            if idx is not None:
                return self.pools[idx].head_object(bucket, obj,
                                                   version_id)
        else:
            for p in self.pools:
                try:
                    return p.head_object(bucket, obj, version_id)
                except (ErrObjectNotFound, ErrVersionNotFound) as e:
                    last = e
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        raise last or ErrObjectNotFound(f"{bucket}/{obj}")

    def delete_object(self, bucket: str, obj: str, version_id: str = "",
                      versioned: bool = False):
        if self.draining and not (versioned and version_id == ""):
            # Mid-drain an object can transiently live on two pools
            # (copied, source not yet reaped).  A hard delete must
            # remove EVERY copy — deleting only the first probe hit
            # would let the surviving duplicate resurrect the object.
            hit = False
            res = None
            for p in self.pools:
                try:
                    res = p.delete_object(bucket, obj, version_id,
                                          versioned)
                    hit = True
                except (ErrObjectNotFound, ErrVersionNotFound,
                        ErrBucketNotFound):
                    continue
            if hit:
                return res
            if not self.bucket_exists(bucket):
                raise ErrBucketNotFound(bucket)
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        idx = self._pool_with_object(bucket, obj, version_id)
        if idx is None:
            if not self.bucket_exists(bucket):
                raise ErrBucketNotFound(bucket)
            if versioned and version_id == "":
                # Delete marker still lands on the placement pool.
                return self.pools[self.get_pool_idx(
                    bucket, obj)].delete_object(bucket, obj, version_id,
                                                versioned)
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        return self.pools[idx].delete_object(bucket, obj, version_id,
                                             versioned)

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "",
                     max_keys: int = 10000) -> list[FileInfo]:
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        merged: dict[str, FileInfo] = {}
        for p in self.pools:
            try:
                for fi in p.list_objects(bucket, prefix,
                                         marker=marker,
                                         max_keys=max_keys):
                    prev = merged.get(fi.name)
                    if prev is None or fi.mod_time_ns > prev.mod_time_ns:
                        merged[fi.name] = fi
            except ErrBucketNotFound:
                continue
        return [merged[k] for k in sorted(merged)][:max_keys]

    def list_object_names(self, bucket: str,
                          prefix: str = "") -> list[str]:
        names: set[str] = set()
        for p in self.pools:
            for es in getattr(p, "sets", [p]):
                try:
                    names.update(es.list_object_names(bucket, prefix))
                except StorageError:
                    continue
        return sorted(names)

    def list_object_versions(self, bucket: str, obj: str) -> list[FileInfo]:
        """Version history merged across pools (an overwrite during a
        drain legitimately splits an object's versions between the
        draining source and the destination), deduped by version id,
        newest first — the single-pool result is unchanged."""
        merged: dict[str, FileInfo] = {}
        found = False
        for p in self.pools:
            try:
                vers = p.list_object_versions(bucket, obj)
            except (ErrObjectNotFound, StorageError):
                continue
            found = True
            for fi in vers:
                prev = merged.get(fi.version_id)
                if prev is None or fi.mod_time_ns > prev.mod_time_ns:
                    merged[fi.version_id] = fi
        if not found:
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        out = sorted(merged.values(),
                     key=lambda fi: (-fi.mod_time_ns, fi.version_id))
        for i, fi in enumerate(out):
            fi.is_latest = i == 0
        return out

    # -- multipart -----------------------------------------------------------

    def new_multipart_upload(self, bucket: str, obj: str, **kw) -> str:
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        idx = self.get_pool_idx(bucket, obj)
        uid = self.pools[idx].new_multipart_upload(bucket, obj, **kw)
        # Uploads are pool-sticky: encode the pool into the id.
        return f"{idx}.{uid}"

    def _split_upload_id(self, upload_id: str) -> tuple[int, str]:
        # A drained pool's pending uploads were re-staged elsewhere; the
        # client still holds the OLD id, so follow the relocation map
        # (persisted in the decom journal, reloaded at boot).
        upload_id = self.upload_relocations.get(upload_id, upload_id)
        idx, _, rest = upload_id.partition(".")
        try:
            idx = int(idx)
        except ValueError:
            from .multipart import ErrUploadNotFound
            raise ErrUploadNotFound(upload_id) from None
        if not 0 <= idx < len(self.pools):
            from .multipart import ErrUploadNotFound
            raise ErrUploadNotFound(upload_id) from None
        return idx, rest

    def put_object_part(self, bucket: str, obj: str, upload_id: str,
                        part_number: int, data: bytes):
        idx, uid = self._split_upload_id(upload_id)
        return self.pools[idx].put_object_part(bucket, obj, uid,
                                               part_number, data)

    def complete_multipart_upload(self, bucket: str, obj: str,
                                  upload_id: str, parts, **kw):
        idx, uid = self._split_upload_id(upload_id)
        return self.pools[idx].complete_multipart_upload(bucket, obj, uid,
                                                         parts, **kw)

    def abort_multipart_upload(self, bucket: str, obj: str,
                               upload_id: str) -> None:
        idx, uid = self._split_upload_id(upload_id)
        self.pools[idx].abort_multipart_upload(bucket, obj, uid)

    def list_parts(self, bucket: str, obj: str, upload_id: str):
        idx, uid = self._split_upload_id(upload_id)
        return self.pools[idx].list_parts(bucket, obj, uid)

    def list_multipart_uploads(self, bucket: str,
                               prefix: str = "") -> list[dict]:
        out = []
        for i, p in enumerate(self.pools):
            for u in p.list_multipart_uploads(bucket, prefix):
                u = dict(u)
                u["upload_id"] = f"{i}.{u['upload_id']}"
                out.append(u)
        return sorted(out, key=lambda u: (u["object"], u["upload_id"]))

    def update_object_metadata(self, bucket: str, obj: str, fi) -> None:
        """Merge-updated FileInfo back onto the stripe (the
        updateObjectMetadata seam, cmd/erasure-object.go:1513).
        Erasure sets update per drive so each drive keeps its own
        inline shard + erasure index (ErasureSet.update_object_metadata);
        single-copy backends take the FileInfo whole."""
        for p in self.pools:
            for es in getattr(p, "sets", [p]):
                try:
                    if hasattr(es, "update_object_metadata"):
                        es.update_object_metadata(bucket, obj, fi)
                        return
                    res = es._map_drives(
                        lambda d: d.update_metadata(bucket, obj, fi))
                    if any(e is None for _, e in res):
                        return
                except StorageError:
                    continue
        raise ErrObjectNotFound(f"{bucket}/{obj}")

    # -- heal ----------------------------------------------------------------

    def heal_object(self, bucket: str, obj: str, version_id: str = "",
                    **kw):
        idx = self._pool_with_object(bucket, obj)
        if idx is None:
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        return self.pools[idx].heal_object(bucket, obj, version_id, **kw)

    def heal_bucket(self, bucket: str) -> dict:
        out = {}
        for i, p in enumerate(self.pools):
            healed = p.heal_bucket(bucket)
            if healed:
                out[i] = healed
        return out

    # -- capacity / status ---------------------------------------------------

    def disk_usage(self) -> dict:
        """Cluster capacity summed over every pool (admin info / usage
        accounting see ONE namespace, not per-pool slices)."""
        total = free = 0
        for p in self.pools:
            du = p.disk_usage()
            total += du["total"]
            free += du["free"]
        return {"total": total, "free": free}

    def pool_status(self) -> list[dict]:
        """Per-pool capacity + drain state rows (admin `pools` listing
        and the mtpu_pool_* metric families)."""
        out = []
        for i, p in enumerate(self.pools):
            du = p.disk_usage()
            row = {"pool": i, "total": du["total"], "free": du["free"],
                   "draining": i in self.draining}
            d = self.decommissions.get(i)
            if d is not None:
                row["decommission"] = d.status()
            out.append(row)
        return out
