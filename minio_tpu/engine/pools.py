"""erasureServerPools equivalent: the top-level ObjectLayer.

Pools are independent ErasureSets stacks added over time for capacity.
Writes go to the pool already holding the object, else the pool with the
most free space; reads/deletes probe pools in order (cf.
erasureServerPools.getPoolIdx, /root/reference/cmd/erasure-server-pool.go:373,
PutObject :812, GetObjectNInfo :661).
"""

from __future__ import annotations

from ..storage.errors import (ErrBucketExists, ErrBucketNotFound,
                              ErrObjectNotFound, ErrVersionNotFound,
                              StorageError)
from ..storage.xlmeta import FileInfo
from .sets import ErasureSets


class ServerPools:
    """The ObjectLayer facade over one or more pools."""

    def __init__(self, pools: list[ErasureSets]):
        if not pools:
            raise ValueError("need at least one pool")
        self.pools = pools
        self.deployment_id = pools[0].deployment_id

    # -- pool placement ------------------------------------------------------

    def _pool_with_object(self, bucket: str, obj: str,
                          version_id: str = "") -> int | None:
        for i, p in enumerate(self.pools):
            try:
                p.head_object(bucket, obj, version_id)
                return i
            except (ErrObjectNotFound, ErrVersionNotFound,
                    ErrBucketNotFound):
                continue
            # Anything else (e.g. read-quorum loss) must propagate: treating
            # a degraded pool as "object not here" would place an overwrite
            # PUT on another pool and leave a permanently stale duplicate.
        return None

    def get_pool_idx(self, bucket: str, obj: str) -> int:
        """Existing pool wins; else most free space
        (cf. getPoolIdx, erasure-server-pool.go:373).

        Single pool short-circuits BEFORE the existence probe (the
        reference's SinglePool() fast path): the probe needs read
        quorum, and a key whose last write died mid-publish (one drive
        holds the version — below quorum) would otherwise 503 every
        overwrite PUT forever.  With one pool there is no placement
        decision to protect, so the write must always proceed."""
        if len(self.pools) == 1:
            return 0
        existing = self._pool_with_object(bucket, obj)
        if existing is not None:
            return existing
        frees = [p.disk_usage()["free"] for p in self.pools]
        return max(range(len(frees)), key=lambda i: frees[i])

    # -- bucket ops ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        errs = []
        for p in self.pools:
            try:
                p.make_bucket(bucket)
                errs.append(None)
            except StorageError as e:
                errs.append(e)
        if errs and all(isinstance(e, ErrBucketExists) for e in errs):
            raise ErrBucketExists(bucket)
        real = [e for e in errs
                if e is not None and not isinstance(e, ErrBucketExists)]
        if real:
            raise real[0]

    def bucket_exists(self, bucket: str, cached: bool = False) -> bool:
        # cached=True is the write hot path's pre-check (see
        # ErasureSet.bucket_exists); explicit queries always stat.
        return any(p.bucket_exists(bucket, cached=cached)
                   for p in self.pools)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        errs = []
        for p in self.pools:
            try:
                p.delete_bucket(bucket, force=force)
                errs.append(None)
            except StorageError as e:
                errs.append(e)
        if errs and all(isinstance(e, ErrBucketNotFound) for e in errs):
            raise ErrBucketNotFound(bucket)
        real = [e for e in errs
                if e is not None and not isinstance(e, ErrBucketNotFound)]
        if real:
            raise real[0]

    def list_buckets(self) -> list[str]:
        names: set[str] = set()
        for p in self.pools:
            names.update(p.list_buckets())
        return sorted(names)

    # -- object ops ----------------------------------------------------------

    def put_object(self, bucket: str, obj: str, data: bytes,
                   **kw) -> FileInfo:
        if not self.bucket_exists(bucket, cached=True):
            raise ErrBucketNotFound(bucket)
        return self.pools[self.get_pool_idx(bucket, obj)].put_object(
            bucket, obj, data, **kw)

    def get_object(self, bucket: str, obj: str, offset: int = 0,
                   length: int = -1, version_id: str = ""):
        last: StorageError | None = None
        for p in self.pools:
            try:
                return p.get_object(bucket, obj, offset, length, version_id)
            except (ErrObjectNotFound, ErrVersionNotFound) as e:
                last = e
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        raise last or ErrObjectNotFound(f"{bucket}/{obj}")

    def get_object_iter(self, bucket: str, obj: str, offset: int = 0,
                        length: int = -1, version_id: str = ""):
        """Streaming read: (fi, chunk iterator); falls back to a whole-
        object read on backends without a streaming path."""
        last: StorageError | None = None
        for p in self.pools:
            try:
                if hasattr(p, "get_object_iter"):
                    return p.get_object_iter(bucket, obj, offset, length,
                                             version_id)
                fi, data = p.get_object(bucket, obj, offset, length,
                                        version_id)
                return fi, iter((data,))
            except (ErrObjectNotFound, ErrVersionNotFound) as e:
                last = e
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        raise last or ErrObjectNotFound(f"{bucket}/{obj}")

    def head_object(self, bucket: str, obj: str,
                    version_id: str = "") -> FileInfo:
        last: StorageError | None = None
        for p in self.pools:
            try:
                return p.head_object(bucket, obj, version_id)
            except (ErrObjectNotFound, ErrVersionNotFound) as e:
                last = e
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        raise last or ErrObjectNotFound(f"{bucket}/{obj}")

    def delete_object(self, bucket: str, obj: str, version_id: str = "",
                      versioned: bool = False):
        idx = self._pool_with_object(bucket, obj, version_id)
        if idx is None:
            if not self.bucket_exists(bucket):
                raise ErrBucketNotFound(bucket)
            if versioned and version_id == "":
                # Delete marker still lands on the placement pool.
                return self.pools[self.get_pool_idx(
                    bucket, obj)].delete_object(bucket, obj, version_id,
                                                versioned)
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        return self.pools[idx].delete_object(bucket, obj, version_id,
                                             versioned)

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "",
                     max_keys: int = 10000) -> list[FileInfo]:
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        merged: dict[str, FileInfo] = {}
        for p in self.pools:
            try:
                for fi in p.list_objects(bucket, prefix,
                                         marker=marker,
                                         max_keys=max_keys):
                    prev = merged.get(fi.name)
                    if prev is None or fi.mod_time_ns > prev.mod_time_ns:
                        merged[fi.name] = fi
            except ErrBucketNotFound:
                continue
        return [merged[k] for k in sorted(merged)][:max_keys]

    def list_object_names(self, bucket: str,
                          prefix: str = "") -> list[str]:
        names: set[str] = set()
        for p in self.pools:
            for es in getattr(p, "sets", [p]):
                try:
                    names.update(es.list_object_names(bucket, prefix))
                except StorageError:
                    continue
        return sorted(names)

    def list_object_versions(self, bucket: str, obj: str) -> list[FileInfo]:
        for p in self.pools:
            try:
                return p.list_object_versions(bucket, obj)
            except (ErrObjectNotFound, StorageError):
                continue
        raise ErrObjectNotFound(f"{bucket}/{obj}")

    # -- multipart -----------------------------------------------------------

    def new_multipart_upload(self, bucket: str, obj: str, **kw) -> str:
        if not self.bucket_exists(bucket):
            raise ErrBucketNotFound(bucket)
        idx = self.get_pool_idx(bucket, obj)
        uid = self.pools[idx].new_multipart_upload(bucket, obj, **kw)
        # Uploads are pool-sticky: encode the pool into the id.
        return f"{idx}.{uid}"

    @staticmethod
    def _split_upload_id(upload_id: str) -> tuple[int, str]:
        idx, _, rest = upload_id.partition(".")
        try:
            return int(idx), rest
        except ValueError:
            from .multipart import ErrUploadNotFound
            raise ErrUploadNotFound(upload_id) from None

    def put_object_part(self, bucket: str, obj: str, upload_id: str,
                        part_number: int, data: bytes):
        idx, uid = self._split_upload_id(upload_id)
        return self.pools[idx].put_object_part(bucket, obj, uid,
                                               part_number, data)

    def complete_multipart_upload(self, bucket: str, obj: str,
                                  upload_id: str, parts, **kw):
        idx, uid = self._split_upload_id(upload_id)
        return self.pools[idx].complete_multipart_upload(bucket, obj, uid,
                                                         parts, **kw)

    def abort_multipart_upload(self, bucket: str, obj: str,
                               upload_id: str) -> None:
        idx, uid = self._split_upload_id(upload_id)
        self.pools[idx].abort_multipart_upload(bucket, obj, uid)

    def list_parts(self, bucket: str, obj: str, upload_id: str):
        idx, uid = self._split_upload_id(upload_id)
        return self.pools[idx].list_parts(bucket, obj, uid)

    def list_multipart_uploads(self, bucket: str,
                               prefix: str = "") -> list[dict]:
        out = []
        for i, p in enumerate(self.pools):
            for u in p.list_multipart_uploads(bucket, prefix):
                u = dict(u)
                u["upload_id"] = f"{i}.{u['upload_id']}"
                out.append(u)
        return sorted(out, key=lambda u: (u["object"], u["upload_id"]))

    def update_object_metadata(self, bucket: str, obj: str, fi) -> None:
        """Merge-updated FileInfo back onto the stripe (the
        updateObjectMetadata seam, cmd/erasure-object.go:1513).
        Erasure sets update per drive so each drive keeps its own
        inline shard + erasure index (ErasureSet.update_object_metadata);
        single-copy backends take the FileInfo whole."""
        for p in self.pools:
            for es in getattr(p, "sets", [p]):
                try:
                    if hasattr(es, "update_object_metadata"):
                        es.update_object_metadata(bucket, obj, fi)
                        return
                    res = es._map_drives(
                        lambda d: d.update_metadata(bucket, obj, fi))
                    if any(e is None for _, e in res):
                        return
                except StorageError:
                    continue
        raise ErrObjectNotFound(f"{bucket}/{obj}")

    # -- heal ----------------------------------------------------------------

    def heal_object(self, bucket: str, obj: str, version_id: str = "",
                    **kw):
        idx = self._pool_with_object(bucket, obj)
        if idx is None:
            raise ErrObjectNotFound(f"{bucket}/{obj}")
        return self.pools[idx].heal_object(bucket, obj, version_id, **kw)

    def heal_bucket(self, bucket: str) -> dict:
        out = {}
        for i, p in enumerate(self.pools):
            healed = p.heal_bucket(bucket)
            if healed:
                out[i] = healed
        return out
