"""Multipart uploads: each part an independent erasure-coded stream.

The erasure-multipart equivalent (/root/reference/cmd/erasure-multipart.go:
NewMultipartUpload :39, PutObjectPart :400, CompleteMultipartUpload :771):
uploads stage under the reserved system volume, each part is encoded with
the SAME stripe geometry chosen at upload creation (so a 5 TiB object is
10,000 independent device-batched EC streams), and completion atomically
publishes all parts as one version via rename_data.

S3 semantics preserved: out-of-order part uploads, part overwrite
(last-write-wins), multipart ETag = md5(concat(part md5s))-N, minimum part
size for all but the last part.
"""

from __future__ import annotations

import hashlib
import time
import uuid

from ..observe import span as ospan
from ..observe.metrics import DATA_PATH
from ..parallel import pipeline as pl
from ..storage import bitrot_io
from ..storage.drive import MULTIPART_DIR, SYS_VOL, TMP_DIR
from ..storage.errors import (ErrErasureWriteQuorum, ErrFileNotFound,
                              ErrPathNotFound, StorageError)
from ..storage.xlmeta import (ErasureInfo, FileInfo, ObjectPartInfo,
                              XLMeta, new_uuid)
from ..utils import msgpackx, streams
from ..utils.crashpoints import crash_point
from . import quorum as Q
from .erasure_set import BATCH_BLOCKS, BLOCK_SIZE, ErasureSet

MIN_PART_SIZE = 5 * 1024 * 1024        # S3 minimum for all but the last part
MAX_PARTS = 10_000                     # docs/minio-limits.md:24-29

# Upload metadata keys (internal).
_MP_OBJECT_KEY = "x-mtpu-internal-mp-object"
_MP_BUCKET_KEY = "x-mtpu-internal-mp-bucket"


class ErrInvalidPart(StorageError):
    pass


class ErrInvalidPartOrder(StorageError):
    pass


class ErrPartTooSmall(StorageError):
    pass


class ErrUploadNotFound(StorageError):
    pass


def _upload_root(bucket: str, obj: str) -> str:
    h = hashlib.sha256(f"{bucket}/{obj}".encode()).hexdigest()[:32]
    return f"{MULTIPART_DIR}/{h}"


def _upload_path(bucket: str, obj: str, upload_id: str) -> str:
    return f"{_upload_root(bucket, obj)}/{upload_id}"


def new_multipart_upload(es: ErasureSet, bucket: str, obj: str, *,
                         metadata: dict | None = None,
                         parity: int | None = None) -> str:
    """Create an upload: fix the stripe geometry now so every part encodes
    identically (cf. newMultipartUpload, erasure-multipart.go:39)."""
    from ..storage.errors import ErrBucketNotFound
    if not es.bucket_exists(bucket):
        raise ErrBucketNotFound(bucket)
    parity = es.clamp_parity(parity)
    offline = sum(1 for d in es.drives if d is None)
    if offline and parity < es.n // 2:
        parity = min(parity + offline, es.n // 2)
    k = es.n - parity
    distribution = Q.hash_order(f"{bucket}/{obj}", es.n)
    upload_id = f"{new_uuid()}x{time.time_ns()}"
    meta = dict(metadata or {})
    meta[_MP_OBJECT_KEY] = obj
    meta[_MP_BUCKET_KEY] = bucket
    path = _upload_path(bucket, obj, upload_id)

    def write_one(pos):
        d = es.drives[pos]
        if d is None:
            raise ErrFileNotFound("offline")
        ec = ErasureInfo(data_blocks=k, parity_blocks=parity,
                         block_size=BLOCK_SIZE,
                         index=distribution[pos], distribution=distribution,
                         checksums=[])
        fi = FileInfo(volume=SYS_VOL, name=path, mod_time_ns=time.time_ns(),
                      metadata=meta, erasure=ec)
        d.write_metadata(SYS_VOL, path, fi)

    res = es._map_drives_positions(write_one)
    err = Q.reduce_write_quorum_errs([e for _, e in res], es.n // 2 + 1)
    if err is not None:
        raise err
    return upload_id


def _read_upload_fi(es: ErasureSet, bucket: str, obj: str,
                    upload_id: str) -> FileInfo:
    path = _upload_path(bucket, obj, upload_id)
    res = es._map_drives(lambda d: d.read_version(SYS_VOL, path))
    metas = [m for m, _ in res]
    n_found = sum(1 for m in metas if m is not None)
    if n_found < es._live_quorum():
        raise ErrUploadNotFound(f"{bucket}/{obj}: {upload_id}")
    return next(m for m in metas if m is not None)


def _part_meta_blob(part_number: int, etag: str, total: int,
                    algo: str) -> bytes:
    return msgpackx.packb({
        "n": part_number, "etag": etag, "size": total,
        "as": total, "mt": time.time_ns(), "algo": algo})


def put_object_part(es: ErasureSet, bucket: str, obj: str, upload_id: str,
                    part_number: int, data) -> ObjectPartInfo:
    """Encode one part as its own EC stream into the upload's staging dir
    (cf. PutObjectPart, erasure-multipart.go:400).  `data` is bytes or a
    reader — a reader streams through encode in O(batch) memory exactly
    like ErasureSet.put_object.

    The encode→write loop is a bounded StagePipeline: the shard appends
    of batch *i* run on the iter pool while batch *i+1* encodes on the
    caller's thread (the fused kernel and file IO both release the GIL,
    so the two stages genuinely overlap even on one core).  The encode
    is double-buffered so the in-flight batch survives the next fused
    put_frame.  Parts that fit one device batch skip staging-then-rename
    round trips: one encode, then a single per-drive fan-out that writes
    shard + rename + part meta together."""
    if not 1 <= part_number <= MAX_PARTS:
        raise ErrInvalidPart(f"part number {part_number}")
    fi = _read_upload_fi(es, bucket, obj, upload_id)
    ec = fi.erasure
    k, m = ec.data_blocks, ec.parity_blocks
    path = _upload_path(bucket, obj, upload_id)
    write_quorum = k + (1 if k == m else 0)

    stream = None
    if streams.is_reader(data):
        stream, data = data, b""

    # Stage under a unique name then rename into place, so a concurrent
    # re-upload of the same part can't interleave appends.
    stage = f"{path}/stage-{uuid.uuid4().hex}.{part_number}"
    algo = bitrot_io.write_algo()

    if stream is None and 0 < len(data) <= BATCH_BLOCKS * BLOCK_SIZE:
        # Small-part fast path (covers every trailing part of a large
        # upload): ONE device/native dispatch encodes the whole part,
        # then ONE fan-out per drive does shard write + publish rename +
        # part meta — instead of the streaming path's three rounds
        # (append, rename, meta) per drive.
        t0 = time.perf_counter()
        total = len(data)
        # ETag digest overlaps the encode dispatch (same bytes, same
        # order: byte-identical to hashlib.md5(data)).
        etag_md5 = streams.PipelinedMD5()
        etag_md5.feed(data)
        try:
            per_drive = Q.unshuffle_to_drives(
                es._encode_full(bytes(data), k, m, algo), ec.distribution)
        finally:
            etag_md5.close()
        etag = etag_md5.hexdigest()
        part_meta = _part_meta_blob(part_number, etag, total, algo)
        t1 = time.perf_counter()

        def put_one(pos):
            d = es.drives[pos]
            if d is None:
                raise ErrFileNotFound("offline")
            d.create_file(SYS_VOL, stage, per_drive[pos])
            d.rename_file(SYS_VOL, stage, SYS_VOL,
                          f"{path}/part.{part_number}")
            d.write_all(SYS_VOL, f"{path}/part.{part_number}.meta",
                        part_meta)

        try:
            res = es._map_drives_positions(put_one)
            err = Q.reduce_write_quorum_errs([e for _, e in res],
                                             write_quorum)
            if err is not None:
                raise err
            crash_point("mp.part.post_publish")
        finally:
            _cleanup_stage(es, stage)
        t2 = time.perf_counter()
        DATA_PATH.record_mp_batch(total, t1 - t0, t2 - t1)
        ospan.record("mp.encode", t1 - t0)
        ospan.record("mp.write", t2 - t1)
        return ObjectPartInfo(number=part_number, size=total,
                              actual_size=total, etag=etag)

    failed = [d is None for d in es.drives]
    md5 = streams.PipelinedMD5()
    total = 0

    def counted_chunks():
        nonlocal total
        for chunk, is_last in streams.batched_chunks(
                data, stream, BATCH_BLOCKS * BLOCK_SIZE):
            md5.update(chunk)
            total += len(chunk)
            yield chunk, is_last

    def shuffle(batch_shards):
        return Q.unshuffle_to_drives(batch_shards, ec.distribution)

    def write_batch(per_drive):
        def write_one(pos):
            d = es.drives[pos]
            if d is None or failed[pos]:
                return
            d.append_file(SYS_VOL, stage, per_drive[pos])

        for pos, (_, e) in enumerate(
                es._map_drives_positions(write_one)):
            if e is not None:
                failed[pos] = True
        if sum(1 for f in failed if not f) < write_quorum:
            raise ErrErasureWriteQuorum(
                f"{sum(1 for f in failed if not f)} < {write_quorum}")

    seen = [0]

    def record(read_s, compute_s, write_s):
        nbytes, seen[0] = total - seen[0], total
        DATA_PATH.record_mp_batch(nbytes, read_s + compute_s, write_s)
        # on_batch runs in the caller (traced) thread: bridge the
        # pipeline's measured stage times into the span tree.
        ospan.record("mp.encode", read_s + compute_s)
        ospan.record("mp.write", write_s)

    try:
        # Encode of batch i+1 (the `reads` pull) overlaps the shard
        # appends of batch i (one write in flight keeps per-drive
        # append order).  double_buffer: the async batch must survive
        # the next fused put_frame's arena reuse.
        pl.StagePipeline(es._iter_pool).run(
            es._encode_chunks(counted_chunks(), k, m, algo,
                              double_buffer=True),
            shuffle, write_batch, on_batch=record)

        etag = md5.hexdigest()
        part_meta = _part_meta_blob(part_number, etag, total, algo)

        def publish(pos):
            d = es.drives[pos]
            if d is None or failed[pos]:
                raise ErrFileNotFound("offline/failed")
            if total == 0:
                d.create_file(SYS_VOL, f"{path}/part.{part_number}", b"")
            else:
                d.rename_file(SYS_VOL, stage, SYS_VOL,
                              f"{path}/part.{part_number}")
            d.write_all(SYS_VOL, f"{path}/part.{part_number}.meta",
                        part_meta)

        with ospan.span("mp.publish"):
            res = es._map_drives_positions(publish)
        err = Q.reduce_write_quorum_errs([e for _, e in res],
                                         write_quorum)
        if err is not None:
            raise err
        crash_point("mp.part.post_publish")
    finally:
        md5.close()
        _cleanup_stage(es, stage)
    return ObjectPartInfo(number=part_number, size=total,
                          actual_size=total, etag=etag)


def _cleanup_stage(es: ErasureSet, stage: str) -> None:
    def rm(d):
        try:
            d.delete(SYS_VOL, stage)
        except StorageError:
            pass
    es._map_drives(rm)


def list_parts(es: ErasureSet, bucket: str, obj: str,
               upload_id: str) -> list[ObjectPartInfo]:
    """Quorum-agreed part list (cf. ListObjectParts)."""
    parts, _ = _list_parts_with_algos(es, bucket, obj, upload_id)
    return parts


def _list_parts_with_algos(es: ErasureSet, bucket: str, obj: str,
                           upload_id: str):
    """Part list + per-part bitrot algo map from the part metas."""
    _read_upload_fi(es, bucket, obj, upload_id)  # validates upload
    path = _upload_path(bucket, obj, upload_id)

    def scan(d) -> list[tuple]:
        keys = []
        try:
            names = d.list_raw(SYS_VOL, path)
        except StorageError:
            return keys
        for name in names:
            if not name.endswith(".meta") or not name.startswith("part."):
                continue
            try:
                pm = msgpackx.unpackb(d.read_all(SYS_VOL, f"{path}/{name}"))
            except StorageError:
                continue
            keys.append((pm["n"], pm["etag"], pm["size"], pm["as"],
                         pm.get("algo", "highwayhash256S")))
        return keys

    # One listing + meta-read sweep per drive, fanned out on the pool
    # (each sweep is a burst of small GIL-releasing syscalls).
    votes: dict[tuple, int] = {}
    for keys, _ in es._map_drives(scan):
        for key in keys or ():
            votes[key] = votes.get(key, 0) + 1
    quorum = es._live_quorum()
    best: dict[int, tuple] = {}
    for key, count in votes.items():
        if count >= quorum:
            n = key[0]
            if n not in best or votes[best[n]] < count:
                best[n] = key
    parts = [ObjectPartInfo(number=n, size=key[2], actual_size=key[3],
                            etag=key[1])
             for n, key in sorted(best.items())]
    algos = {n: key[4] for n, key in best.items()}
    return parts, algos


def upload_metadata(es: ErasureSet, bucket: str, obj: str,
                    upload_id: str) -> dict:
    """Client metadata an upload was created with (internal staging
    keys stripped) — what a relocated upload must be re-created with."""
    fi = _read_upload_fi(es, bucket, obj, upload_id)
    return {k: v for k, v in fi.metadata.items()
            if not k.startswith("x-mtpu-internal-mp-")}


def read_part_bytes(es: ErasureSet, bucket: str, obj: str,
                    upload_id: str, part_number: int) -> bytes:
    """Decode one STAGED part back to plaintext — the decommission
    mover's relocation read.  Staged parts are ordinary EC shard
    streams under the system volume, so the object read path decodes
    them once aimed at the staging layout: `_read_part` composes its
    path as `{name}/{data_dir}/part.{n}`, and name=<upload root>,
    data_dir=<upload id> lands exactly on `multipart/<hash>/<id>/part.n`."""
    fi_up = _read_upload_fi(es, bucket, obj, upload_id)
    ec = fi_up.erasure
    parts, algos = _list_parts_with_algos(es, bucket, obj, upload_id)
    info = next((p for p in parts if p.number == part_number), None)
    if info is None:
        raise ErrInvalidPart(f"part {part_number}")
    if info.size == 0:
        return b""
    # Client part numbers may be sparse; parts[] is indexed part_number-1
    # inside _read_part, so pad the synthetic list up to this part.
    pad = [ObjectPartInfo(number=i + 1, size=0, actual_size=0, etag="")
           for i in range(part_number - 1)]
    ec_read = ErasureInfo(
        data_blocks=ec.data_blocks, parity_blocks=ec.parity_blocks,
        block_size=ec.block_size, index=0,
        distribution=ec.distribution,
        checksums=[{"part": part_number,
                    "algo": algos.get(part_number, "highwayhash256S"),
                    "hash": b""}])
    fi = FileInfo(volume=SYS_VOL, name=_upload_root(bucket, obj),
                  data_dir=upload_id, size=info.size,
                  parts=pad + [info], erasure=ec_read)
    buf = bytearray(info.size)
    es._read_part(SYS_VOL, fi.name, fi, part_number, 0, info.size,
                  dst=memoryview(buf), healthy=False)
    return bytes(buf)


def abort_multipart_upload(es: ErasureSet, bucket: str, obj: str,
                           upload_id: str) -> None:
    # No _mark_dirty here on purpose: abort only deletes SYS_VOL
    # staging files — the object namespace never changed, so neither
    # the FileInfo cache nor the hot tier can hold anything stale
    # (complete_multipart_upload, which DOES publish, marks dirty).
    _read_upload_fi(es, bucket, obj, upload_id)  # 404 if unknown
    path = _upload_path(bucket, obj, upload_id)

    def rm(d):
        try:
            d.delete(SYS_VOL, path, recursive=True)
        except StorageError:
            pass
    es._map_drives(rm)


def list_multipart_uploads(es: ErasureSet, bucket: str,
                           prefix: str = "") -> list[dict]:
    """Active uploads for a bucket (cf. ListMultipartUploads)."""
    found: dict[str, dict] = {}
    for d in es.drives:
        if d is None:
            continue
        try:
            entries = list(d.walk_dir(SYS_VOL, MULTIPART_DIR + "/"))
        except StorageError:
            continue
        for rel, raw in entries:
            try:
                fi = XLMeta.from_bytes(raw).latest(SYS_VOL, rel)
            except StorageError:
                continue
            if fi.metadata.get(_MP_BUCKET_KEY) != bucket:
                continue
            o = fi.metadata.get(_MP_OBJECT_KEY, "")
            if prefix and not o.startswith(prefix):
                continue
            upload_id = rel.rsplit("/", 1)[-1]
            found.setdefault(upload_id, {
                "object": o, "upload_id": upload_id,
                "initiated_ns": fi.mod_time_ns})
    return sorted(found.values(), key=lambda u: (u["object"],
                                                 u["upload_id"]))


def complete_multipart_upload(es: ErasureSet, bucket: str, obj: str,
                              upload_id: str,
                              parts: list[tuple[int, str]], *,
                              versioned: bool = False) -> FileInfo:
    """Validate client part list, stitch staged parts into a fresh data
    dir, and publish one version atomically
    (cf. CompleteMultipartUpload, erasure-multipart.go:771)."""
    fi_up = _read_upload_fi(es, bucket, obj, upload_id)
    ec = fi_up.erasure
    listed, part_algos = _list_parts_with_algos(es, bucket, obj, upload_id)
    stored = {p.number: p for p in listed}
    if [n for n, _ in parts] != sorted({n for n, _ in parts}):
        raise ErrInvalidPartOrder("parts must be ascending and unique")

    chosen: list[ObjectPartInfo] = []
    for i, (n, etag) in enumerate(parts):
        p = stored.get(n)
        if p is None or p.etag != etag.strip('"'):
            raise ErrInvalidPart(f"part {n}")
        if p.size < MIN_PART_SIZE and i != len(parts) - 1:
            raise ErrPartTooSmall(
                f"part {n}: {p.size} < {MIN_PART_SIZE}")
        chosen.append(p)
    if not chosen:
        raise ErrInvalidPart("no parts")

    # S3 multipart ETag: md5 of the concatenated binary part md5s, -N.
    md5s = b"".join(bytes.fromhex(p.etag) for p in chosen)
    etag = f"{hashlib.md5(md5s).hexdigest()}-{len(chosen)}"
    total = sum(p.size for p in chosen)
    data_dir = new_uuid()
    version_id = new_uuid() if versioned else ""
    mod_time = time.time_ns()
    meta = {k: v for k, v in fi_up.metadata.items()
            if not k.startswith("x-mtpu-internal-mp-")}
    meta["etag"] = etag
    path = _upload_path(bucket, obj, upload_id)
    tmp_id = f"complete-{uuid.uuid4().hex}"
    k_, m_ = ec.data_blocks, ec.parity_blocks
    write_quorum = k_ + (1 if k_ == m_ else 0)

    def fi_for(pos: int) -> FileInfo:
        ec_pos = ErasureInfo(
            data_blocks=k_, parity_blocks=m_, block_size=BLOCK_SIZE,
            index=ec.distribution[pos], distribution=ec.distribution,
            checksums=[{"part": i + 1,
                        "algo": part_algos.get(p.number,
                                               "highwayhash256S"),
                        "hash": b""}
                       for i, p in enumerate(chosen)])
        return FileInfo(
            volume=bucket, name=obj, version_id=version_id,
            data_dir=data_dir, mod_time_ns=mod_time, size=total,
            metadata=meta,
            parts=[ObjectPartInfo(i + 1, p.size, p.actual_size, p.etag)
                   for i, p in enumerate(chosen)],
            erasure=ec_pos)

    def publish(pos):
        d = es.drives[pos]
        if d is None:
            raise ErrFileNotFound("offline")
        # Verify this drive actually has every chosen part — right shard
        # size AND the quorum-elected etag from the drive's own part meta.
        # Size alone is not enough: a drive that missed a same-size part
        # re-upload still holds the OLD content and would publish a torn
        # stripe whose bitrot frames are self-consistent (silent
        # corruption on reads that select this row).
        for p in chosen:
            logical = _shard_len(ec, p.size)
            want = bitrot_io.bitrot_shard_file_size(logical, ec.shard_size)
            if d.file_size(SYS_VOL, f"{path}/part.{p.number}") != want:
                raise ErrFileNotFound(f"part {p.number} incomplete here")
            try:
                pm = msgpackx.unpackb(
                    d.read_all(SYS_VOL, f"{path}/part.{p.number}.meta"))
            except StorageError:
                raise ErrFileNotFound(f"part {p.number} meta missing here") \
                    from None
            if pm.get("etag") != p.etag or pm.get("size") != p.size:
                raise ErrFileNotFound(f"part {p.number} stale here")
        # Renumber: client part numbers may be sparse; on disk the object
        # uses contiguous part.1..part.N.
        for i, p in enumerate(chosen):
            d.rename_file(SYS_VOL, f"{path}/part.{p.number}",
                          SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.{i + 1}")
        crash_point("mp.complete.publish")
        d.rename_data(SYS_VOL, f"{TMP_DIR}/{tmp_id}", fi_for(pos),
                      bucket, obj)

    # The publish mutates the object namespace: hold the same write lock
    # as PUT/DELETE so a concurrent overwrite can't interleave per-drive
    # metadata writes (cf. NSLock in CompleteMultipartUpload,
    # erasure-multipart.go:771).  Each drive's publish is a chain of
    # stats + meta reads + renames — force the pool fan-out so the
    # per-drive chains assemble concurrently instead of serially, even
    # on the 1-core host (the work is syscalls, not Python).
    t0 = time.perf_counter()
    with es.nslock.write_locked(bucket, obj, timeout=30.0), \
            ospan.span("mp.publish"):
        res = es._map_drives_positions(publish, parallel=True)
    DATA_PATH.record_mp_complete(time.perf_counter() - t0)
    errs = [e for _, e in res]
    err = Q.reduce_write_quorum_errs(errs, write_quorum)
    if err is not None:
        # Roll back so the upload stays retryable (S3 allows retrying a
        # failed CompleteMultipartUpload): un-stage any parts parked in
        # tmp, drop the sub-quorum published version where publish
        # succeeded, and KEEP the upload dir.
        def rollback(pos):
            d = es.drives[pos]
            if d is None:
                return
            for i, p in enumerate(chosen):
                try:
                    d.rename_file(SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.{i + 1}",
                                  SYS_VOL, f"{path}/part.{p.number}")
                except StorageError:
                    pass
            if errs[pos] is None:
                try:
                    d.delete_version(bucket, obj, version_id)
                except StorageError:
                    pass
            try:
                d.delete(SYS_VOL, f"{TMP_DIR}/{tmp_id}", recursive=True)
            except StorageError:
                pass
        es._map_drives_positions(rollback)
        raise err
    crash_point("mp.complete.post_publish")

    # Success: sweep staging + the whole upload dir.
    def rm(d):
        for p_ in (f"{TMP_DIR}/{tmp_id}", path):
            try:
                d.delete(SYS_VOL, p_, recursive=True)
            except StorageError:
                pass
    es._map_drives(rm)
    es._mark_dirty(bucket)
    return fi_for(0)


def _shard_len(ec: ErasureInfo, part_size: int) -> int:
    return ec.shard_file_size(part_size)
