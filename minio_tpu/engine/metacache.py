"""Listing metacache: streamed quorum-merged walks with persisted,
resumable continuations.

The cmd/metacache-*.go equivalent, streamed the way the reference
streams it (metacache-set.go listPath + metacache-stream.go):

- the walk is a GENERATOR: each of the asked drives serves bounded
  pages (walk_page, with subtree pruning past the resume marker), a
  k-way merge quorum-votes per name, and entries flow out in lexical
  order — memory is O(asked_drives x page), never O(bucket);
- results persist as COMPRESSED SEGMENTS (zlib msgpack, ~SEG_ENTRIES
  names each) plus a small index keyed by (bucket, prefix); a later
  page whose marker lands inside persisted territory streams from the
  matching segment — across calls AND across server restarts — and
  the live walk resumes exactly where persistence stopped;
- the listing quorum is tunable (MTPU_LIST_ASK: "strict" = every
  drive, or a count; default majority), the askDisks role
  (cmd/metacache-set.go:92).

Bucket writes bump a generation counter that invalidates affected
caches (the metacache-manager role).
"""

from __future__ import annotations

import hashlib
import heapq
import os
import threading
import time
import zlib

from ..storage.drive import SYS_VOL
from ..storage.errors import StorageError
from ..storage.xlmeta import FileInfo, XLMeta
from ..utils import msgpackx
from . import quorum as Q

CACHE_TTL = 30.0            # seconds a cache stays valid without writes
CACHE_DIR = "metacache"
SEG_ENTRIES = 2000          # entries per persisted segment
WALK_PAGE = 1000            # per-drive page size


def _ask_count(n_online: int) -> int:
    """How many drives a listing asks (cf. askDisks,
    cmd/metacache-set.go:92): default majority; MTPU_LIST_ASK a count
    or "strict" (all)."""
    v = os.environ.get("MTPU_LIST_ASK", "")
    if v == "strict":
        return n_online
    if v.isdigit() and int(v) > 0:
        return min(int(v), n_online)
    return max(1, n_online // 2 + 1)


class Metacache:
    def __init__(self, es):
        self.es = es
        self._mu = threading.Lock()
        self._gen: dict[str, int] = {}          # bucket -> generation
        # (bucket, prefix, gen) -> state dict:
        #   {"at": ts, "segs": [[last_name, seq], ...],
        #    "done": bool, "last": str, "next_seq": int}
        self._idx: dict[tuple, dict] = {}
        self._seg_cache: tuple | None = None    # (path, entries) LRU-1
        self._persisted_paths: dict[str, set] = {}
        self.walks = 0                          # streams opened
        self.streamed_entries = 0               # entries pulled live

    # -- invalidation --------------------------------------------------------

    def bump(self, bucket: str) -> None:
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1
            for key in [k for k in self._idx if k[0] == bucket]:
                del self._idx[key]
            self._seg_cache = None
            paths = self._persisted_paths.pop(bucket, set())
        # Drop persisted caches for this bucket too; other nodes fall
        # back to the TTL bound (the reference's metacache life window).
        for path in paths:
            def rm(d, p=path):
                d.delete(SYS_VOL, p, recursive=True)
            try:
                self.es._map_drives(rm)
            except StorageError:
                pass

    def _generation(self, bucket: str) -> int:
        with self._mu:
            return self._gen.get(bucket, 0)

    # -- streamed walk + quorum merge (metacache-set.go listPath) ------------

    def _stream(self, bucket: str, prefix: str, after: str = "",
                info: dict | None = None):
        """Quorum-agreed FileInfo generator in lexical name order.

        Every asked drive serves bounded pages; a k-way merge groups
        per name; a name needs metadata agreement among the asked
        drives' LIVE copies (find_file_info_in_quorum with the quorum
        shrinking as drives fail mid-walk, like the old ok_drives
        accounting) to be listed. If EVERY asked drive fails the
        stream raises — a truncated walk must never read as a
        complete listing. Pass `info` to learn post-hoc whether any
        drive failed (callers then skip caching the result)."""
        self.walks += 1
        online = [d for d in self.es.drives if d is not None]
        if not online:
            raise StorageError("listing failed: no drives online")
        asked = online[:_ask_count(len(online))]
        if info is None:
            info = {}
        info["failed"] = 0
        info["asked"] = len(asked)

        def pages(d):
            cursor = after
            while True:
                try:
                    entries, eof = d.walk_page(bucket, prefix,
                                               after=cursor,
                                               limit=WALK_PAGE)
                except StorageError:
                    info["failed"] += 1
                    return
                yield from entries
                if eof or not entries:
                    return
                cursor = entries[-1][0]

        merged = heapq.merge(*(pages(d) for d in asked),
                             key=lambda e: e[0])
        cur_name, cur_raws = None, []

        def resolve(name, raws):
            fis = []
            for raw in raws:
                try:
                    fis.append(XLMeta.from_bytes(raw).latest(bucket,
                                                             name))
                except StorageError:
                    continue
            alive = max(1, info["asked"] - info["failed"])
            try:
                fi = Q.find_file_info_in_quorum(fis, max(1, alive // 2))
            except StorageError:
                return None
            return None if fi.deleted else fi

        for name, raw in merged:
            if name == cur_name:
                cur_raws.append(raw)
                continue
            if cur_name is not None:
                fi = resolve(cur_name, cur_raws)
                if fi is not None:
                    self.streamed_entries += 1
                    yield fi
            cur_name, cur_raws = name, [raw]
        if cur_name is not None:
            fi = resolve(cur_name, cur_raws)
            if fi is not None:
                self.streamed_entries += 1
                yield fi
        if info["failed"] >= info["asked"]:
            raise StorageError(
                f"listing failed on all {info['asked']} asked drives")

    # -- persisted segments (metacache-stream.go persistence) ----------------

    def _base_path(self, bucket: str, prefix: str) -> str:
        h = hashlib.sha256(
            f"{bucket}\x00{prefix}".encode()).hexdigest()[:24]
        return f"{CACHE_DIR}/{h}"

    def _write_sys(self, bucket: str, path: str, payload: bytes) -> None:
        with self._mu:
            self._persisted_paths.setdefault(bucket, set()).add(path)

        def put(d):
            d.write_all(SYS_VOL, path, payload)
        try:
            self.es._map_drives(put)
        except StorageError:
            pass

    def _read_sys(self, path: str) -> bytes | None:
        for d in self.es.drives:
            if d is None:
                continue
            try:
                return d.read_all(SYS_VOL, path)
            except StorageError:
                continue
        return None

    @staticmethod
    def _pack_entries(entries: list) -> bytes:
        return zlib.compress(msgpackx.packb(
            [{"n": fi.name, "s": fi.size, "mt": fi.mod_time_ns,
              "v": fi.version_id, "m": dict(fi.metadata)}
             for fi in entries]), 1)

    @staticmethod
    def _unpack_entries(bucket: str, payload: bytes) -> list:
        return [FileInfo(volume=bucket, name=e["n"], size=e["s"],
                         mod_time_ns=e["mt"], version_id=e["v"],
                         metadata=e["m"])
                for e in msgpackx.unpackb(zlib.decompress(payload))]

    def _persist_segment(self, bucket, prefix, state, entries) -> None:
        # seq is MONOTONIC per cache (never reused after a lost-segment
        # truncation) so a replacement segment gets a fresh path and a
        # seq every reader's rescan cursor is guaranteed to be below.
        seq = state["next_seq"]
        state["next_seq"] = seq + 1
        path = f"{self._base_path(bucket, prefix)}/{seq}.seg"
        self._write_sys(bucket, path, self._pack_entries(entries))
        # Seed the LRU so the caller's rescan serves these entries
        # from memory instead of re-reading + decompressing what we
        # hold right now — and so a persist that failed on every drive
        # (ENOSPC) still makes forward progress in-process instead of
        # looping through the lost-segment path.
        with self._mu:
            self._seg_cache = (path, list(entries))
        state["segs"].append([entries[-1].name, seq])
        state["last"] = entries[-1].name
        self._persist_index(bucket, prefix, state)

    def _persist_index(self, bucket, prefix, state) -> None:
        path = f"{self._base_path(bucket, prefix)}/index"
        self._write_sys(bucket, path, msgpackx.packb(state))

    def _load_segment(self, bucket, prefix, seq) -> list | None:
        path = f"{self._base_path(bucket, prefix)}/{seq}.seg"
        with self._mu:
            if self._seg_cache and self._seg_cache[0] == path:
                return self._seg_cache[1]
        payload = self._read_sys(path)
        if payload is None:
            return None
        try:
            entries = self._unpack_entries(bucket, payload)
        except Exception:  # noqa: BLE001 — corrupt cache = miss
            return None
        with self._mu:
            self._seg_cache = (path, entries)
        return entries

    def _state_for(self, bucket: str, prefix: str, gen: int) -> dict:
        key = (bucket, prefix, gen)
        with self._mu:
            st = self._idx.get(key)
        if st is not None and time.time() - st["at"] <= CACHE_TTL:
            return st
        # A restart (or another caller's cache): adopt the persisted
        # index when fresh.
        raw = self._read_sys(f"{self._base_path(bucket, prefix)}/index")
        st = None
        if raw is not None:
            try:
                cand = msgpackx.unpackb(raw)
                if time.time() - cand.get("at", 0) <= CACHE_TTL:
                    st = cand
            except Exception:  # noqa: BLE001
                st = None
        if st is None:
            st = {"at": time.time(), "segs": [], "done": False,
                  "last": "", "next_seq": 0}
        st.setdefault("next_seq",
                      max((s[1] for s in st["segs"]), default=-1) + 1)
        with self._mu:
            self._idx[key] = st
        return st

    # -- public API ----------------------------------------------------------

    def list(self, bucket: str, prefix: str = "", marker: str = "",
             max_keys: int = 10000) -> list:
        """One page of the cached, quorum-merged listing.

        Serves from persisted segments where the marker lands in
        already-walked territory; otherwise extends the walk from
        exactly where it stopped, persisting new segments as they
        fill. Never materializes more than (page + one segment)."""
        from itertools import islice
        gen = self._generation(bucket)
        state = self._state_for(bucket, prefix, gen)
        with self._mu:
            lock = self._idx.setdefault(
                (bucket, prefix, gen, "extend-lock"), threading.Lock())
        out: list = []
        seen_seq = -1
        while True:
            # serve any segments not yet scanned, in order
            for last, seq in list(state["segs"]):
                if seq <= seen_seq:
                    continue
                if len(out) >= max_keys:
                    break
                seen_seq = seq
                if last <= marker:
                    continue
                seg = self._load_segment(bucket, prefix, seq)
                if seg is None:
                    # lost segment (drive churn): drop it and every
                    # later one, resume the live walk from the last
                    # intact segment (the replacement re-persists
                    # under a fresh, higher seq — see _persist_segment)
                    with lock:
                        state["segs"] = [s for s in state["segs"]
                                         if s[1] < seq]
                        state["last"] = (state["segs"][-1][0]
                                         if state["segs"] else "")
                        state["done"] = False
                    break
                out.extend(fi for fi in seg if fi.name > marker)
            if len(out) >= max_keys or state["done"]:
                return out[:max_keys]
            # extend the walk by one segment (serialized; a racing
            # caller's extension shows up as new segments on rescan)
            with lock:
                if state["done"] or (state["segs"]
                                     and state["segs"][-1][1] > seen_seq):
                    continue                      # rescan new segments
                info: dict = {}
                stream = self._stream(bucket, prefix,
                                      after=state["last"], info=info)
                pending = list(islice(stream, SEG_ENTRIES))
                if info["failed"]:
                    # Degraded walk: serve the FULL requested page
                    # live (keep draining the same stream up to
                    # max_keys) but cache NOTHING — a truncated
                    # listing must not persist as authoritative (nor
                    # mark the cache done).
                    for fi in pending:
                        if fi.name > marker:
                            out.append(fi)
                    # A mid-drain all-drives failure PROPAGATES: a
                    # short page reads as "listing complete" to every
                    # pagination client (IsTruncated=false) — silent
                    # truncation loses data downstream, a 5xx does not.
                    for fi in stream:
                        if fi.name > marker:
                            out.append(fi)
                        if len(out) > max_keys:
                            break
                    return out[:max_keys]
                if len(pending) < SEG_ENTRIES:
                    state["done"] = True
                if pending:
                    self._persist_segment(bucket, prefix, state,
                                          pending)
                else:
                    self._persist_index(bucket, prefix, state)
