"""Listing metacache: walk results computed once, cached, and reused.

The cmd/metacache-*.go equivalent: a listing walks listing-quorum drives
in parallel, quorum-merges the entries, and the result is kept — in
memory AND persisted msgpack-on-drives — so the next page (or the next
client asking for the same prefix) streams from cache instead of
re-walking every drive. Bucket writes bump a generation counter that
invalidates affected caches (the metacache-manager role).
"""

from __future__ import annotations

import hashlib
import threading
import time

from ..storage.drive import SYS_VOL
from ..storage.errors import StorageError
from ..storage.xlmeta import XLMeta
from ..utils import msgpackx
from . import quorum as Q

CACHE_TTL = 30.0            # seconds a cache stays valid without writes
CACHE_DIR = "metacache"


class _Entry:
    __slots__ = ("name", "size", "mod_time_ns", "etag", "version_id",
                 "metadata")

    def __init__(self, name, size, mod_time_ns, etag, version_id,
                 metadata):
        self.name = name
        self.size = size
        self.mod_time_ns = mod_time_ns
        self.etag = etag
        self.version_id = version_id
        self.metadata = metadata


class Metacache:
    def __init__(self, es):
        self.es = es
        self._mu = threading.Lock()
        self._gen: dict[str, int] = {}          # bucket -> generation
        self._mem: dict[tuple, tuple] = {}      # (bucket,prefix,gen) ->
        #                                         (created, entries)
        self._persisted_paths: dict[str, set] = {}
        self.walks = 0                          # instrumentation

    # -- invalidation --------------------------------------------------------

    def bump(self, bucket: str) -> None:
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1
            for key in [k for k in self._mem if k[0] == bucket]:
                del self._mem[key]
            paths = self._persisted_paths.pop(bucket, set())
        # Drop persisted caches for this bucket too; other nodes fall
        # back to the TTL bound (the reference's metacache life window).
        for path in paths:
            def rm(d, p=path):
                d.delete(SYS_VOL, p)
            try:
                self.es._map_drives(rm)
            except StorageError:
                pass

    def _generation(self, bucket: str) -> int:
        with self._mu:
            return self._gen.get(bucket, 0)

    # -- walk + merge (cf. metacache-set.go listPath) ------------------------

    def _walk_merge(self, bucket: str, prefix: str) -> list:
        self.walks += 1
        per_name: dict[str, list] = {}
        res = self.es._map_drives(
            lambda d: list(d.walk_dir(bucket, prefix)))
        ok_drives = sum(1 for _, e in res if e is None)
        if ok_drives == 0:
            raise StorageError(f"listing failed on all drives: "
                               f"{[str(e) for _, e in res if e]}")
        for entries, e in res:
            if e is not None:
                continue
            for name, raw in entries:
                try:
                    fi = XLMeta.from_bytes(raw).latest(bucket, name)
                except StorageError:
                    continue
                per_name.setdefault(name, []).append(fi)
        quorum = max(1, ok_drives // 2)
        out = []
        for name in sorted(per_name):
            try:
                fi = Q.find_file_info_in_quorum(per_name[name], quorum)
            except StorageError:
                continue
            if not fi.deleted:
                out.append(fi)
        return out

    # -- persisted cache (cf. metacache-stream persistence) ------------------

    def _cache_path(self, bucket: str, prefix: str) -> str:
        h = hashlib.sha256(f"{bucket}\x00{prefix}".encode()).hexdigest()[:24]
        return f"{CACHE_DIR}/{h}.cache"

    def _persist(self, bucket: str, prefix: str, entries: list) -> None:
        payload = msgpackx.packb({
            "at": time.time(), "bucket": bucket, "prefix": prefix,
            "entries": [{"n": fi.name, "s": fi.size, "mt": fi.mod_time_ns,
                         "e": fi.metadata.get("etag", ""),
                         "v": fi.version_id,
                         "m": dict(fi.metadata)} for fi in entries]})
        path = self._cache_path(bucket, prefix)
        with self._mu:
            self._persisted_paths.setdefault(bucket, set()).add(path)

        def put(d):
            d.write_all(SYS_VOL, path, payload)
        try:
            self.es._map_drives(put)
        except StorageError:
            pass

    def _load_persisted(self, bucket: str, prefix: str):
        path = self._cache_path(bucket, prefix)
        for d in self.es.drives:
            if d is None:
                continue
            try:
                obj = msgpackx.unpackb(d.read_all(SYS_VOL, path))
            except StorageError:
                continue
            if time.time() - obj.get("at", 0) > CACHE_TTL:
                return None
            from ..storage.xlmeta import FileInfo
            return [FileInfo(volume=bucket, name=e["n"], size=e["s"],
                             mod_time_ns=e["mt"], version_id=e["v"],
                             metadata=e["m"])
                    for e in obj.get("entries", [])]
        return None

    # -- public API ----------------------------------------------------------

    def list(self, bucket: str, prefix: str = "", marker: str = "",
             max_keys: int = 10000) -> list:
        """Cached quorum-merged listing with marker pagination."""
        gen = self._generation(bucket)
        key = (bucket, prefix, gen)
        with self._mu:
            hit = self._mem.get(key)
        if hit is not None and time.time() - hit[0] <= CACHE_TTL:
            entries = hit[1]
        else:
            entries = self._load_persisted(bucket, prefix)
            if entries is None:
                entries = self._walk_merge(bucket, prefix)
                self._persist(bucket, prefix, entries)
            with self._mu:
                self._mem[key] = (time.time(), entries)
        if marker:
            entries = [fi for fi in entries if fi.name > marker]
        return entries[:max_keys]
