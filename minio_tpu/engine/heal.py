"""Heal subsystem: object heal, bucket heal, resumable drive heal.

The reference's healing stack rebuilt on the batched device codec:

- ``heal_object`` classifies every drive's copy of an object version
  (ok / offline / missing / outdated / corrupt), elects the latest
  quorum metadata, and reconstructs outdated drives with ONE batched
  decode->re-encode device dispatch per part instead of the reference's
  streaming per-block pipe (cf. healObject,
  /root/reference/cmd/erasure-healing.go:244, and Erasure.Heal,
  /root/reference/cmd/erasure-lowlevel-heal.go:31).
- Dangling objects (provably unrecoverable) are purged
  (cf. isObjectDangling, /root/reference/cmd/erasure-healing.go:834).
- ``HealingTracker`` persists resumable per-drive healing progress on the
  drive being healed (cf. healingTracker / .healing.bin,
  /root/reference/cmd/background-newdisks-heal-ops.go:48).
- ``heal_drive`` walks the whole set onto one new/replaced drive
  (cf. healErasureSet, /root/reference/cmd/global-heal.go:166).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field

import numpy as np

from ..storage import bitrot_io
from ..storage.drive import SYS_VOL, TMP_DIR, LocalDrive
from ..storage.errors import (ErrErasureReadQuorum, ErrFileCorrupt,
                              ErrFileNotFound, ErrFileVersionNotFound,
                              ErrVolumeNotFound, StorageError)
from ..storage.xlmeta import FileInfo, XLMeta
from ..utils import msgpackx
from . import quorum as Q
from .erasure_set import BLOCK_SIZE, ErasureSet

# Drive states (cf. madmin drive states in the reference heal API).
DRIVE_OK = "ok"
DRIVE_OFFLINE = "offline"
DRIVE_MISSING = "missing"
DRIVE_OUTDATED = "outdated"
DRIVE_CORRUPT = "corrupt"

HEALING_FILE = "healing.bin"  # lives under <drive>/.mtpu.sys/


@dataclass
class HealResult:
    """Outcome of healing one object version (madmin.HealResultItem-like)."""
    bucket: str
    object: str
    version_id: str = ""
    size: int = 0
    before: list[str] = field(default_factory=list)
    after: list[str] = field(default_factory=list)
    healed_drives: list[int] = field(default_factory=list)
    purged: bool = False          # dangling object removed

    @property
    def healed(self) -> bool:
        return bool(self.healed_drives) or self.purged


def object_version_ids(es: ErasureSet, bucket: str, obj: str) -> list[str]:
    """Union of version ids seen on any drive (newest-first best effort)."""
    seen: dict[str, int] = {}
    res = es._map_drives(lambda d: d.read_all(bucket, f"{obj}/xl.meta"))
    for raw, e in res:
        if e is not None:
            continue
        try:
            meta = XLMeta.from_bytes(raw)
        except StorageError:
            continue
        for v in meta.versions:
            vid = v.get("id", "")
            seen[vid] = max(seen.get(vid, 0), v.get("mt", 0))
    return [vid for vid, _ in
            sorted(seen.items(), key=lambda kv: kv[1], reverse=True)]


def classify_drives(es: ErasureSet, bucket: str, obj: str, fi: FileInfo,
                    metas: list[FileInfo | None],
                    errs: list[Exception | None],
                    deep: bool = False) -> list[str]:
    """Per-drive-position state for one elected version.

    cf. shouldHealObjectOnDisk + disksWithAllParts,
    /root/reference/cmd/erasure-healing.go:206.
    """
    want_key = Q._fi_key(fi)
    states: list[str] = []
    for pos, d in enumerate(es.drives):
        if d is None:
            states.append(DRIVE_OFFLINE)
            continue
        meta = metas[pos]
        if meta is None:
            err = errs[pos]
            if isinstance(err, (ErrFileNotFound, ErrFileVersionNotFound,
                                ErrVolumeNotFound)):
                states.append(DRIVE_MISSING)
            elif isinstance(err, ErrFileCorrupt):
                states.append(DRIVE_CORRUPT)
            else:
                states.append(DRIVE_OFFLINE)
            continue
        if Q._fi_key(meta) != want_key:
            states.append(DRIVE_OUTDATED)
            continue
        states.append(_verify_drive_data(d, bucket, obj, fi, meta, deep))
    return states


def _verify_drive_data(d: LocalDrive, bucket: str, obj: str, fi: FileInfo,
                       meta: FileInfo, deep: bool) -> str:
    """Check this drive's shard data for the version: size always, full
    bitrot verify when deep (cf. VerifyFile server-side deep scan,
    /root/reference/cmd/xl-storage.go:2194)."""
    if fi.deleted:
        return DRIVE_OK
    if fi.inline_data is not None or not fi.data_dir:
        # Inline shard rides in xl.meta; deep-verify its frames.
        if deep and meta.inline_data is not None and fi.erasure is not None:
            try:
                bitrot_io.unframe_shard(meta.inline_data,
                                        fi.erasure.shard_size, verify=True,
                                        algo=fi.erasure.bitrot_algo())
            except StorageError:
                return DRIVE_CORRUPT
        if meta.inline_data is None:
            return DRIVE_CORRUPT
        return DRIVE_OK
    ec = fi.erasure
    for part in fi.parts:
        path = f"{obj}/{fi.data_dir}/part.{part.number}"
        logical = ec.shard_file_size(part.size)
        algo = ec.bitrot_algo(part.number)
        want = bitrot_io.bitrot_shard_file_size(logical, ec.shard_size,
                                                algo)
        try:
            if deep:
                d.verify_file(bucket, path, ec.shard_size, logical,
                              algo=algo)
            elif d.file_size(bucket, path) != want:
                return DRIVE_CORRUPT
        except ErrFileNotFound:
            return DRIVE_MISSING
        except StorageError:
            return DRIVE_CORRUPT
    return DRIVE_OK


def heal_object(es: ErasureSet, bucket: str, obj: str, version_id: str = "",
                deep: bool = False, dry_run: bool = False,
                remove_dangling: bool = True) -> list[HealResult]:
    """Heal one object: every version when version_id == "", else that one.

    Returns one HealResult per version examined.
    cf. healObject, /root/reference/cmd/erasure-healing.go:244.
    """
    if version_id:
        vids = [version_id]
    else:
        vids = object_version_ids(es, bucket, obj)
        if not vids:
            # No drive has any metadata: nothing to heal (or the object is
            # gone); mirror the reference's not-found no-op.
            return []
    # Heal mutates shard files + metadata: same write lock as PUT/DELETE
    # (cf. NSLock in healObject, cmd/erasure-healing.go:276).
    with es.nslock.write_locked(bucket, obj, timeout=30.0):
        return [_heal_version(es, bucket, obj, vid, deep, dry_run,
                              remove_dangling) for vid in vids]


def _heal_version(es: ErasureSet, bucket: str, obj: str, version_id: str,
                  deep: bool, dry_run: bool,
                  remove_dangling: bool) -> HealResult:
    res = es._map_drives(lambda d: d.read_version(bucket, obj, version_id))
    metas = [m for m, _ in res]
    errs = [e for _, e in res]
    result = HealResult(bucket=bucket, object=obj, version_id=version_id)

    n_found = sum(1 for m in metas if m is not None)
    read_quorum, write_quorum = Q.object_quorum_from_meta(
        metas, es.n, es.default_parity)
    try:
        fi = Q.find_file_info_in_quorum(metas, read_quorum) \
            if n_found else None
    except ErrErasureReadQuorum:
        fi = None

    if fi is None:
        # Sub-quorum metadata. Purge only when provably dangling: every
        # drive reported a definite answer (no offline/unknown that could
        # be hiding a copy) and still no quorum
        # (cf. isObjectDangling, erasure-healing.go:834).
        definite = all(
            d is None or m is not None or isinstance(
                e, (ErrFileNotFound, ErrFileVersionNotFound,
                    ErrVolumeNotFound, ErrFileCorrupt))
            for d, m, e in zip(es.drives, metas, errs))
        offline = sum(1 for d in es.drives if d is None)
        if remove_dangling and definite and n_found + offline < read_quorum:
            result.before = [DRIVE_OFFLINE if d is None else
                             (DRIVE_OK if m is not None else DRIVE_MISSING)
                             for d, m in zip(es.drives, metas)]
            if not dry_run:
                _purge_version(es, bucket, obj, version_id, metas)
            result.purged = True
            result.after = [DRIVE_OFFLINE if d is None else DRIVE_MISSING
                            for d in es.drives]
            return result
        raise ErrErasureReadQuorum(
            f"heal {bucket}/{obj}@{version_id}: "
            f"{n_found} metas < quorum {read_quorum}")

    result.version_id = fi.version_id
    result.size = fi.size
    states = classify_drives(es, bucket, obj, fi, metas, errs, deep)
    result.before = list(states)
    targets = [pos for pos, st in enumerate(states)
               if st in (DRIVE_MISSING, DRIVE_OUTDATED, DRIVE_CORRUPT)
               and es.drives[pos] is not None]
    if not targets:
        result.after = list(states)
        return result
    if dry_run:
        result.after = list(states)
        result.healed_drives = targets
        return result

    if fi.deleted or fi.inline_data is not None or not fi.data_dir:
        _heal_metadata_only(es, bucket, obj, fi, metas, states, targets)
    else:
        sources = [pos for pos, st in enumerate(states) if st == DRIVE_OK]
        k = fi.erasure.data_blocks
        if len(sources) < k:
            raise ErrErasureReadQuorum(
                f"heal {bucket}/{obj}: only {len(sources)} intact copies "
                f"< {k} needed")
        _heal_data(es, bucket, obj, fi, sources, targets)

    after = list(states)
    for pos in targets:
        after[pos] = DRIVE_OK
    result.after = after
    result.healed_drives = targets
    return result


def _purge_version(es: ErasureSet, bucket: str, obj: str, version_id: str,
                   metas: list[FileInfo | None]) -> None:
    """Remove a dangling version wherever it exists."""
    def rm(d):
        try:
            d.delete_version(bucket, obj, version_id)
        except (ErrFileNotFound, ErrFileVersionNotFound):
            pass
    es._map_drives(rm)


def _ensure_bucket_on(drive, bucket: str) -> None:
    """Heal explicitly recreates a missing bucket volume on its target
    drive — the data path itself refuses to resurrect volumes (a PUT
    racing a bucket delete must fail, drive._ensure_parent_in_vol), so
    only heal gets to bring the directory back (cf. healBucket before
    object heal, /root/reference/cmd/erasure-healing.go:281)."""
    from ..storage.errors import ErrVolumeExists
    try:
        drive.make_volume(bucket)
    except ErrVolumeExists:
        pass


def _heal_metadata_only(es, bucket, obj, fi: FileInfo, metas, states,
                        targets: list[int]) -> None:
    """Delete markers and inline objects: rewrite xl.meta on targets.

    The inline shard for a target drive is the shard its stripe position
    owns; reconstruct it from intact copies when the source lacks it."""
    if fi.deleted:
        for pos in targets:
            _ensure_bucket_on(es.drives[pos], bucket)
            es.drives[pos].write_metadata(bucket, obj, fi)
        return
    ec = fi.erasure
    dist = ec.distribution
    k, m = ec.data_blocks, ec.parity_blocks
    # Gather intact framed inline shards by shard index.
    shard_bytes: list[bytes | None] = [None] * (k + m)
    for pos, st in enumerate(states):
        meta = metas[pos]
        if st == DRIVE_OK and meta is not None and meta.inline_data is not None:
            shard_bytes[dist[pos] - 1] = meta.inline_data
    # Unframe + verify available shards to logical rows.
    logical = ec.shard_file_size(fi.size)
    rows: list[np.ndarray | None] = [None] * (k + m)
    for s, data in enumerate(shard_bytes):
        if data is None:
            continue
        try:
            row = bitrot_io.unframe_shard(data, ec.shard_size, verify=True,
                                          algo=ec.bitrot_algo())
            if row.size == logical:
                rows[s] = row
        except StorageError:
            continue
    need = sorted({dist[pos] - 1 for pos in targets
                   if rows[dist[pos] - 1] is None})
    if need:
        avail = [s for s in range(k + m) if rows[s] is not None]
        if len(avail) < k:
            raise ErrErasureReadQuorum(
                f"heal inline {bucket}/{obj}: {len(avail)} < {k}")
        rebuilt = _reconstruct_rows(es, fi, rows, avail, need)
        for s, row in zip(need, rebuilt):
            rows[s] = row
    for pos in targets:
        s = dist[pos] - 1
        framed = bitrot_io.frame_shard(rows[s], ec.shard_size,
                                       ec.bitrot_algo())
        fi_pos = _fi_for_drive(fi, pos, inline=framed)
        _ensure_bucket_on(es.drives[pos], bucket)
        es.drives[pos].write_metadata(bucket, obj, fi_pos)


def _fi_for_drive(fi: FileInfo, pos: int,
                  inline: bytes | None = None) -> FileInfo:
    """Per-drive FileInfo: erasure.index points at this drive's shard."""
    ec = fi.erasure
    from ..storage.xlmeta import ErasureInfo
    ec_pos = None
    if ec is not None:
        ec_pos = ErasureInfo(
            data_blocks=ec.data_blocks, parity_blocks=ec.parity_blocks,
            block_size=ec.block_size, index=ec.distribution[pos],
            distribution=list(ec.distribution), algorithm=ec.algorithm,
            checksums=list(ec.checksums))
    return FileInfo(
        volume=fi.volume, name=fi.name, version_id=fi.version_id,
        data_dir=fi.data_dir if inline is None else "",
        mod_time_ns=fi.mod_time_ns, size=fi.size, deleted=fi.deleted,
        metadata=dict(fi.metadata), parts=list(fi.parts), erasure=ec_pos,
        inline_data=inline)


def _reconstruct_rows(es: ErasureSet, fi: FileInfo,
                      rows: list[np.ndarray | None], avail: list[int],
                      need: list[int]) -> list[np.ndarray]:
    """Rebuild `need` shard rows (full logical shard-file contents) from K
    available rows — batched device matmul for the full blocks, CPU codec
    for the tail fragment (cf. Erasure.Heal decode->re-encode,
    /root/reference/cmd/erasure-lowlevel-heal.go:31)."""
    ec = fi.erasure
    k, m = ec.data_blocks, ec.parity_blocks
    shard_size = ec.shard_size
    logical = rows[avail[0]].size
    use = avail[:k]
    # Host fast path: RS is positional, so whole LOGICAL rows (full
    # blocks AND tail in one go) transform with per-row pointers — no
    # batch stacking, no per-block loop (native ec_gf_rows, GFNI when
    # the CPU has it).
    if not es._use_device and k + m <= 64:
        try:
            from native import ecio_native
            return ecio_native.gf_transform_rows(
                [rows[s] for s in use], list(use), k, m, list(need))
        except Exception:  # noqa: BLE001 — no toolchain: batch path
            pass
    # Split logical shard into full-block matrix + tail.
    n_full = logical // shard_size
    tail_len = logical - n_full * shard_size
    out_rows = [np.zeros(logical, dtype=np.uint8) for _ in need]
    if n_full:
        x = np.stack([rows[s][:n_full * shard_size].reshape(n_full,
                                                            shard_size)
                      for s in use], axis=1)  # (B, K, S)
        y = es._transform(k, m, x, tuple(use), tuple(need))  # (B, T, S)
        for j in range(len(need)):
            out_rows[j][:n_full * shard_size] = y[:, j, :].reshape(-1)
    if tail_len:
        shards_in: list[np.ndarray | None] = [None] * (k + m)
        for s in avail:
            shards_in[s] = rows[s][n_full * shard_size:]
        full = es._cpu(k, m).reconstruct(shards_in)
        for j, s in enumerate(need):
            out_rows[j][n_full * shard_size:] = full[s]
    return out_rows


def _heal_data(es: ErasureSet, bucket: str, obj: str, fi: FileInfo,
               sources: list[int], targets: list[int]) -> None:
    """Reconstruct every part's shard files onto the target drives and
    publish atomically via rename_data."""
    ec = fi.erasure
    dist = ec.distribution
    k = ec.data_blocks
    tmp_id = f"heal-{uuid.uuid4().hex}"
    need = sorted({dist[pos] - 1 for pos in targets})

    try:
        for part in fi.parts:
            path = f"{obj}/{fi.data_dir}/part.{part.number}"
            logical = ec.shard_file_size(part.size)
            rows: list[np.ndarray | None] = [None] * (k + ec.parity_blocks)
            got = 0
            # Read + verify source shards until K good ones (spares beyond
            # the first K cover sources that fail at read time).
            for pos in sources:
                if got >= k:
                    break
                s = dist[pos] - 1
                try:
                    d = es.drives[pos]
                    # mmap on local drives: the fused unframe verifies
                    # straight off the page cache (no read() copy).
                    raw = (d.read_file_view(bucket, path)
                           if isinstance(d, LocalDrive)
                           else d.read_file(bucket, path))
                    row = bitrot_io.unframe_shard(
                        raw, ec.shard_size, verify=True,
                        algo=ec.bitrot_algo(part.number))
                    if row.size != logical:
                        raise ErrFileCorrupt("short shard")
                    rows[s] = row
                    got += 1
                except StorageError:
                    continue
            if got < k:
                raise ErrErasureReadQuorum(
                    f"heal {bucket}/{obj} part {part.number}: "
                    f"{got} readable < {k}")
            avail = [s for s in range(len(rows)) if rows[s] is not None]
            missing = [s for s in need if rows[s] is None]
            rebuilt = _reconstruct_rows(es, fi, rows, avail, missing) \
                if missing else []
            for s, row in zip(missing, rebuilt):
                rows[s] = row
            for pos in targets:
                s = dist[pos] - 1
                framed = bitrot_io.frame_shard(
                    rows[s], ec.shard_size, ec.bitrot_algo(part.number))
                es.drives[pos].create_file(
                    SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.{part.number}",
                    framed)
        for pos in targets:
            fi_pos = _fi_for_drive(fi, pos)
            _ensure_bucket_on(es.drives[pos], bucket)
            es.drives[pos].rename_data(SYS_VOL, f"{TMP_DIR}/{tmp_id}",
                                       fi_pos, bucket, obj)
    finally:
        for pos in targets:
            try:
                es.drives[pos].delete(SYS_VOL, f"{TMP_DIR}/{tmp_id}",
                                      recursive=True)
            except StorageError:
                pass


def heal_format(es: ErasureSet) -> list[int]:
    """Restore format.json + the system volume on drives that lost
    them (wiped/replaced disk) — the HealFormat step that must precede
    bucket/object healing, because every write stages through the sys
    volume's tmp dir (cf. HealFormat, cmd/format-erasure.go:798).
    Returns healed positions."""
    from ..storage.format import load_format, new_format, save_format
    fmts: list[dict | None] = []
    for d in es.drives:
        if d is None:
            fmts.append(None)
            continue
        try:
            fmts.append(load_format(d))
        except StorageError:
            fmts.append(None)
    ref = next((f for f in fmts if f), None)
    if ref is None:
        return []
    layout = ref["xl"]["sets"]
    healed = []
    for pos, (d, f) in enumerate(zip(es.drives, fmts)):
        if d is None or f is not None:
            continue
        try:
            d.init_sys_volume()
            save_format(d, new_format(ref["id"], layout,
                                      layout[es.set_index][pos]))
            healed.append(pos)
        except StorageError:
            continue
    return healed


def heal_bucket(es: ErasureSet, bucket: str) -> list[int]:
    """Create the bucket volume on drives missing it; returns healed
    positions (cf. HealBucket, /root/reference/cmd/erasure-bucket.go)."""
    res = es._map_drives(lambda d: d.stat_volume(bucket))
    present = sum(1 for _, e in res if e is None)
    if present < es._live_quorum():
        raise ErrVolumeNotFound(bucket)
    healed = []
    for pos, (_, e) in enumerate(res):
        if e is not None and es.drives[pos] is not None:
            try:
                es.drives[pos].make_volume(bucket)
                healed.append(pos)
            except StorageError:
                pass
    return healed


# ---------------------------------------------------------------------------
# Resumable drive healing (new/replaced disk).
# ---------------------------------------------------------------------------

@dataclass
class HealingTracker:
    """Persisted on the drive being healed; survives restarts mid-heal
    (cf. healingTracker, /root/reference/cmd/background-newdisks-heal-ops.go:48)."""
    heal_id: str = ""
    started_ns: int = 0
    resume_bucket: str = ""
    resume_object: str = ""
    objects_healed: int = 0
    objects_failed: int = 0
    bytes_healed: int = 0
    finished: bool = False

    def save(self, drive: LocalDrive) -> None:
        drive.write_all(SYS_VOL, HEALING_FILE, msgpackx.packb({
            "id": self.heal_id, "start": self.started_ns,
            "rb": self.resume_bucket, "ro": self.resume_object,
            "oh": self.objects_healed, "of": self.objects_failed,
            "bh": self.bytes_healed, "fin": self.finished}))

    @classmethod
    def load(cls, drive: LocalDrive) -> "HealingTracker | None":
        try:
            d = msgpackx.unpackb(drive.read_all(SYS_VOL, HEALING_FILE))
        except StorageError:
            return None
        return cls(heal_id=d.get("id", ""), started_ns=d.get("start", 0),
                   resume_bucket=d.get("rb", ""),
                   resume_object=d.get("ro", ""),
                   objects_healed=d.get("oh", 0),
                   objects_failed=d.get("of", 0),
                   bytes_healed=d.get("bh", 0),
                   finished=d.get("fin", False))

    @staticmethod
    def clear(drive: LocalDrive) -> None:
        try:
            drive.delete(SYS_VOL, HEALING_FILE)
        except StorageError:
            pass


def _set_objects(es: ErasureSet, bucket: str, skip_pos: int) -> list[str]:
    """Union of object names for a bucket across all drives but skip_pos."""
    names: set[str] = set()
    for pos, d in enumerate(es.drives):
        if d is None or pos == skip_pos:
            continue
        try:
            for name, _ in d.walk_dir(bucket):
                names.add(name)
        except StorageError:
            continue
    return sorted(names)


def heal_drive(es: ErasureSet, pos: int,
               checkpoint_every: int = 64) -> HealingTracker:
    """Walk the whole set onto one (new/replaced/wiped) drive, resumably.

    cf. healErasureSet, /root/reference/cmd/global-heal.go:166."""
    drive = es.drives[pos]
    if drive is None:
        raise ErrVolumeNotFound(f"drive position {pos} offline")
    tracker = HealingTracker.load(drive)
    if tracker is None or tracker.finished:
        tracker = HealingTracker(heal_id=str(uuid.uuid4()),
                                 started_ns=time.time_ns())
        tracker.save(drive)

    buckets = es.list_buckets()
    since_ckpt = 0
    for bucket in buckets:
        if bucket < tracker.resume_bucket:
            continue
        heal_bucket(es, bucket)
        for obj in _set_objects(es, bucket, skip_pos=pos):
            if (bucket == tracker.resume_bucket
                    and obj <= tracker.resume_object):
                continue
            try:
                for r in heal_object(es, bucket, obj):
                    if pos in r.healed_drives:
                        tracker.objects_healed += 1
                        tracker.bytes_healed += r.size
            except StorageError:
                tracker.objects_failed += 1
            tracker.resume_bucket, tracker.resume_object = bucket, obj
            since_ckpt += 1
            if since_ckpt >= checkpoint_every:
                tracker.save(drive)
                since_ckpt = 0
    tracker.finished = True
    tracker.save(drive)
    return tracker
