"""Heal subsystem: object heal, bucket heal, resumable drive heal.

The reference's healing stack rebuilt on the batched device codec:

- ``heal_object`` classifies every drive's copy of an object version
  (ok / offline / missing / outdated / corrupt), elects the latest
  quorum metadata, and reconstructs outdated drives with ONE batched
  decode->re-encode device dispatch per part instead of the reference's
  streaming per-block pipe (cf. healObject,
  /root/reference/cmd/erasure-healing.go:244, and Erasure.Heal,
  /root/reference/cmd/erasure-lowlevel-heal.go:31).
- Dangling objects (provably unrecoverable) are purged
  (cf. isObjectDangling, /root/reference/cmd/erasure-healing.go:834).
- ``HealingTracker`` persists resumable per-drive healing progress on the
  drive being healed (cf. healingTracker / .healing.bin,
  /root/reference/cmd/background-newdisks-heal-ops.go:48).
- ``heal_drive`` walks the whole set onto one new/replaced drive
  (cf. healErasureSet, /root/reference/cmd/global-heal.go:166).
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from ..observe import span as ospan
from ..observe.metrics import DATA_PATH
from ..parallel import pipeline as pl
from ..storage import bitrot_io
from ..storage.drive import SYS_VOL, TMP_DIR, LocalDrive
from ..storage.errors import (ErrErasureReadQuorum, ErrFileCorrupt,
                              ErrFileNotFound, ErrFileVersionNotFound,
                              ErrVolumeNotFound, StorageError)
from ..storage.xlmeta import FileInfo, XLMeta
from ..utils import msgpackx
from . import quorum as Q
from .erasure_set import BATCH_BLOCKS, BLOCK_SIZE, ErasureSet

# Drive states (cf. madmin drive states in the reference heal API).
DRIVE_OK = "ok"
DRIVE_OFFLINE = "offline"
DRIVE_MISSING = "missing"
DRIVE_OUTDATED = "outdated"
DRIVE_CORRUPT = "corrupt"

HEALING_FILE = "healing.bin"  # lives under <drive>/.mtpu.sys/


@dataclass
class HealResult:
    """Outcome of healing one object version (madmin.HealResultItem-like)."""
    bucket: str
    object: str
    version_id: str = ""
    size: int = 0
    before: list[str] = field(default_factory=list)
    after: list[str] = field(default_factory=list)
    healed_drives: list[int] = field(default_factory=list)
    purged: bool = False          # dangling object removed

    @property
    def healed(self) -> bool:
        return bool(self.healed_drives) or self.purged


def object_version_ids(es: ErasureSet, bucket: str, obj: str) -> list[str]:
    """Union of version ids seen on any drive (newest-first best effort)."""
    seen: dict[str, int] = {}
    res = es._map_drives(lambda d: d.read_all(bucket, f"{obj}/xl.meta"))
    for raw, e in res:
        if e is not None:
            continue
        try:
            meta = XLMeta.from_bytes(raw)
        except StorageError:
            continue
        for v in meta.versions:
            vid = v.get("id", "")
            seen[vid] = max(seen.get(vid, 0), v.get("mt", 0))
    return [vid for vid, _ in
            sorted(seen.items(), key=lambda kv: kv[1], reverse=True)]


def classify_drives(es: ErasureSet, bucket: str, obj: str, fi: FileInfo,
                    metas: list[FileInfo | None],
                    errs: list[Exception | None],
                    deep: bool = False) -> list[str]:
    """Per-drive-position state for one elected version.

    cf. shouldHealObjectOnDisk + disksWithAllParts,
    /root/reference/cmd/erasure-healing.go:206.
    """
    want_key = Q._fi_key(fi)
    states: list[str] = []
    for pos, d in enumerate(es.drives):
        if d is None:
            states.append(DRIVE_OFFLINE)
            continue
        meta = metas[pos]
        if meta is None:
            err = errs[pos]
            if isinstance(err, (ErrFileNotFound, ErrFileVersionNotFound,
                                ErrVolumeNotFound)):
                states.append(DRIVE_MISSING)
            elif isinstance(err, ErrFileCorrupt):
                states.append(DRIVE_CORRUPT)
            else:
                states.append(DRIVE_OFFLINE)
            continue
        if Q._fi_key(meta) != want_key:
            states.append(DRIVE_OUTDATED)
            continue
        states.append(_verify_drive_data(d, bucket, obj, fi, meta, deep))
    return states


def _verify_drive_data(d: LocalDrive, bucket: str, obj: str, fi: FileInfo,
                       meta: FileInfo, deep: bool) -> str:
    """Check this drive's shard data for the version: size always, full
    bitrot verify when deep (cf. VerifyFile server-side deep scan,
    /root/reference/cmd/xl-storage.go:2194)."""
    if fi.deleted:
        return DRIVE_OK
    if fi.inline_data is not None or not fi.data_dir:
        # Inline shard rides in xl.meta; deep-verify its frames.
        if deep and meta.inline_data is not None and fi.erasure is not None:
            try:
                bitrot_io.unframe_shard(meta.inline_data,
                                        fi.erasure.shard_size, verify=True,
                                        algo=fi.erasure.bitrot_algo())
            except StorageError:
                return DRIVE_CORRUPT
        if meta.inline_data is None:
            return DRIVE_CORRUPT
        return DRIVE_OK
    ec = fi.erasure
    for part in fi.parts:
        path = f"{obj}/{fi.data_dir}/part.{part.number}"
        logical = ec.shard_file_size(part.size)
        algo = ec.bitrot_algo(part.number)
        want = bitrot_io.bitrot_shard_file_size(logical, ec.shard_size,
                                                algo)
        try:
            if deep:
                d.verify_file(bucket, path, ec.shard_size, logical,
                              algo=algo)
            elif d.file_size(bucket, path) != want:
                return DRIVE_CORRUPT
        except ErrFileNotFound:
            return DRIVE_MISSING
        except StorageError:
            return DRIVE_CORRUPT
    return DRIVE_OK


def heal_object(es: ErasureSet, bucket: str, obj: str, version_id: str = "",
                deep: bool = False, dry_run: bool = False,
                remove_dangling: bool = True) -> list[HealResult]:
    """Heal one object: every version when version_id == "", else that one.

    Returns one HealResult per version examined.
    cf. healObject, /root/reference/cmd/erasure-healing.go:244.
    """
    if version_id:
        vids = [version_id]
    else:
        vids = object_version_ids(es, bucket, obj)
        if not vids:
            # No drive has any metadata: nothing to heal (or the object is
            # gone); mirror the reference's not-found no-op.
            return []
    # Heal mutates shard files + metadata: same write lock as PUT/DELETE
    # (cf. NSLock in healObject, cmd/erasure-healing.go:276).
    with es.nslock.write_locked(bucket, obj, timeout=30.0):
        results = [_heal_version(es, bucket, obj, vid, deep, dry_run,
                                 remove_dangling) for vid in vids]
        # Heal is a mutation like any other: promoted spares / purged
        # dangling versions change what a read elects, so the FileInfo
        # cache and hot tier must be invalidated (a missed bump here
        # would let the hot cache serve the pre-heal body forever).
        if not dry_run and any(r.healed_drives or r.purged
                               for r in results):
            es._mark_dirty(bucket)
        return results


def _heal_version(es: ErasureSet, bucket: str, obj: str, version_id: str,
                  deep: bool, dry_run: bool,
                  remove_dangling: bool) -> HealResult:
    res = es._map_drives(lambda d: d.read_version(bucket, obj, version_id))
    metas = [m for m, _ in res]
    errs = [e for _, e in res]
    result = HealResult(bucket=bucket, object=obj, version_id=version_id)

    n_found = sum(1 for m in metas if m is not None)
    read_quorum, write_quorum = Q.object_quorum_from_meta(
        metas, es.n, es.default_parity)
    try:
        fi = Q.find_file_info_in_quorum(metas, read_quorum) \
            if n_found else None
    except ErrErasureReadQuorum:
        fi = None

    if fi is None:
        # Sub-quorum metadata. Purge only when provably dangling: every
        # drive reported a definite answer (no offline/unknown that could
        # be hiding a copy) and still no quorum
        # (cf. isObjectDangling, erasure-healing.go:834).
        definite = all(
            d is None or m is not None or isinstance(
                e, (ErrFileNotFound, ErrFileVersionNotFound,
                    ErrVolumeNotFound, ErrFileCorrupt))
            for d, m, e in zip(es.drives, metas, errs))
        offline = sum(1 for d in es.drives if d is None)
        if remove_dangling and definite and n_found + offline < read_quorum:
            result.before = [DRIVE_OFFLINE if d is None else
                             (DRIVE_OK if m is not None else DRIVE_MISSING)
                             for d, m in zip(es.drives, metas)]
            if not dry_run:
                _purge_version(es, bucket, obj, version_id, metas)
            result.purged = True
            result.after = [DRIVE_OFFLINE if d is None else DRIVE_MISSING
                            for d in es.drives]
            return result
        raise ErrErasureReadQuorum(
            f"heal {bucket}/{obj}@{version_id}: "
            f"{n_found} metas < quorum {read_quorum}")

    result.version_id = fi.version_id
    result.size = fi.size
    states = classify_drives(es, bucket, obj, fi, metas, errs, deep)
    result.before = list(states)
    targets = [pos for pos, st in enumerate(states)
               if st in (DRIVE_MISSING, DRIVE_OUTDATED, DRIVE_CORRUPT)
               and es.drives[pos] is not None]
    if not targets:
        result.after = list(states)
        return result
    if dry_run:
        result.after = list(states)
        result.healed_drives = targets
        return result

    if fi.deleted or fi.inline_data is not None or not fi.data_dir:
        _heal_metadata_only(es, bucket, obj, fi, metas, states, targets)
    else:
        sources = [pos for pos, st in enumerate(states) if st == DRIVE_OK]
        k = fi.erasure.data_blocks
        if len(sources) < k:
            raise ErrErasureReadQuorum(
                f"heal {bucket}/{obj}: only {len(sources)} intact copies "
                f"< {k} needed")
        _heal_data(es, bucket, obj, fi, sources, targets)

    after = list(states)
    for pos in targets:
        after[pos] = DRIVE_OK
    result.after = after
    result.healed_drives = targets
    return result


def _purge_version(es: ErasureSet, bucket: str, obj: str, version_id: str,
                   metas: list[FileInfo | None]) -> None:
    """Remove a dangling version wherever it exists."""
    def rm(d):
        try:
            d.delete_version(bucket, obj, version_id)
        except (ErrFileNotFound, ErrFileVersionNotFound):
            pass
    es._map_drives(rm)


def _ensure_bucket_on(drive, bucket: str) -> None:
    """Heal explicitly recreates a missing bucket volume on its target
    drive — the data path itself refuses to resurrect volumes (a PUT
    racing a bucket delete must fail, drive._ensure_parent_in_vol), so
    only heal gets to bring the directory back (cf. healBucket before
    object heal, /root/reference/cmd/erasure-healing.go:281)."""
    from ..storage.errors import ErrVolumeExists
    try:
        drive.make_volume(bucket)
    except ErrVolumeExists:
        pass


def _heal_metadata_only(es, bucket, obj, fi: FileInfo, metas, states,
                        targets: list[int]) -> None:
    """Delete markers and inline objects: rewrite xl.meta on targets.

    The inline shard for a target drive is the shard its stripe position
    owns; reconstruct it from intact copies when the source lacks it."""
    if fi.deleted:
        for pos in targets:
            _ensure_bucket_on(es.drives[pos], bucket)
            es.drives[pos].write_metadata(bucket, obj, fi)
        return
    ec = fi.erasure
    dist = ec.distribution
    k, m = ec.data_blocks, ec.parity_blocks
    # Gather intact framed inline shards by shard index.
    shard_bytes: list[bytes | None] = [None] * (k + m)
    for pos, st in enumerate(states):
        meta = metas[pos]
        if st == DRIVE_OK and meta is not None and meta.inline_data is not None:
            shard_bytes[dist[pos] - 1] = meta.inline_data
    # Unframe + verify available shards to logical rows.
    logical = ec.shard_file_size(fi.size)
    rows: list[np.ndarray | None] = [None] * (k + m)
    for s, data in enumerate(shard_bytes):
        if data is None:
            continue
        try:
            row = bitrot_io.unframe_shard(data, ec.shard_size, verify=True,
                                          algo=ec.bitrot_algo())
            if row.size == logical:
                rows[s] = row
        except StorageError:
            continue
    need = sorted({dist[pos] - 1 for pos in targets
                   if rows[dist[pos] - 1] is None})
    if need:
        avail = [s for s in range(k + m) if rows[s] is not None]
        if len(avail) < k:
            raise ErrErasureReadQuorum(
                f"heal inline {bucket}/{obj}: {len(avail)} < {k}")
        rebuilt = _reconstruct_rows(es, fi, rows, avail, need)
        for s, row in zip(need, rebuilt):
            rows[s] = row
    for pos in targets:
        s = dist[pos] - 1
        framed = bitrot_io.frame_shard(rows[s], ec.shard_size,
                                       ec.bitrot_algo())
        fi_pos = _fi_for_drive(fi, pos, inline=framed)
        _ensure_bucket_on(es.drives[pos], bucket)
        es.drives[pos].write_metadata(bucket, obj, fi_pos)


def _fi_for_drive(fi: FileInfo, pos: int,
                  inline: bytes | None = None) -> FileInfo:
    """Per-drive FileInfo: erasure.index points at this drive's shard."""
    ec = fi.erasure
    from ..storage.xlmeta import ErasureInfo
    ec_pos = None
    if ec is not None:
        ec_pos = ErasureInfo(
            data_blocks=ec.data_blocks, parity_blocks=ec.parity_blocks,
            block_size=ec.block_size, index=ec.distribution[pos],
            distribution=list(ec.distribution), algorithm=ec.algorithm,
            checksums=list(ec.checksums))
    return FileInfo(
        volume=fi.volume, name=fi.name, version_id=fi.version_id,
        data_dir=fi.data_dir if inline is None else "",
        mod_time_ns=fi.mod_time_ns, size=fi.size, deleted=fi.deleted,
        metadata=dict(fi.metadata), parts=list(fi.parts), erasure=ec_pos,
        inline_data=inline)


def _reconstruct_rows(es: ErasureSet, fi: FileInfo,
                      rows: list[np.ndarray | None], avail: list[int],
                      need: list[int]) -> list[np.ndarray]:
    """Rebuild `need` shard rows (full logical shard-file contents) from K
    available rows — batched device matmul for the full blocks, CPU codec
    for the tail fragment (cf. Erasure.Heal decode->re-encode,
    /root/reference/cmd/erasure-lowlevel-heal.go:31)."""
    ec = fi.erasure
    k, m = ec.data_blocks, ec.parity_blocks
    shard_size = ec.shard_size
    logical = rows[avail[0]].size
    use = avail[:k]
    # Host fast path: RS is positional, so whole LOGICAL rows (full
    # blocks AND tail in one go) transform with per-row pointers — no
    # batch stacking, no per-block loop (native ec_gf_rows, GFNI when
    # the CPU has it).
    if not es._use_device and k + m <= 64:
        try:
            from native import ecio_native
            return ecio_native.gf_transform_rows(
                [rows[s] for s in use], list(use), k, m, list(need))
        except Exception:  # noqa: BLE001 — no toolchain: batch path
            pass
    # Split logical shard into full-block matrix + tail.
    n_full = logical // shard_size
    tail_len = logical - n_full * shard_size
    out_rows = [np.zeros(logical, dtype=np.uint8) for _ in need]
    if n_full:
        x = np.stack([rows[s][:n_full * shard_size].reshape(n_full,
                                                            shard_size)
                      for s in use], axis=1)  # (B, K, S)
        y = es._transform(k, m, x, tuple(use), tuple(need))  # (B, T, S)
        for j in range(len(need)):
            out_rows[j][:n_full * shard_size] = y[:, j, :].reshape(-1)
    if tail_len:
        shards_in: list[np.ndarray | None] = [None] * (k + m)
        for s in avail:
            shards_in[s] = rows[s][n_full * shard_size:]
        full = es._cpu(k, m).reconstruct(shards_in)
        for j, s in enumerate(need):
            out_rows[j][n_full * shard_size:] = full[s]
    return out_rows


#: Blocks per reconstruct batch — one device dispatch / native C pass,
#: and the memory bound of the heal pipeline (O(batch), never O(part)).
HEAL_BATCH_BLOCKS = BATCH_BLOCKS


def _pipelined() -> bool:
    """Env escape hatch (MTPU_HEAL_PIPELINE=0): run the one-shot serial
    reference path. The equivalence test drives both implementations
    over the same corruption matrix and diffs the repaired bytes."""
    return os.environ.get("MTPU_HEAL_PIPELINE", "1") != "0"


def _heal_data(es: ErasureSet, bucket: str, obj: str, fi: FileInfo,
               sources: list[int], targets: list[int]) -> None:
    """Reconstruct every part's shard files onto the target drives and
    publish atomically via rename_data.

    Pipelined: surviving-shard reads fan out across drives, parts are
    staged in HEAL_BATCH_BLOCKS-deep batches through a double-buffered
    read -> verify+decode(+re-encode) -> write pipeline (the Erasure.Heal
    role, cmd/erasure-lowlevel-heal.go:31, on the PUT path's `pending`
    scheme), so drive I/O for batch i+1 overlaps the decode of batch i
    and the repaired-shard appends of batch i-1."""
    ec = fi.erasure
    dist = ec.distribution
    tmp_id = f"heal-{uuid.uuid4().hex}"
    need = sorted({dist[pos] - 1 for pos in targets})

    try:
        for part in fi.parts:
            if _pipelined():
                _heal_part_pipelined(es, bucket, obj, fi, part, sources,
                                     targets, need, tmp_id)
            else:
                _heal_part_serial(es, bucket, obj, fi, part, sources,
                                  targets, need, tmp_id)
        with ospan.span("heal.publish"):
            for pos in targets:
                fi_pos = _fi_for_drive(fi, pos)
                _ensure_bucket_on(es.drives[pos], bucket)
                es.drives[pos].rename_data(SYS_VOL, f"{TMP_DIR}/{tmp_id}",
                                           fi_pos, bucket, obj)
        DATA_PATH.record_heal_object()
    finally:
        for pos in targets:
            try:
                es.drives[pos].delete(SYS_VOL, f"{TMP_DIR}/{tmp_id}",
                                      recursive=True)
            except StorageError:
                pass


def _heal_part_serial(es: ErasureSet, bucket: str, obj: str, fi: FileInfo,
                      part, sources: list[int], targets: list[int],
                      need: list[int], tmp_id: str) -> None:
    """Reference implementation: whole-part staging, serial drive loop
    (the pre-pipeline path, kept as the equivalence oracle)."""
    ec = fi.erasure
    dist = ec.distribution
    k = ec.data_blocks
    path = f"{obj}/{fi.data_dir}/part.{part.number}"
    logical = ec.shard_file_size(part.size)
    rows: list[np.ndarray | None] = [None] * (k + ec.parity_blocks)
    got = 0
    # Read + verify source shards until K good ones (spares beyond
    # the first K cover sources that fail at read time).
    for pos in sources:
        if got >= k:
            break
        s = dist[pos] - 1
        try:
            d = es.drives[pos]
            # mmap on local drives: the fused unframe verifies
            # straight off the page cache (no read() copy).
            raw = (d.read_file_view(bucket, path)
                   if isinstance(d, LocalDrive)
                   else d.read_file(bucket, path))
            row = bitrot_io.unframe_shard(
                raw, ec.shard_size, verify=True,
                algo=ec.bitrot_algo(part.number))
            if row.size != logical:
                raise ErrFileCorrupt("short shard")
            rows[s] = row
            got += 1
        except StorageError:
            continue
    if got < k:
        raise ErrErasureReadQuorum(
            f"heal {bucket}/{obj} part {part.number}: "
            f"{got} readable < {k}")
    avail = [s for s in range(len(rows)) if rows[s] is not None]
    missing = [s for s in need if rows[s] is None]
    rebuilt = _reconstruct_rows(es, fi, rows, avail, missing) \
        if missing else []
    for s, row in zip(missing, rebuilt):
        rows[s] = row
    for pos in targets:
        s = dist[pos] - 1
        framed = bitrot_io.frame_shard(
            rows[s], ec.shard_size, ec.bitrot_algo(part.number))
        es.drives[pos].create_file(
            SYS_VOL, f"{TMP_DIR}/{tmp_id}/part.{part.number}",
            framed)


def _heal_part_pipelined(es: ErasureSet, bucket: str, obj: str,
                         fi: FileInfo, part, sources: list[int],
                         targets: list[int], need: list[int],
                         tmp_id: str) -> None:
    """Batched double-buffered reconstruct of one part onto the targets.

    Memory is O(batch): surviving shards are read as ranged frame
    segments (fanned out across drives), each HEAL_BATCH_BLOCKS batch is
    verified+decoded in one native/device pass (+re-encoded for parity
    targets), framed vectorized, and appended to the per-target staging
    files with one write in flight — so batch i+1's reads overlap batch
    i's decode and batch i-1's writes. A bitrot hit or read failure
    drops the source and promotes a spare for that batch onward, exactly
    like the GET path's spare-read policy."""
    from ..ops import coalesce, fused
    from ..ops import devcache as devcache_mod
    from .erasure_set import _ecio_mod, _mesh_mode
    ec = fi.erasure
    dist = ec.distribution
    k, m = ec.data_blocks, ec.parity_blocks
    S = ec.shard_size
    algo = ec.bitrot_algo(part.number)
    hs = bitrot_io.digest_size(algo)
    frame = hs + S
    logical = ec.shard_file_size(part.size)
    n_full = part.size // BLOCK_SIZE
    tail_shard = logical - n_full * S
    want = bitrot_io.bitrot_shard_file_size(logical, S, algo)
    path = f"{obj}/{fi.data_dir}/part.{part.number}"
    tmp_path = f"{TMP_DIR}/{tmp_id}/part.{part.number}"
    need_data = [s for s in need if s < k]
    need_parity = [s for s in need if s >= k]

    src_pos = {dist[pos] - 1: pos for pos in sources}
    candidates = sorted(src_pos)
    serial = es._serial_local()

    def quorum_err(got: int) -> ErrErasureReadQuorum:
        return ErrErasureReadQuorum(
            f"heal {bucket}/{obj} part {part.number}: "
            f"{got} readable < {k}")

    # Source election: a framed-size stat weeds out missing/truncated
    # shards before any data moves (fan-out: one stat per drive).
    def usable(s: int) -> bool:
        d = es.drives[src_pos[s]]
        try:
            return d is not None and d.file_size(bucket, path) == want
        except StorageError:
            return False

    if serial:
        good = [s for s in candidates if usable(s)]
    else:
        flags = list(es.pool.map(usable, candidates))
        good = [s for s, f in zip(candidates, flags) if f]
    if len(good) < k:
        raise quorum_err(len(good))
    sel = good[:k]          # kept sorted; mutated on bitrot/read failure
    spares = good[k:]

    fused_host = None
    if not es._use_device and algo == "mxh256" and k + m <= 64 \
            and not _mesh_mode():
        fused_host = _ecio_mod()
    # Device-resident shard cache: a prior healthy GET's verified data
    # matrix can cover a heal batch — the rebuild then runs straight
    # off residency (host copy, or the already-placed device array):
    # zero re-reads of source shards, zero uploads.
    dcache = devcache_mod.get() if devcache_mod.enabled() else None

    def read_one(s: int, lo: int, ln: int) -> bytes:
        raw = es.drives[src_pos[s]].read_file(bucket, path, lo, ln)
        if len(raw) != ln:
            raise ErrFileCorrupt(
                f"short shard segment ({len(raw)} != {ln})")
        return raw

    def read_batch(batch):
        """Read stage: fan the selected sources' frame segments out
        across drives. Failures are left out — the compute stage drops
        the source and promotes a spare."""
        b0, nb = batch
        lo, ln = b0 * frame, nb * frame
        t0 = time.perf_counter()
        cur = list(sel)
        data: dict[int, bytes] = {}
        if serial:
            for s in cur:
                try:
                    data[s] = read_one(s, lo, ln)
                except StorageError:
                    pass
        else:
            futs = {s: es.pool.submit(read_one, s, lo, ln) for s in cur}
            for s, f in futs.items():
                try:
                    data[s] = f.result()
                except StorageError:
                    pass
        return batch, data, time.perf_counter() - t0

    def compute(item):
        """Verify + decode (+ re-encode parity) one batch; on a bad row,
        swap in a spare and rerun the batch."""
        (b0, nb), data, read_s = item
        lo, ln = b0 * frame, nb * frame
        t0 = time.perf_counter()
        if dcache is not None:
            found = dcache.lookup_range(
                es._devcache_owner, bucket, obj, part.number,
                fi.data_dir, algo, b0, b0 + nb)
            if found is not None:
                # The batch's verified systematic matrix is resident:
                # rebuild every target from it.  GF arithmetic is
                # exact, so the rebuilt rows are byte-identical to the
                # re-read path's (the cached bytes ARE the shards that
                # passed verify at fill time).
                e, boff = found
                y = e.host[boff:boff + nb]
                out = {}
                rebuilt = None
                if need:
                    xd = e.dev
                    if es._use_device and xd is not None \
                            and algo in fused.DEVICE_ALGOS \
                            and not _mesh_mode():
                        # Already device-resident: dispatch against the
                        # placed array — zero upload.
                        _, reb_d = fused.verify_and_transform(
                            xd[boff:boff + nb], k, m, tuple(range(k)),
                            tuple(need), algo=algo,
                            device=es.device_idx)
                        rebuilt = np.asarray(reb_d)
                    else:
                        rebuilt = np.asarray(es._transform(
                            k, m, y, tuple(range(k)), tuple(need)))
                for j, s in enumerate(need):
                    out[s] = rebuilt[:, j, :]
                stack = np.stack([out[s] for s in need])
                framed = bitrot_io.frame_shard_views(
                    None, None, None, algo, shards=stack)
                return ((b0, nb), dict(zip(need, framed)), read_s,
                        time.perf_counter() - t0)
        while True:
            # Reconcile with the current selection: a source dropped by
            # an earlier batch leaves a hole in this prefetched read; a
            # promoted spare has no bytes yet.
            for s in [s for s in sel if s not in data]:
                try:
                    data[s] = read_one(s, lo, ln)
                except StorageError:
                    sel.remove(s)
            while len(sel) < k:
                if not spares:
                    raise quorum_err(len(sel))
                s = spares.pop(0)
                try:
                    data[s] = read_one(s, lo, ln)
                except StorageError:
                    continue
                sel.append(s)
                sel.sort()
            cur = list(sel)
            out: dict[int, np.ndarray] = {}
            if fused_host is not None:
                # ONE C pass: digest every chosen row, gather the data
                # matrix, rebuild missing data rows. Parity targets
                # re-encode from the full matrix right after.
                dmiss = [s for s in range(k) if s not in cur]
                dtargets = dmiss if need_parity else \
                    [s for s in dmiss if s in need_data]
                y, okf, nbad = fused_host.get_verify(
                    [data[s] for s in cur], cur, nb, S, k, m, dtargets)
                if nbad:
                    for j, s in enumerate(cur):
                        if not okf[j]:
                            sel.remove(s)
                            data.pop(s, None)
                    continue
                for s in need_data:
                    out[s] = y[:, s, :]
                if need_parity:
                    prows = np.asarray(es._native(k, m).transform_blocks(
                        y, tuple(range(k)), tuple(need_parity)))
                    for j, s in enumerate(need_parity):
                        out[s] = prows[:, j, :]
                break
            # Generic path: gather rows, digest-verify, then ONE
            # transform straight to every needed row — transform_matrix
            # maps any K sources to arbitrary targets, parity included.
            bufs = {s: np.frombuffer(data[s], dtype=np.uint8)
                    .reshape(nb, frame) for s in cur}
            x = np.empty((nb, k, S), dtype=np.uint8)
            for i, s in enumerate(cur):
                x[:, i, :] = bufs[s][:, hs:]
            co = coalesce.get() if coalesce.enabled() else None
            if es._use_device and algo in fused.DEVICE_ALGOS \
                    and bitrot_io.device_preferred(algo) \
                    and not _mesh_mode():
                if co is not None:
                    # Heal shares the verify_and_transform queue with
                    # degraded GETs — concurrent heals of sibling parts
                    # (same damage pattern) pack into one dispatch.
                    h = co.submit(
                        ("vt", k, m, tuple(cur), tuple(need), algo, S),
                        x, es._vt_kernel(k, m, tuple(cur), tuple(need),
                                         algo, device=es.device_idx),
                        weight=nb, device=es.device_idx)
                    try:
                        digests, rebuilt = h.result()
                        h.release()
                    except Exception:  # noqa: BLE001 — direct fallback
                        DATA_PATH.record_co_fallback()
                        digests, rebuilt = fused.verify_and_transform(
                            x, k, m, tuple(cur), tuple(need), algo=algo,
                            device=es.device_idx)
                        digests = np.asarray(digests)
                        rebuilt = np.asarray(rebuilt) if need else None
                    if not need:
                        rebuilt = None
                else:
                    digests, rebuilt = fused.verify_and_transform(
                        x, k, m, tuple(cur), tuple(need), algo=algo,
                        device=es.device_idx)
                    digests = np.asarray(digests)
                    rebuilt = np.asarray(rebuilt) if need else None
            else:
                if co is not None and co.hot(es.device_idx):
                    h = co.submit(("digest", algo, S),
                                  x.reshape(nb * k, S),
                                  coalesce.make_digest_kernel(algo),
                                  weight=nb, device=es.device_idx)
                    try:
                        digests = h.result().reshape(nb, k, hs)
                        h.release()
                    except Exception:  # noqa: BLE001 — direct fallback
                        DATA_PATH.record_co_fallback()
                        digests = bitrot_io._hash_batch(
                            x.reshape(nb * k, S), algo).reshape(nb, k, hs)
                else:
                    digests = bitrot_io._hash_batch(
                        x.reshape(nb * k, S), algo).reshape(nb, k, hs)
                rebuilt = np.asarray(es._transform(
                    k, m, x, tuple(cur), tuple(need))) if need else None
            bad = [cur[i] for i in range(k)
                   if not np.array_equal(digests[:, i],
                                         bufs[cur[i]][:, :hs])]
            if bad:
                for s in bad:
                    sel.remove(s)
                    data.pop(s, None)
                continue
            for j, s in enumerate(need):
                out[s] = rebuilt[:, j, :]
            break
        # Vectorized framing of the rebuilt rows (same frame layout the
        # serial frame_shard produces, batch-concatenation identical).
        stack = np.stack([out[s] for s in need])         # (T, nb, S)
        framed = bitrot_io.frame_shard_views(None, None, None, algo,
                                             shards=stack)
        payload = dict(zip(need, framed))
        return (b0, nb), payload, read_s, time.perf_counter() - t0

    def write_batch(res):
        """Write stage: append the repaired frames to every target's
        staging file (fan-out across target drives)."""
        (b0, nb), payload, read_s, decode_s = res
        t0 = time.perf_counter()

        def put(pos):
            es.drives[pos].append_file(SYS_VOL, tmp_path,
                                       payload[dist[pos] - 1])
        if serial or len(targets) == 1:
            for pos in targets:
                put(pos)
        else:
            list(es.pool.map(put, targets))
        DATA_PATH.record_heal_batch(
            nb, HEAL_BATCH_BLOCKS, len(sel) * nb * frame,
            len(targets) * nb * frame, read_s, decode_s,
            time.perf_counter() - t0)

    batches = [(b0, min(HEAL_BATCH_BLOCKS, n_full - b0))
               for b0 in range(0, n_full, HEAL_BATCH_BLOCKS)]
    # The pipeline threads pay off even on the 1-core host: reads,
    # appends, and the native decode all release the GIL, so disk I/O
    # for neighboring batches genuinely overlaps the C pass.
    def bridge(read_s, compute_s, write_s):
        # Runs in the (possibly traced) caller thread — an
        # admin-triggered heal shows its stage times in the trace.
        ospan.record("heal.read", read_s)
        ospan.record("heal.decode", compute_s)
        ospan.record("heal.write", write_s)

    pl.StagePipeline(es._iter_pool).run(
        pl.prefetch_map(read_batch, batches, es._iter_pool, depth=1),
        compute, write_batch, on_batch=bridge)

    if tail_shard:
        # Tail fragment (one short frame per shard): CPU oracle codec,
        # same bytes as the serial whole-row path.
        lo, ln = n_full * frame, hs + tail_shard
        shards_in: list[np.ndarray | None] = [None] * (k + m)
        got = 0
        for s in list(sel) + spares:
            if got >= k:
                break
            try:
                row = bitrot_io.unframe_shard(
                    read_one(s, lo, ln), tail_shard, verify=True,
                    algo=algo)
                if row.size != tail_shard:
                    raise ErrFileCorrupt("short tail")
                shards_in[s] = row
                got += 1
            except StorageError:
                continue
        if got < k:
            raise quorum_err(got)
        if any(shards_in[s] is None for s in need):
            full = es._cpu(k, m).reconstruct(shards_in)
            for s in need:
                if shards_in[s] is None:
                    shards_in[s] = full[s]
        for pos in targets:
            es.drives[pos].append_file(
                SYS_VOL, tmp_path,
                bitrot_io.frame_shard(shards_in[dist[pos] - 1], S, algo))


def heal_format(es: ErasureSet) -> list[int]:
    """Restore format.json + the system volume on drives that lost
    them (wiped/replaced disk) — the HealFormat step that must precede
    bucket/object healing, because every write stages through the sys
    volume's tmp dir (cf. HealFormat, cmd/format-erasure.go:798).
    Returns healed positions."""
    from ..storage.format import load_format, new_format, save_format
    fmts: list[dict | None] = []
    for d in es.drives:
        if d is None:
            fmts.append(None)
            continue
        try:
            fmts.append(load_format(d))
        except StorageError:
            fmts.append(None)
    ref = next((f for f in fmts if f), None)
    if ref is None:
        return []
    layout = ref["xl"]["sets"]
    healed = []
    for pos, (d, f) in enumerate(zip(es.drives, fmts)):
        if d is None or f is not None:
            continue
        try:
            d.init_sys_volume()
            save_format(d, new_format(ref["id"], layout,
                                      layout[es.set_index][pos]))
            healed.append(pos)
        except StorageError:
            continue
    return healed


def heal_bucket(es: ErasureSet, bucket: str) -> list[int]:
    """Create the bucket volume on drives missing it; returns healed
    positions (cf. HealBucket, /root/reference/cmd/erasure-bucket.go)."""
    res = es._map_drives(lambda d: d.stat_volume(bucket))
    present = sum(1 for _, e in res if e is None)
    if present < es._live_quorum():
        raise ErrVolumeNotFound(bucket)
    healed = []
    for pos, (_, e) in enumerate(res):
        if e is not None and es.drives[pos] is not None:
            try:
                es.drives[pos].make_volume(bucket)
                healed.append(pos)
            except StorageError:
                pass
    return healed


# ---------------------------------------------------------------------------
# Resumable drive healing (new/replaced disk).
# ---------------------------------------------------------------------------

@dataclass
class HealingTracker:
    """Persisted on the drive being healed; survives restarts mid-heal
    (cf. healingTracker, /root/reference/cmd/background-newdisks-heal-ops.go:48)."""
    heal_id: str = ""
    started_ns: int = 0
    resume_bucket: str = ""
    resume_object: str = ""
    objects_healed: int = 0
    objects_failed: int = 0
    bytes_healed: int = 0
    finished: bool = False

    def save(self, drive: LocalDrive) -> None:
        drive.write_all(SYS_VOL, HEALING_FILE, msgpackx.packb({
            "id": self.heal_id, "start": self.started_ns,
            "rb": self.resume_bucket, "ro": self.resume_object,
            "oh": self.objects_healed, "of": self.objects_failed,
            "bh": self.bytes_healed, "fin": self.finished}))

    @classmethod
    def load(cls, drive: LocalDrive) -> "HealingTracker | None":
        try:
            d = msgpackx.unpackb(drive.read_all(SYS_VOL, HEALING_FILE))
        except StorageError:
            return None
        return cls(heal_id=d.get("id", ""), started_ns=d.get("start", 0),
                   resume_bucket=d.get("rb", ""),
                   resume_object=d.get("ro", ""),
                   objects_healed=d.get("oh", 0),
                   objects_failed=d.get("of", 0),
                   bytes_healed=d.get("bh", 0),
                   finished=d.get("fin", False))

    @staticmethod
    def clear(drive: LocalDrive) -> None:
        try:
            drive.delete(SYS_VOL, HEALING_FILE)
        except StorageError:
            pass


def _set_objects(es: ErasureSet, bucket: str, skip_pos: int) -> list[str]:
    """Union of object names for a bucket across all drives but skip_pos."""
    names: set[str] = set()
    for pos, d in enumerate(es.drives):
        if d is None or pos == skip_pos:
            continue
        try:
            for name, _ in d.walk_dir(bucket):
                names.add(name)
        except StorageError:
            continue
    return sorted(names)


def _heal_workers(es: ErasureSet, workers: int | None) -> int:
    """Bounded default: a couple of concurrent object heals per spare
    core, 1 on the serial-local host (same policy as the data-path
    fan-out, ErasureSet._SERIAL_FANOUT).  Under foreground pressure
    the overload plane shrinks the pool further — heal yields to
    GET/PUT for drives and coalescer lanes (server/qos.py)."""
    from ..server import qos as _qos
    if workers is not None:
        return _qos.scale_workers(max(1, int(workers)), "heal")
    n = 1 if es._serial_local() else min(4, os.cpu_count() or 1)
    return _qos.scale_workers(n, "heal")


def heal_drive(es: ErasureSet, pos: int, checkpoint_every: int = 64,
               workers: int | None = None,
               stop: threading.Event | None = None) -> HealingTracker:
    """Walk the whole set onto one (new/replaced/wiped) drive, resumably,
    healing up to `workers` objects concurrently through the reconstruct
    pipeline (bounded submission window — no unbounded queue growth).

    The HealingTracker checkpoint only ever advances over the CONTIGUOUS
    completed prefix of the sorted walk: with concurrent workers, object
    i+1 may finish before object i, and persisting i+1 as the resume
    point would skip i forever if the heal is interrupted mid-batch.
    Re-healing a beyond-frontier object on resume is a no-op.

    cf. healErasureSet, /root/reference/cmd/global-heal.go:166."""
    drive = es.drives[pos]
    if drive is None:
        raise ErrVolumeNotFound(f"drive position {pos} offline")
    tracker = HealingTracker.load(drive)
    if tracker is None or tracker.finished:
        tracker = HealingTracker(heal_id=str(uuid.uuid4()),
                                 started_ns=time.time_ns())
        tracker.save(drive)
    workers = _heal_workers(es, workers)

    def walk():
        for bucket in es.list_buckets():
            if bucket < tracker.resume_bucket:
                continue
            heal_bucket(es, bucket)
            for obj in _set_objects(es, bucket, skip_pos=pos):
                if (bucket == tracker.resume_bucket
                        and obj <= tracker.resume_object):
                    continue
                yield bucket, obj

    def heal_one(item):
        bucket, obj = item
        healed = nbytes = 0
        for r in heal_object(es, bucket, obj):
            if pos in r.healed_drives:
                healed += 1
                nbytes += r.size
        return healed, nbytes

    mu = threading.Lock()
    frontier = pl.Frontier()
    items: dict[int, tuple[str, str]] = {}
    done_below = 0          # items consumed by the frontier so far
    since_ckpt = 0
    pool = ThreadPoolExecutor(max_workers=workers) if workers > 1 else None
    try:
        for idx, item, res, err in pl.run_window(
                heal_one, walk(), pool, window=workers * 2, stop=stop):
            if err is not None and not isinstance(err, StorageError):
                raise err
            with mu:
                if err is not None:
                    tracker.objects_failed += 1
                else:
                    tracker.objects_healed += res[0]
                    tracker.bytes_healed += res[1]
                items[idx] = item
                front = frontier.mark(idx)
                while done_below < front:
                    tracker.resume_bucket, tracker.resume_object = \
                        items.pop(done_below)
                    done_below += 1
                    since_ckpt += 1
                if since_ckpt >= checkpoint_every:
                    tracker.save(drive)
                    since_ckpt = 0
    finally:
        if pool is not None:
            pool.shutdown(wait=True)
    if stop is None or not stop.is_set():
        tracker.finished = True
    tracker.save(drive)
    return tracker


def heal_bucket_objects(es: ErasureSet, bucket: str, prefix: str = "",
                        deep: bool = False, remove_dangling: bool = True,
                        workers: int | None = None,
                        stop: threading.Event | None = None,
                        on_object=None) -> list[HealResult]:
    """Heal every object in a bucket through the same bounded worker
    pool as heal_drive (the per-bucket arm of the background heal
    sequence). `on_object(name, results, err)` observes each object as
    it completes; non-storage errors propagate."""
    workers = _heal_workers(es, workers)
    names = [n for n in _set_objects(es, bucket, skip_pos=-1)
             if n.startswith(prefix)]

    def one(name):
        return heal_object(es, bucket, name, deep=deep,
                           remove_dangling=remove_dangling)

    results: list[HealResult] = []
    pool = ThreadPoolExecutor(max_workers=workers) if workers > 1 else None
    try:
        from ..server import qos as _qos
        for _, name, res, err in pl.run_window(
                one, names, pool, window=workers * 2, stop=stop):
            if err is not None and not isinstance(err, StorageError):
                raise err
            if on_object is not None:
                on_object(name, res, err)
            if err is None and res:
                results.extend(res)
            # Pace between objects under foreground pressure (no-op
            # below the threshold: one float compare per object).
            _qos.bg_pause("heal")
    finally:
        if pool is not None:
            pool.shutdown(wait=True)
    return results


def device_parallel_enabled() -> bool:
    """MTPU_HEAL_DEVICE_PARALLEL=0 is the serial-sweep oracle the
    equivalence tests diff against (read per call)."""
    return os.environ.get("MTPU_HEAL_DEVICE_PARALLEL", "1") != "0"


def sweep_sets_device_parallel(sets, job, stop: threading.Event | None = None):
    """Run `job(es)` over every erasure set with device-parallelism
    (PR 10): sets are grouped by their lane affinity (`es.device_idx`)
    and one worker thread per device runs its group's sets in order —
    per-set heal jobs against DIFFERENT devices dispatch concurrently
    while one device's own jobs stay serial (no oversubscribing a
    single accelerator queue, and within-device ordering matches the
    serial sweep).  With one group, a stop request, or the serial
    oracle flag, this degrades to the plain in-order loop.

    Returns {set_index: job result}.  The first exception any group
    raised is re-raised after every group finished — same containment
    the serial loop gets from its caller, but no set is silently
    skipped because a sibling on another device failed."""
    groups: dict[int, list] = {}
    for es in sets:
        groups.setdefault(getattr(es, "device_idx", 0), []).append(es)
    results: dict[int, object] = {}
    if not device_parallel_enabled() or len(groups) <= 1:
        for es in sets:
            if stop is not None and stop.is_set():
                break
            results[es.set_index] = job(es)
        return results
    mu = threading.Lock()
    errors: list[BaseException] = []

    def run_group(group):
        for es in group:
            if stop is not None and stop.is_set():
                return
            try:
                r = job(es)
            except BaseException as e:  # noqa: BLE001 — collect, re-raise
                with mu:
                    errors.append(e)
                return
            with mu:
                results[es.set_index] = r

    threads = [threading.Thread(target=run_group, args=(g,),
                                name=f"mtpu-heal-d{d}", daemon=True)
               for d, g in sorted(groups.items())]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results
