"""erasureSets equivalent: a static hash ring of N erasure sets.

Each object routes to exactly one set via SipHash-2-4 keyed by the
deployment id (cf. sipHashMod + getHashedSet,
/root/reference/cmd/erasure-sets.go:734,771). Bucket operations fan out to
every set; listings quorum-merge across sets. Format bootstrap binds each
drive to its (set, position) slot (cf. newErasureSets,
/root/reference/cmd/erasure-sets.go:342).
"""

from __future__ import annotations

import uuid

from ..storage.drive import LocalDrive
from ..storage.errors import (ErrBucketExists, ErrBucketNotFound,
                              StorageError)
from ..storage.format import init_format_sets
from ..storage.xlmeta import FileInfo
from ..utils.siphash import sip_hash_mod
from . import heal as heal_mod
from . import multipart as mp
from .erasure_set import ErasureSet


class ErasureSets:
    """N sets x set_drive_count drives, one pool's worth of capacity."""

    def __init__(self, drives: list[LocalDrive | None],
                 set_drive_count: int,
                 default_parity: int | None = None,
                 deployment_id: str | None = None,
                 nslock=None, preloaded_format: dict | None = None):
        """preloaded_format: a format already loaded+verified by the
        cluster boot (wait_format) — skips a second full-deployment
        format scan, which in a cluster is one RPC round-trip per
        remote drive."""
        if set_drive_count < 2:
            raise ValueError("set_drive_count must be >= 2")
        if len(drives) % set_drive_count != 0:
            raise ValueError(
                f"{len(drives)} drives not divisible by set size "
                f"{set_drive_count}")
        self.set_drive_count = set_drive_count
        self.set_count = len(drives) // set_drive_count
        rows = [drives[i * set_drive_count:(i + 1) * set_drive_count]
                for i in range(self.set_count)]
        fmt = (preloaded_format if preloaded_format is not None
               else init_format_sets(rows, deployment_id))
        self.deployment_id = fmt["id"]
        self._dep_key = uuid.UUID(self.deployment_id).bytes
        self.sets = [ErasureSet(row, default_parity=default_parity,
                                set_index=i, nslock=nslock)
                     for i, row in enumerate(rows)]

    # -- placement -----------------------------------------------------------

    def set_for(self, obj: str) -> ErasureSet:
        """The set this object lives on (cf. getHashedSet,
        /root/reference/cmd/erasure-sets.go:771)."""
        idx = sip_hash_mod(obj, self.set_count, self._dep_key)
        return self.sets[idx]

    # -- bucket ops (fan out to all sets) ------------------------------------

    def make_bucket(self, bucket: str) -> None:
        errs = []
        for s in self.sets:
            try:
                s.make_bucket(bucket)
                errs.append(None)
            except StorageError as e:
                errs.append(e)
        if errs and all(isinstance(e, ErrBucketExists) for e in errs):
            raise ErrBucketExists(bucket)
        real = [e for e in errs
                if e is not None and not isinstance(e, ErrBucketExists)]
        if real:
            raise real[0]

    def bucket_exists(self, bucket: str, cached: bool = False) -> bool:
        return any(s.bucket_exists(bucket, cached=cached)
                   for s in self.sets)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        errs = []
        for s in self.sets:
            try:
                s.delete_bucket(bucket, force=force)
                errs.append(None)
            except StorageError as e:
                errs.append(e)
        if errs and all(isinstance(e, ErrBucketNotFound) for e in errs):
            raise ErrBucketNotFound(bucket)
        real = [e for e in errs
                if e is not None and not isinstance(e, ErrBucketNotFound)]
        if real:
            raise real[0]

    def list_buckets(self) -> list[str]:
        names: set[str] = set()
        for s in self.sets:
            names.update(s.list_buckets())
        return sorted(names)

    # -- object ops (route to one set) ---------------------------------------

    def put_object(self, bucket: str, obj: str, data: bytes,
                   **kw) -> FileInfo:
        return self.set_for(obj).put_object(bucket, obj, data, **kw)

    def get_object(self, bucket: str, obj: str, offset: int = 0,
                   length: int = -1, version_id: str = ""):
        return self.set_for(obj).get_object(bucket, obj, offset, length,
                                            version_id)

    def get_object_iter(self, bucket: str, obj: str, offset: int = 0,
                        length: int = -1, version_id: str = ""):
        return self.set_for(obj).get_object_iter(bucket, obj, offset,
                                                 length, version_id)

    def sendfile_plan(self, bucket: str, obj: str, offset: int = 0,
                      length: int = -1, version_id: str = ""):
        """Kernel-send plan when the owning set's framing allows it
        (ErasureSet.sendfile_plan), else None."""
        return self.set_for(obj).sendfile_plan(bucket, obj, offset,
                                               length, version_id)

    def head_object(self, bucket: str, obj: str,
                    version_id: str = "") -> FileInfo:
        return self.set_for(obj).head_object(bucket, obj, version_id)

    def delete_object(self, bucket: str, obj: str, version_id: str = "",
                      versioned: bool = False):
        return self.set_for(obj).delete_object(bucket, obj, version_id,
                                               versioned)

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "",
                     max_keys: int = 10000) -> list[FileInfo]:
        merged: list[FileInfo] = []
        for s in self.sets:
            merged.extend(s.list_objects(bucket, prefix,
                                         marker=marker,
                                         max_keys=max_keys))
        merged.sort(key=lambda fi: fi.name)
        return merged[:max_keys]

    def list_object_versions(self, bucket: str, obj: str) -> list[FileInfo]:
        return self.set_for(obj).list_object_versions(bucket, obj)

    # -- multipart (route to one set) ----------------------------------------

    def new_multipart_upload(self, bucket: str, obj: str, **kw) -> str:
        return mp.new_multipart_upload(self.set_for(obj), bucket, obj, **kw)

    def put_object_part(self, bucket: str, obj: str, upload_id: str,
                        part_number: int, data: bytes):
        return mp.put_object_part(self.set_for(obj), bucket, obj,
                                  upload_id, part_number, data)

    def complete_multipart_upload(self, bucket: str, obj: str,
                                  upload_id: str, parts, **kw) -> FileInfo:
        return mp.complete_multipart_upload(self.set_for(obj), bucket, obj,
                                            upload_id, parts, **kw)

    def abort_multipart_upload(self, bucket: str, obj: str,
                               upload_id: str) -> None:
        mp.abort_multipart_upload(self.set_for(obj), bucket, obj, upload_id)

    def list_parts(self, bucket: str, obj: str, upload_id: str):
        return mp.list_parts(self.set_for(obj), bucket, obj, upload_id)

    def read_part_bytes(self, bucket: str, obj: str, upload_id: str,
                        part_number: int) -> bytes:
        return mp.read_part_bytes(self.set_for(obj), bucket, obj,
                                  upload_id, part_number)

    def upload_metadata(self, bucket: str, obj: str,
                        upload_id: str) -> dict:
        return mp.upload_metadata(self.set_for(obj), bucket, obj,
                                  upload_id)

    def list_multipart_uploads(self, bucket: str,
                               prefix: str = "") -> list[dict]:
        out = []
        for s in self.sets:
            out.extend(mp.list_multipart_uploads(s, bucket, prefix))
        return sorted(out, key=lambda u: (u["object"], u["upload_id"]))

    # -- heal ----------------------------------------------------------------

    def heal_object(self, bucket: str, obj: str, version_id: str = "",
                    **kw) -> list[heal_mod.HealResult]:
        return heal_mod.heal_object(self.set_for(obj), bucket, obj,
                                    version_id, **kw)

    def heal_bucket(self, bucket: str) -> dict[int, list[int]]:
        # Device-parallel sweep (PR 10): sets on different device lanes
        # heal concurrently; MTPU_HEAL_DEVICE_PARALLEL=0 restores the
        # serial in-order loop.
        res = heal_mod.sweep_sets_device_parallel(
            self.sets, lambda s: heal_mod.heal_bucket(s, bucket))
        return {i: healed for i, s in enumerate(self.sets)
                if (healed := res.get(s.set_index))}

    def device_map(self) -> dict[int, list[int]]:
        """device index -> set indices affine to it (admin-info)."""
        out: dict[int, list[int]] = {}
        for i, s in enumerate(self.sets):
            out.setdefault(s.device_idx, []).append(i)
        return out

    # -- capacity ------------------------------------------------------------

    def disk_usage(self) -> dict:
        total = free = 0
        for s in self.sets:
            for d in s.drives:
                if d is None:
                    continue
                try:
                    info = d.disk_info()
                except StorageError:
                    # Breaker-OFFLINE (circuit open) or otherwise dead:
                    # report the capacity we can still see.
                    continue
                total += info["total"]
                free += info["free"]
        return {"total": total, "free": free}
