"""dsync: quorum-based distributed read-write mutex.

The internal/dsync equivalent (/root/reference/internal/dsync/drwmutex.go:64):
a lock is acquired by broadcasting to ALL lockers in the set and winning a
quorum — n/2+1 for writes, n/2 for reads (tolerance math :375); losers
release everything and retry with jitter until the deadline. A held lock
is kept alive by a background refresh loop; losing refresh quorum fires
the loss callback so the owning operation can cancel
(cf. startContinousLockRefresh :221).

Lockers implement the LocalLocker surface; remote ones go through
rpc.lock_rpc.RemoteLocker. Transport failures count as vote-no, exactly
like the reference treats an unreachable lock server.
"""

from __future__ import annotations

import random
import threading
import time
import uuid

from ..storage.errors import StorageError


class LockLost(StorageError):
    """Lock acquisition timed out or quorum was lost mid-operation.
    Subclasses StorageError so handler-level `except StorageError` paths
    map it to a retryable 503 (api_errors.from_storage_error)."""


class DRWMutex:
    def __init__(self, resource: str, lockers: list, *,
                 refresh_interval: float = 10.0,
                 lease_duration: float | None = None,
                 loss_callback=None):
        self.resource = resource
        self.lockers = lockers
        self.refresh_interval = refresh_interval
        # Lease contract (cf. drwmutex.go refresh + local-locker stale
        # sweep): a holder that cannot REACH refresh quorum within
        # lease_duration must consider the lock lost — by then a
        # partitioned majority may have stale-swept its entry and
        # granted the lock to someone else, so acking work under the
        # old grant could conflict.  The default (2.5 intervals) keeps
        # the lease safely under LocalLocker's 30s stale_after at the
        # default 10s refresh: the holder gives up BEFORE the survivors
        # hand out the resource.
        self.lease_duration = (lease_duration
                               if lease_duration is not None
                               else refresh_interval * 2.5)
        self.loss_callback = loss_callback
        self.uid = uuid.uuid4().hex
        self._held: str | None = None          # "w" | "r" | None
        self._mode: str | None = None          # sticky: what we acquired
        self._lease_ok_at = 0.0    # monotonic time of last quorum ack
        self._stop_refresh = threading.Event()
        self._refresh_thread: threading.Thread | None = None

    # -- quorum math (cf. drwmutex.go: write n/2+1, read n/2) ---------------

    @property
    def write_quorum(self) -> int:
        return len(self.lockers) // 2 + 1

    @property
    def read_quorum(self) -> int:
        return max(len(self.lockers) // 2, 1)

    # -- acquire -------------------------------------------------------------

    def _broadcast(self, op: str) -> int:
        votes = 0
        for lk in self.lockers:
            try:
                if getattr(lk, op)(self.resource, self.uid):
                    votes += 1
            except Exception:  # noqa: BLE001 — unreachable locker = no vote
                continue
        return votes

    def _release_all(self, op: str) -> None:
        for lk in self.lockers:
            try:
                getattr(lk, op)(self.resource, self.uid)
            except Exception:  # noqa: BLE001
                continue

    def _acquire(self, op: str, unop: str, quorum: int,
                 timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        attempt = 0
        while True:
            votes = self._broadcast(op)
            if votes >= quorum:
                return True
            # Lost the election: release our partial votes so competing
            # acquirers aren't deadlocked on fragments.
            self._release_all(unop)
            if time.monotonic() >= deadline:
                return False
            attempt += 1
            time.sleep(min(0.05 * attempt, 0.5) * (0.5 + random.random()))

    def get_lock(self, timeout: float = 10.0) -> bool:
        if self._acquire("lock", "unlock", self.write_quorum, timeout):
            self._held = self._mode = "w"
            self._lease_ok_at = time.monotonic()
            self._start_refresh()
            return True
        return False

    def get_rlock(self, timeout: float = 10.0) -> bool:
        if self._acquire("rlock", "runlock", self.read_quorum, timeout):
            self._held = self._mode = "r"
            self._lease_ok_at = time.monotonic()
            self._start_refresh()
            return True
        return False

    # -- lease validity ------------------------------------------------------

    def lease_expired(self) -> bool:
        """Whether the holder's lease has run out: no refresh quorum ack
        within lease_duration.  A partitioned holder whose refresh
        rounds hang (black-holed lockers stall each round for the full
        transport timeout) trips this even before the refresh loop
        counts a failed round — the ack gate the operation checks
        BEFORE acknowledging its result."""
        return (self._held is not None
                and time.monotonic() - self._lease_ok_at
                > self.lease_duration)

    def is_held(self) -> bool:
        """Held AND lease-valid — the only state in which an operation
        may ack work done under this lock."""
        return self._held is not None and not self.lease_expired()

    # -- release -------------------------------------------------------------

    def unlock(self) -> None:
        """Release on every locker — even after a refresh-quorum loss
        (minority lockers may still hold our vote; leaving it would wedge
        them until the stale sweep)."""
        self._stop_refresh.set()
        if self._mode == "w":
            self._release_all("unlock")
        elif self._mode == "r":
            self._release_all("runlock")
        self._held = self._mode = None

    # -- refresh loop --------------------------------------------------------

    def _start_refresh(self) -> None:
        self._stop_refresh.clear()
        quorum = self.write_quorum if self._held == "w" else self.read_quorum

        def loop():
            while not self._stop_refresh.wait(self.refresh_interval):
                votes = 0
                for lk in self.lockers:
                    try:
                        if lk.refresh(self.resource, self.uid):
                            votes += 1
                    except Exception:  # noqa: BLE001
                        continue
                if votes >= quorum and not self.lease_expired():
                    # Quorum ack within the lease window: renew.  A
                    # quorum that only arrived AFTER the lease ran out
                    # does NOT resurrect it — the survivors may already
                    # have stale-swept us and granted the lock onward.
                    self._lease_ok_at = time.monotonic()
                    continue
                self._held = None
                if self.loss_callback is not None:
                    self.loss_callback(self.resource)
                return

        self._refresh_thread = threading.Thread(target=loop, daemon=True)
        self._refresh_thread.start()

    # -- context manager -----------------------------------------------------

    def __enter__(self) -> "DRWMutex":
        if not self.get_lock():
            raise LockLost(f"could not lock {self.resource}")
        return self

    def __exit__(self, *exc) -> None:
        self.unlock()
