"""In-memory lock table — one per node (cmd/local-locker.go equivalent).

Tracks write/read locks per resource with owner uids and last-refresh
timestamps; locks whose owner stops refreshing go stale and are swept so
a crashed client can't wedge the namespace
(cf. stale-lock force release, internal/dsync/drwmutex.go:256).
"""

from __future__ import annotations

import threading
import time


class LocalLocker:
    def __init__(self, stale_after: float = 30.0):
        self._mu = threading.Lock()
        # resource -> {"writer": uid|None, "readers": {uid: refresh_ts},
        #              "wts": refresh_ts}
        self._table: dict[str, dict] = {}
        self.stale_after = stale_after

    def _entry(self, resource: str) -> dict:
        return self._table.setdefault(
            resource, {"writer": None, "readers": {}, "wts": 0.0})

    def _sweep(self, e: dict) -> None:
        now = time.monotonic()
        if e["writer"] is not None and now - e["wts"] > self.stale_after:
            e["writer"] = None
        e["readers"] = {uid: ts for uid, ts in e["readers"].items()
                        if now - ts < self.stale_after}

    # -- NetLocker surface ---------------------------------------------------

    def lock(self, resource: str, uid: str) -> bool:
        with self._mu:
            e = self._entry(resource)
            self._sweep(e)
            if e["writer"] is not None or e["readers"]:
                return e["writer"] == uid      # re-entrant refresh-as-lock
            e["writer"] = uid
            e["wts"] = time.monotonic()
            return True

    def unlock(self, resource: str, uid: str) -> bool:
        with self._mu:
            e = self._table.get(resource)
            if e is None or e["writer"] != uid:
                return False
            e["writer"] = None
            return True

    def rlock(self, resource: str, uid: str) -> bool:
        with self._mu:
            e = self._entry(resource)
            self._sweep(e)
            if e["writer"] is not None:
                return False
            e["readers"][uid] = time.monotonic()
            return True

    def runlock(self, resource: str, uid: str) -> bool:
        with self._mu:
            e = self._table.get(resource)
            if e is None or uid not in e["readers"]:
                return False
            del e["readers"][uid]
            return True

    def refresh(self, resource: str, uid: str) -> bool:
        with self._mu:
            e = self._table.get(resource)
            if e is None:
                return False
            now = time.monotonic()
            if e["writer"] == uid:
                e["wts"] = now
                return True
            if uid in e["readers"]:
                e["readers"][uid] = now
                return True
            return False

    def force_unlock(self, resource: str) -> bool:
        with self._mu:
            self._table.pop(resource, None)
            return True

    def stats(self) -> dict:
        with self._mu:
            return {"resources": len(self._table),
                    "write_locked": sum(1 for e in self._table.values()
                                        if e["writer"] is not None)}
