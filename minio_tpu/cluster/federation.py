"""Bucket DNS federation over an etcd v3 KV store.

The cmd/etcd.go + internal/config/dns role: in a federated deployment,
every cluster publishes a CoreDNS-style SRV record per bucket under
`/skydns/<reversed domain>/<bucket>/` in etcd; CoreDNS serves those
records so clients resolve `bucket.domain` to whichever cluster owns
the bucket, and a cluster receiving a request for a bucket it does NOT
own can answer with a redirect to the owner.

The client speaks etcd's v3 JSON gateway (the gRPC-gateway etcd ships,
`/v3/kv/{put,range,deleterange}` with base64 keys/values) — the same
store the reference writes through clientv3. The env has no live etcd
(zero egress); tests run this client against an in-process fake
speaking the same routes, which is exactly how the wire encoding is
validated.
"""

from __future__ import annotations

import base64
import http.client
import json
import time


class FederationError(Exception):
    pass


class EtcdClient:
    """Minimal etcd v3 JSON-gateway KV client."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.host, self.port, self.timeout = host, port, timeout

    def _call(self, path: str, payload: dict) -> dict:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request("POST", path, body=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise FederationError(f"etcd: {e}") from None
        finally:
            conn.close()
        if resp.status != 200:
            raise FederationError(f"etcd: {resp.status} {data[:200]}")
        try:
            return json.loads(data) if data else {}
        except ValueError as e:
            raise FederationError(f"etcd: bad response: {e}") from None

    @staticmethod
    def _b64(s: bytes) -> str:
        return base64.b64encode(s).decode()

    def put(self, key: str, value: bytes) -> None:
        self._call("/v3/kv/put", {"key": self._b64(key.encode()),
                                  "value": self._b64(value)})

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        """All (key, value) pairs under a prefix."""
        start = prefix.encode()
        end = start[:-1] + bytes([start[-1] + 1]) if start else b"\x00"
        out = self._call("/v3/kv/range",
                         {"key": self._b64(start),
                          "range_end": self._b64(end)})
        pairs = []
        for kv in out.get("kvs", []) or []:
            pairs.append((base64.b64decode(kv["key"]).decode(),
                          base64.b64decode(kv.get("value", ""))))
        return pairs

    def delete(self, key_or_prefix: str, prefix: bool = False) -> int:
        start = key_or_prefix.encode()
        payload = {"key": self._b64(start)}
        if prefix:
            end = start[:-1] + bytes([start[-1] + 1])
            payload["range_end"] = self._b64(end)
        out = self._call("/v3/kv/deleterange", payload)
        return int(out.get("deleted", 0))


class BucketDNS:
    """The CoreDNS store (internal/config/dns/etcd_dns.go): SRV records
    for `bucket.domain` under /skydns/<reversed-domain>/<bucket>/."""

    PREFIX = "/skydns"

    def __init__(self, etcd: EtcdClient, domain: str, my_host: str,
                 my_port: int):
        self.etcd = etcd
        self.domain = domain.strip(".")
        self.my_host = my_host
        self.my_port = my_port

    def _bucket_prefix(self, bucket: str) -> str:
        rev = "/".join(reversed(self.domain.split(".")))
        return f"{self.PREFIX}/{rev}/{bucket}/"

    def put(self, bucket: str) -> None:
        """Publish this cluster as the bucket's owner."""
        rec = {"host": self.my_host, "port": str(self.my_port),
               "ttl": 30, "creationDate": time.strftime(
                   "%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        key = self._bucket_prefix(bucket) + \
            f"{self.my_host}:{self.my_port}"
        self.etcd.put(key, json.dumps(rec).encode())

    def get(self, bucket: str) -> list[dict]:
        """The bucket's SRV records (empty = bucket unknown
        federation-wide)."""
        out = []
        for key, value in self.etcd.range(self._bucket_prefix(bucket)):
            try:
                rec = json.loads(value)
            except ValueError:
                continue
            rec["key"] = key
            out.append(rec)
        return out

    def delete(self, bucket: str) -> None:
        self.etcd.delete(self._bucket_prefix(bucket), prefix=True)

    def list(self) -> dict[str, list[dict]]:
        """bucket -> records, across the whole domain."""
        rev = "/".join(reversed(self.domain.split(".")))
        base = f"{self.PREFIX}/{rev}/"
        out: dict[str, list[dict]] = {}
        for key, value in self.etcd.range(base):
            rest = key[len(base):]
            bucket = rest.split("/", 1)[0]
            try:
                rec = json.loads(value)
            except ValueError:
                continue
            out.setdefault(bucket, []).append(rec)
        return out

    def owner_endpoint(self, bucket: str) -> str | None:
        """Where a request for `bucket` should go — None when this
        cluster owns it (or nobody does)."""
        for rec in self.get(bucket):
            host, port = rec.get("host"), int(rec.get("port", 0))
            if host == self.my_host and port == self.my_port:
                return None
            return f"http://{host}:{port}"
        return None
