"""Namespace locks: per-(bucket, object) mutual exclusion, local or dist.

The cmd/namespace-lock.go:224 equivalent: the engine asks for
NSLockMap.new_lock(bucket, object) and gets either an in-process RW lock
(standalone) or a dsync.DRWMutex over the set's lockers (distributed) —
the same facade the reference swaps behind NewNSLock.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from .dsync import DRWMutex, LockLost


class _LocalRWLock:
    """Writer-preferring in-process RW lock (internal/lsync analogue)."""

    def __init__(self):
        self._mu = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_write(self, timeout: float) -> bool:
        with self._mu:
            self._writers_waiting += 1
            try:
                ok = self._mu.wait_for(
                    lambda: not self._writer and self._readers == 0,
                    timeout=timeout)
                if not ok:
                    return False
                self._writer = True
                return True
            finally:
                self._writers_waiting -= 1
                # Readers wait on writers_waiting == 0; a writer that
                # timed out must wake them or they stall needlessly.
                self._mu.notify_all()

    def release_write(self) -> None:
        with self._mu:
            self._writer = False
            self._mu.notify_all()

    def acquire_read(self, timeout: float) -> bool:
        with self._mu:
            ok = self._mu.wait_for(
                lambda: not self._writer and self._writers_waiting == 0,
                timeout=timeout)
            if not ok:
                return False
            self._readers += 1
            return True

    def release_read(self) -> None:
        with self._mu:
            self._readers -= 1
            self._mu.notify_all()


class NSLockMap:
    def __init__(self, lockers: list | None = None):
        """lockers=None -> standalone (in-process locks); otherwise a
        distributed map over the given (local+remote) lockers."""
        from .dynamic_timeout import DynamicTimeout
        self.lockers = lockers
        # Adaptive lock deadline (cf. dynamicTimeout use at NewNSLock
        # call sites, cmd/dynamic-timeouts.go:36): callers that don't
        # pass an explicit timeout get one tuned from observed outcomes.
        self.acquire_timeout = DynamicTimeout(default_s=10.0,
                                              minimum_s=1.0,
                                              maximum_s=60.0)
        # resource -> [lock, refcount]; entries are deleted at refcount 0
        # (the reference refcounts nsLockMap entries the same way,
        # cmd/namespace-lock.go) so the map doesn't grow with every key
        # ever touched.
        self._local: dict[str, list] = {}
        self._mu = threading.Lock()

    def _local_acquire(self, resource: str) -> _LocalRWLock:
        with self._mu:
            entry = self._local.setdefault(resource, [_LocalRWLock(), 0])
            entry[1] += 1
            return entry[0]

    def _local_release(self, resource: str) -> None:
        with self._mu:
            entry = self._local.get(resource)
            if entry is not None:
                entry[1] -= 1
                if entry[1] <= 0:
                    del self._local[resource]

    @contextmanager
    def _locked(self, resource: str, write: bool, timeout: float | None):
        import time as _time
        adaptive = timeout is None
        if adaptive:
            timeout = self.acquire_timeout.timeout()
        t0 = _time.monotonic()
        if self.lockers is None:
            lk = self._local_acquire(resource)
            try:
                ok = (lk.acquire_write(timeout) if write
                      else lk.acquire_read(timeout))
                if adaptive:
                    if ok:
                        self.acquire_timeout.log_success(
                            _time.monotonic() - t0)
                    else:
                        self.acquire_timeout.log_timeout()
                if not ok:
                    raise LockLost(resource)
                try:
                    yield
                finally:
                    if write:
                        lk.release_write()
                    else:
                        lk.release_read()
            finally:
                self._local_release(resource)
            return
        lost = threading.Event()
        dm = DRWMutex(resource, self.lockers,
                      loss_callback=lambda r: lost.set())
        ok = dm.get_lock(timeout) if write else dm.get_rlock(timeout)
        if adaptive:
            if ok:
                self.acquire_timeout.log_success(_time.monotonic() - t0)
            else:
                self.acquire_timeout.log_timeout()
        if not ok:
            raise LockLost(resource)
        try:
            yield
        finally:
            # Lease validity is sampled BEFORE unlock clears the held
            # state: a partitioned holder whose refresh never reached
            # quorum within the lease window must not ack, even if the
            # loss callback hasn't fired yet (a black-holed refresh
            # round can stall past the whole operation).
            expired = dm.lease_expired()
            dm.unlock()
        # The refresh loop lost quorum (or the lease ran out) while the
        # operation ran: another node may have acquired the lock
        # mid-mutation, so the caller must treat the result as suspect
        # (the reference cancels the op context via lockLossCallback,
        # drwmutex.go:221).
        if lost.is_set() or expired:
            raise LockLost(f"{resource}: lock lost during operation")

    def write_locked(self, bucket: str, obj: str,
                     timeout: float | None = None):
        """timeout=None uses the adaptive deadline."""
        return self._locked(f"{bucket}/{obj}", True, timeout)

    def read_locked(self, bucket: str, obj: str,
                    timeout: float | None = None):
        return self._locked(f"{bucket}/{obj}", False, timeout)
