"""Site replication: mirror IAM + bucket configuration across sites.

The cmd/site-replication.go equivalent: a site group shares users,
policies, buckets and bucket configs; changes made on one site are
pushed to the others over their admin/S3 APIs (signed with each site's
root credentials). Object data replication between sites composes with
bucket.replication targets; this module covers the control-plane half
the reference's site replication adds on top.
"""

from __future__ import annotations

import json

from ..server.client import S3Client, S3ClientError
from ..storage.errors import StorageError

_REPLICATED_CONFIGS = ("versioning", "policy", "lifecycle",
                       "object-lock", "tagging", "quota", "notification")


class SitePeer:
    def __init__(self, name: str, endpoint: str, access_key: str,
                 secret_key: str):
        self.name = name
        self.endpoint = endpoint
        self.cli = S3Client(endpoint, access_key, secret_key)

    # -- control-plane pushes ------------------------------------------------

    def push_user(self, access_key: str, secret_key: str,
                  policies: list[str]) -> bool:
        body = json.dumps({"accessKey": access_key,
                           "secretKey": secret_key,
                           "policies": policies}).encode()
        status, _, _ = self.cli.request("POST", "/minio/admin/v1/users",
                                        body=body)
        return status == 200

    def push_policy(self, name: str, doc: dict) -> bool:
        body = json.dumps({"name": name, "policy": doc}).encode()
        status, _, _ = self.cli.request("POST",
                                        "/minio/admin/v1/policies",
                                        body=body)
        return status == 200

    def push_bucket(self, bucket: str, configs: dict[str, bytes]) -> bool:
        try:
            self.cli.make_bucket(bucket)
        except S3ClientError as e:
            if e.code not in ("BucketAlreadyOwnedByYou",
                              "BucketAlreadyExists"):
                return False
        ok = True
        for sub, data in configs.items():
            status, _, _ = self.cli.request("PUT", f"/{bucket}",
                                            query={sub: ""}, body=data)
            ok = ok and status == 200
        return ok


class SiteReplicator:
    """Attached to the 'source of truth' site; fans control-plane changes
    out to the peer sites."""

    def __init__(self, iam, meta, peers: list[SitePeer]):
        self.iam = iam                   # IAMSys
        self.meta = meta                 # BucketMetadataSys
        self.peers = peers
        self.pushed = 0
        self.failed = 0

    def _fan(self, fn) -> int:
        ok = 0
        for peer in self.peers:
            try:
                if fn(peer):
                    ok += 1
                    self.pushed += 1
                else:
                    self.failed += 1
            except Exception:  # noqa: BLE001 — peer down: count + continue
                self.failed += 1
        return ok

    # -- hooks (call after local mutations) ----------------------------------

    def on_user_added(self, access_key: str, secret_key: str,
                      policies: list[str]) -> int:
        return self._fan(lambda p: p.push_user(access_key, secret_key,
                                               policies))

    def on_policy_set(self, name: str, doc: dict) -> int:
        return self._fan(lambda p: p.push_policy(name, doc))

    def on_bucket_config(self, bucket: str) -> int:
        configs = self._bucket_configs(bucket)
        return self._fan(lambda p: p.push_bucket(bucket, configs))

    def _bucket_configs(self, bucket: str) -> dict[str, bytes]:
        from ..bucket.metadata import CONFIG_FILES
        out = {}
        for sub in _REPLICATED_CONFIGS:
            kind = sub.replace("-", "_")
            if kind not in CONFIG_FILES:
                continue
            try:
                data = self.meta.get(bucket, kind)
            except StorageError:
                continue
            if data is not None:
                out[sub] = data
        return out

    # -- full resync ---------------------------------------------------------

    def sync_all(self, buckets: list[str]) -> dict:
        stats = {"users": 0, "policies": 0, "buckets": 0}
        if self.iam is not None:
            with self.iam._mu:
                users = [u for u in self.iam._users.values()
                         if u.kind == "user"]
                policies = {n: p for n, p in self.iam._policies.items()
                            if n not in ("readwrite", "readonly",
                                         "writeonly")}
            for name, p in policies.items():
                if self.on_policy_set(name, p.doc):
                    stats["policies"] += 1
            for u in users:
                if self.on_user_added(u.access_key, u.secret_key,
                                      u.policies):
                    stats["users"] += 1
        for bucket in buckets:
            if self.on_bucket_config(bucket):
                stats["buckets"] += 1
        return stats
