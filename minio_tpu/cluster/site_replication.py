"""Site replication: mirror IAM + bucket configuration across sites.

The cmd/site-replication.go equivalent: a site group shares users,
policies, buckets and bucket configs; changes made on one site are
pushed to the others over their admin/S3 APIs (signed with each site's
root credentials). Object data replication between sites composes with
bucket.replication targets; this module covers the control-plane half
the reference's site replication adds on top.
"""

from __future__ import annotations

import json

from ..server.client import S3Client, S3ClientError
from ..storage.errors import StorageError

_REPLICATED_CONFIGS = ("versioning", "policy", "lifecycle",
                       "object-lock", "tagging", "quota", "notification")


class SitePeer:
    def __init__(self, name: str, endpoint: str, access_key: str,
                 secret_key: str):
        self.name = name
        self.endpoint = endpoint
        self.cli = S3Client(endpoint, access_key, secret_key)

    # -- control-plane pushes ------------------------------------------------

    def push_user(self, access_key: str, secret_key: str,
                  policies: list[str],
                  status: str = "enabled") -> bool:
        body = json.dumps({"accessKey": access_key,
                           "secretKey": secret_key,
                           "policies": policies, "status": status,
                           "srInternal": True}).encode()
        status, _, _ = self.cli.request("POST", "/minio/admin/v1/users",
                                        body=body)
        return status == 200

    def push_policy(self, name: str, doc: dict) -> bool:
        body = json.dumps({"name": name, "policy": doc,
                           "srInternal": True}).encode()
        status, _, _ = self.cli.request("POST",
                                        "/minio/admin/v1/policies",
                                        body=body)
        return status == 200

    def push_service_account(self, parent: str, access_key: str,
                             secret_key: str,
                             policies: list[str]) -> bool:
        body = json.dumps({"parent": parent, "accessKey": access_key,
                           "secretKey": secret_key,
                           "policies": list(policies),
                           "srInternal": True}).encode()
        status, _, _ = self.cli.request(
            "POST", "/minio/admin/v1/service-accounts", body=body)
        return status == 200

    def push_group(self, name: str, members: list[str],
                   policies: list[str]) -> bool:
        body = json.dumps({"name": name, "members": list(members),
                           "policies": list(policies),
                           "setPolicies": list(policies),
                           "srInternal": True}).encode()
        status, _, _ = self.cli.request("POST",
                                        "/minio/admin/v1/groups",
                                        body=body)
        return status == 200

    def remote_iam_listing(self) -> dict | None:
        """The peer's IAM inventory, for deletion reconciliation."""
        try:
            _, _, u = self.cli.request("GET", "/minio/admin/v1/users")
            _, _, p = self.cli.request("GET",
                                       "/minio/admin/v1/policies")
            _, _, g = self.cli.request("GET", "/minio/admin/v1/groups")
            _, _, a = self.cli.request(
                "GET", "/minio/admin/v1/service-accounts")
            return {"users": json.loads(u).get("users", []),
                    "policies": json.loads(p).get("policies", []),
                    "groups": json.loads(g).get("groups", []),
                    "svc": [x["accessKey"] for x in
                            json.loads(a).get("accounts", [])]}
        except Exception:  # noqa: BLE001 — peer down
            return None

    def delete_user(self, access_key: str) -> bool:
        status, _, _ = self.cli.request(
            "DELETE", "/minio/admin/v1/users",
            query={"accessKey": access_key, "srInternal": "1"})
        return status == 200

    def delete_policy(self, name: str) -> bool:
        status, _, _ = self.cli.request(
            "DELETE", "/minio/admin/v1/policies",
            query={"name": name, "srInternal": "1"})
        return status in (200, 404)

    def delete_group(self, name: str) -> bool:
        status, _, _ = self.cli.request(
            "DELETE", "/minio/admin/v1/groups",
            query={"name": name, "srInternal": "1"})
        return status in (200, 404)

    def push_leave(self) -> bool:
        status, _, _ = self.cli.request(
            "POST", "/minio/admin/v1/site-replication",
            body=json.dumps({"action": "leave"}).encode())
        return status == 200

    SR_HDR = {"x-mtpu-sr-internal": "1"}

    def push_bucket(self, bucket: str, configs: dict[str, bytes]) -> bool:
        status, _, _ = self.cli.request("PUT", f"/{bucket}",
                                        headers=dict(self.SR_HDR))
        if status not in (200, 409):
            return False
        ok = True
        for sub, data in configs.items():
            status, _, _ = self.cli.request("PUT", f"/{bucket}",
                                            query={sub: ""}, body=data,
                                            headers=dict(self.SR_HDR))
            ok = ok and status == 200
        return ok

    def delete_bucket(self, bucket: str) -> bool:
        status, _, _ = self.cli.request("DELETE", f"/{bucket}",
                                        headers=dict(self.SR_HDR))
        return status in (200, 204, 404)


class SiteReplicator:
    """Attached to the 'source of truth' site; fans control-plane changes
    out to the peer sites."""

    def __init__(self, iam, meta, peers: list[SitePeer]):
        self.iam = iam                   # IAMSys
        self.meta = meta                 # BucketMetadataSys
        self.peers = peers
        self.pushed = 0
        self.failed = 0

    def _fan(self, fn) -> int:
        ok = 0
        for peer in self.peers:
            try:
                if fn(peer):
                    ok += 1
                    self.pushed += 1
                else:
                    self.failed += 1
            except Exception:  # noqa: BLE001 — peer down: count + continue
                self.failed += 1
        return ok

    # -- hooks (call after local mutations) ----------------------------------

    def on_user_added(self, access_key: str, secret_key: str,
                      policies: list[str],
                      status: str = "enabled") -> int:
        return self._fan(lambda p: p.push_user(access_key, secret_key,
                                               policies, status))

    def on_policy_set(self, name: str, doc: dict) -> int:
        return self._fan(lambda p: p.push_policy(name, doc))

    def on_bucket_config(self, bucket: str) -> int:
        configs = self._bucket_configs(bucket)
        return self._fan(lambda p: p.push_bucket(bucket, configs))

    def _bucket_configs(self, bucket: str) -> dict[str, bytes]:
        from ..bucket.metadata import CONFIG_FILES
        out = {}
        for sub in _REPLICATED_CONFIGS:
            kind = sub.replace("-", "_")
            if kind not in CONFIG_FILES:
                continue
            try:
                data = self.meta.get(bucket, kind)
            except StorageError:
                continue
            if data is not None:
                out[sub] = data
        return out

    # -- full resync ---------------------------------------------------------

    def sync_all(self, buckets: list[str]) -> dict:
        stats = {"users": 0, "policies": 0, "buckets": 0}
        if self.iam is not None:
            with self.iam._mu:
                users = [u for u in self.iam._users.values()
                         if u.kind == "user"]
                policies = {n: p for n, p in self.iam._policies.items()
                            if n not in ("readwrite", "readonly",
                                         "writeonly")}
            for name, p in policies.items():
                if self.on_policy_set(name, p.doc):
                    stats["policies"] += 1
            for u in users:
                if self.on_user_added(u.access_key, u.secret_key,
                                      u.policies, u.status):
                    stats["users"] += 1
        for bucket in buckets:
            if self.on_bucket_config(bucket):
                stats["buckets"] += 1
        return stats


# ---------------------------------------------------------------------------
# round-5: membership protocol, IAM-complete sync, drift reconciliation
# ---------------------------------------------------------------------------

import hashlib as _hashlib
import threading as _threading
import time as _time

_STATE_KEY = "config/site-replication/state.json"


class SiteReplicationSys:
    """The SiteReplicationSys role (cmd/site-replication.go:173): a
    persistent site-group membership with a join handshake, change
    fan-out, and drift detection + reconciliation.

    - add_peers (AddPeerClusters :257): validate every site (reachable,
      distinct deployment ids), then push the agreed state to every
      member over its admin plane (InternalJoinReq :469);
    - local_digest / status: per-category content digests (buckets'
      replicated configs, users, service accounts, groups, policies)
      compared across members -> a drift report naming the categories
      out of sync per site;
    - reconcile (syncLocalToPeers :1285): push the full local truth —
      users incl. policy mappings, SERVICE ACCOUNTS with their
      credentials, groups, policies, buckets + configs — to every
      drifted peer, then re-run status.
    """

    def __init__(self, pools, iam, meta, my_name: str = "",
                 my_endpoint: str = "", creds=None):
        self.pools = pools
        self.iam = iam
        self.meta = meta
        self.my_name = my_name
        self.my_endpoint = my_endpoint
        self.creds = creds
        self._mu = _threading.Lock()
        self.state: dict = self._load() or {}

    # -- persistence ---------------------------------------------------------

    def _load(self) -> dict | None:
        try:
            _, data = self.pools.get_object(".mtpu.sys", _STATE_KEY)
            return json.loads(data)
        except (StorageError, ValueError):
            return None

    def _save(self) -> None:
        self.pools.put_object(".mtpu.sys", _STATE_KEY,
                              json.dumps(self.state).encode())

    @property
    def enabled(self) -> bool:
        return bool(self.state.get("sites"))

    @property
    def deployment_id(self) -> str:
        return getattr(self.pools, "deployment_id", "")

    def _peers(self) -> list[SitePeer]:
        """Clients for every member EXCEPT this site."""
        out = []
        for site in self.state.get("sites", []):
            if site["deploymentId"] == self.deployment_id:
                continue
            out.append(SitePeer(site["name"], site["endpoint"],
                                site["accessKey"], site["secretKey"]))
        return out

    # -- join handshake ------------------------------------------------------

    def add_peers(self, sites: list[dict]) -> dict:
        """Coordinator side of `mc admin replicate add`: validate every
        site, assemble the group state, push it to every member, then
        run one full reconcile so the group starts converged."""
        seen: dict[str, str] = {}
        enriched = []
        for site in sites:
            cli = S3Client(site["endpoint"], site["accessKey"],
                           site["secretKey"])
            status, _, body = cli.request(
                "GET", "/minio/admin/v1/site-replication",
                query={"internal": "deployment"})
            if status != 200:
                raise StorageError(
                    f"site {site['name']}: unreachable or unauthorized "
                    f"({status})")
            dep = json.loads(body).get("deploymentId", "")
            if not dep:
                raise StorageError(f"site {site['name']}: no deployment id")
            if dep in seen:
                raise StorageError(
                    f"sites {seen[dep]!r} and {site['name']!r} are the "
                    f"same deployment ({dep}) — a site cannot join a "
                    "group twice")
            seen[dep] = site["name"]
            enriched.append({**site, "deploymentId": dep})
        state = {"group_id": _hashlib.sha256(
                     "".join(sorted(seen)).encode()).hexdigest()[:16],
                 "sites": enriched,
                 "updated": _time.time()}
        # push the agreed state to EVERY member (including this one)
        results = {}
        for site in enriched:
            cli = S3Client(site["endpoint"], site["accessKey"],
                           site["secretKey"])
            status, _, body = cli.request(
                "POST", "/minio/admin/v1/site-replication",
                body=json.dumps({"action": "join",
                                 "state": state}).encode())
            results[site["name"]] = (status == 200)
        with self._mu:
            # Membership state is group-shared; the push ledger is
            # strictly local — carry it across the replacement.
            ledger = self.state.get("pushed_iam")
            self.state = dict(state)
            if ledger:
                self.state["pushed_iam"] = ledger
            self._save()
        sync = self.reconcile()
        return {"joined": results, "initial_sync": sync}

    def accept_join(self, state: dict) -> None:
        """Member side (InternalJoinReq): the group must include us."""
        ids = [s["deploymentId"] for s in state.get("sites", [])]
        if self.deployment_id not in ids:
            raise StorageError(
                f"join state does not include this deployment "
                f"({self.deployment_id})")
        with self._mu:
            # Keep OUR push ledger (and drop any the coordinator's
            # payload might carry — it describes the sender's pushes,
            # not ours).
            ledger = self.state.get("pushed_iam")
            self.state = {k: v for k, v in state.items()
                          if k != "pushed_iam"}
            if ledger:
                self.state["pushed_iam"] = ledger
            self._save()

    def accept_leave(self) -> None:
        """This site was removed from the group: forget the membership
        so hooks stop firing and reconcile stops pushing."""
        with self._mu:
            self.state = {}
            self._save()

    def remove_site(self, name: str) -> dict:
        """Drop a member and push the shrunk state to the remainder."""
        with self._mu:
            removed = [s for s in self.state.get("sites", [])
                       if s["name"] == name]
            sites = [s for s in self.state.get("sites", [])
                     if s["name"] != name]
            if not removed:
                raise StorageError(f"no site named {name!r} in group")
            self.state["sites"] = sites
            self.state["updated"] = _time.time()
            state = dict(self.state)
            self._save()
        results = {}
        for site in sites:
            if site["deploymentId"] == self.deployment_id:
                continue
            cli = S3Client(site["endpoint"], site["accessKey"],
                           site["secretKey"])
            status, _, _ = cli.request(
                "POST", "/minio/admin/v1/site-replication",
                body=json.dumps({"action": "join",
                                 "state": state}).encode())
            results[site["name"]] = (status == 200)
        # the ejected member must STOP acting as a group member — tell
        # it to clear its persisted state (an unreachable ejectee can
        # no longer be trusted anyway; best effort)
        for site in removed:
            try:
                SitePeer(site["name"], site["endpoint"],
                         site["accessKey"],
                         site["secretKey"]).push_leave()
            except Exception:  # noqa: BLE001
                pass
        return {"removed": name, "pushed": results}

    # -- digests + drift -----------------------------------------------------

    def local_digest(self) -> dict:
        """Content digests per replicated category — equal digests on
        two sites mean that category is in sync."""
        def h(obj) -> str:
            return _hashlib.sha256(
                json.dumps(obj, sort_keys=True).encode()).hexdigest()[:16]

        users = {}
        svc = {}
        with self.iam._mu:
            for ak, u in sorted(self.iam._users.items()):
                if u.kind == "user":
                    users[ak] = [u.secret_key, sorted(u.policies),
                                 u.status]
                elif u.kind == "service":
                    svc[ak] = [u.secret_key, u.parent,
                               sorted(u.policies)]
            groups = {n: [sorted(g.get("members", [])),
                          sorted(g.get("policies", []))]
                      for n, g in sorted(self.iam._groups.items())}
            policies = {n: p.doc for n, p in
                        sorted(self.iam._policies.items())
                        if n not in ("readwrite", "readonly",
                                     "writeonly")}
        buckets = {}
        for b in self.pools.list_buckets():
            if b.startswith(".mtpu"):
                continue
            cfgs = {}
            for sub in _REPLICATED_CONFIGS:
                kind = sub.replace("-", "_")
                try:
                    data = self.meta.get(b, kind)
                except StorageError:
                    data = None
                if data is not None:
                    cfgs[sub] = _hashlib.sha256(data).hexdigest()[:16]
            buckets[b] = cfgs
        return {"users": h(users), "svc_accounts": h(svc),
                "groups": h(groups), "policies": h(policies),
                "buckets": h(buckets)}

    def status(self) -> dict:
        """Drift report (SiteReplicationStatus): every member's digest
        vs ours, with the drifted categories named."""
        mine = self.local_digest()
        sites_out = []
        for site in self.state.get("sites", []):
            if site["deploymentId"] == self.deployment_id:
                sites_out.append({"name": site["name"], "self": True,
                                  "inSync": True, "drift": []})
                continue
            cli = S3Client(site["endpoint"], site["accessKey"],
                           site["secretKey"])
            try:
                status, _, body = cli.request(
                    "GET", "/minio/admin/v1/site-replication",
                    query={"internal": "digest"})
                theirs = json.loads(body) if status == 200 else None
            except Exception:  # noqa: BLE001 — peer down
                theirs = None
            if theirs is None:
                sites_out.append({"name": site["name"], "self": False,
                                  "inSync": False,
                                  "drift": ["unreachable"]})
                continue
            drift = sorted(k for k in mine if theirs.get(k) != mine[k])
            sites_out.append({"name": site["name"], "self": False,
                              "inSync": not drift, "drift": drift})
        return {"groupId": self.state.get("group_id", ""),
                "sites": sites_out}

    # -- reconcile -----------------------------------------------------------

    def reconcile(self) -> dict:
        """Push the local truth to every drifted member, then report
        the post-state (the periodic resync of syncLocalToPeers)."""
        before = self.status()
        drifted = [s["name"] for s in before["sites"]
                   if not s["self"] and not s["inSync"]]
        pushed = {}
        with self.iam._mu:
            svcs = [u for u in self.iam._users.values()
                    if u.kind == "service"]
            groups = {n: dict(g)
                      for n, g in self.iam._groups.items()}
            local_users = {ak for ak, u in self.iam._users.items()
                           if u.kind == "user"}
            local_svc = {ak for ak, u in self.iam._users.items()
                         if u.kind == "service"}
            local_groups = set(self.iam._groups)
            local_policies = {n for n in self.iam._policies
                              if n not in ("readwrite", "readonly",
                                           "writeonly")}
        # Deletion ledger: only entities THIS site's sync has ever
        # propagated may be deleted on a peer. A bare "peer has it,
        # we don't" sweep wipes pre-existing IAM the moment a site
        # with its own users joins the group (add_peers → reconcile)
        # — those credentials are the peer's truth to push to US, not
        # remnants. Entities in the ledger that are gone locally ARE
        # remnants: deleting them is what makes local deletions
        # converge instead of ping-ponging back from a stale peer.
        ledger = self.state.get("pushed_iam", {})
        known = {cat: set(ledger.get(cat, []))
                 for cat in ("users", "svc", "policies", "groups")}
        if drifted:
            peers = [p for p in self._peers() if p.name in drifted]
            rep = SiteReplicator(self.iam, self.meta, peers)
            buckets = [b for b in self.pools.list_buckets()
                       if not b.startswith(".mtpu")]
            pushed = rep.sync_all(buckets)
            # IAM-complete extras: service accounts, groups, policy
            # mappings ride on top of sync_all's users/policies/buckets
            for peer in peers:
                for u in svcs:
                    peer.push_service_account(u.parent, u.access_key,
                                              u.secret_key, u.policies)
                for name, g in groups.items():
                    peer.push_group(name, g.get("members", []),
                                    g.get("policies", []))
                listing = peer.remote_iam_listing()
                if listing is None:
                    continue
                for ak in (set(listing["users"]) - local_users) \
                        & known["users"]:
                    peer.delete_user(ak)
                for ak in (set(listing["svc"]) - local_svc) \
                        & known["svc"]:
                    peer.delete_user(ak)
                for n in ((set(listing["policies"]) - local_policies
                           - {"readwrite", "readonly", "writeonly"})
                          & known["policies"]):
                    peer.delete_policy(n)
                for n in (set(listing["groups"]) - local_groups) \
                        & known["groups"]:
                    peer.delete_group(n)
        # Fold the local truth into the ledger on EVERY reconcile —
        # whatever is local while we're a member is (being) pushed.
        # Grow-only: an entry must outlive its local deletion so the
        # delete keeps propagating to peers that were unreachable (or
        # not yet drifted) this round.
        merged = {"users": sorted(known["users"] | local_users),
                  "svc": sorted(known["svc"] | local_svc),
                  "policies": sorted(known["policies"] | local_policies),
                  "groups": sorted(known["groups"] | local_groups)}
        if merged != ledger:
            with self._mu:
                self.state["pushed_iam"] = merged
                self._save()
        after = self.status()
        return {"drift_before": [s for s in before["sites"]
                                 if not s["inSync"]],
                "pushed": pushed,
                "drift_after": [s for s in after["sites"]
                                if not s["inSync"]]}
