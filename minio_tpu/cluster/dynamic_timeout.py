"""Dynamic timeouts: deadlines that adapt to observed latencies.

The cmd/dynamic-timeouts.go:36 equivalent: lock/op deadlines start at a
default and adjust from a sliding window of observed outcomes — many
timeouts push the deadline up (x1.25 steps), consistently fast
successes pull it back down (towards the observed p-high), bounded by
[minimum, maximum]. Used by callers that wrap lock acquisition or slow
drive ops.
"""

from __future__ import annotations

import threading


class DynamicTimeout:
    WINDOW = 64
    GROW = 1.25
    # Separate grow/shrink thresholds with a neutral dead band between
    # them (the reference uses >=33% grow / <10% shrink): without the
    # band, a workload whose tail sits near the deadline oscillates —
    # shrink snaps onto the fast majority, the next window times out the
    # tail, grow crawls back, repeat.
    GROW_TRIGGER = 0.33        # >=33% timeouts => grow
    SHRINK_TRIGGER = 0.05      # <5% timeouts => consider gradual shrink

    def __init__(self, default_s: float, minimum_s: float,
                 maximum_s: float | None = None):
        self.minimum = minimum_s
        self.maximum = maximum_s or default_s * 16
        self._timeout = max(min(default_s, self.maximum), self.minimum)
        self._mu = threading.Lock()
        self._entries: list[tuple[bool, float]] = []   # (timed_out, took_s)

    def timeout(self) -> float:
        with self._mu:
            return self._timeout

    def log_success(self, took_s: float) -> None:
        self._log(False, took_s)

    def log_timeout(self) -> None:
        self._log(True, 0.0)

    def _log(self, timed_out: bool, took_s: float) -> None:
        with self._mu:
            self._entries.append((timed_out, took_s))
            if len(self._entries) < self.WINDOW:
                return
            n_timeout = sum(1 for t, _ in self._entries if t)
            frac = n_timeout / len(self._entries)
            if frac >= self.GROW_TRIGGER:
                self._timeout = min(self._timeout * self.GROW,
                                    self.maximum)
            elif frac < self.SHRINK_TRIGGER:
                # Gradual shrink toward the p95 of successes (with 2x
                # headroom), at most one GROW step per window so a
                # mistake costs one window, not a cliff.
                succ = sorted(took for t, took in self._entries if not t)
                if succ:
                    p_high = succ[max(int(len(succ) * 0.95) - 1, 0)]
                    candidate = max(p_high * 2.0, self.minimum,
                                    self._timeout / self.GROW)
                    if candidate < self._timeout:
                        self._timeout = candidate
            # frac in [SHRINK_TRIGGER, GROW_TRIGGER): neutral band, hold.
            self._entries.clear()
