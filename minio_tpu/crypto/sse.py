"""Server-side encryption: packetized AES-256-GCM streaming AEAD.

The cmd/encryption-v1.go + DARE (sio) equivalent: object data is sealed
in 64 KiB packets, each AES-GCM with a per-object data key and a
sequence-derived nonce (so packets can't be reordered/truncated without
detection). Three modes, same as the reference:
  - SSE-S3: data key from the KMS, sealed key in object metadata,
  - SSE-C: client supplies the 256-bit key per request (key never
    stored; only its MD5 for verification),
  - SSE-KMS: SSE-S3 with an explicit KMS key id.
Metadata layout mirrors the reference's internal crypto headers
(internal/crypto/metadata.go): sealed key, algorithm, key MD5.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import secrets
import struct

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:                       # gated optional dep (see kms)
    AESGCM = None

from .kms import KMS, KMSError, _require_aesgcm

PACKET_SIZE = 64 * 1024

# metadata keys (internal; never returned to clients as-is)
META_ALGO = "x-mtpu-internal-sse-algo"          # "SSE-S3" | "SSE-C"
META_SEALED_KEY = "x-mtpu-internal-sse-sealed-key"
META_KMS_KEY_ID = "x-mtpu-internal-sse-kms-id"
META_KEY_MD5 = "x-mtpu-internal-sse-c-key-md5"
META_SSEC_IV = "x-mtpu-internal-sse-c-iv"
META_ACTUAL_SIZE = "x-mtpu-internal-actual-size"

# request headers
H_SSE = "x-amz-server-side-encryption"
H_SSEC_ALGO = "x-amz-server-side-encryption-customer-algorithm"
H_SSEC_KEY = "x-amz-server-side-encryption-customer-key"
H_SSEC_MD5 = "x-amz-server-side-encryption-customer-key-md5"


class SSEError(Exception):
    pass


def _nonce(base: bytes, seq: int, final: bool) -> bytes:
    # 12-byte nonce: 4-byte packet counter (MSB marks the final packet,
    # preventing truncation) + 8 random base bytes.
    flag = 0x80000000 if final else 0
    return struct.pack(">I", seq | flag) + base


def seal(data: bytes, key: bytes) -> bytes:
    """Plaintext -> [8B nonce-base][packets: 4B len + ct+tag]..."""
    _require_aesgcm()
    aes = AESGCM(key)
    base = secrets.token_bytes(8)
    out = bytearray(base)
    n_packets = max(1, -(-len(data) // PACKET_SIZE))
    for i in range(n_packets):
        chunk = data[i * PACKET_SIZE:(i + 1) * PACKET_SIZE]
        ct = aes.encrypt(_nonce(base, i, i == n_packets - 1), chunk, b"")
        out += struct.pack(">I", len(ct)) + ct
    return bytes(out)


def unseal(blob: bytes, key: bytes) -> bytes:
    _require_aesgcm()
    aes = AESGCM(key)
    if len(blob) < 8:
        raise SSEError("ciphertext too short")
    base = blob[:8]
    pos = 8
    out = bytearray()
    seq = 0
    while pos < len(blob):
        if pos + 4 > len(blob):
            raise SSEError("truncated packet header")
        (clen,) = struct.unpack(">I", blob[pos:pos + 4])
        pos += 4
        ct = blob[pos:pos + clen]
        if len(ct) != clen:
            raise SSEError("truncated packet")
        pos += clen
        final = pos >= len(blob)
        try:
            out += aes.decrypt(_nonce(base, seq, final), ct, b"")
        except Exception:  # noqa: BLE001
            raise SSEError("decryption failed (wrong key or corrupt "
                           "data)") from None
        seq += 1
    return bytes(out)


# -- mode handling -----------------------------------------------------------

def parse_ssec_key(headers: dict) -> bytes | None:
    """Extract + verify an SSE-C customer key from request headers."""
    h = {k.lower(): v for k, v in headers.items()}
    if h.get(H_SSEC_ALGO, "") == "":
        return None
    if h[H_SSEC_ALGO] != "AES256":
        raise SSEError("SSE-C algorithm must be AES256")
    try:
        key = base64.b64decode(h.get(H_SSEC_KEY, ""))
    except ValueError:
        raise SSEError("bad SSE-C key encoding") from None
    if len(key) != 32:
        raise SSEError("SSE-C key must be 256 bits")
    md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    if h.get(H_SSEC_MD5, "") not in ("", md5):
        raise SSEError("SSE-C key MD5 mismatch")
    return key


def derive_object_key(customer_key: bytes, iv: bytes, bucket: str,
                      object_key: str) -> bytes:
    """Per-object sealing key from the customer key: never seal with the
    raw client key directly — one key reused across many objects with a
    64-bit random nonce base risks GCM nonce reuse.  The reference
    derives a unique ObjectKey per object the same way
    (internal/crypto/key.go GenerateKey: HMAC over a random IV and the
    bucket/object path)."""
    return hmac.new(customer_key,
                    iv + b"\x00" + f"{bucket}/{object_key}".encode(),
                    hashlib.sha256).digest()


def encrypt_for_put(data: bytes, headers: dict, kms: KMS | None,
                    bucket: str = "", object_key: str = ""):
    """-> (stored_bytes, metadata_updates) or (data, {}) when no SSE."""
    h = {k.lower(): v for k, v in headers.items()}
    ssec_key = parse_ssec_key(headers)
    if ssec_key is not None:
        iv = secrets.token_bytes(32)
        obj_key = derive_object_key(ssec_key, iv, bucket, object_key)
        sealed = seal(data, obj_key)
        return sealed, {
            META_ALGO: "SSE-C",
            META_KEY_MD5: base64.b64encode(
                hashlib.md5(ssec_key).digest()).decode(),
            META_SSEC_IV: base64.b64encode(iv).decode(),
            META_ACTUAL_SIZE: str(len(data)),
        }
    if h.get(H_SSE, "") in ("AES256", "aws:kms"):
        if kms is None:
            raise SSEError("SSE-S3 requested but no KMS configured")
        key_id, data_key, sealed_key = kms.generate_data_key()
        sealed = seal(data, data_key)
        return sealed, {
            META_ALGO: "SSE-S3",
            META_KMS_KEY_ID: key_id,
            META_SEALED_KEY: base64.b64encode(sealed_key).decode(),
            META_ACTUAL_SIZE: str(len(data)),
        }
    return data, {}


def decrypt_for_get(stored: bytes, metadata: dict, headers: dict,
                    kms: KMS | None, bucket: str = "",
                    object_key: str = "") -> bytes:
    algo = metadata.get(META_ALGO, "")
    if not algo:
        return stored
    if algo == "SSE-C":
        key = parse_ssec_key(headers)
        if key is None:
            raise SSEError("object is SSE-C encrypted; key required")
        md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
        if md5 != metadata.get(META_KEY_MD5, ""):
            raise SSEError("SSE-C key does not match object key")
        iv_b64 = metadata.get(META_SSEC_IV, "")
        if iv_b64:
            key = derive_object_key(key, base64.b64decode(iv_b64),
                                    bucket, object_key)
        # else: legacy object sealed directly with the customer key
        return unseal(stored, key)
    if algo == "SSE-S3":
        if kms is None:
            raise SSEError("object is KMS encrypted; no KMS configured")
        try:
            data_key = kms.decrypt_data_key(
                metadata.get(META_KMS_KEY_ID, ""),
                base64.b64decode(metadata.get(META_SEALED_KEY, "")))
        except (KMSError, ValueError) as e:
            raise SSEError(str(e)) from None
        return unseal(stored, data_key)
    raise SSEError(f"unknown SSE algorithm {algo!r}")


def response_headers(metadata: dict) -> dict:
    algo = metadata.get(META_ALGO, "")
    if algo == "SSE-C":
        return {H_SSEC_ALGO: "AES256",
                H_SSEC_MD5: metadata.get(META_KEY_MD5, "")}
    if algo == "SSE-S3":
        return {H_SSE: "AES256"}
    return {}


def is_encrypted(metadata: dict) -> bool:
    return bool(metadata.get(META_ALGO))
