"""KMS: key-encryption-key service behind a narrow interface.

The internal/kms equivalent: a KMS hands out (plaintext, sealed) data
keys and unseals them later. StaticKMS seals with a locally-held master
key (the reference's single-key KMS, internal/kms/single-key.go);
the interface is what a KES-backed client would also implement.
"""

from __future__ import annotations

import os
import secrets

from cryptography.hazmat.primitives.ciphers.aead import AESGCM


class KMSError(Exception):
    pass


class KMS:
    """Interface: generate_data_key() -> (key_id, plaintext, sealed);
    decrypt_data_key(key_id, sealed, context) -> plaintext."""

    def generate_data_key(self, context: bytes = b""):
        raise NotImplementedError

    def decrypt_data_key(self, key_id: str, sealed: bytes,
                         context: bytes = b"") -> bytes:
        raise NotImplementedError


class StaticKMS(KMS):
    """Master key held in memory/env (MTPU_KMS_SECRET_KEY)."""

    def __init__(self, master_key: bytes | None = None,
                 key_id: str = "mtpu-default-key"):
        if master_key is None:
            env = os.environ.get("MTPU_KMS_SECRET_KEY", "")
            master_key = (bytes.fromhex(env) if env
                          else b"\x00" * 32)
        if len(master_key) != 32:
            raise KMSError("master key must be 32 bytes")
        self._master = master_key
        self.key_id = key_id

    def generate_data_key(self, context: bytes = b""):
        plaintext = secrets.token_bytes(32)
        nonce = secrets.token_bytes(12)
        sealed = nonce + AESGCM(self._master).encrypt(nonce, plaintext,
                                                      context)
        return self.key_id, plaintext, sealed

    def decrypt_data_key(self, key_id: str, sealed: bytes,
                         context: bytes = b"") -> bytes:
        if key_id != self.key_id:
            raise KMSError(f"unknown key id {key_id!r}")
        try:
            return AESGCM(self._master).decrypt(sealed[:12], sealed[12:],
                                                context)
        except Exception as e:  # noqa: BLE001
            raise KMSError(f"unseal failed: {e}") from None
