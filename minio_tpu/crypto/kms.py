"""KMS: key-encryption-key service behind a narrow interface.

The internal/kms equivalent: a KMS hands out (plaintext, sealed) data
keys and unseals them later. StaticKMS seals with a locally-held master
key (the reference's single-key KMS, internal/kms/single-key.go);
the interface is what a KES-backed client would also implement.
"""

from __future__ import annotations

import os
import secrets

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:                       # gated optional dep: the
    AESGCM = None                         # server must boot without it;
                                          # SSE/KMS paths error on use


class KMSError(Exception):
    pass


def _require_aesgcm():
    if AESGCM is None:
        raise KMSError("SSE unavailable: the 'cryptography' package "
                       "is not installed")


class KMS:
    """Interface: generate_data_key() -> (key_id, plaintext, sealed);
    decrypt_data_key(key_id, sealed, context) -> plaintext."""

    def generate_data_key(self, context: bytes = b""):
        raise NotImplementedError

    def decrypt_data_key(self, key_id: str, sealed: bytes,
                         context: bytes = b"") -> bytes:
        raise NotImplementedError


class StaticKMS(KMS):
    """Master key held in memory/env (MTPU_KMS_SECRET_KEY)."""

    def __init__(self, master_key: bytes | None = None,
                 key_id: str = "mtpu-default-key",
                 allow_insecure_zero_key: bool = False):
        """allow_insecure_zero_key: migration-only escape hatch so data
        written under the old implicit all-zero default stays readable
        (e.g. a one-off re-encrypt pass); never set on a serving path."""
        if master_key is None:
            env = os.environ.get("MTPU_KMS_SECRET_KEY", "")
            if not env:
                # Never fall back to a well-known key: the reference
                # refuses to serve SSE without a configured KMS key
                # (internal/kms/single-key.go ParseSecretKey).
                raise KMSError(
                    "no KMS master key configured "
                    "(set MTPU_KMS_SECRET_KEY to 32 hex-encoded bytes)")
            try:
                master_key = bytes.fromhex(env)
            except ValueError:
                raise KMSError("MTPU_KMS_SECRET_KEY is not valid hex "
                               "(need 32 hex-encoded bytes)") from None
        if len(master_key) != 32:
            raise KMSError("master key must be 32 bytes")
        if master_key == b"\x00" * 32 and not allow_insecure_zero_key:
            raise KMSError("refusing all-zero KMS master key")
        self._master = master_key
        self.key_id = key_id
        # Named keys are DERIVED from the root secret (HMAC(master,
        # key id)) — the KES "create key" admin surface without any
        # key-material state to replicate (cf. kes key derivation;
        # internal/kms/kms.go CreateKey). The default key uses the
        # master directly for backward compatibility with data sealed
        # before named keys existed.
        self._created: set[str] = {key_id}

    def _key_for(self, key_id: str, for_decrypt: bool = False) -> bytes:
        if key_id == self.key_id:
            return self._master
        if key_id not in self._created and not for_decrypt:
            # ENCRYPT/status paths enforce the created-set (a typo'd
            # id must not probe as healthy). DECRYPT derives for any
            # id: data sealed under a key proves the key was created,
            # and the created-set is in-memory only — a restart must
            # never strand sealed data whose key material is
            # deterministically derivable.
            raise KMSError(f"unknown key id {key_id!r}")
        import hmac as _hmac
        import hashlib as _hashlib
        return _hmac.new(self._master, b"mtpu-kms-key:" + key_id.encode(),
                         _hashlib.sha256).digest()

    # -- admin surface (cf. KMSCreateKey/KMSKeyStatus admin handlers) --------

    def create_key(self, key_id: str) -> None:
        if not key_id or "/" in key_id:
            raise KMSError(f"invalid key id {key_id!r}")
        self._created.add(key_id)

    def list_keys(self) -> list[str]:
        return sorted(self._created)

    def key_status(self, key_id: str) -> dict:
        """Round-trip health probe: seal + unseal under the key (the
        reference's KMSKeyStatusHandler does exactly this)."""
        try:
            kid, plaintext, sealed = self.generate_data_key(
                b"status-probe", key_id=key_id)
            ok = self.decrypt_data_key(kid, sealed,
                                       b"status-probe") == plaintext
            return {"keyId": key_id, "encryptionErr": "",
                    "decryptionErr": "" if ok else "round-trip mismatch"}
        except KMSError as e:
            return {"keyId": key_id, "encryptionErr": str(e),
                    "decryptionErr": ""}

    def generate_data_key(self, context: bytes = b"",
                          key_id: str | None = None):
        key_id = key_id or self.key_id
        _require_aesgcm()
        plaintext = secrets.token_bytes(32)
        nonce = secrets.token_bytes(12)
        sealed = nonce + AESGCM(self._key_for(key_id)).encrypt(
            nonce, plaintext, context)
        return key_id, plaintext, sealed

    def decrypt_data_key(self, key_id: str, sealed: bytes,
                         context: bytes = b"") -> bytes:
        _require_aesgcm()
        try:
            return AESGCM(self._key_for(key_id, for_decrypt=True)).decrypt(
                sealed[:12], sealed[12:], context)
        except Exception as e:  # noqa: BLE001
            raise KMSError(f"unseal failed: {e}") from None


def seal_with_kms(kms: KMS, plaintext: bytes,
                  context: bytes = b"") -> dict:
    """Seal a config blob under a fresh KMS data key -> JSON-able doc.
    One audited sealing format for every subsystem that persists
    secrets (tier configs, etc.); the payload framing is sse.seal's."""
    from .sse import seal
    key_id, pk, sealed_key = kms.generate_data_key(context)
    return {"v": 2, "keyId": key_id, "sealedKey": sealed_key.hex(),
            "ciphertext": seal(plaintext, pk).hex()}


def unseal_with_kms(kms: KMS, doc: dict, context: bytes = b"") -> bytes:
    """Inverse of seal_with_kms. Raises KMSError/SSEError on mismatch."""
    from .sse import unseal
    pk = kms.decrypt_data_key(doc["keyId"],
                              bytes.fromhex(doc["sealedKey"]), context)
    return unseal(bytes.fromhex(doc["ciphertext"]), pk)


def is_sealed_doc(doc) -> bool:
    return (isinstance(doc, dict) and doc.get("v") == 2
            and "ciphertext" in doc and "sealedKey" in doc)


def kms_from_env() -> StaticKMS | None:
    """A keyed KMS if the environment provides one, else None — callers
    must then reject SSE-S3/SSE-KMS requests instead of silently sealing
    under a known key."""
    if not os.environ.get("MTPU_KMS_SECRET_KEY", ""):
        return None
    return StaticKMS()
