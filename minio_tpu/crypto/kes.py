"""KES-shaped KMS client: the external key-server protocol.

The internal/kms KES client role (cf. internal/kms/kms.go:29 and
github.com/minio/kes-go): data keys are generated and unsealed by an
external key server over its REST API —

    POST /v1/key/generate/{name}   {"context": b64} ->
         {"plaintext": b64, "ciphertext": b64}
    POST /v1/key/decrypt/{name}    {"ciphertext": b64, "context": b64} ->
         {"plaintext": b64}
    GET  /v1/status                -> {"version": ...}

KESKMS implements the same narrow KMS interface StaticKMS does
(generate_data_key/decrypt_data_key), so SSE, tier-config sealing and
the KMS admin surface work unchanged against an external server. The
env has no live KES (zero egress); tests run the client against an
in-process fake speaking the same routes, which is exactly how the
HTTP encoding is validated. Production KES requires mTLS — the client
takes an ssl context for that; the fake runs plaintext.
"""

from __future__ import annotations

import base64
import http.client
import json

from .kms import KMS, KMSError


class KESKMS(KMS):
    """KMS backed by a KES server."""

    def __init__(self, host: str, port: int, default_key: str = "minio-key",
                 tls_context=None, timeout: float = 5.0):
        self.host, self.port = host, port
        self.key_id = default_key
        self._tls = tls_context
        self.timeout = timeout

    def _conn(self):
        if self._tls is not None:
            return http.client.HTTPSConnection(
                self.host, self.port, timeout=self.timeout,
                context=self._tls)
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    def _call(self, method: str, path: str, payload: dict | None) -> dict:
        conn = self._conn()
        try:
            body = json.dumps(payload).encode() if payload is not None \
                else None
            conn.request(method, path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException) as e:
            # every transport failure honors the KMSError contract —
            # a malformed response must not 500 an SSE request
            raise KMSError(f"kes: {e}") from None
        finally:
            conn.close()
        if resp.status != 200:
            try:
                msg = json.loads(data).get("message", data[:200])
            except ValueError:
                msg = data[:200]
            raise KMSError(f"kes: {resp.status} {msg}")
        try:
            return json.loads(data) if data else {}
        except ValueError as e:
            raise KMSError(f"kes: bad response: {e}") from None

    # -- KMS interface -------------------------------------------------------

    def generate_data_key(self, context: bytes = b"",
                          key_id: str | None = None):
        key_id = key_id or self.key_id
        out = self._call(
            "POST", f"/v1/key/generate/{key_id}",
            {"context": base64.b64encode(context).decode()})
        try:
            plaintext = base64.b64decode(out["plaintext"])
            sealed = base64.b64decode(out["ciphertext"])
        except (KeyError, ValueError) as e:
            raise KMSError(f"kes: malformed generate reply: {e}") from None
        return key_id, plaintext, sealed

    def decrypt_data_key(self, key_id: str, sealed: bytes,
                         context: bytes = b"") -> bytes:
        out = self._call(
            "POST", f"/v1/key/decrypt/{key_id}",
            {"ciphertext": base64.b64encode(sealed).decode(),
             "context": base64.b64encode(context).decode()})
        try:
            return base64.b64decode(out["plaintext"])
        except (KeyError, ValueError) as e:
            raise KMSError(f"kes: malformed decrypt reply: {e}") from None

    # -- admin surface parity with StaticKMS ---------------------------------

    def create_key(self, key_id: str) -> None:
        if not key_id or "/" in key_id:
            raise KMSError(f"invalid key id {key_id!r}")
        self._call("POST", f"/v1/key/create/{key_id}", {})

    def list_keys(self) -> list[str]:
        out = self._call("GET", "/v1/key/list/*", None)
        return sorted(out.get("keys", []))

    def key_status(self, key_id: str) -> dict:
        try:
            kid, plaintext, sealed = self.generate_data_key(
                b"status-probe", key_id=key_id)
            ok = self.decrypt_data_key(kid, sealed,
                                       b"status-probe") == plaintext
            return {"keyId": key_id, "encryptionErr": "",
                    "decryptionErr": "" if ok else "round-trip mismatch"}
        except KMSError as e:
            return {"keyId": key_id, "encryptionErr": str(e),
                    "decryptionErr": ""}

    def status(self) -> dict:
        return self._call("GET", "/v1/status", None)
