"""Fused Pallas TPU kernel for the batched GF(2^8) bit-plane matmul.

The portable XLA path (ops/erasure_jax.py) materializes bf16 bit-planes in
HBM — 16x the input bytes of traffic; measured ~4x slower than this kernel on
chip. Here unpack -> MXU matmul -> mod-2 -> byte pack are fused into one
VMEM-resident pass per (block, lane-tile) grid step, so HBM traffic is just
shard bytes in + computed shards out — the device analogue of the reference
streaming 1 MiB blocks through AVX512 registers (cmd/erasure-encode.go:73).

Design notes (measured on the target chip):
- Plane construction by 2D `concat` of `(x >> j) & 1` slices avoids the
  cross-sublane relayouts that made a 4D-reshape variant ~50x slower.
- The matmul is skinny ((8R x 8C) @ (8C x TILE_S), e.g. 32x64 for EC:8+4 —
  ~12% MXU occupancy) but the kernel is HBM-bound on the target, so
  occupancy tricks (block-diagonal batching, int8 MXU) measured neutral;
  the simple 2D form is kept.
- Encode, decode/reconstruct, and heal all call this one kernel with
  different (tiny, host-built) matrices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane tile along the shard dimension; multiple of 128.
DEFAULT_TILE_S = 8192

# Set True in tests to exercise the kernel in interpreter mode off-TPU.
FORCE_INTERPRET = False


def _choose_tile_s(s: int) -> int | None:
    """Largest multiple-of-128 tile <= DEFAULT_TILE_S that divides s."""
    for t in range(min(DEFAULT_TILE_S, s - s % 128), 0, -128):
        if s % t == 0:
            return t
    return None


def _unpack_mm_pack(x, mat_ref, rows: int):
    planes = jnp.concatenate(
        [(x >> j) & 1 for j in range(8)], axis=0).astype(jnp.bfloat16)
    y = jnp.dot(mat_ref[...], planes,
                preferred_element_type=jnp.float32)      # (8R, TS)
    bits = y.astype(jnp.int32) & 1                       # plane-major: row j*R+r
    out = bits[0:rows]
    for j in range(1, 8):
        out = out | (bits[j * rows:(j + 1) * rows] << j)
    return out.astype(jnp.uint8)


def _kernel(mat_ref, x_ref, out_ref, *, rows: int):
    """One grid step: (C, TILE_S) uint8 shards -> (R, TILE_S) output shards."""
    x = x_ref[0].astype(jnp.int32)                      # (C, TS)
    out_ref[0] = _unpack_mm_pack(x, mat_ref, rows)


def _kernel_salted(salt_ref, mat_ref, x_ref, out_ref, *, rows: int):
    """Benchmark-protocol variant: input bytes are xor-perturbed by a
    per-dispatch scalar INSIDE the kernel (VMEM, zero extra HBM traffic)
    so a timing loop can defeat CSE/hoisting without the host-side
    128 MiB xor pass that used to dominate the measurement."""
    x = (x_ref[0].astype(jnp.int32) ^ salt_ref[0]) & 0xFF
    out_ref[0] = _unpack_mm_pack(x, mat_ref, rows)


@functools.partial(jax.jit,
                   static_argnames=("rows", "tile_s", "interpret"))
def _pallas_gf_matmul(mat: jax.Array, x: jax.Array, rows: int,
                      tile_s: int, interpret: bool = False,
                      salt: jax.Array | None = None) -> jax.Array:
    b, c, s = x.shape
    common = dict(
        grid=(b, s // tile_s),
        out_specs=pl.BlockSpec((1, rows, tile_s), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, rows, s), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * (8 * rows) * (8 * c) * s * b,
            bytes_accessed=b * c * s + b * rows * s,
            transcendentals=0),
        interpret=interpret,
    )
    mat_spec = pl.BlockSpec((8 * rows, 8 * c), lambda i, j: (0, 0),
                            memory_space=pltpu.VMEM)
    x_spec = pl.BlockSpec((1, c, tile_s), lambda i, j: (i, 0, j),
                          memory_space=pltpu.VMEM)
    if salt is None:
        return pl.pallas_call(
            functools.partial(_kernel, rows=rows),
            in_specs=[mat_spec, x_spec], **common)(mat, x)
    return pl.pallas_call(
        functools.partial(_kernel_salted, rows=rows),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), mat_spec,
                  x_spec], **common)(salt, mat, x)


def gf_matmul_blocks(mat_bits: jax.Array | np.ndarray, x: jax.Array,
                     rows: int, salt: jax.Array | None = None) -> jax.Array:
    """Fused-kernel GF(2^8) batched matmul; drop-in for the XLA path.

    mat_bits: (8R, 8C) plane-major bit matrix; x: (B, C, S) uint8 shards.
    Falls back to the portable XLA path when the geometry doesn't tile
    (shard size not a multiple of 128) or when off-TPU outside tests.

    salt: optional (1,) int32 — xors every input byte inside the kernel
    (benchmark protocol; production passes None and pays nothing).
    """
    from . import erasure_jax

    x = jnp.asarray(x, dtype=jnp.uint8)
    b, c, s = x.shape
    mat = jnp.asarray(mat_bits, dtype=jnp.bfloat16)
    tile_s = _choose_tile_s(s)
    on_tpu = jax.default_backend() == "tpu"
    if (not on_tpu and not FORCE_INTERPRET) or tile_s is None or b == 0:
        if salt is not None:
            x = x ^ salt[0].astype(jnp.uint8)
        return erasure_jax._gf_matmul_blocks(mat, x, rows)
    return _pallas_gf_matmul(mat, x, rows, tile_s, interpret=not on_tpu,
                             salt=salt)
