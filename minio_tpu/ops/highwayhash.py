"""HighwayHash-256 — bit-identical to the reference's bitrot hash.

The reference's default bitrot algorithm is HighwayHash256S (streaming), keyed
with a magic 256-bit key (/root/reference/cmd/bitrot.go:37). Every shard block
written to disk is framed as [32-byte HighwayHash256 | shard bytes]
(/root/reference/cmd/bitrot-streaming.go). To be able to verify/produce the
reference's on-disk frames, this implementation must match the upstream
HighwayHash algorithm exactly; it is validated against the reference's
self-test golden chain (/root/reference/cmd/bitrot.go:215-220) in
tests/test_highwayhash.py.

Implementation notes: 4x64-bit lanes held as python ints (masked to 64 bits).
A numpy-vectorized multi-stream variant (many independent hashes advanced in
lockstep — the shape the TPU kernel parallelizes over) lives in
`HighwayHashVec`. State update math follows the published HighwayHash
portable algorithm (google/highwayhash hh_portable.h).
"""

from __future__ import annotations

import struct

import numpy as np

MASK64 = (1 << 64) - 1

# HighwayHash init constants (sqrt/pi derived, from the published algorithm).
INIT0 = (0xDBE6D5D5FE4CCE2F, 0xA4093822299F31D0,
         0x13198A2E03707344, 0x243F6A8885A308D3)
INIT1 = (0x3BD39E10CB0EF593, 0xC0ACF169B5F18A8C,
         0xBE5466CF34E90C6C, 0x452821E638D01377)

# Magic bitrot key: HH-256 of the first 100 decimals of pi with a zero key
# (/root/reference/cmd/bitrot.go:37).
MAGIC_KEY = bytes([
    0x4B, 0xE7, 0x34, 0xFA, 0x8E, 0x23, 0x8A, 0xCD,
    0x26, 0x3E, 0x83, 0xE6, 0xBB, 0x96, 0x85, 0x52,
    0x04, 0x0F, 0x93, 0x5D, 0xA3, 0x9F, 0x44, 0x14,
    0x97, 0xE0, 0x9D, 0x13, 0x22, 0xDE, 0x36, 0xA0,
])

SIZE = 32        # digest bytes (256-bit)
BLOCK_SIZE = 32  # hash.Hash BlockSize (one 32-byte packet), per the Go package


def _rot32_within64(x: int, count: int) -> int:
    """Rotate each 32-bit half of a 64-bit lane left by count."""
    lo = x & 0xFFFFFFFF
    hi = x >> 32
    lo = ((lo << count) | (lo >> (32 - count))) & 0xFFFFFFFF if count else lo
    hi = ((hi << count) | (hi >> (32 - count))) & 0xFFFFFFFF if count else hi
    return (hi << 32) | lo


class HighwayHash256:
    """Streaming HighwayHash-256 over 32-byte packets."""

    def __init__(self, key: bytes = MAGIC_KEY):
        if len(key) != 32:
            raise ValueError("key must be 32 bytes")
        self.key = struct.unpack("<4Q", key)
        self.reset()

    def reset(self) -> None:
        k = self.key
        self.v0 = [INIT0[i] ^ k[i] for i in range(4)]
        self.v1 = [INIT1[i] ^ (((k[i] >> 32) | (k[i] << 32)) & MASK64)
                   for i in range(4)]
        self.mul0 = list(INIT0)
        self.mul1 = list(INIT1)
        self._buf = b""

    # -- core update ----------------------------------------------------------

    def _update_packet(self, lanes: tuple[int, int, int, int]) -> None:
        v0, v1, mul0, mul1 = self.v0, self.v1, self.mul0, self.mul1
        for i in range(4):
            v1[i] = (v1[i] + mul0[i] + lanes[i]) & MASK64
            mul0[i] ^= ((v1[i] & 0xFFFFFFFF) * (v0[i] >> 32)) & MASK64
            v0[i] = (v0[i] + mul1[i]) & MASK64
            mul1[i] ^= ((v0[i] & 0xFFFFFFFF) * (v1[i] >> 32)) & MASK64
        self._zipper_merge_and_add(v1[1], v1[0], v0, 1, 0)
        self._zipper_merge_and_add(v1[3], v1[2], v0, 3, 2)
        self._zipper_merge_and_add(v0[1], v0[0], v1, 1, 0)
        self._zipper_merge_and_add(v0[3], v0[2], v1, 3, 2)

    @staticmethod
    def _zipper_merge_and_add(v1: int, v0: int, add: list[int],
                              i1: int, i0: int) -> None:
        add[i0] = (add[i0] + (
            (((v0 & 0xFF000000) | (v1 & 0xFF00000000)) >> 24)
            | (((v0 & 0xFF0000000000) | (v1 & 0xFF000000000000)) >> 16)
            | (v0 & 0xFF0000)
            | ((v0 & 0xFF00) << 32)
            | ((v1 & 0xFF00000000000000) >> 8)
            | ((v0 << 56) & MASK64)
        )) & MASK64
        add[i1] = (add[i1] + (
            (((v1 & 0xFF000000) | (v0 & 0xFF00000000)) >> 24)
            | (v1 & 0xFF0000)
            | ((v1 & 0xFF0000000000) >> 16)
            | ((v1 & 0xFF00) << 24)
            | ((v0 & 0xFF000000000000) >> 8)
            | ((v1 & 0xFF) << 48)
            | (v0 & 0xFF00000000000000)
        )) & MASK64

    # -- streaming interface --------------------------------------------------

    def update(self, data: bytes) -> "HighwayHash256":
        buf = self._buf + data
        n = (len(buf) // 32) * 32
        for off in range(0, n, 32):
            self._update_packet(struct.unpack_from("<4Q", buf, off))
        self._buf = buf[n:]
        return self

    write = update  # Go hash.Hash naming

    def _update_remainder(self, bytes_: bytes) -> None:
        size_mod32 = len(bytes_)
        assert 0 < size_mod32 < 32
        size_mod4 = size_mod32 & 3
        remainder = bytes_[size_mod32 & ~3:]
        for i in range(4):
            self.v0[i] = (self.v0[i] + ((size_mod32 << 32) + size_mod32)) & MASK64
            self.v1[i] = _rot32_within64(self.v1[i], size_mod32)
        packet = bytearray(32)
        packet[: size_mod32 & ~3] = bytes_[: size_mod32 & ~3]
        if size_mod32 & 16:
            # Reads the 4 bytes ending at remainder+size_mod4, which may reach
            # back before the remainder start (Load3 AllowReadBeforeAndReturn).
            for i in range(4):
                packet[28 + i] = bytes_[(size_mod32 & ~3) + size_mod4 - 4 + i]
        elif size_mod4:
            packet[16] = remainder[0]
            packet[17] = remainder[size_mod4 >> 1]
            packet[18] = remainder[size_mod4 - 1]
        self._update_packet(struct.unpack("<4Q", bytes(packet)))

    def _permute_and_update(self) -> None:
        v0 = self.v0
        permuted = (
            ((v0[2] >> 32) | (v0[2] << 32)) & MASK64,
            ((v0[3] >> 32) | (v0[3] << 32)) & MASK64,
            ((v0[0] >> 32) | (v0[0] << 32)) & MASK64,
            ((v0[1] >> 32) | (v0[1] << 32)) & MASK64,
        )
        self._update_packet(permuted)

    @staticmethod
    def _modular_reduction(a3u: int, a2: int, a1: int, a0: int) -> tuple[int, int]:
        a3 = a3u & 0x3FFFFFFFFFFFFFFF
        m1 = a1 ^ (((a3 << 1) | (a2 >> 63)) & MASK64) ^ (((a3 << 2) | (a2 >> 62)) & MASK64)
        m0 = a0 ^ ((a2 << 1) & MASK64) ^ ((a2 << 2) & MASK64)
        return m1, m0

    def digest(self) -> bytes:
        """Finalize a copy of the state and return the 32-byte digest."""
        st = self._clone()
        if st._buf:
            st._update_remainder(st._buf)
        for _ in range(10):
            st._permute_and_update()
        m1a, m0a = self._modular_reduction(
            (st.v1[1] + st.mul1[1]) & MASK64, (st.v1[0] + st.mul1[0]) & MASK64,
            (st.v0[1] + st.mul0[1]) & MASK64, (st.v0[0] + st.mul0[0]) & MASK64)
        m1b, m0b = self._modular_reduction(
            (st.v1[3] + st.mul1[3]) & MASK64, (st.v1[2] + st.mul1[2]) & MASK64,
            (st.v0[3] + st.mul0[3]) & MASK64, (st.v0[2] + st.mul0[2]) & MASK64)
        return struct.pack("<4Q", m0a, m1a, m0b, m1b)

    sum256 = digest

    def _clone(self) -> "HighwayHash256":
        c = object.__new__(HighwayHash256)
        c.key = self.key
        c.v0 = list(self.v0)
        c.v1 = list(self.v1)
        c.mul0 = list(self.mul0)
        c.mul1 = list(self.mul1)
        c._buf = self._buf
        return c


def highwayhash256(data: bytes, key: bytes = MAGIC_KEY) -> bytes:
    """One-shot 256-bit HighwayHash."""
    return HighwayHash256(key).update(data).digest()


# ---------------------------------------------------------------------------
# Vectorized multi-stream variant: N independent hashes advanced in lockstep.
# This is the data layout the TPU bitrot kernel uses — one hash state per
# shard-block, parallel across the batch (cf. SURVEY.md §7 hard part #3:
# parallelize across shard streams, not within one).
# ---------------------------------------------------------------------------

class HighwayHashVec:
    """N parallel HighwayHash-256 states over uint64 numpy lanes.

    All streams must consume identically-sized inputs (the bitrot use case:
    every shard block in a batch has the same shard_size).
    """

    def __init__(self, n: int, key: bytes = MAGIC_KEY):
        k = np.frombuffer(key, dtype="<u8").astype(np.uint64)
        init0 = np.array(INIT0, dtype=np.uint64)
        init1 = np.array(INIT1, dtype=np.uint64)
        krot = (k >> np.uint64(32)) | (k << np.uint64(32))
        self.n = n
        self.v0 = np.broadcast_to(init0 ^ k, (n, 4)).copy()
        self.v1 = np.broadcast_to(init1 ^ krot, (n, 4)).copy()
        self.mul0 = np.broadcast_to(init0, (n, 4)).copy()
        self.mul1 = np.broadcast_to(init1, (n, 4)).copy()

    def _update_packets(self, lanes: np.ndarray) -> None:
        """lanes: (n, 4) uint64 — one 32-byte packet per stream."""
        v0, v1, mul0, mul1 = self.v0, self.v1, self.mul0, self.mul1
        M32 = np.uint64(0xFFFFFFFF)
        S32 = np.uint64(32)
        v1 += mul0 + lanes
        mul0 ^= (v1 & M32) * (v0 >> S32)
        v0 += mul1
        mul1 ^= (v0 & M32) * (v1 >> S32)
        self._zipper(v1, v0)
        self._zipper(v0, v1)

    @staticmethod
    def _zipper(src: np.ndarray, dst: np.ndarray) -> None:
        """dst[:, {0,1}] += zipper_merge(src[:, {0,1}]), same for {2,3}."""
        def u(x):
            return np.uint64(x)
        for (i0, i1) in ((0, 1), (2, 3)):
            v0 = src[:, i0]
            v1 = src[:, i1]
            dst[:, i0] += (
                (((v0 & u(0xFF000000)) | (v1 & u(0xFF00000000))) >> u(24))
                | (((v0 & u(0xFF0000000000)) | (v1 & u(0xFF000000000000))) >> u(16))
                | (v0 & u(0xFF0000))
                | ((v0 & u(0xFF00)) << u(32))
                | ((v1 & u(0xFF00000000000000)) >> u(8))
                | (v0 << u(56)))
            dst[:, i1] += (
                (((v1 & u(0xFF000000)) | (v0 & u(0xFF00000000))) >> u(24))
                | (v1 & u(0xFF0000))
                | ((v1 & u(0xFF0000000000)) >> u(16))
                | ((v1 & u(0xFF00)) << u(24))
                | ((v0 & u(0xFF000000000000)) >> u(8))
                | ((v1 & u(0xFF)) << u(48))
                | (v0 & u(0xFF00000000000000)))

    def update(self, data: np.ndarray) -> "HighwayHashVec":
        """data: (n, L) uint8 with L % 32 == 0 — bulk packets for all streams."""
        n, L = data.shape
        assert n == self.n and L % 32 == 0
        lanes = data.reshape(n, L // 32, 4, 8).view("<u8")[..., 0].astype(np.uint64)
        for p in range(L // 32):
            self._update_packets(lanes[:, p, :])
        return self

    def update_remainder(self, data: np.ndarray) -> "HighwayHashVec":
        """data: (n, r) uint8, 0 < r < 32 — identical tail for all streams."""
        n, r = data.shape
        assert n == self.n and 0 < r < 32
        size_mod4 = r & 3
        base = r & ~3
        self.v0 += np.uint64((r << 32) + r)
        # rotate32 each half of every v1 lane by r bits
        lo = self.v1 & np.uint64(0xFFFFFFFF)
        hi = self.v1 >> np.uint64(32)
        rr = np.uint64(r)
        lo = ((lo << rr) | (lo >> np.uint64(32 - r))) & np.uint64(0xFFFFFFFF)
        hi = ((hi << rr) | (hi >> np.uint64(32 - r))) & np.uint64(0xFFFFFFFF)
        self.v1 = (hi << np.uint64(32)) | lo
        packet = np.zeros((n, 32), dtype=np.uint8)
        packet[:, :base] = data[:, :base]
        remainder = data[:, base:]
        if r & 16:
            for i in range(4):
                packet[:, 28 + i] = data[:, base + size_mod4 - 4 + i]
        elif size_mod4:
            packet[:, 16] = remainder[:, 0]
            packet[:, 17] = remainder[:, size_mod4 >> 1]
            packet[:, 18] = remainder[:, size_mod4 - 1]
        lanes = packet.reshape(n, 4, 8).view("<u8")[..., 0].astype(np.uint64)
        self._update_packets(lanes)
        return self

    def digest(self) -> np.ndarray:
        """Finalize all streams; returns (n, 32) uint8 digests."""
        st = HighwayHashVec.__new__(HighwayHashVec)
        st.n = self.n
        st.v0, st.v1 = self.v0.copy(), self.v1.copy()
        st.mul0, st.mul1 = self.mul0.copy(), self.mul1.copy()
        for _ in range(10):
            v0 = st.v0
            swap = lambda x: (x >> np.uint64(32)) | (x << np.uint64(32))
            permuted = np.stack(
                [swap(v0[:, 2]), swap(v0[:, 3]), swap(v0[:, 0]), swap(v0[:, 1])],
                axis=1)
            st._update_packets(permuted)
        def modred(a3u, a2, a1, a0):
            a3 = a3u & np.uint64(0x3FFFFFFFFFFFFFFF)
            m1 = a1 ^ ((a3 << np.uint64(1)) | (a2 >> np.uint64(63))) \
                 ^ ((a3 << np.uint64(2)) | (a2 >> np.uint64(62)))
            m0 = a0 ^ (a2 << np.uint64(1)) ^ (a2 << np.uint64(2))
            return m1, m0
        m1a, m0a = modred(st.v1[:, 1] + st.mul1[:, 1], st.v1[:, 0] + st.mul1[:, 0],
                          st.v0[:, 1] + st.mul0[:, 1], st.v0[:, 0] + st.mul0[:, 0])
        m1b, m0b = modred(st.v1[:, 3] + st.mul1[:, 3], st.v1[:, 2] + st.mul1[:, 2],
                          st.v0[:, 3] + st.mul0[:, 3], st.v0[:, 2] + st.mul0[:, 2])
        out = np.stack([m0a, m1a, m0b, m1b], axis=1)
        return out.astype("<u8").view(np.uint8).reshape(self.n, 32)


def highwayhash256_batch(blocks: np.ndarray, key: bytes = MAGIC_KEY) -> np.ndarray:
    """Hash a batch of equal-length blocks: (n, L) uint8 -> (n, 32) digests."""
    n, L = blocks.shape
    h = HighwayHashVec(n, key)
    base = (L // 32) * 32
    if base:
        h.update(blocks[:, :base])
    if L % 32:
        h.update_remainder(blocks[:, base:])
    return h.digest()
