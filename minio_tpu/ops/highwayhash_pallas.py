"""Pallas TPU kernel for multi-stream HighwayHash-256 bulk packets.

The XLA scan formulation (highwayhash_jax) pays per-op dispatch overhead
on every one of thousands of sequential packets. This kernel moves the
WHOLE packet chain inside one Mosaic program: state lives in VMEM
scratch, packets stream through in (PB, 4, S) chunks via the pipeline,
and the packet-chunk grid dimension is sequential so scratch carries the
chain across chunks.

Layout notes (what made it fast): every 64-bit lane is TWO SEPARATE 1-D
(S,) uint32 arrays — 32 state arrays total. The (4, S) formulation with
`.at[lane].set` updates (fine under XLA) materializes whole-array copies
per zipper step inside Mosaic; unrolled per-lane scalars keep each op a
plain elementwise vreg instruction.

Only the bulk multiple-of-32 prefix runs here; remainder packets and
finalization reuse the (bit-identical) XLA path, which also serves as
the correctness oracle in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import highwayhash_jax as hj

PB = 64           # packets per pipelined chunk
SBLK = 1024       # streams per program: wide 1-D ops keep the VPU busy
#                   despite the serial packet chain.


def _update_lanes(st: tuple, lanes: tuple) -> tuple:
    """One packet, fully unrolled per lane.

    st: 32-tuple of (S,) uint32 — [group v0,v1,mul0,mul1] x [lane 0..3]
    x [hi,lo]; lanes: 8-tuple (lane0_hi, lane0_lo, ... lane3_lo).
    """
    add64, xor64 = hj._add64, hj._xor64
    mul = hj._mul32x32

    def g(group, lane):                       # -> (hi, lo)
        base = group * 8 + lane * 2
        return (st[base], st[base + 1])

    v0 = [g(0, i) for i in range(4)]
    v1 = [g(1, i) for i in range(4)]
    mul0 = [g(2, i) for i in range(4)]
    mul1 = [g(3, i) for i in range(4)]

    for i in range(4):
        lane = (lanes[2 * i], lanes[2 * i + 1])
        v1[i] = add64(add64(v1[i], mul0[i]), lane)
        mul0[i] = xor64(mul0[i], mul(v1[i][1], v0[i][0]))
        v0[i] = add64(v0[i], mul1[i])
        mul1[i] = xor64(mul1[i], mul(v0[i][1], v1[i][0]))
    for (i0, i1) in ((0, 1), (2, 3)):
        a0, a1 = hj._zipper_addend(v1[i0], v1[i1])
        v0[i0] = add64(v0[i0], a0)
        v0[i1] = add64(v0[i1], a1)
    for (i0, i1) in ((0, 1), (2, 3)):
        a0, a1 = hj._zipper_addend(v0[i0], v0[i1])
        v1[i0] = add64(v1[i0], a0)
        v1[i1] = add64(v1[i1], a1)

    out = []
    for group in (v0, v1, mul0, mul1):
        for pair in group:
            out.extend(pair)
    return tuple(out)


def _kernel(hi_ref, lo_ref, out_ref, st_ref, *, init: np.ndarray):
    import jax.experimental.pallas as pl

    k = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(k == 0)
    def _():
        st_ref[...] = jnp.broadcast_to(
            jnp.asarray(init, dtype=jnp.uint32)[:, None], st_ref.shape)

    state = tuple(st_ref[w] for w in range(32))

    def body(i, st):
        lanes = []
        for lane in range(4):
            lanes.append(hi_ref[i, lane])
            lanes.append(lo_ref[i, lane])
        return _update_lanes(st, tuple(lanes))

    state = jax.lax.fori_loop(0, hi_ref.shape[0], body, state)
    st_ref[...] = jnp.stack(state)

    @pl.when(k == nk - 1)
    def _():
        out_ref[...] = st_ref[...]


@functools.lru_cache(maxsize=32)
def _bulk_fn(p: int, s: int, key: bytes):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # init words, flattened in kernel state order.
    k = np.frombuffer(key, dtype="<u8")
    i0 = np.array(hj.INIT0, dtype=np.uint64)
    i1 = np.array(hj.INIT1, dtype=np.uint64)
    krot = (k >> np.uint64(32)) | (k << np.uint64(32))
    init = np.empty(32, dtype=np.uint32)
    for gi, v in enumerate((i0 ^ k, i1 ^ krot, i0, i1)):
        for lane in range(4):
            init[gi * 8 + lane * 2] = np.uint32(v[lane] >> np.uint64(32))
            init[gi * 8 + lane * 2 + 1] = np.uint32(
                v[lane] & np.uint64(0xFFFFFFFF))

    grid = (s // SBLK, p // PB)
    return pl.pallas_call(
        functools.partial(_kernel, init=init),
        grid=grid,
        in_specs=[
            pl.BlockSpec((PB, 4, SBLK), lambda j, kk: (kk, 0, j)),
            pl.BlockSpec((PB, 4, SBLK), lambda j, kk: (kk, 0, j)),
        ],
        out_specs=pl.BlockSpec((32, SBLK), lambda j, kk: (0, j)),
        out_shape=jax.ShapeDtypeStruct((32, s), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((32, SBLK), jnp.uint32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )


def bulk_state(hi: jax.Array, lo: jax.Array, key: bytes):
    """Run the bulk packets through the kernel; returns the state dict in
    highwayhash_jax's (4, S)-pair format. hi/lo: (P, 4, S) uint32 with
    P % PB == 0 and S % SBLK == 0 (caller pads streams)."""
    p, _, s = hi.shape
    out = _bulk_fn(p, s, key)(hi, lo)          # (32, S)

    def group(gi):
        his = jnp.stack([out[gi * 8 + lane * 2] for lane in range(4)])
        los = jnp.stack([out[gi * 8 + lane * 2 + 1] for lane in range(4)])
        return (his, los)

    return {"v0": group(0), "v1": group(1),
            "mul0": group(2), "mul1": group(3)}


def supported(n_streams: int, n_packets: int) -> bool:
    """OFF by default (MTPU_HH_PALLAS=1 enables).

    Measured on v5e: this kernel reaches ~1 GB/s vs the XLA scan's
    ~2 GB/s at 1024 streams x 4096 packets — HighwayHash's dependent
    32x32->64 multiply chain has no fast VPU lowering (each mul is five
    16-bit partial products with carries), so in-kernel execution saves
    dispatch overhead but loses more to serialized emulated multiplies.
    Kept as the documented negative result for SURVEY §7 hard-part #3;
    the XLA scan remains the production device path. The env gate lives
    in hh256_batch_jax (part of the jit cache key); this checks only
    backend/shape feasibility.
    """
    return (jax.default_backend() == "tpu"
            and n_packets >= PB
            and n_streams >= SBLK // 4)
