"""Fixed-record shared-memory ring for cross-process dispatch descriptors.

The shard bytes themselves live in the ShmArena (ops/shm_arena.py);
what crosses the process boundary per work item is one 64-byte
descriptor.  The ring is a bounded MPMC queue over an anonymous shared
mapping created before fork:

  * records are fixed-size (64 B) so producers and consumers never
    frame-parse — slot i is at i * REC;
  * two fork-inherited semaphores carry the item/space counts (blocking
    put/get with timeouts, no busy polling);
  * two locks serialize multi-producer tails and multi-consumer heads
    (the worker pool has N producers on the request ring and one
    consumer; response rings are 1:1).

The descriptor schema is owned by the callers (ops/coalesce.py's
remote front end packs/unpacks with struct); the ring moves opaque
64-byte records.
"""

from __future__ import annotations

import mmap
import multiprocessing

import numpy as np

REC = 64                           # bytes per record
_HDR = 16                          # head u64 + tail u64


class RingClosed(RuntimeError):
    pass


class ShmRing:
    """Bounded MPMC ring of fixed 64-byte records over fork-shared
    anonymous memory.  Create pre-fork; use from any inheriting
    process."""

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._mm = mmap.mmap(-1, _HDR + self.capacity * REC)
        self._idx = np.frombuffer(self._mm, dtype=np.uint64, count=2)
        ctx = multiprocessing.get_context("fork")
        self._items = ctx.Semaphore(0)
        self._space = ctx.Semaphore(self.capacity)
        self._pmu = ctx.Lock()      # producers (tail)
        self._cmu = ctx.Lock()      # consumers (head)

    def put(self, rec: bytes, timeout: float | None = None) -> bool:
        """Append one record; False on timeout (ring full)."""
        if len(rec) > REC:
            raise ValueError(f"record {len(rec)}B > {REC}B")
        if not self._space.acquire(timeout=timeout):
            return False
        rec = rec.ljust(REC, b"\x00")
        with self._pmu:
            tail = int(self._idx[1])
            off = _HDR + (tail % self.capacity) * REC
            self._mm[off:off + REC] = rec
            self._idx[1] = tail + 1
        self._items.release()
        return True

    def get(self, timeout: float | None = None) -> bytes | None:
        """Pop the oldest record; None on timeout (ring empty)."""
        if not self._items.acquire(timeout=timeout):
            return None
        with self._cmu:
            head = int(self._idx[0])
            off = _HDR + (head % self.capacity) * REC
            rec = bytes(self._mm[off:off + REC])
            self._idx[0] = head + 1
        self._space.release()
        return rec

    def drain(self) -> list[bytes]:
        """Non-blocking: pop everything currently queued (a respawned
        worker clears stale responses addressed to its predecessor)."""
        out = []
        while True:
            rec = self.get(timeout=0)
            if rec is None:
                return out
            out.append(rec)

    def depth(self) -> int:
        """Approximate queue depth (lock-free gauge read)."""
        return max(0, int(self._idx[1]) - int(self._idx[0]))
