"""Device (JAX/XLA) multi-stream HighwayHash-256 — the bitrot kernel.

The reference hashes every shard block with Go-assembly HighwayHash
(/root/reference/cmd/bitrot-streaming.go:35, minio/highwayhash). A hash
stream is inherently sequential, so the TPU formulation parallelizes
ACROSS streams (SURVEY.md §7 hard-part #3): N independent shard-block
states advance in lockstep, one 32-byte packet per scan step, all lanes
vectorized on the VPU.

TPUs have no native 64-bit integers, so every 64-bit lane is carried as a
(hi, lo) pair of uint32 arrays; adds propagate carries explicitly and the
32x32->64 multiply is built from 16-bit partial products. All shapes are
static: (4, N) per state word, scanned over the packet axis. The result is
bit-identical to the reference's magic-keyed HighwayHash256
(validated against /root/reference/cmd/bitrot.go:215 golden chains in
tests/test_highwayhash.py).

State layout is (4 lanes, N streams): the stream axis lands on the VPU's
128-wide lane dimension, so throughput scales with the number of
shard-blocks in flight — exactly the batch shape the erasure matmul
already uses, which lets verify fuse into decode as one dispatch
(`ops/fused.py`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .highwayhash import INIT0, INIT1, MAGIC_KEY

U32 = jnp.uint32
_M16 = np.uint32(0xFFFF)


def _c64(x: int):
    """Split a python 64-bit constant into (hi, lo) uint32 scalars."""
    return np.uint32((x >> 32) & 0xFFFFFFFF), np.uint32(x & 0xFFFFFFFF)


# -- 64-bit primitive ops on (hi, lo) uint32 pairs --------------------------

def _add64(a, b):
    ah, al = a
    bh, bl = b
    lo = al + bl
    carry = (lo < al).astype(U32)
    return ah + bh + carry, lo


def _xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _or64(a, b):
    return a[0] | b[0], a[1] | b[1]


def _and64c(a, c: int):
    ch, cl = _c64(c)
    return a[0] & ch, a[1] & cl


def _shl64(a, s: int):
    ah, al = a
    if s == 0:
        return ah, al
    if s >= 32:
        return (al << (s - 32)) if s > 32 else al, jnp.zeros_like(al)
    return (ah << s) | (al >> (32 - s)), al << s


def _shr64(a, s: int):
    ah, al = a
    if s == 0:
        return ah, al
    if s >= 32:
        return jnp.zeros_like(ah), (ah >> (s - 32)) if s > 32 else ah
    return ah >> s, (al >> s) | (ah << (32 - s))


def _swap32(a):
    """Rotate a 64-bit lane by 32 = swap hi/lo words."""
    return a[1], a[0]


def _mul32x32(a: jax.Array, b: jax.Array):
    """Full 64-bit product of two uint32 arrays, as a (hi, lo) pair."""
    a0, a1 = a & _M16, a >> 16
    b0, b1 = b & _M16, b >> 16
    ll = a0 * b0
    mid = a0 * b1 + a1 * b0          # may wrap: recover the carry
    mid_carry = (mid < a0 * b1).astype(U32)
    lo = ll + (mid << 16)
    lo_carry = (lo < ll).astype(U32)
    hi = a1 * b1 + (mid >> 16) + (mid_carry << 16) + lo_carry
    return hi, lo


# -- HighwayHash state -------------------------------------------------------

def _init_state(n: int, key: bytes):
    """8 arrays of shape (4, n): v0/v1/mul0/mul1 x hi/lo."""
    k = np.frombuffer(key, dtype="<u8")
    i0 = np.array(INIT0, dtype=np.uint64)
    i1 = np.array(INIT1, dtype=np.uint64)
    krot = (k >> np.uint64(32)) | (k << np.uint64(32))
    v0 = i0 ^ k
    v1 = i1 ^ krot

    def pair(v):
        hi = (v >> np.uint64(32)).astype(np.uint32)
        lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        return (jnp.broadcast_to(jnp.asarray(hi)[:, None], (4, n)),
                jnp.broadcast_to(jnp.asarray(lo)[:, None], (4, n)))

    return {"v0": pair(v0), "v1": pair(v1),
            "mul0": pair(i0), "mul1": pair(i1)}


def _zipper_addend(v0, v1):
    """The two zipper-merge 64-bit addends for a lane pair (v0, v1).

    Byte shuffles expressed as mask/shift 64-ops; XLA folds them into a
    handful of u32 shifts per word.
    """
    a0 = _shr64(_or64(_and64c(v0, 0xFF000000), _and64c(v1, 0xFF00000000)), 24)
    a0 = _or64(a0, _shr64(_or64(_and64c(v0, 0xFF0000000000),
                                _and64c(v1, 0xFF000000000000)), 16))
    a0 = _or64(a0, _and64c(v0, 0xFF0000))
    a0 = _or64(a0, _shl64(_and64c(v0, 0xFF00), 32))
    a0 = _or64(a0, _shr64(_and64c(v1, 0xFF00000000000000), 8))
    a0 = _or64(a0, _shl64(v0, 56))

    a1 = _shr64(_or64(_and64c(v1, 0xFF000000), _and64c(v0, 0xFF00000000)), 24)
    a1 = _or64(a1, _and64c(v1, 0xFF0000))
    a1 = _or64(a1, _shr64(_and64c(v1, 0xFF0000000000), 16))
    a1 = _or64(a1, _shl64(_and64c(v1, 0xFF00), 24))
    a1 = _or64(a1, _shr64(_and64c(v0, 0xFF000000000000), 8))
    a1 = _or64(a1, _shl64(_and64c(v1, 0xFF), 48))
    a1 = _or64(a1, _and64c(v0, 0xFF00000000000000))
    return a0, a1


def _lane(pair, i):
    return pair[0][i], pair[1][i]


def _set_lane(pair, i, val):
    return (pair[0].at[i].set(val[0]), pair[1].at[i].set(val[1]))


def _update_packet(state, lanes):
    """One packet for all streams. lanes: (hi, lo) each (4, n) uint32."""
    v0, v1 = state["v0"], state["v1"]
    mul0, mul1 = state["mul0"], state["mul1"]

    v1 = _add64(_add64(v1, mul0), lanes)
    mul0 = _xor64(mul0, _mul32x32(v1[1], v0[0]))     # v1.lo32 * v0.hi32
    v0 = _add64(v0, mul1)
    mul1 = _xor64(mul1, _mul32x32(v0[1], v1[0]))

    # zipper_merge_and_add on lane pairs (0,1) and (2,3), v1 -> v0, v0 -> v1.
    def merge_into(dst, src):
        for (i0, i1) in ((0, 1), (2, 3)):
            a0, a1 = _zipper_addend(_lane(src, i0), _lane(src, i1))
            dst = _set_lane(dst, i0, _add64(_lane(dst, i0), a0))
            dst = _set_lane(dst, i1, _add64(_lane(dst, i1), a1))
        return dst

    v0 = merge_into(v0, v1)
    v1 = merge_into(v1, v0)
    return {"v0": v0, "v1": v1, "mul0": mul0, "mul1": mul1}


def _bytes_to_lanes(x: jax.Array):
    """(n, P, 32) uint8 packets -> ((P, 4, n) hi, (P, 4, n) lo) uint32."""
    n, p, _ = x.shape
    b = x.reshape(n, p, 4, 8).astype(U32)
    lo = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)
    hi = b[..., 4] | (b[..., 5] << 8) | (b[..., 6] << 16) | (b[..., 7] << 24)
    return (jnp.transpose(hi, (1, 2, 0)), jnp.transpose(lo, (1, 2, 0)))


def _rot32_each(pair, r: int):
    """Rotate each 32-bit half of every 64-bit lane left by r (r < 32)."""
    if r == 0:
        return pair
    hi, lo = pair
    return ((hi << r) | (hi >> (32 - r)), (lo << r) | (lo >> (32 - r)))


def _remainder_packet(tail: jax.Array) -> jax.Array:
    """Build the final padded packet for a 0<r<32 byte tail: (n, r) -> (n, 32).

    Mirrors the scalar remainder rules (Load3/AllowReadBefore semantics of
    the published algorithm; cf. highwayhash.HighwayHash256._update_remainder).
    """
    n, r = tail.shape
    mod4 = r & 3
    base = r & ~3
    zeros = lambda w: jnp.zeros((n, w), dtype=jnp.uint8)
    if r & 16:
        return jnp.concatenate(
            [tail[:, :base], zeros(28 - base),
             tail[:, base + mod4 - 4:base + mod4]], axis=1)
    if mod4:
        b16 = tail[:, base][:, None]
        b17 = tail[:, base + (mod4 >> 1)][:, None]
        b18 = tail[:, base + mod4 - 1][:, None]
        return jnp.concatenate(
            [tail[:, :base], zeros(16 - base), b16, b17, b18, zeros(13)],
            axis=1)
    return jnp.concatenate([tail[:, :base], zeros(32 - base)], axis=1)


def _finalize(state):
    """10 permute rounds + modular reduction -> (n, 32) uint8 digests."""
    for _ in range(10):
        v0 = state["v0"]
        permuted_hi = jnp.stack([v0[1][2], v0[1][3], v0[1][0], v0[1][1]])
        permuted_lo = jnp.stack([v0[0][2], v0[0][3], v0[0][0], v0[0][1]])
        state = _update_packet(state, (permuted_hi, permuted_lo))

    v0, v1 = state["v0"], state["v1"]
    mul0, mul1 = state["mul0"], state["mul1"]

    def modred(a3, a2, a1, a0):
        a3 = _and64c(a3, 0x3FFFFFFFFFFFFFFF)
        m1 = _xor64(a1, _or64(_shl64(a3, 1), _shr64(a2, 63)))
        m1 = _xor64(m1, _or64(_shl64(a3, 2), _shr64(a2, 62)))
        m0 = _xor64(a0, _shl64(a2, 1))
        m0 = _xor64(m0, _shl64(a2, 2))
        return m1, m0

    def s(pair, i):
        return _lane(pair, i)

    m1a, m0a = modred(_add64(s(v1, 1), s(mul1, 1)), _add64(s(v1, 0), s(mul1, 0)),
                      _add64(s(v0, 1), s(mul0, 1)), _add64(s(v0, 0), s(mul0, 0)))
    m1b, m0b = modred(_add64(s(v1, 3), s(mul1, 3)), _add64(s(v1, 2), s(mul1, 2)),
                      _add64(s(v0, 3), s(mul0, 3)), _add64(s(v0, 2), s(mul0, 2)))

    words = []  # 8 little-endian u32 words -> 32 bytes
    for pair in (m0a, m1a, m0b, m1b):
        words.extend([pair[1], pair[0]])     # lo word first
    w = jnp.stack(words, axis=1)             # (n, 8) uint32
    shifts = jnp.arange(4, dtype=U32) * 8
    b = (w[..., None] >> shifts) & U32(0xFF)  # (n, 8, 4)
    return b.reshape(-1, 32).astype(jnp.uint8)


_SCAN_UNROLL = 8


def _scan_packets(state, hi: jax.Array, lo: jax.Array,
                  unroll: int = 1):
    """Advance the state over (P, 4, n) packet lanes with lax.scan."""
    p = hi.shape[0]
    main = (p // unroll) * unroll
    if main:
        xs = (hi[:main].reshape(-1, unroll, *hi.shape[1:]),
              lo[:main].reshape(-1, unroll, *lo.shape[1:]))

        def body(st, lane):
            for i in range(unroll):
                st = _update_packet(st, (lane[0][i], lane[1][i]))
            return st, None

        state, _ = jax.lax.scan(body, state, xs)
    for i in range(main, p):                  # static tail (< unroll)
        state = _update_packet(state, (hi[i], lo[i]))
    return state


def _hh256_impl(x: jax.Array, key: bytes,
                allow_pallas: bool = False) -> jax.Array:
    n, length = x.shape
    state = _init_state(n, key)
    n_packets = length // 32
    if n_packets:
        hi, lo = _bytes_to_lanes(
            x[:, :n_packets * 32].reshape(n, n_packets, 32))
        # Long streams on TPU can run the packet chain inside one Pallas
        # program (highwayhash_pallas.py — gated experiment); everything
        # else takes the portable scan (unrolled for long streams to
        # amortize the loop).
        kernel_done = False
        try:
            from . import highwayhash_pallas as hp
            if allow_pallas and hp.supported(n, n_packets):
                main = (n_packets // hp.PB) * hp.PB
                s_pad = (-n) % hp.SBLK
                hi_m, lo_m = hi[:main], lo[:main]
                if s_pad:
                    pad = ((0, 0), (0, 0), (0, s_pad))
                    hi_m = jnp.pad(hi_m, pad)
                    lo_m = jnp.pad(lo_m, pad)
                state = hp.bulk_state(hi_m, lo_m, key)
                if s_pad:
                    state = {k: (v[0][:, :n], v[1][:, :n])
                             for k, v in state.items()}
                if main < n_packets:
                    state = _scan_packets(state, hi[main:], lo[main:])
                kernel_done = True
        except Exception:  # noqa: BLE001 — fall back to the XLA path
            state = _init_state(n, key)
        if not kernel_done:
            u = _SCAN_UNROLL if n_packets >= 64 else 1
            state = _scan_packets(state, hi, lo, u)
    r = length % 32
    if r:
        tail = x[:, n_packets * 32:]
        packet = _remainder_packet(tail)
        rr = np.uint64(((r << 32) + r) & 0xFFFFFFFFFFFFFFFF)
        add = (jnp.full((4, n), np.uint32(rr >> np.uint64(32))),
               jnp.full((4, n), np.uint32(rr & np.uint64(0xFFFFFFFF))))
        state["v0"] = _add64(state["v0"], add)
        state["v1"] = _rot32_each(state["v1"], r)
        lanes = _bytes_to_lanes(packet[:, None, :])
        state = _update_packet(state, (lanes[0][0], lanes[1][0]))
    return _finalize(state)


@functools.lru_cache(maxsize=8)
def _jit_for_key(key: bytes, allow_pallas: bool):
    # allow_pallas is part of the cache key: the env flag is consulted at
    # trace time, so a program compiled one way must never be served for
    # the other setting.
    return jax.jit(functools.partial(_hh256_impl, key=key,
                                     allow_pallas=allow_pallas))


def hh256_batch_jax(blocks, key: bytes = MAGIC_KEY) -> jax.Array:
    """Hash N equal-length byte streams on device: (n, L) uint8 -> (n, 32).

    Bit-identical to the reference's magic-keyed HighwayHash256; any L
    (remainder rules included). One compiled program per (n, L) shape.
    """
    import os
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    allow_pallas = os.environ.get("MTPU_HH_PALLAS", "") == "1"
    return _jit_for_key(key, allow_pallas)(blocks)
