"""Recycling pool of page-aligned scratch buffers (internal/bpool role).

The reference keeps a capped pool of aligned byte slabs
(internal/bpool/bpool.go) so the O_DIRECT read/write path and the
erasure pipeline reuse scratch instead of allocating per request.  Ours
layers leases on the existing ShmArena (ops/shm_arena.py): one named
arena per process tree holds the slabs, a lease pins a page-aligned
uint8 view, and release returns the run for immediate reuse — the
anonymous-mmap-per-call pattern (storage/diskio._direct_read) and the
verify-sweep's whole-file bytearray both become recycled arena runs.

Lifetime discipline: leases are explicitly released (context manager
or .release()); a leaked lease is reclaimed by a weakref.finalize
backstop when its view dies, so a raising caller cannot wedge the
arena.  When the arena is momentarily full the pool degrades to a
plain page-aligned anonymous mmap (counted as a fallback) — callers
never block on scratch.

Knobs: MTPU_BPOOL=0 kills the pool (every get is a fallback
allocation — the no-pooling oracle); MTPU_BPOOL_MB sizes the arena
(default 32).  Stats feed the mtpu_bpool_* gauge family.
"""

from __future__ import annotations

import collections
import mmap
import os
import threading
import weakref

import numpy as np

from .shm_arena import ArenaFull, ShmArena

#: ShmArena slot granularity for scratch runs: O_DIRECT scratch is a
#: few hundred KiB (BULK-sized reads), verify sweeps lease frame
#: batches — 64 KiB slots keep waste low without bloating the bitmap.
_SLOT = 64 << 10

_POOL: "BufferPool | None" = None
_POOL_MU = threading.Lock()


def bpool_enabled() -> bool:
    return os.environ.get("MTPU_BPOOL", "1") != "0"


def bpool_bytes() -> int:
    try:
        mb = int(os.environ.get("MTPU_BPOOL_MB", "32"))
    except ValueError:
        mb = 32
    return max(1, mb) << 20


class Lease:
    """One pinned scratch run: `.view` is a page-aligned uint8 ndarray
    of exactly the requested length.  Release early; finalize is only
    the leak backstop.

    The backstop must never take the arena lock: finalizers run in GC
    context, and cyclic collection can fire while THIS thread already
    holds the arena's condition variable (a non-reentrant fork-shared
    lock).  So `backstop` is a lock-free deque append; the pool drains
    the queue on its next get()."""

    __slots__ = ("view", "_release", "_fin", "__weakref__")

    def __init__(self, view: np.ndarray, release,
                 backstop=None) -> None:
        self.view = view
        self._release = release
        self._fin = (weakref.finalize(self, backstop)
                     if backstop is not None else None)

    def release(self) -> None:
        if self._fin is not None:
            self._fin.detach()
            self._fin = None
        rel, self._release = self._release, None
        if rel is not None:
            rel()
        self.view = None

    def __enter__(self) -> np.ndarray:
        return self.view

    def __exit__(self, *exc) -> None:
        self.release()


class BufferPool:
    """Aligned-scratch lease pool over one ShmArena segment."""

    def __init__(self, total_bytes: int | None = None):
        # An explicit size means the caller wants THAT bound honoured,
        # so it gets a private segment; the default shares one named
        # segment per process tree (ShmArena.named ignores the size of
        # every caller after the first).
        if total_bytes is None:
            self.arena = ShmArena.named("bpool", bpool_bytes(),
                                        slot_bytes=_SLOT)
        else:
            self.arena = ShmArena(total_bytes, slot_bytes=_SLOT)
        self._mu = threading.Lock()
        #: (off, nbytes) runs whose lease died unreleased — freed on
        #: the next get() (see Lease docstring for why not in-place).
        self._leaked: collections.deque = collections.deque()
        self.gets = 0
        self.fallbacks = 0
        self.released = 0
        self.leak_reclaims = 0

    def _drain_leaked(self) -> None:
        dq = self._leaked
        while dq:
            try:
                off, n = dq.popleft()
            except IndexError:
                break
            self.arena.free(off, n)
            with self._mu:
                self.leak_reclaims += 1

    def get(self, nbytes: int) -> Lease:
        """Lease `nbytes` of page-aligned scratch.  Pool off or arena
        momentarily full -> private anonymous mmap (never blocks)."""
        nbytes = int(nbytes)
        self._drain_leaked()
        with self._mu:
            self.gets += 1
        if bpool_enabled() and nbytes <= self.arena.nslots * _SLOT:
            try:
                off = self.arena.alloc(nbytes, timeout=0)
            except ArenaFull:
                pass
            else:
                view = self.arena.view(off, nbytes)

                def _rel(arena=self.arena, off=off, n=nbytes,
                         pool=self):
                    arena.free(off, n)
                    with pool._mu:
                        pool.released += 1

                return Lease(view, _rel,
                             backstop=lambda dq=self._leaked,
                             off=off, n=nbytes: dq.append((off, n)))
        with self._mu:
            self.fallbacks += 1
        if nbytes == 0:
            return Lease(np.empty(0, dtype=np.uint8), None)
        mm = mmap.mmap(-1, nbytes)      # anonymous maps are page-aligned
        view = np.frombuffer(mm, dtype=np.uint8, count=nbytes)
        # the ndarray keeps `mm` alive through its base; nothing to free
        return Lease(view, None)

    def stats(self) -> dict:
        a = self.arena.stats()
        with self._mu:
            return {
                "gets": self.gets,
                "fallbacks": self.fallbacks,
                "released": self.released,
                "leak_reclaims": self.leak_reclaims,
                "pool_bytes": a["arena_bytes"],
                "in_use_bytes": a["in_use_bytes"],
                "high_water_bytes": a["high_water_bytes"],
            }


def default_pool() -> BufferPool:
    """Process-wide pool (created on first use; create before fork to
    share the segment across a worker pool)."""
    global _POOL
    with _POOL_MU:
        if _POOL is None:
            _POOL = BufferPool()
        return _POOL


def stats() -> dict | None:
    """Scrape-side stats: None when no pool was ever created (the
    metrics render must not force the segment into existence)."""
    with _POOL_MU:
        return None if _POOL is None else _POOL.stats()
