"""Startup self-test guards — production sanity checks, not just pytest.

The reference hard-fails server boot if the erasure codec or bitrot hash
produce unexpected bytes (erasureSelfTest golden-xxhash table,
/root/reference/cmd/erasure-coding.go:158; bitrotSelfTest golden chain,
/root/reference/cmd/bitrot.go:214). Same contract here: a corrupted
build/toolchain must refuse to serve rather than write bad shards.

Kept fast (~ms): a handful of geometry configs through the CPU codec +
one encode/reconstruct round trip + the HighwayHash golden chain.
"""

from __future__ import annotations

import hashlib


class SelfTestError(RuntimeError):
    pass


def erasure_self_test() -> None:
    import numpy as np

    from .erasure_cpu import ReedSolomonCPU

    rng = np.random.default_rng(0xEC)
    for (k, m) in ((2, 2), (4, 2), (8, 4), (12, 4)):
        data = rng.integers(0, 256, size=k * 64, dtype=np.uint8).tobytes()
        rs = ReedSolomonCPU(k, m)
        shards = rs.encode_data(data)
        # Knock out `m` shards, reconstruct, compare.
        gone = list(range(0, 2 * m, 2))[:m]
        partial = [None if i in gone else s for i, s in enumerate(shards)]
        rec = rs.reconstruct(partial)
        for i in gone:
            if not np.array_equal(rec[i], shards[i]):
                raise SelfTestError(f"erasure self-test EC:{k}+{m} "
                                    f"reconstruct mismatch row {i}")


# Golden chain from the published HighwayHash algorithm with the magic
# bitrot key: digest of b"" then iterated digest-of-digest, pinned at
# build time from the scalar implementation (itself validated against
# the reference's constants in tests/test_highwayhash.py).
_HH_CHAIN_SHA256 = \
    "48883e06e9e249f4681c369484fc12a4f5f6891fde90a1a7be5a33288d46f3f2"


def bitrot_self_test() -> None:
    from .highwayhash import HighwayHash256

    h = b""
    for _ in range(8):
        hh = HighwayHash256()
        hh.update(h)
        h = hh.digest()
    if hashlib.sha256(h).hexdigest() != _HH_CHAIN_SHA256:
        raise SelfTestError("bitrot (HighwayHash256) self-test mismatch")


# Golden chain for mxh256 (the default write algorithm, ops/mxhash.py):
# digest of b"" then iterated digest-of-digest, pinned at build time from
# the exact-integer numpy spec implementation.
_MXH_CHAIN_SHA256 = \
    "d6373d19d83d8c7d0a34aa26414e76ea7ba722c0b0895b23e971fa4912566bc7"


def mxhash_self_test() -> None:
    from .mxhash import mxh256

    h = b""
    for _ in range(8):
        h = mxh256(h)
    if hashlib.sha256(h).hexdigest() != _MXH_CHAIN_SHA256:
        raise SelfTestError("bitrot (mxh256) self-test mismatch")


def digest_self_test() -> None:
    """Validate EVERY compiled native digest path (not just the one
    runtime dispatch would pick) against hashlib before serving: a
    miscompiled SIMD body must refuse to boot, same contract as the
    erasure/bitrot golden tests.  Skips silently when the native lib is
    unavailable or disabled — the hashlib oracle needs no check."""
    from ..utils import digestlanes
    if not digestlanes.use_native():
        return
    from native import digest_native as dn

    # Sizes straddling every padding boundary (RFC 1321 / FIPS 180-4:
    # 55/56/57 one-vs-two pad blocks, 63/64/65 block edges).
    sizes = (0, 1, 55, 56, 57, 63, 64, 65, 1000)
    bufs = [bytes((i * 37 + j) % 256 for j in range(n))
            for i, n in enumerate(sizes)]
    for isa in dn.supported_md5_isas():
        got = dn.md5_batch(bufs, isa)
        want = [hashlib.md5(b).digest() for b in bufs]
        if got != want:
            raise SelfTestError(
                f"md5 self-test mismatch on {dn.MD5_ISA_NAMES[isa]}")
    for isa in dn.supported_sha_isas():
        got = dn.sha256_batch(bufs, isa)
        want = [hashlib.sha256(b).digest() for b in bufs]
        if got != want:
            raise SelfTestError(
                f"sha256 self-test mismatch on {dn.SHA_ISA_NAMES[isa]}")


def device_lane_self_test() -> None:
    """Encode+hash golden vectors on EVERY configured device lane before
    serving (PR 10 device sharding): a device whose compiled kernels or
    HBM produce wrong bytes must refuse to boot, named by index, rather
    than corrupt the slice of erasure sets affine to it.  Single-lane
    hosts run exactly one pass (the historical default-device check);
    skips silently when jax is unavailable."""
    import numpy as np

    from . import devices as devices_mod
    from .erasure_cpu import ReedSolomonCPU
    from .mxhash import mxh256

    if devices_mod.jax_device(0) is None:
        return
    from . import fused

    k, m, s = 2, 2, 128
    rng = np.random.default_rng(0xD0D)
    x = rng.integers(0, 256, size=(1, k, s), dtype=np.uint8)
    rs = ReedSolomonCPU(k, m)
    want_parity = np.stack(
        rs.encode([x[0, i] for i in range(k)])[k:], axis=0)
    rows = np.concatenate([x[0], want_parity], axis=0)
    want_digests = [mxh256(rows[i].tobytes()) for i in range(k + m)]
    for dev in range(devices_mod.n_devices()):
        try:
            parity, digests = fused.encode_and_hash(
                x, k, m, algo="mxh256", device=dev)
            parity = np.asarray(parity)[0]
            digests = np.asarray(digests)[:, 0]
        except Exception as e:  # noqa: BLE001 — name the device
            raise SelfTestError(
                f"device lane self-test dispatch failed on device "
                f"{dev}: {e}") from e
        if not np.array_equal(parity, want_parity):
            raise SelfTestError(
                f"device lane self-test encode mismatch on device {dev}")
        if [d.tobytes() for d in digests] != want_digests:
            raise SelfTestError(
                f"device lane self-test digest mismatch on device {dev}")


def metrics_registry_self_test() -> None:
    """Every exported metric family must carry a help string, live in
    the mtpu_ namespace, and appear in the README's Observability
    section — boot-time drift guard: a family added without docs
    refuses to serve.  The README may name families via brace groups
    (mtpu_api_last_minute_{p50,p99}) or trailing-* wildcards
    (mtpu_worker_*); an absent README (stripped install) skips the doc
    check, never the help/namespace check."""
    import re
    from pathlib import Path

    from ..observe.metrics import MetricsRegistry

    fams = MetricsRegistry().families()
    if not fams:
        raise SelfTestError("metrics registry exports no families")
    names = []
    for m in fams:
        if not getattr(m, "help", ""):
            raise SelfTestError(
                f"metric family {m.name} has no help string")
        if not m.name.startswith("mtpu_"):
            raise SelfTestError(
                f"metric family {m.name} outside the mtpu_ namespace")
        names.append(m.name)
    readme = Path(__file__).resolve().parents[2] / "README.md"
    try:
        text = readme.read_text(encoding="utf-8")
    except OSError:
        return
    documented: set[str] = set()
    prefixes: list[str] = []
    for tok in re.findall(r"mtpu_[\w{},*]+", text):
        if "{" in tok and "}" in tok:
            base, rest = tok.split("{", 1)
            inner, tail = rest.split("}", 1)
            for alt in inner.split(","):
                documented.add(base + alt + tail)
        elif tok.endswith("*"):
            prefixes.append(tok[:-1])
        else:
            documented.add(tok)
    missing = [n for n in names
               if n not in documented
               and not any(n.startswith(p) for p in prefixes)]
    if missing:
        raise SelfTestError(
            "metric families missing from the README metrics table: "
            + ", ".join(sorted(missing)))


def run_startup_self_tests() -> None:
    erasure_self_test()
    bitrot_self_test()
    mxhash_self_test()
    digest_self_test()
    device_lane_self_test()
    metrics_registry_self_test()
    # Fail boot on a misconfigured bitrot write algorithm (clear config
    # error now, not a confusing per-request failure later).
    from ..storage.bitrot_io import write_algo
    write_algo()
