"""Startup self-test guards — production sanity checks, not just pytest.

The reference hard-fails server boot if the erasure codec or bitrot hash
produce unexpected bytes (erasureSelfTest golden-xxhash table,
/root/reference/cmd/erasure-coding.go:158; bitrotSelfTest golden chain,
/root/reference/cmd/bitrot.go:214). Same contract here: a corrupted
build/toolchain must refuse to serve rather than write bad shards.

Kept fast (~ms): a handful of geometry configs through the CPU codec +
one encode/reconstruct round trip + the HighwayHash golden chain.
"""

from __future__ import annotations

import hashlib


class SelfTestError(RuntimeError):
    pass


def erasure_self_test() -> None:
    import numpy as np

    from .erasure_cpu import ReedSolomonCPU

    rng = np.random.default_rng(0xEC)
    for (k, m) in ((2, 2), (4, 2), (8, 4), (12, 4)):
        data = rng.integers(0, 256, size=k * 64, dtype=np.uint8).tobytes()
        rs = ReedSolomonCPU(k, m)
        shards = rs.encode_data(data)
        # Knock out `m` shards, reconstruct, compare.
        gone = list(range(0, 2 * m, 2))[:m]
        partial = [None if i in gone else s for i, s in enumerate(shards)]
        rec = rs.reconstruct(partial)
        for i in gone:
            if not np.array_equal(rec[i], shards[i]):
                raise SelfTestError(f"erasure self-test EC:{k}+{m} "
                                    f"reconstruct mismatch row {i}")


# Golden chain from the published HighwayHash algorithm with the magic
# bitrot key: digest of b"" then iterated digest-of-digest, pinned at
# build time from the scalar implementation (itself validated against
# the reference's constants in tests/test_highwayhash.py).
_HH_CHAIN_SHA256 = \
    "48883e06e9e249f4681c369484fc12a4f5f6891fde90a1a7be5a33288d46f3f2"


def bitrot_self_test() -> None:
    from .highwayhash import HighwayHash256

    h = b""
    for _ in range(8):
        hh = HighwayHash256()
        hh.update(h)
        h = hh.digest()
    if hashlib.sha256(h).hexdigest() != _HH_CHAIN_SHA256:
        raise SelfTestError("bitrot (HighwayHash256) self-test mismatch")


# Golden chain for mxh256 (the default write algorithm, ops/mxhash.py):
# digest of b"" then iterated digest-of-digest, pinned at build time from
# the exact-integer numpy spec implementation.
_MXH_CHAIN_SHA256 = \
    "d6373d19d83d8c7d0a34aa26414e76ea7ba722c0b0895b23e971fa4912566bc7"


def mxhash_self_test() -> None:
    from .mxhash import mxh256

    h = b""
    for _ in range(8):
        h = mxh256(h)
    if hashlib.sha256(h).hexdigest() != _MXH_CHAIN_SHA256:
        raise SelfTestError("bitrot (mxh256) self-test mismatch")


def digest_self_test() -> None:
    """Validate EVERY compiled native digest path (not just the one
    runtime dispatch would pick) against hashlib before serving: a
    miscompiled SIMD body must refuse to boot, same contract as the
    erasure/bitrot golden tests.  Skips silently when the native lib is
    unavailable or disabled — the hashlib oracle needs no check."""
    from ..utils import digestlanes
    if not digestlanes.use_native():
        return
    from native import digest_native as dn

    # Sizes straddling every padding boundary (RFC 1321 / FIPS 180-4:
    # 55/56/57 one-vs-two pad blocks, 63/64/65 block edges).
    sizes = (0, 1, 55, 56, 57, 63, 64, 65, 1000)
    bufs = [bytes((i * 37 + j) % 256 for j in range(n))
            for i, n in enumerate(sizes)]
    for isa in dn.supported_md5_isas():
        got = dn.md5_batch(bufs, isa)
        want = [hashlib.md5(b).digest() for b in bufs]
        if got != want:
            raise SelfTestError(
                f"md5 self-test mismatch on {dn.MD5_ISA_NAMES[isa]}")
    for isa in dn.supported_sha_isas():
        got = dn.sha256_batch(bufs, isa)
        want = [hashlib.sha256(b).digest() for b in bufs]
        if got != want:
            raise SelfTestError(
                f"sha256 self-test mismatch on {dn.SHA_ISA_NAMES[isa]}")


def run_startup_self_tests() -> None:
    erasure_self_test()
    bitrot_self_test()
    mxhash_self_test()
    digest_self_test()
    # Fail boot on a misconfigured bitrot write algorithm (clear config
    # error now, not a confusing per-request failure later).
    from ..storage.bitrot_io import write_algo
    write_algo()
