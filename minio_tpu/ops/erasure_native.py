"""Native (AVX-512/AVX2) GF(2^8) codec — the engine's host-path backend.

Role: SURVEY.md §7 hard-part #5 ("a TPU failure must degrade, not
corrupt") and the honest host-path e2e numbers: when the process has no
TPU — or the TPU is only reachable over a slow tunnel — the erasure
engine runs shard math through native/rs_cpu.cc, the same vpshufb
nibble-table technique as the reference's klauspost/reedsolomon assembly
(go.mod:41).  Tables come from the repo's own gf256, so bytes on disk
are identical to the device path's (differentially tested).

rs_encode applies an arbitrary (R, C) coefficient matrix, so the one
entry point covers encode (parity matrix), decode (inverted-submatrix
rows), and heal — exactly like the device kernel's transform seam.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


@functools.lru_cache(maxsize=4096)
def _tables_cached(mat_bytes: bytes, r: int, c: int) -> np.ndarray:
    """(R, C, 32) uint8 nibble tables [lo16 | hi16] for a GF matrix."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, c)
    mul = gf256.mul_table()                     # (256, 256) products
    v = np.arange(16, dtype=np.uint8)
    tabs = np.empty((r, c, 32), dtype=np.uint8)
    tabs[:, :, :16] = mul[mat][:, :, v]
    tabs[:, :, 16:] = mul[mat][:, :, v << 4]
    return np.ascontiguousarray(tabs)


def tables_for_matrix(gf_mat: np.ndarray) -> np.ndarray:
    gf_mat = np.ascontiguousarray(gf_mat, dtype=np.uint8)
    r, c = gf_mat.shape
    return _tables_cached(gf_mat.tobytes(), r, c)


@functools.lru_cache(maxsize=4096)
def transform_matrix(k: int, m: int, sources: tuple[int, ...],
                     targets: tuple[int, ...]) -> np.ndarray:
    """(T, K) GF byte matrix mapping `sources` rows -> `targets` rows
    (byte-level sibling of erasure_jax._transform_matrix_bits)."""
    full = gf256.build_matrix(k, k + m)
    inv = gf256.gf_mat_invert(full[list(sources)[:k], :])
    return gf256.gf_matmul(full[list(targets), :], inv)


def _apply(tabs: np.ndarray, x: np.ndarray, rows: int) -> np.ndarray:
    """(B, C, S) uint8 -> (B, rows, S) via native rs_encode per block.

    ctypes releases the GIL during each C call, so engine thread pools
    overlap these with drive I/O for free.
    """
    from native import rs_comparator
    lib = rs_comparator.load()
    x = np.ascontiguousarray(x, dtype=np.uint8)
    b, c, s = x.shape
    out = np.empty((b, rows, s), dtype=np.uint8)
    for i in range(b):
        lib.rs_encode(tabs.ctypes.data, x[i].ctypes.data,
                      out[i].ctypes.data, c, rows, s)
    return out


class ReedSolomonNative:
    """Drop-in for ReedSolomonTPU's encode/transform seam, on the host.

    Returns numpy arrays (already host-resident — callers that
    np.asarray() the device result get a no-op).
    """

    def __init__(self, data_shards: int, parity_shards: int):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards

    def encode_blocks(self, data: np.ndarray,
                      salt=None) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        if salt is not None:
            data = data ^ np.uint8(int(salt[0]) & 0xFF)
        tabs = tables_for_matrix(
            gf256.parity_matrix(self.data_shards, self.parity_shards))
        return _apply(tabs, data, self.parity_shards)

    def transform_blocks(self, shards: np.ndarray,
                         sources: tuple[int, ...],
                         targets: tuple[int, ...],
                         salt=None) -> np.ndarray:
        shards = np.asarray(shards, dtype=np.uint8)
        if salt is not None:
            shards = shards ^ np.uint8(int(salt[0]) & 0xFF)
        mat = transform_matrix(self.data_shards, self.parity_shards,
                               tuple(sources), tuple(targets))
        return _apply(tables_for_matrix(mat), shards, len(targets))
