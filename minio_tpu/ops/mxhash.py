"""mxh256: a TPU-native bitrot checksum built from exact integer matmuls.

Role: the device-fast bitrot algorithm in the registry
(storage/bitrot_io.py), the role HighwayHash256S plays in the reference
(/root/reference/cmd/bitrot.go:39).  HighwayHash's dependent 64-bit
multiply chain has no fast TPU lowering (measured ~1-2 GB/s on the VPU,
see ops/highwayhash_pallas.py); mxh256 is designed so the whole digest is
MXU work: bytes enter a matmul directly, with NO bit-plane unpack and NO
sequential dependency, so verify runs at erasure-codec speed.

Construction (spec, implemented twice: here in exact-integer numpy — the
golden reference — and traced for device in ops/mxhash_jax.py):

  - The message is zero-padded to a multiple of C=256 bytes and split
    into chunks; bytes are read as int8 (two's complement).
  - Each chunk is multiplied by a fixed pseudorandom matrix A of shape
    (256, 8) with ODD int8 entries, accumulating exactly in int32:
    |sum| <= 256*128*255 < 2^24, so the arithmetic is exact integer
    linear algebra — no modular reduction, no rounding, bit-identical on
    any backend.  The 8 int32 words are serialized little-endian into a
    32-byte chunk digest.
  - The (n_chunks * 32)-byte digest string is hashed again by the same
    rule, shrinking 8x per level, until one 32-byte digest remains
    (a static number of levels for a static input length).
  - The final digest is XORed with a 32-byte length tag
    SHA256(seed || len) — levels only see zero-padded content, the tag
    pins the exact byte length (kills zero-pad/length ambiguity).

Detection strength (bitrot = NON-adversarial media corruption, the same
threat model as the reference's fixed-key HighwayHash use):
  - any single corrupted byte is detected with certainty (A's entries are
    odd, hence nonzero: one byte's delta changes all 8 words);
  - a corruption confined to one chunk escapes only if its delta vector
    is an exact integer null vector of A^T — probability ~2^-56 over the
    pseudorandom A for a 2-byte error, astronomically less for bursts;
  - corruption spanning chunks must additionally collide through every
    higher level.
mxh256 is an error-detection code, not a cryptographic MAC.

Matrix/tag material derives from SHA-256 streams of fixed seeds, so the
function is a stable public spec with golden vectors (ops/selftest.py).
"""

from __future__ import annotations

import functools
import hashlib
import struct

import numpy as np

CHUNK = 256        # bytes hashed per matmul row
WORDS = 8          # int32 accumulators per chunk
DIGEST_SIZE = 4 * WORDS   # 32 bytes, same frame slot as HighwayHash256

_SEED_A = b"minio-tpu/mxh256/A/v1"
_SEED_LEN = b"minio-tpu/mxh256/len/v1"


def _sha_stream(seed: bytes, nbytes: int) -> bytes:
    out = bytearray()
    i = 0
    while len(out) < nbytes:
        out += hashlib.sha256(seed + struct.pack("<Q", i)).digest()
        i += 1
    return bytes(out[:nbytes])


@functools.lru_cache(maxsize=1)
def matrix_a() -> np.ndarray:
    """The fixed (CHUNK, WORDS) odd-int8 mixing matrix (spec constant)."""
    raw = np.frombuffer(_sha_stream(_SEED_A, CHUNK * WORDS), dtype=np.uint8)
    return (raw | 1).astype(np.int8).reshape(CHUNK, WORDS)


def length_tag(n: int) -> np.ndarray:
    """32-byte length tag XORed into the final digest."""
    d = hashlib.sha256(_SEED_LEN + struct.pack("<Q", n)).digest()
    return np.frombuffer(d, dtype=np.uint8)


def _level_np(rows: np.ndarray) -> np.ndarray:
    """One tree level: (n, L) uint8 -> (n, 32*ceil(L/256)) uint8."""
    n, ln = rows.shape
    pad = (-ln) % CHUNK
    if pad or ln == 0:
        rows = np.pad(rows, ((0, 0), (0, max(pad, CHUNK - ln))))
    chunks = rows.reshape(n, -1, CHUNK).view(np.int8)
    # Exact: int32 accumulation of int8 x int8 products.
    h = chunks.astype(np.int32) @ matrix_a().astype(np.int32)  # (n, nc, 8)
    return np.ascontiguousarray(h.astype("<i4")).view(np.uint8).reshape(n, -1)


def mxh256_batch(blocks: np.ndarray) -> np.ndarray:
    """(n, L) uint8 -> (n, 32) uint8 digests (the golden host path)."""
    blocks = np.ascontiguousarray(np.asarray(blocks, dtype=np.uint8))
    if blocks.ndim != 2:
        raise ValueError("mxh256_batch expects (n, L)")
    n, ln = blocks.shape
    cur = blocks
    while True:
        cur = _level_np(cur)
        if cur.shape[1] == DIGEST_SIZE:
            break
    return cur ^ length_tag(ln)[None, :]


def mxh256(data: bytes) -> bytes:
    """Digest of one byte string."""
    buf = np.frombuffer(data, dtype=np.uint8)[None, :]
    return mxh256_batch(np.ascontiguousarray(buf))[0].tobytes()
