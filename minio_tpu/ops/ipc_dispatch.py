"""Cross-process kernel dispatch: the remote face of ops/coalesce.py.

In the pre-fork worker pool (server/workers.py) every HTTP worker runs
the full parse/auth/digest/drive-IO vertical, but ONE process — the
device owner — holds JAX/native kernel state and runs the real
`DispatchCoalescer`.  This module is the wire between them:

  worker                          owner
  ------                          -----
  RemoteCoalescer.submit(key,     serve_owner(): pop descriptor,
    payload)                        map the arena slot zero-copy,
    -> write payload into a         rebuild the kernel FROM THE KEY
       ShmArena slot                (kernel_from_key — the coalescer
    -> push a 64B descriptor        contract says the key encodes
       on the request ring          every parameter the kernel closes
    -> return a RemoteHandle        over, which is what makes remote
                                    execution possible at all),
  RemoteHandle.result()             submit to the owner's LOCAL
    <- listener thread pops the     coalescer — cross-WORKER packing
       response descriptor,         happens there — then write the
       copies arrays out of the     result arrays into a response
       response slot, frees it      slot and push a descriptor on the
                                    worker's response ring.

Nothing larger than 64 bytes is ever pickled or queued; shard batches
move through the preallocated arena in place.

Fallback ladder (liveness beats packing, always):
  * arena full / ring full -> compute locally in the worker
    (`DATA_PATH.record_ipc_fallback`);
  * owner heartbeat stale -> fail every pending handle, route
    everything locally until the supervisor respawns the owner under a
    new generation (mirrors PR 5's dispatcher-death contract one level
    up);
  * any per-item owner error -> the handle raises and the engine's
    existing per-request direct fallback recomputes the span.

Routing policy (`MTPU_IPC_DISPATCH`):
  * ``auto`` (default) — only kernels that need the accelerator route
    remotely (single device owner); host-native kernels (ecio put_frame,
    AVX Reed-Solomon, host hashes) already release the GIL inside C and
    scale better N-way in the workers than funneled through one owner;
  * ``all``  — every coalescable kind routes remotely (differential
    tests exercise the full protocol on CPU-only hosts);
  * ``0``    — never (workers behave like MTPU_WORKERS=0 oracles with
    their own in-process coalescers).
"""

from __future__ import annotations

import itertools
import json
import os
import struct
import threading
import time

import numpy as np

from ..observe import span as ospan
from ..observe.metrics import DATA_PATH
from . import coalesce
from .shm_arena import ArenaFull

#: descriptor wire format (one ipc_ring record):
#: magic, worker_id, req_id, slot_off, total_len, hdr_len, status, gen,
#: device — the coalescer-lane index the submitting set is affine to
#: (PR 10), so the owner routes the arena slot to the right device lane
#: without parsing the JSON header.  48 bytes, still inside the 64-byte
#: ring record.
_DESC = struct.Struct("<IIQQQIiII")
_MAGIC = 0x4D545055            # "MTPU"

#: descriptor status codes
ST_REQ = 0                     # request (worker -> owner)
ST_OK = 0                      # response: slot holds hdr+arrays
ST_ERR = 1                     # response: slot holds {"error": ...}
ST_DROP = 2                    # response: no slot (owner overloaded)


def mode() -> str:
    v = os.environ.get("MTPU_IPC_DISPATCH", "auto").strip().lower()
    return v if v in ("auto", "all", "0") else "auto"


def alloc_timeout_s() -> float:
    try:
        return max(0.05,
                   float(os.environ.get("MTPU_IPC_ALLOC_TIMEOUT_S", "2")))
    except ValueError:
        return 2.0


def owner_stale_s() -> float:
    try:
        return max(0.2, float(os.environ.get("MTPU_OWNER_STALE_S", "2")))
    except ValueError:
        return 2.0


# -- kernel registry ----------------------------------------------------------
#
# The coalescer scheduling contract (ops/coalesce.py) requires that a
# key encodes EVERY parameter its kernel closes over — that is what
# lets unrelated requests share one dispatch.  Here it buys more: the
# owner process can rebuild the kernel from the key alone, so no
# callable ever crosses the process boundary.

_CODECS: dict[tuple, object] = {}
_CODEC_MU = threading.Lock()


def _owner_codec(tag: str, k: int, m: int):
    key = (tag, k, m)
    with _CODEC_MU:
        c = _CODECS.get(key)
        if c is not None:
            return c
    if tag == "dev":
        from .erasure import ReedSolomonTPU
        c = ReedSolomonTPU(k, m)
    else:
        try:
            from native import rs_comparator
            rs_comparator.load()
            from .erasure_native import ReedSolomonNative
            c = ReedSolomonNative(k, m)
        except Exception:  # noqa: BLE001 — no g++/ISA: portable codec
            from .erasure import ReedSolomonTPU
            c = ReedSolomonTPU(k, m)
    with _CODEC_MU:
        _CODECS.setdefault(key, c)
        return _CODECS[key]


def _pf_kernel(k: int, m: int, shard_size: int):
    """Owner-side mirror of ErasureSet._pf_kernel (fused host encode)."""
    from ..engine.erasure_set import _ecio_mod
    from ..storage import bitrot_io
    fused_host = _ecio_mod()
    frame_len = bitrot_io.digest_size("mxh256") + shard_size

    def kernel(stacked, spans, ctx):
        nb = stacked.shape[0]
        per = nb * frame_len
        buf = ctx.rent((k + m) * per)
        outs = [buf[i * per:(i + 1) * per] for i in range(k + m)]
        fused_host.put_frame(stacked, k, m, outs=outs)
        return [[o[lo * frame_len:hi * frame_len] for o in outs]
                for lo, hi in spans]

    return kernel


def _enc_kernel(tag: str, k: int, m: int, algo: str,
                device: int | None = None):
    """Owner-side mirror of ErasureSet._enc_kernel; the tag picks the
    backend the submitting worker would have used, `device` the lane
    the dispatch is placed on."""
    from ..engine.erasure_set import BATCH_BLOCKS
    from . import devices as devices_mod
    from . import fused

    if tag == "fd":
        def kernel(stacked, spans, ctx):
            x, n = coalesce.pad_batch(stacked, BATCH_BLOCKS)
            parity, digests = fused.encode_and_hash(x, k, m, algo=algo,
                                                    device=device)
            parity = np.asarray(parity)[:n]
            digests = np.asarray(digests)[:, :n]
            return [(parity[lo:hi], digests[:, lo:hi])
                    for lo, hi in spans]

        def launch(x, n, spans, ctx):
            # Pipeline form (lane-staged device input, sync deferred to
            # resolve) — same donation rule as the in-process kernel.
            parity_d, digests_d = fused.encode_and_hash(
                x, k, m, algo=algo, device=device, donate=True)

            def resolve():
                parity = np.asarray(parity_d)[:n]
                digests = np.asarray(digests_d)[:, :n]
                return [(parity[lo:hi], digests[:, lo:hi])
                        for lo, hi in spans]

            return resolve

        kernel.launch = launch
        kernel.pad_rows = BATCH_BLOCKS
        return kernel

    codec = _owner_codec(tag, k, m)
    if tag == "dev":
        def kernel(stacked, spans, ctx):
            x, n = coalesce.pad_batch(stacked, BATCH_BLOCKS)
            parity = np.asarray(codec.encode_blocks(
                devices_mod.put(x, device)))[:n]
            return [(parity[lo:hi], None) for lo, hi in spans]

        def launch(x, n, spans, ctx):
            parity_d = codec.encode_blocks(devices_mod.put(x, device))

            def resolve():
                parity = np.asarray(parity_d)[:n]
                return [(parity[lo:hi], None) for lo, hi in spans]

            return resolve

        kernel.launch = launch
        kernel.pad_rows = BATCH_BLOCKS
    else:
        def kernel(stacked, spans, ctx):
            parity = np.asarray(codec.encode_blocks(stacked))
            return [(parity[lo:hi], None) for lo, hi in spans]
    return kernel


def _vt_kernel(k: int, m: int, sources: tuple, targets: tuple, algo: str,
               device: int | None = None):
    """Owner-side mirror of ErasureSet._vt_kernel (fused verify/
    reconstruct)."""
    from ..engine.erasure_set import BATCH_BLOCKS
    from . import fused

    def kernel(stacked, spans, ctx):
        x, n = coalesce.pad_batch(stacked, BATCH_BLOCKS)
        digests, out = fused.verify_and_transform(
            x, k, m, sources, targets, algo=algo, device=device)
        digests = np.asarray(digests)[:n]
        out = np.asarray(out)[:n] if targets else None
        return [(digests[lo:hi], out[lo:hi] if out is not None else None)
                for lo, hi in spans]

    def launch(x, n, spans, ctx):
        digests_d, out_d = fused.verify_and_transform(
            x, k, m, sources, targets, algo=algo, device=device)

        def resolve():
            digests = np.asarray(digests_d)[:n]
            out = np.asarray(out_d)[:n] if targets else None
            return [(digests[lo:hi],
                     out[lo:hi] if out is not None else None)
                    for lo, hi in spans]

        return resolve

    kernel.launch = launch
    kernel.pad_rows = BATCH_BLOCKS
    return kernel


def kernel_from_key(key: tuple, device: int | None = None):
    """Rebuild the dispatch kernel for a coalescer key (placed on lane
    `device` for device-backed kinds).  Raises KeyError for kinds this
    registry does not know (the worker then keeps them local)."""
    kind = key[0]
    if kind == "digest":
        _, algo, _shard = key
        return coalesce.make_digest_kernel(algo)
    if kind == "pf":
        _, k, m, shard = key
        return _pf_kernel(int(k), int(m), int(shard))
    if kind == "enc":
        _, tag, k, m, algo, _shard = key
        return _enc_kernel(str(tag), int(k), int(m), str(algo),
                           device=device)
    if kind == "vt":
        _, k, m, sources, targets, algo, _shard = key
        return _vt_kernel(int(k), int(m), tuple(sources), tuple(targets),
                          str(algo), device=device)
    raise KeyError(f"no remote kernel for key kind {kind!r}")


def _key_to_json(key: tuple) -> list:
    return [list(e) if isinstance(e, (tuple, list)) else e for e in key]


def _key_from_json(items: list) -> tuple:
    return tuple(tuple(e) if isinstance(e, list) else e for e in items)


# -- result wire codec --------------------------------------------------------
#
# Results are (lists/tuples of) ndarrays; each kind flattens to an
# ordered list of optional arrays and rebuilds on the worker.

def _flatten_result(kind: str, res):
    if kind == "pf":                 # list of (k+m) equal-length 1-D rows
        return [np.stack([np.asarray(r) for r in res])]
    if kind == "digest":
        return [np.asarray(res)]
    a, b = res                       # enc: (parity, digests?) / vt: (dg, out?)
    return [np.asarray(a), None if b is None else np.asarray(b)]


def _rebuild_result(kind: str, arrays: list):
    if kind == "pf":
        return list(arrays[0])
    if kind == "digest":
        return arrays[0]
    return arrays[0], arrays[1]


def _encode_arrays(arrays: list) -> tuple[bytes, list[np.ndarray]]:
    """-> (header json bytes, arrays to copy after the header)."""
    meta = []
    payload = []
    for a in arrays:
        if a is None:
            meta.append(None)
            continue
        a = np.ascontiguousarray(a)
        meta.append({"shape": list(a.shape), "dtype": str(a.dtype)})
        payload.append(a)
    return json.dumps({"arrays": meta}).encode(), payload


def _decode_arrays(view: np.ndarray, hdr_len: int) -> list:
    meta = json.loads(bytes(view[:hdr_len]))["arrays"]
    out = []
    cur = int(hdr_len)
    for m in meta:
        if m is None:
            out.append(None)
            continue
        dt = np.dtype(m["dtype"])
        shape = tuple(m["shape"])
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nb = n * dt.itemsize
        # .copy(): the slot is freed as soon as decoding returns.
        out.append(view[cur:cur + nb].view(dt).reshape(shape).copy())
        cur += nb
    return out


# -- worker side --------------------------------------------------------------

class RemoteHandle:
    """Future for one remotely dispatched item — same surface the
    engine already consumes from coalesce.Handle.  Results are copies
    (the arena slot is freed by the listener), so release() has nothing
    pooled to give back."""

    __slots__ = ("_ev", "_res", "_exc", "_t_enq", "_t_done", "_kind",
                 "weight", "nrows")

    def __init__(self, kind: str, weight: int, nrows: int):
        self._ev = threading.Event()
        self._res = None
        self._exc: BaseException | None = None
        self._t_enq = time.monotonic()
        self._t_done: float | None = None
        self._kind = kind
        self.weight = weight
        self.nrows = nrows

    def result(self, timeout: float | None = 120.0):
        if not self._ev.wait(timeout):
            raise TimeoutError("remote dispatch did not complete")
        if self._t_done is not None:
            ospan.record("ipc.wait",
                         max(0.0, self._t_done - self._t_enq))
            self._t_done = None
        if self._exc is not None:
            raise self._exc
        return self._res

    def release(self) -> None:
        pass

    def _finish(self, res=None, exc: BaseException | None = None) -> None:
        self._res = res
        self._exc = exc
        self._t_done = time.monotonic()
        self._ev.set()


class RemoteCoalescer:
    """Worker-process front end: remote-eligible keys ship to the
    device owner; everything else (and every failure) runs on the
    worker's own in-process DispatchCoalescer, which stays the
    correctness oracle."""

    def __init__(self, plane, worker_id: int):
        self.plane = plane
        self.wid = int(worker_id)
        self.local = coalesce.DispatchCoalescer()
        self._mu = threading.Lock()
        self._pending: dict[int, RemoteHandle] = {}
        self._seq = itertools.count(1)
        self._listener: threading.Thread | None = None
        self._stopped = False
        #: owner generation this worker has observed dead (routes local
        #: until the supervisor brings up a NEW generation).
        self._dead_gen = -1
        self.remote_submits = 0
        self.remote_results = 0
        self.remote_errors = 0
        self.fallbacks = 0

    # engine-facing surface ---------------------------------------------------

    def submit(self, key: tuple, payload, fn, weight: int | None = None,
               device: int = 0):
        if not self._remote_eligible(key):
            return self.local.submit(key, payload, fn, weight,
                                     device=device)
        try:
            return self._submit_remote(key, payload, weight, device)
        except Exception:  # noqa: BLE001 — arena/ring full, owner gone
            with self._mu:
                self.fallbacks += 1
            DATA_PATH.record_ipc_fallback()
            return self.local.submit(key, payload, fn, weight,
                                     device=device)

    def hot(self, device: int | None = None) -> bool:
        # Remote routing means digest piggybacking still batches (on the
        # owner) even when this worker's local queues are idle.
        if self._remote_active() and mode() == "all":
            return True
        return self.local.hot(device)

    def note_read(self, delta: int, device: int = 0) -> None:
        self.local.note_read(delta, device=device)

    def lane_stats(self) -> dict:
        return self.local.lane_stats()

    def stats(self) -> dict:
        st = self.local.stats()
        with self._mu:
            st.update({
                "remote_submits": self.remote_submits,
                "remote_results": self.remote_results,
                "remote_errors": self.remote_errors,
                "remote_fallbacks": self.fallbacks,
                "remote_pending": len(self._pending),
                "remote_active": self._remote_active(),
            })
        return st

    def close(self) -> None:
        self._stopped = True
        self._fail_pending(RuntimeError("remote coalescer closed"))
        self.local.close()

    # internals ---------------------------------------------------------------

    def _remote_active(self) -> bool:
        if self.plane is None or mode() == "0":
            return False
        gen = self.plane.owner_gen()
        return self.plane.owner_ok() and gen != self._dead_gen

    def _remote_eligible(self, key: tuple) -> bool:
        m = mode()
        if m == "0" or not self._remote_active():
            return False
        if m == "all":
            return True
        # auto: only accelerator-bound kernels funnel to the single
        # device owner; host-native kernels drop the GIL in C and scale
        # N-way in the workers themselves.
        kind = key[0]
        if kind == "enc":
            return key[1] in ("fd", "dev")
        if kind in ("vt", "digest"):
            return self._device_backend()
        return False

    @staticmethod
    def _device_backend() -> bool:
        from ..engine import erasure_set as es
        if es._USE_DEVICE is None:
            try:
                import jax
                es._USE_DEVICE = jax.default_backend() == "tpu"
            except Exception:  # noqa: BLE001 — no jax: host only
                es._USE_DEVICE = False
        return bool(es._USE_DEVICE)

    def _submit_remote(self, key: tuple, payload, weight,
                       device: int = 0) -> RemoteHandle:
        payload = np.ascontiguousarray(payload)
        nrows = int(payload.shape[0]) if payload.ndim else 1
        hdr = json.dumps({
            "key": _key_to_json(key),
            "shape": list(payload.shape),
            "dtype": str(payload.dtype),
            "w": int(weight) if weight is not None else nrows,
        }).encode()
        total = len(hdr) + payload.nbytes
        arena = self.plane.arena
        off = arena.alloc(total, timeout=alloc_timeout_s())  # ArenaFull ->
        try:                                                 # caller falls back
            view = arena.view(off, total)
            view[:len(hdr)] = np.frombuffer(hdr, dtype=np.uint8)
            if payload.nbytes:
                view[len(hdr):] = payload.reshape(-1).view(np.uint8)
            h = RemoteHandle(key[0],
                             int(weight) if weight is not None else nrows,
                             nrows)
            req = next(self._seq)
            with self._mu:
                if self._stopped:
                    raise RuntimeError("remote coalescer closed")
                self._pending[req] = h
                self.remote_submits += 1
            rec = _DESC.pack(_MAGIC, self.wid, req, off, total, len(hdr),
                             ST_REQ, self.plane.owner_gen() & 0xFFFFFFFF,
                             int(device) & 0xFFFFFFFF)
            if not self.plane.req_ring.put(rec, timeout=1.0):
                with self._mu:
                    self._pending.pop(req, None)
                raise ArenaFull("request ring full")
        except BaseException:
            arena.free(off, total)
            raise
        self._ensure_listener()
        DATA_PATH.record_ipc_submit(nrows)
        return h

    def _ensure_listener(self) -> None:
        if self._listener is None or not self._listener.is_alive():
            with self._mu:
                if self._listener is None or not self._listener.is_alive():
                    self._listener = threading.Thread(
                        target=self._listen, name="mtpu-ipc-listen",
                        daemon=True)
                    self._listener.start()

    def _listen(self) -> None:
        ring = self.plane.resp_rings[self.wid]
        while not self._stopped:
            rec = ring.get(timeout=0.5)
            if rec is None:
                self._check_owner()
                continue
            try:
                (_, _, req, off, total, hlen, status,
                 _gen, _dev) = _DESC.unpack(rec[:_DESC.size])
            except struct.error:
                continue
            with self._mu:
                h = self._pending.pop(req, None)
            try:
                if h is None:
                    # Stale response for a predecessor of this worker
                    # slot — just return the arena space.
                    continue
                if status == ST_OK:
                    arrays = _decode_arrays(
                        self.plane.arena.view(off, total), hlen)
                    h._finish(res=_rebuild_result(h._kind, arrays))
                    with self._mu:
                        self.remote_results += 1
                    DATA_PATH.record_ipc_result()
                elif status == ST_ERR:
                    msg = "owner dispatch failed"
                    try:
                        msg = json.loads(bytes(
                            self.plane.arena.view(off, total)[:hlen])
                        ).get("error", msg)
                    except Exception:  # noqa: BLE001 — torn header
                        pass
                    h._finish(exc=RuntimeError(msg))
                    with self._mu:
                        self.remote_errors += 1
                else:                  # ST_DROP: no response slot
                    h._finish(exc=RuntimeError(
                        "owner overloaded (no response slot)"))
                    with self._mu:
                        self.remote_errors += 1
            except Exception as e:  # noqa: BLE001 — decode fault
                if h is not None:
                    h._finish(exc=e)
            finally:
                if total and status != ST_DROP:
                    self.plane.arena.free(off, total)

    def _check_owner(self) -> None:
        """Owner-death watchdog: a stale heartbeat fails every pending
        handle NOW (their engine callers fall back to direct compute)
        and pins routing local until a fresh owner generation appears."""
        if self.plane is None or self.plane.owner_ok():
            return
        gen = self.plane.owner_gen()
        if gen == self._dead_gen:
            return
        self._dead_gen = gen
        self._fail_pending(RuntimeError("device owner died"))
        DATA_PATH.record_ipc_owner_death()

    def _fail_pending(self, exc: BaseException) -> None:
        with self._mu:
            victims = list(self._pending.values())
            self._pending.clear()
        for h in victims:
            h._finish(exc=exc)


# -- owner side ---------------------------------------------------------------

def owner_threads() -> int:
    try:
        return max(2, int(os.environ.get("MTPU_IPC_OWNER_THREADS", "4")))
    except ValueError:
        return 4


def serve_owner(plane, stop, co=None, nthreads: int | None = None) -> list:
    """Run the owner service: a small pool of reader threads, each
    popping request descriptors and carrying one item through
    submit -> result -> respond.  Multiple readers are what lets the
    owner's LOCAL coalescer pack items from different WORKERS into one
    kernel launch.  Returns the thread list; `stop` is a
    threading.Event the caller sets to retire the service."""
    co = co or coalesce.get()
    threads = []
    for i in range(nthreads or owner_threads()):
        t = threading.Thread(target=_owner_loop, args=(plane, stop, co),
                             name=f"mtpu-ipc-owner-{i}", daemon=True)
        t.start()
        threads.append(t)
    return threads


def _owner_loop(plane, stop, co) -> None:
    while not stop.is_set():
        rec = plane.req_ring.get(timeout=0.25)
        if rec is None:
            continue
        try:
            _serve_one(plane, co, rec)
        except Exception:  # noqa: BLE001 — never kill the service loop
            pass


def _serve_one(plane, co, rec: bytes) -> None:
    try:
        (magic, wid, req, off, total, hlen, _status,
         _gen, dev) = _DESC.unpack(rec[:_DESC.size])
    except struct.error:
        return
    if magic != _MAGIC:
        return
    kind = ""
    try:
        view = plane.arena.view(off, total)
        meta = json.loads(bytes(view[:hlen]))
        key = _key_from_json(meta["key"])
        kind = key[0]
        shape = tuple(meta["shape"])
        dt = np.dtype(meta["dtype"])
        payload = view[hlen:].view(dt).reshape(shape)
        # Route to the lane the submitting set is affine to: the owner
        # packs cross-WORKER traffic per DEVICE, not into one queue.
        fn = kernel_from_key(key, device=dev)
        h = co.submit(key, payload, fn, weight=meta.get("w"),
                      device=dev)
        res = h.result(timeout=120.0)
        arrays = _flatten_result(kind, res)
        hdr, copies = _encode_arrays(arrays)
    except Exception as e:  # noqa: BLE001 — report, don't die
        plane.arena.free(off, total)
        _respond_error(plane, wid, req, e)
        return
    try:
        _respond_ok(plane, wid, req, hdr, copies, freeing=(off, total))
    finally:
        # Release only after the response bytes were copied out — pf
        # results alias the dispatch's pooled scratch buffer.
        h.release()


def _respond_ok(plane, wid, req, hdr: bytes, arrays: list[np.ndarray],
                freeing: tuple) -> None:
    rtotal = len(hdr) + sum(a.nbytes for a in arrays)
    try:
        roff = plane.arena.alloc(rtotal, timeout=2.0)
    except ArenaFull:
        plane.arena.free(*freeing)
        _push_resp(plane, wid,
                   _DESC.pack(_MAGIC, wid, req, 0, 0, 0, ST_DROP, 0, 0))
        return
    view = plane.arena.view(roff, rtotal)
    view[:len(hdr)] = np.frombuffer(hdr, dtype=np.uint8)
    cur = len(hdr)
    for a in arrays:
        if a.nbytes:
            view[cur:cur + a.nbytes] = a.reshape(-1).view(np.uint8)
        cur += a.nbytes
    # The request slot is only reusable once the result no longer
    # aliases pooled dispatch buffers — everything above was copied.
    plane.arena.free(*freeing)
    rec = _DESC.pack(_MAGIC, wid, req, roff, rtotal, len(hdr), ST_OK,
                     0, 0)
    if not _push_resp(plane, wid, rec):
        plane.arena.free(roff, rtotal)


def _respond_error(plane, wid, req, exc: BaseException) -> None:
    hdr = json.dumps({"error": f"{type(exc).__name__}: {exc}"[:400]}).encode()
    try:
        roff = plane.arena.alloc(len(hdr), timeout=1.0)
    except ArenaFull:
        _push_resp(plane, wid,
                   _DESC.pack(_MAGIC, wid, req, 0, 0, 0, ST_DROP, 0, 0))
        return
    view = plane.arena.view(roff, len(hdr))
    view[:] = np.frombuffer(hdr, dtype=np.uint8)
    rec = _DESC.pack(_MAGIC, wid, req, roff, len(hdr), len(hdr), ST_ERR,
                     0, 0)
    if not _push_resp(plane, wid, rec):
        plane.arena.free(roff, len(hdr))


def _push_resp(plane, wid: int, rec: bytes) -> bool:
    try:
        return plane.resp_rings[wid].put(rec, timeout=2.0)
    except Exception:  # noqa: BLE001 — ring torn down mid-shutdown
        return False
