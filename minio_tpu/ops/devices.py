"""Device discovery + deterministic erasure-set → device affinity.

The reference spreads objects across erasure sets with sipHashMod
(cmd/erasure-server-pool.go, mirrored by `engine/sets.py:set_for`).
This module pushes the SAME deterministic index one layer down, to the
accelerator plane:

    device = set_index % n_devices()

so kernel-lane placement needs no coordination protocol: it is stable
across boots, identical in every process of the pre-fork pool (all of
them derive it from the deployment-id-keyed sipHashMod), and trivially
rebalances when the device count changes — exactly the properties the
set placement already has.

Env:

- MTPU_DEVICES=N — lane count override, clamped to the visible device
  topology.  `=1` is the byte-identical single-lane oracle the
  differential tests diff against.  Unset, the count defaults to every
  visible device on a real TPU mesh and 1 on host backends, so CPU CI
  opts into multi-lane explicitly (simulated mesh via
  XLA_FLAGS=--xla_force_host_platform_device_count=8 + MTPU_DEVICES=8).

The env var is read per call so tests can flip lane counts without
re-importing; only the (static per-process) jax device topology is
cached.
"""

from __future__ import annotations

import os

_VISIBLE: tuple[list, str] | None = None


def _visible() -> tuple[list, str]:
    """(devices, backend) — cached; device topology is fixed per
    process.  Import of jax is deferred to first use so import-light
    processes (the pre-fork supervisor) never pay for it."""
    global _VISIBLE
    if _VISIBLE is None:
        try:
            import jax

            _VISIBLE = (list(jax.devices()), jax.default_backend())
        except Exception:  # noqa: BLE001 — no jax → single host lane
            _VISIBLE = ([], "none")
    return _VISIBLE


def visible_count() -> int:
    return max(1, len(_visible()[0]))


def n_devices() -> int:
    """Number of kernel lanes (= devices) the coalescer shards over."""
    v = os.environ.get("MTPU_DEVICES", "").strip()
    if v:
        try:
            n = int(v)
        except ValueError:
            n = 1
        return max(1, min(n, visible_count()))
    devs, backend = _visible()
    if backend == "tpu" and len(devs) > 1:
        return len(devs)
    return 1


def device_for_set(set_index: int) -> int:
    """Lane affinity of an erasure set: same modulo-of-deterministic-
    index scheme as its sipHashMod placement, one layer down."""
    return int(set_index) % n_devices()


def jax_device(idx: int):
    """The jax Device lane `idx` dispatches on (None when jax is
    unavailable).  Indices wrap over the visible topology so a lane
    index is always placeable."""
    devs, _ = _visible()
    if not devs:
        return None
    return devs[int(idx) % len(devs)]


def put(x, device_idx: int | None):
    """Commit `x` onto lane `device_idx`'s device via jax.device_put;
    identity when placement is unavailable or unrequested.  A committed
    input makes every downstream jit execution follow it to that
    device — the whole of 'explicit device placement' for the fused
    kernels."""
    if device_idx is None:
        return x
    dev = jax_device(device_idx)
    if dev is None:
        return x
    import jax

    if isinstance(x, jax.Array):
        # Already device-resident (lane staging upload / devcache):
        # the crossing was counted where it happened.
        return x
    from . import devcache

    devcache.note_h2d(int(getattr(x, "nbytes", 0) or 0), device_idx)
    return jax.device_put(x, dev)


def _reset_after_fork() -> None:
    # A forked child may land on a different backend (workers re-import
    # jax post-fork); drop the cached topology.
    global _VISIBLE
    _VISIBLE = None


os.register_at_fork(after_in_child=_reset_after_fork)
