"""Pre-fork shared-memory arenas for the multi-process dispatch plane.

The worker pool (server/workers.py) moves shard batches between HTTP
worker processes and the device-owner process.  Pickling a 16 MiB
payload through a multiprocessing queue would copy it at least twice
and serialize both ends on the pickler; instead the supervisor
preallocates ONE anonymous shared mapping before forking (``mmap(-1)``
is ``MAP_SHARED | MAP_ANONYMOUS`` — inherited by every child, no
files, no resource-tracker bookkeeping) and the processes exchange
only tiny ``(offset, nbytes)`` descriptors over the IPC ring
(ops/ipc_ring.py).  Workers write shard bytes straight into an arena
slot; the owner maps the same bytes as a numpy view and hands them to
the coalescer zero-copy; results come back through the arena the same
way.

Allocation is a first-fit run of fixed-size slots under one
cross-process lock — the arena sees a few thousand allocations per
second at most (one per shard *batch*, not per byte), so a bitmap scan
is entirely off the hot path.  When the arena is full, ``alloc``
BLOCKS (bounded) — that is the backpressure contract the worker tests
pin: a flood of writers slows down instead of corrupting or
deadlocking, and a caller that cannot get a slot within its budget
falls back to computing locally.

Stats (occupancy, high-water, waits, timeouts) live in the shared
header so ANY process — each worker's /metrics endpoint — can export
them without an RPC.
"""

from __future__ import annotations

import mmap
import multiprocessing
import os
import threading
import time

import numpy as np

#: shared header: i64[8] = in_use_bytes, high_water_bytes, allocs,
#: frees, waits, timeouts, slot_bytes, nslots
_HDR_SLOTS = 8
_HDR_BYTES = _HDR_SLOTS * 8

#: process-local registry of named arenas (see ShmArena.named): the
#: mapping itself is anonymous, so "named" reuse means "same instance
#: within this process tree" — create before fork and every child
#: inherits the one segment under the same name.
_NAMED: dict[str, "ShmArena"] = {}
_NAMED_MU = threading.Lock()


def default_arena_bytes() -> int:
    try:
        mb = int(os.environ.get("MTPU_SHM_ARENA_MB", "256"))
    except ValueError:
        mb = 256
    return max(8, mb) << 20


class ArenaFull(RuntimeError):
    """alloc() exhausted its wait budget — the caller should degrade
    to local/inline work, not die."""


class ShmArena:
    """Slot arena over one anonymous shared mapping.

    Create BEFORE fork; every inheriting process calls alloc/free/view
    on its inherited copy — all state that matters (header, bitmap,
    slot bytes) lives inside the mapping, and the allocator lock is a
    fork-inherited ``multiprocessing`` primitive.
    """

    def __init__(self, total_bytes: int | None = None,
                 slot_bytes: int = 1 << 20):
        if total_bytes is None:
            total_bytes = default_arena_bytes()
        self.slot_bytes = int(slot_bytes)
        self.nslots = max(1, int(total_bytes) // self.slot_bytes)
        # layout: [header][bitmap nslots bytes][refcounts int32]
        #         [pending-free int32][slots]
        # Refcounts/pending live per RUN HEAD: retain() pins an
        # allocation against free() — an evicting writer (the hot
        # cache) cannot reuse slots a reader is still copying out of;
        # the free is deferred and performed by the last release().
        self._ref_off = _HDR_BYTES + self.nslots
        self._pend_off = self._ref_off + self.nslots * 4
        # Page-align the data region: slot sizes are powers of two, so
        # every slot start is then page-aligned too — a requirement for
        # O_DIRECT readv into pooled scratch (ops/bpool.py).
        self._data_off = -(-(self._pend_off + self.nslots * 4)
                           // mmap.PAGESIZE) * mmap.PAGESIZE
        self._mm = mmap.mmap(-1, self._data_off
                             + self.nslots * self.slot_bytes)
        self._hdr = np.frombuffer(self._mm, dtype=np.int64,
                                  count=_HDR_SLOTS)
        self._bitmap = np.frombuffer(self._mm, dtype=np.uint8,
                                     count=self.nslots, offset=_HDR_BYTES)
        self._refs = np.frombuffer(self._mm, dtype=np.int32,
                                   count=self.nslots,
                                   offset=self._ref_off)
        self._pend = np.frombuffer(self._mm, dtype=np.int32,
                                   count=self.nslots,
                                   offset=self._pend_off)
        self._hdr[6] = self.slot_bytes
        self._hdr[7] = self.nslots
        ctx = multiprocessing.get_context("fork")
        self._cv = ctx.Condition(ctx.Lock())

    @classmethod
    def named(cls, name: str, total_bytes: int | None = None,
              slot_bytes: int = 1 << 20) -> "ShmArena":
        """One arena per name per process tree: the first caller
        creates the segment, later callers (and, after fork, children
        that inherited the module state) get the SAME instance — so
        independent subsystems can agree on a shared segment without
        passing the object through every constructor."""
        with _NAMED_MU:
            a = _NAMED.get(name)
            if a is None:
                a = cls(total_bytes, slot_bytes)
                _NAMED[name] = a
            return a

    # -- allocation ----------------------------------------------------------

    def _find_run_locked(self, want: int) -> int:
        """First run of `want` free slots, or -1."""
        bm = self._bitmap
        run = 0
        for i in range(self.nslots):
            if bm[i]:
                run = 0
            else:
                run += 1
                if run == want:
                    return i - want + 1
        return -1

    def alloc(self, nbytes: int, timeout: float | None = 5.0) -> int:
        """Reserve `nbytes` of contiguous arena space; returns the byte
        offset (pass it to view()/free()).  Blocks while the arena is
        full, up to `timeout` — then raises ArenaFull (backpressure,
        then degrade; never deadlock)."""
        want = max(1, -(-int(nbytes) // self.slot_bytes))
        if want > self.nslots:
            raise ArenaFull(
                f"request {nbytes}B exceeds arena "
                f"({self.nslots * self.slot_bytes}B)")
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cv:
            first = self._find_run_locked(want)
            waited = False
            while first < 0:
                waited = True
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    self._hdr[5] += 1       # timeouts
                    raise ArenaFull(
                        f"arena full ({want} slot(s) wanted)")
                self._cv.wait(timeout=(0.25 if left is None
                                       else min(left, 0.25)))
                first = self._find_run_locked(want)
            self._bitmap[first:first + want] = 1
            self._hdr[0] += want * self.slot_bytes
            if self._hdr[0] > self._hdr[1]:
                self._hdr[1] = self._hdr[0]
            self._hdr[2] += 1
            if waited:
                self._hdr[4] += 1
        return self._data_off + first * self.slot_bytes

    def _free_locked(self, first: int, want: int) -> None:
        self._bitmap[first:first + want] = 0
        self._hdr[0] -= want * self.slot_bytes
        self._hdr[3] += 1
        self._cv.notify_all()

    def free(self, offset: int, nbytes: int) -> None:
        """Release an allocation.  If a reader still holds a retain()
        on it, the free is DEFERRED: the slots stay marked in-use until
        the last release() performs the actual bitmap clear (so the
        reader's view never gets reused under it)."""
        first = (int(offset) - self._data_off) // self.slot_bytes
        want = max(1, -(-int(nbytes) // self.slot_bytes))
        with self._cv:
            if self._refs[first] > 0:
                self._pend[first] = want
                return
            self._free_locked(first, want)

    # -- per-entry refcounts (in-flight reader protection) -------------------

    def retain(self, offset: int) -> None:
        """Pin an allocation against free(): the caller may copy bytes
        out of view() without holding any higher-level lock."""
        first = (int(offset) - self._data_off) // self.slot_bytes
        with self._cv:
            self._refs[first] += 1

    def release(self, offset: int) -> None:
        """Drop a retain(); the last release performs any free() that
        was deferred while the allocation was pinned."""
        first = (int(offset) - self._data_off) // self.slot_bytes
        with self._cv:
            if self._refs[first] > 0:
                self._refs[first] -= 1
            if self._refs[first] == 0 and self._pend[first]:
                want = int(self._pend[first])
                self._pend[first] = 0
                self._free_locked(first, want)

    def view(self, offset: int, nbytes: int) -> np.ndarray:
        """uint8 view of an allocated range — zero-copy in every
        process that inherited the mapping."""
        return np.frombuffer(self._mm, dtype=np.uint8,
                             count=int(nbytes), offset=int(offset))

    def reset(self) -> None:
        """Drop every allocation (supervisor-only: called between
        owner generations when no worker holds a live slot)."""
        with self._cv:
            self._bitmap[:] = 0
            self._refs[:] = 0
            self._pend[:] = 0
            self._hdr[0] = 0
            self._cv.notify_all()

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        h = self._hdr
        return {
            "arena_bytes": self.nslots * self.slot_bytes,
            "in_use_bytes": int(h[0]),
            "high_water_bytes": int(h[1]),
            "allocs": int(h[2]),
            "frees": int(h[3]),
            "alloc_waits": int(h[4]),
            "alloc_timeouts": int(h[5]),
        }
