"""CPU-reference Reed-Solomon codec (numpy) — the correctness oracle.

Byte-for-byte compatible with the reference's codec
(klauspost/reedsolomon behind /root/reference/cmd/erasure-coding.go): same
field, same systematic Vandermonde coding matrix, same Split padding rules.
Validated against the reference's startup self-test golden xxhash table
(/root/reference/cmd/erasure-coding.go:169) in tests/test_erasure_golden.py.

This module is also the fallback codec when no TPU is available, and the
oracle that the JAX/Pallas device codecs are differential-tested against.
"""

from __future__ import annotations

import numpy as np

from . import gf256


class ReedSolomonCPU:
    """Systematic RS(data, parity) codec over GF(2^8).

    Shards are numpy uint8 arrays of equal length. Mirrors the narrow seam of
    the reference's `Erasure` struct (Split/Encode/Reconstruct), cf.
    /root/reference/cmd/erasure-coding.go:35.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("data and parity shard counts must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("data+parity must be <= 256")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_matrix(data_shards, self.total_shards)
        self.parity_rows = self.matrix[data_shards:, :]

    # -- Split ----------------------------------------------------------------

    def split(self, data: bytes | np.ndarray) -> list[np.ndarray]:
        """Split a byte buffer into data_shards equal shards, zero-padded.

        per_shard = ceil(len/data_shards), matching klauspost Split as used by
        EncodeData (/root/reference/cmd/erasure-coding.go:81).
        """
        buf = np.frombuffer(bytes(data), dtype=np.uint8) if not isinstance(
            data, np.ndarray) else data.astype(np.uint8, copy=False).ravel()
        if buf.size == 0:
            raise ValueError("cannot split empty buffer")
        per_shard = -(-buf.size // self.data_shards)
        padded = np.zeros(per_shard * self.data_shards, dtype=np.uint8)
        padded[:buf.size] = buf
        return [padded[i * per_shard:(i + 1) * per_shard]
                for i in range(self.data_shards)]

    # -- Encode ---------------------------------------------------------------

    def encode(self, data_shards_list: list[np.ndarray]) -> list[np.ndarray]:
        """Compute parity shards; returns full shard list [data..., parity...]."""
        assert len(data_shards_list) == self.data_shards
        d = np.stack([np.asarray(s, dtype=np.uint8) for s in data_shards_list])
        parity = gf256.gf_matmul(self.parity_rows, d)
        return list(d) + [parity[i] for i in range(self.parity_shards)]

    def encode_data(self, data: bytes | np.ndarray) -> list[np.ndarray]:
        """Split + encode in one call (reference EncodeData)."""
        return self.encode(self.split(data))

    # -- Verify ---------------------------------------------------------------

    def verify(self, shards: list[np.ndarray]) -> bool:
        d = np.stack(shards[:self.data_shards])
        expect = gf256.gf_matmul(self.parity_rows, d)
        got = np.stack(shards[self.data_shards:])
        return bool(np.array_equal(expect, got))

    # -- Reconstruct ----------------------------------------------------------

    def _decode_matrix_for(self, available: list[int]) -> np.ndarray:
        """Inverse of the coding-matrix rows for the first data_shards
        available shards; maps those shards back to the original data."""
        rows = available[:self.data_shards]
        sub = self.matrix[rows, :]
        return gf256.gf_mat_invert(sub)

    def reconstruct(self, shards: list[np.ndarray | None],
                    data_only: bool = False) -> list[np.ndarray]:
        """Return a new full shard list with missing (None/empty) entries
        recomputed; the input list is not mutated.

        Mirrors klauspost Reconstruct/ReconstructData as driven by
        DecodeDataBlocks (/root/reference/cmd/erasure-coding.go:96).
        """
        if len(shards) != self.total_shards:
            raise ValueError("wrong number of shards")
        # Normalize: accept bytes or uint8 arrays; None/empty means missing.
        shards = [None if s is None else
                  (np.frombuffer(s, dtype=np.uint8) if isinstance(s, (bytes, bytearray))
                   else np.asarray(s, dtype=np.uint8))
                  for s in shards]
        available = [i for i, s in enumerate(shards) if s is not None and s.size > 0]
        if len(available) < self.data_shards:
            raise ValueError("too few shards to reconstruct")
        sizes = {shards[i].size for i in available}
        if len(sizes) != 1:
            raise ValueError(f"available shards have unequal sizes: {sorted(sizes)}")
        if len(available) == self.total_shards:
            return list(shards)  # nothing to do

        use = available[:self.data_shards]
        sub_shards = np.stack([shards[i] for i in use])
        dec = self._decode_matrix_for(available)
        # Recover the original data shards.
        data = gf256.gf_matmul(dec, sub_shards)

        out: list[np.ndarray] = []
        for i in range(self.total_shards):
            s = shards[i]
            if s is not None and s.size > 0:
                out.append(s)
            elif i < self.data_shards:
                out.append(data[i].copy())
            elif data_only:
                out.append(np.zeros(0, dtype=np.uint8))
            else:
                row = self.matrix[i][None, :]
                out.append(gf256.gf_matmul(row, data)[0])
        return out

    def reconstruct_data(self, shards: list[np.ndarray | None]) -> list[np.ndarray]:
        return self.reconstruct(shards, data_only=True)

    # -- Geometry (reference ShardSize/ShardFileSize math) --------------------

    @staticmethod
    def ceil_frac(num: int, den: int) -> int:
        return -(-num // den)

    def shard_size(self, block_size: int) -> int:
        """ceil(block_size / data_shards) — cf. erasure-coding.go:122."""
        return self.ceil_frac(block_size, self.data_shards)

    def shard_file_size(self, total_length: int, block_size: int) -> int:
        """Size of one shard file for an object of total_length bytes
        erasure-coded in block_size blocks — cf. erasure-coding.go:127."""
        if total_length == 0:
            return 0
        if total_length < 0:
            return -1
        num_blocks = total_length // block_size
        last = total_length % block_size
        return (num_blocks * self.shard_size(block_size)
                + self.ceil_frac(last, self.data_shards))

    def shard_file_offset(self, start_offset: int, length: int,
                          total_length: int, block_size: int) -> int:
        """Effective end offset within a shard file for a ranged read —
        cf. erasure-coding.go:141."""
        shard_size = self.shard_size(block_size)
        shard_file_size = self.shard_file_size(total_length, block_size)
        end_block = (start_offset + length) // block_size
        till = (end_block + 1) * shard_size
        return min(till, shard_file_size)
