"""Fused bitrot-verify + erasure-transform: one dispatch, one HBM pass.

North-star config #5 (BASELINE.json): the reference verifies each shard
block's bitrot hash at read time (cmd/bitrot-streaming.go:142) and then
reconstructs missing shards with a separate SIMD pass
(cmd/erasure-decode.go:206). Here both run as ONE jitted device program
over the same (B, K, S) shard batch:

  - digests: the per-shard-block bitrot digest of every input row —
    mxh256 (MXU int8 matmuls, ops/mxhash_jax.py) or HighwayHash256
    (VPU scan, ops/highwayhash_jax.py) depending on the object's
    recorded algorithm,
  - targets: the GF(2^8) bit-plane matmul on the MXU reconstructing the
    requested rows.

XLA schedules the hash and the erasure matmul from the same HBM-resident
input, so the shard bytes cross HBM once instead of twice. The host
compares the 32-byte digests against the frame hashes (tiny) and decides
quorum / spare-read policy exactly like the unfused path.

Also provides the PUT-side fusion: encode parity AND hash all k+m shard
rows in one dispatch (the streaming-bitrot writer analogue,
cmd/bitrot-streaming.go:35).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..observe import span as ospan
from . import devcache
from . import devices as devices_mod
from . import erasure_jax, erasure_pallas
from .highwayhash import MAGIC_KEY
from .highwayhash_jax import _hh256_impl
from .mxhash_jax import mxh256_rows

# Algorithms with a device digest kernel (usable in the fused paths).
DEVICE_ALGOS = ("mxh256", "highwayhash256S", "highwayhash256")


def _traced_dispatch(name: str, fn, x, device: int | None = None):
    """Run a jitted kernel call; inside a traced request the span covers
    dispatch AND device completion (block_until_ready), so the trace
    attributes real device time (tagged with the lane's device index
    when the dispatch is placed). Untraced calls stay fully async —
    callers sync via np.asarray exactly as before."""
    if not ospan.active():
        return fn(x)
    with ospan.span(name) as sp:
        if device is not None:
            sp.tag(device=int(device))
        out = fn(x)
        jax.block_until_ready(out)
        return out


def _placed(x, device: int | None):
    """Commit the input batch to lane `device`'s jax device (PR 10
    erasure-set affinity): jit executions follow a committed input, so
    this one device_put is the whole placement story for every fused
    kernel. `device=None` keeps the historical default-device path.

    Inputs that are ALREADY jax arrays (a coalescer lane's pipelined
    staging upload, a devcache-resident batch) pass straight through —
    they crossed the boundary once when they were placed, and the h2d
    ledger counted them there; re-placing would both double the tunnel
    crossing and double the count."""
    if isinstance(x, jax.Array):
        return x
    nbytes = int(getattr(x, "nbytes", 0) or 0)
    if device is None:
        devcache.note_h2d(nbytes)
        return jnp.asarray(x, dtype=jnp.uint8)
    dev = devices_mod.jax_device(device)
    if dev is None:
        devcache.note_h2d(nbytes)
        return jnp.asarray(x, dtype=jnp.uint8)
    devcache.note_h2d(nbytes, device)
    return jax.device_put(jnp.asarray(x, dtype=jnp.uint8), dev)


def donate_ok() -> bool:
    """Input-buffer donation is only a win (and only warning-free) on
    accelerator backends where XLA actually reuses the device
    allocation; the host-CPU backend ignores donations with a warning
    per dispatch, so gate it off there."""
    return devices_mod._visible()[1] in ("tpu", "gpu")


def _digest_rows(x2d: jax.Array, algo: str, key: bytes) -> jax.Array:
    """(n, S) uint8 -> (n, 32) digests with the algo's device kernel."""
    if algo == "mxh256":
        return mxh256_rows(x2d)
    if algo in ("highwayhash256S", "highwayhash256"):
        return _hh256_impl(x2d, key)
    raise ValueError(f"no device kernel for bitrot algo {algo!r}")


@functools.lru_cache(maxsize=16)
def _hash_rows2d_jit(algo: str, key: bytes):
    @jax.jit
    def fn(x):  # (N, S) uint8
        return _digest_rows(x, algo, key)
    return fn


def hash_rows_async(x, algo: str, key: bytes = MAGIC_KEY):
    """(N, S) rows -> (N, 32) digests as an UNSYNCED jax array — the
    coalescer lanes' pipelined digest form (the caller resolves via
    np.asarray one dispatch later).  `x` may already be device-resident
    (counted at its placement site)."""
    if not isinstance(x, jax.Array):
        devcache.note_h2d(int(getattr(x, "nbytes", 0) or 0))
        x = jnp.asarray(x, dtype=jnp.uint8)
    return _hash_rows2d_jit(algo, key)(x)


@functools.lru_cache(maxsize=16)
def _hash_rows_jit(algo: str, key: bytes):
    @jax.jit
    def fn(x):  # (B, K, S) uint8
        b, kk, s = x.shape
        return _digest_rows(x.reshape(b * kk, s), algo, key).reshape(
            b, kk, 32)
    return fn


@functools.lru_cache(maxsize=512)
def _verify_transform_jit(k: int, m: int, sources: tuple[int, ...],
                          targets: tuple[int, ...], algo: str, key: bytes):
    mat = jnp.asarray(
        erasure_jax._transform_matrix_bits(k, m, sources, targets),
        dtype=jnp.bfloat16)
    rows = len(targets)

    @jax.jit
    def fn(x):  # x: (B, K, S) uint8 — rows in `sources` order
        b, kk, s = x.shape
        digests = _digest_rows(x.reshape(b * kk, s), algo, key).reshape(
            b, kk, 32)
        out = erasure_pallas.gf_matmul_blocks(mat, x, rows)
        return digests, out

    return fn


def verify_and_transform(x, k: int, m: int, sources: tuple[int, ...],
                         targets: tuple[int, ...],
                         algo: str = "highwayhash256S",
                         key: bytes = MAGIC_KEY,
                         device: int | None = None):
    """((B, K, S) shard rows) -> ((B, K, 32) digests, (B, T, S) rebuilt rows).

    Digests are of the INPUT rows (callers compare them against the bitrot
    frame hashes); rebuilt rows are the GF transform sources->targets.
    With no targets (nothing missing) only the hash runs.  `device` is
    the coalescer-lane index the dispatch is placed on (None = default
    device, the pre-sharding behavior).
    """
    x = _placed(x, device)
    if not targets:
        return _traced_dispatch("device.verify",
                                _hash_rows_jit(algo, key), x,
                                device=device), None
    fn = _verify_transform_jit(k, m, tuple(sources), tuple(targets),
                               algo, key)
    return _traced_dispatch("device.verify_transform", fn, x,
                            device=device)


@functools.lru_cache(maxsize=64)
def _encode_hash_jit(k: int, m: int, algo: str, key: bytes,
                     donate: bool = False):
    mat = jnp.asarray(erasure_jax._encode_matrix_bits(k, m),
                      dtype=jnp.bfloat16)

    def fn(x):  # x: (B, K, S) uint8 data shards
        b, kk, s = x.shape
        parity = erasure_pallas.gf_matmul_blocks(mat, x, m)
        full = jnp.concatenate([x, parity], axis=1)       # (B, K+M, S)
        digests = _digest_rows(
            full.transpose(1, 0, 2).reshape((kk + m) * b, s),
            algo, key).reshape(kk + m, b, 32)
        return parity, digests

    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def encode_and_hash(x, k: int, m: int, algo: str = "highwayhash256S",
                    key: bytes = MAGIC_KEY,
                    device: int | None = None,
                    donate: bool = False):
    """((B, K, S) data) -> ((B, M, S) parity, (K+M, B, 32) digests).

    The PUT hot path: parity AND per-shard-block bitrot digests in one
    device dispatch; framing on the host is then pure byte interleaving.
    Digest layout is shard-major to match frame_shards_batch's
    (n_shards, n_blocks) order.  `device` places the dispatch on that
    coalescer lane's device (None = default device).  `donate=True`
    hands the placed input buffer to XLA for reuse — legal because the
    encode input is placement-owned (nothing retains it after the
    dispatch; the devcache only ever retains VERIFY inputs), and only
    honored on accelerator backends (donate_ok)."""
    x = _placed(x, device)
    return _traced_dispatch(
        "device.encode_hash",
        _encode_hash_jit(k, m, algo, key,
                         donate=bool(donate) and donate_ok()), x,
        device=device)
