"""Cross-request dispatch coalescing for the erasure/bitrot data plane.

PRs 1-3 made each *individual* request's kernel traffic batched, but
every dispatch still belongs to exactly one request: N concurrent 1 MiB
PUTs cost N small `encode_and_hash` launches instead of one large one,
and dispatch overhead dominates exactly where the accelerator should
shine.  This module applies the insight behind continuous batching in
inference serving (Orca-style iteration-level scheduling) to object
storage: a dispatcher thread drains per-kernel queues that all
in-flight requests submit to, packs compatible work items into ONE
batched kernel call, and scatters the per-item slices back through
futures.

Since PR 10 the scheduler is sharded per device: `DispatchCoalescer`
is a facade over one `DispatchLane` per visible device (lane count =
`ops/devices.n_devices()`), and every submit carries the device index
its erasure set is affine to (`set_index % n_devices` — the sipHashMod
placement scheme one layer down).  Each lane owns one device, runs its
own dispatcher thread, packs cross-set batches that map to ITS device,
and keeps its own stats block — per-lane occupancy EMAs never pollute
another lane's adaptive-window decision, and concurrent PUTs against
sets on different devices launch kernels concurrently instead of
serializing behind one queue.  The default single-lane configuration
(CPU hosts, MTPU_DEVICES=1) is byte-for-byte the pre-sharding
scheduler.

Scheduling contract (per lane):

- items are compatible when they share a key `(kind, k, m, algo,
  shard_size, ...)` — same kernel, same geometry, so their block axes
  simply concatenate;
- the dispatcher always serves the key whose HEAD item is oldest
  (FIFO across requests — no request is starved because another key is
  busier), and never skips a head item because it is large: an item
  bigger than the batch budget dispatches alone;
- adaptive window: when recent traffic shows no concurrency
  (occupancy EMA ~1) a lone item fires immediately — a single-client
  request never waits.  Under load the dispatcher holds the head item
  up to MTPU_COALESCE_WINDOW_US for company, and the serialization of
  dispatches itself does most of the packing: arrivals during an
  in-flight kernel call land in the next batch for free;
- bounded-queue backpressure: submit() blocks while the total queued
  weight exceeds a small multiple of the batch budget, so a flood of
  writers cannot buffer unbounded shard batches in memory.

Env (read per call so tests flip them without re-importing):

- MTPU_COALESCE=0 disables coalescing — the direct-dispatch oracle the
  equivalence tests diff against;
- MTPU_COALESCE_WINDOW_US: max time the oldest queued item waits for
  company once the window engages (default 250);
- MTPU_COALESCE_MAX_BATCH: batch budget in 1 MiB-block weight units
  (default 64 — two full per-request encode batches per dispatch);
- MTPU_DEVICES: lane count (see ops/devices.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from ..observe import span as ospan
from ..observe.metrics import DATA_PATH
from . import devcache


def enabled() -> bool:
    return os.environ.get("MTPU_COALESCE", "1") != "0"


def window_s() -> float:
    try:
        us = float(os.environ.get("MTPU_COALESCE_WINDOW_US", "250"))
    except ValueError:
        us = 250.0
    return max(0.0, us) / 1e6


def max_batch() -> int:
    try:
        return max(1, int(os.environ.get("MTPU_COALESCE_MAX_BATCH", "64")))
    except ValueError:
        return 64


def pad_batch(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Zero-pad axis 0 up to the next multiple so jit'd device kernels
    see a bounded set of shapes (32, 64, ...) instead of one compile per
    coalesced batch size.  Returns (padded, original_n)."""
    n = x.shape[0]
    pad = (-n) % multiple
    if not pad:
        return x, n
    return np.concatenate(
        [x, np.zeros((pad,) + x.shape[1:], dtype=x.dtype)]), n


class _BufPool:
    """Free-list of uint8 scratch buffers for kernels whose OUTPUT is
    large (the fused host put_frame writes ~2x the data size of framed
    shards): a fresh mmap-threshold allocation per dispatch pays
    ~0.5 ms/MiB in page faults, so released dispatch buffers are reused
    — the cross-request analogue of ecio_native's per-thread arena,
    which the coalescer cannot use because results outlive the
    dispatcher thread's next call."""

    KEEP = 4

    def __init__(self):
        self._mu = threading.Lock()
        self._bufs: list[np.ndarray] = []

    def rent(self, nbytes: int) -> np.ndarray:
        with self._mu:
            for i, b in enumerate(self._bufs):
                if b.size >= nbytes:
                    return self._bufs.pop(i)
        return np.empty(nbytes, dtype=np.uint8)

    def give(self, buf: np.ndarray) -> None:
        with self._mu:
            self._bufs.append(buf)
            if len(self._bufs) > self.KEEP:
                self._bufs.sort(key=lambda b: b.size)
                self._bufs.pop(0)       # drop the smallest


class DispatchCtx:
    """Per-dispatch context handed to kernels.  `rent()` borrows a
    pooled scratch buffer that is returned to the pool once every item
    of the dispatch has been release()d by its consumer (refcounted —
    an unreleased handle just forfeits reuse, never corrupts)."""

    __slots__ = ("_pool", "_mu", "_refs", "buf")

    def __init__(self, pool: _BufPool, nitems: int):
        self._pool = pool
        self._mu = threading.Lock()
        self._refs = nitems
        self.buf = None

    def rent(self, nbytes: int) -> np.ndarray:
        self.buf = self._pool.rent(nbytes)
        return self.buf

    def _deref(self) -> None:
        with self._mu:
            self._refs -= 1
            done = self._refs == 0
        if done and self.buf is not None:
            self._pool.give(self.buf)
            self.buf = None


class Handle:
    """Future for one submitted work item.  `result()` blocks until the
    dispatcher resolved the item (and bridges the measured queue wait
    into the caller's span tree as the `coalesce.wait` stage);
    `release()` tells the buffer pool the caller is done with any
    pooled views this result aliases."""

    __slots__ = ("_ev", "_res", "_exc", "_t_enq", "_t_disp", "_ctx",
                 "weight", "nrows")

    def __init__(self, weight: int, nrows: int):
        self._ev = threading.Event()
        self._res = None
        self._exc: BaseException | None = None
        self._t_enq = time.monotonic()
        self._t_disp: float | None = None
        self._ctx: DispatchCtx | None = None
        self.weight = weight
        self.nrows = nrows

    def result(self, timeout: float | None = 120.0):
        if not self._ev.wait(timeout):
            raise TimeoutError("coalesced dispatch did not complete")
        if self._t_disp is not None:
            ospan.record("coalesce.wait",
                         max(0.0, self._t_disp - self._t_enq))
            self._t_disp = None
        if self._exc is not None:
            raise self._exc
        return self._res

    def release(self) -> None:
        ctx, self._ctx = self._ctx, None
        if ctx is not None:
            ctx._deref()


class DispatchLane:
    """One device's scheduler: per-key FIFO queues + one daemon
    dispatcher thread (started lazily on first queued submit).  All
    state — queues, occupancy EMA, buffer pool, lifetime stats — is
    lane-private, so one device's traffic never skews another lane's
    adaptive-window decision."""

    #: queued-weight cap as a multiple of the batch budget — beyond
    #: this, submit() blocks (backpressure) instead of buffering.
    QUEUE_FACTOR = 4

    def __init__(self, device: int = 0):
        self.device = int(device)
        self._mu = threading.Lock()
        self._work = threading.Condition(self._mu)
        self._space = threading.Condition(self._mu)
        self._queues: dict[tuple, deque] = {}
        self._fns: dict[tuple, object] = {}
        self._pending_weight = 0
        self._pending_items = 0
        self._dispatching = False
        self._inline = 0
        self._inflight_reads = 0
        # Occupancy EMA drives the adaptive window: ~1.0 means lone
        # requests (fire immediately), >1 means concurrent traffic is
        # actually packing (waiting the window pays for itself).
        self._ema = 1.0
        self._thread: threading.Thread | None = None
        self._stopped = False
        # Set (to the fatal exception) if the dispatcher thread ever
        # dies: queued handles are failed and every later submit runs
        # inline on the caller — degraded to direct dispatch, but no
        # submitter can hang on a scheduler that no longer exists.
        self._broken: BaseException | None = None
        self._bufs = _BufPool()
        # H2D pipeline state (ISSUE 17: pinned staging + double-buffered
        # uploads).  Two page-aligned bpool staging leases alternate per
        # dispatch; `_pending` holds at most ONE launched-but-unresolved
        # batch: while its kernel executes on-device, the next batch
        # packs into the spare staging buffer and ships via async
        # device_put — host pack/scatter overlapped with device compute.
        # Lane-thread-private except for the stats counters.
        self._staging: list = [None, None]
        self._staging_flip = 0
        self._pending: tuple | None = None
        # Lifetime stats (mirrored into DATA_PATH per dispatch).
        self.dispatches = 0
        self.items = 0
        self.weight = 0
        self.wait_s = 0.0
        self.max_items = 0
        self.batch_faults = 0
        self.member_retries = 0
        self.h2d_bytes = 0
        self.h2d_dispatches = 0
        self.pipeline_dispatches = 0
        self.pack_s = 0.0
        self.h2d_s = 0.0
        self.resolve_s = 0.0
        self.overlap_s = 0.0

    # -- submission ----------------------------------------------------------

    def submit(self, key: tuple, payload: np.ndarray, fn,
               weight: int | None = None) -> Handle:
        """Queue one work item.  `payload` is the item's batch (axis 0
        is the concat axis); `fn(stacked, spans, ctx)` computes the
        whole coalesced batch and returns one result per (lo, hi) span;
        `weight` is the item's cost in budget units (default: axis-0
        length).  All submitters of a key MUST pass an equivalent fn —
        the key encodes every parameter the kernel closes over."""
        payload = np.asarray(payload)
        nrows = int(payload.shape[0]) if payload.ndim else 1
        h = Handle(int(weight) if weight is not None else nrows, nrows)
        cap = self.QUEUE_FACTOR * max_batch()
        with self._mu:
            if self._stopped:
                raise RuntimeError("coalescer closed")
            # Idle fast path: nothing queued, nothing in flight, no
            # recent packing — run the dispatch on THIS thread (direct
            # semantics: a lone request pays zero handoff latency, the
            # measured ~25% single-client PUT tax of waking a scheduler
            # thread per batch on a 1-core host).  A concurrent submit
            # observes `_inline` and queues instead, so the moment two
            # requests overlap, packing begins.
            inline = (self._broken is not None
                      or (not self._pending_items and not self._dispatching
                          and self._inline == 0 and self._ema <= 1.05))
            if inline:
                self._inline += 1
            else:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._loop,
                        name=f"mtpu-coalesce-d{self.device}",
                        daemon=True)
                    self._thread.start()
                # Backpressure: an item never waits on its OWN weight
                # (a single oversized item must always be admissible).
                while self._pending_weight and \
                        self._pending_weight + h.weight > cap:
                    self._space.wait(0.05)
                    cap = self.QUEUE_FACTOR * max_batch()
                q = self._queues.get(key)
                if q is None:
                    q = self._queues[key] = deque()
                self._fns[key] = fn
                q.append((payload, h))
                self._pending_weight += h.weight
                self._pending_items += 1
                self._work.notify()
        if inline:
            try:
                self._dispatch([(payload, h)], h.weight, fn)
            finally:
                with self._mu:
                    self._inline -= 1
        return h

    # -- routing signals -----------------------------------------------------

    def hot(self) -> bool:
        """Whether routing MORE work through this lane is likely to
        batch (vs. adding a thread handoff to a lone request): work is
        queued or dispatching right now, recent dispatches packed >1
        item, or >1 read is concurrently in flight."""
        return (self._pending_items > 0 or self._dispatching
                or self._inline > 0 or self._ema > 1.05
                or self._inflight_reads > 1)

    def note_read(self, delta: int) -> None:
        """Healthy-GET concurrency signal (GET-only storms never queue
        encode work, so queue depth alone cannot ignite hot())."""
        with self._mu:
            self._inflight_reads += delta

    # -- dispatcher ----------------------------------------------------------

    def _queue_weight(self, q: deque) -> int:
        return sum(h.weight for _, h in q)

    def _pick_key(self):
        oldest_key, oldest_t = None, None
        for key, q in self._queues.items():
            if q and (oldest_t is None or q[0][1]._t_enq < oldest_t):
                oldest_key, oldest_t = key, q[0][1]._t_enq
        return oldest_key

    def _loop(self) -> None:
        try:
            while True:
                do_drain = False
                with self._mu:
                    key = self._pick_key()
                    while key is None:
                        if self._pending is not None:
                            # A launched batch is in flight.  Give new
                            # work one window to arrive (so its pack
                            # overlaps the executing kernel), then
                            # resolve — NEVER park indefinitely on
                            # `_work` with an unresolved launch: its
                            # waiters would deadlock against an idle
                            # queue.
                            self._work.wait(window_s() or 0.0005)
                            key = self._pick_key()
                            if key is None:
                                do_drain = True
                            break
                        if self._stopped:
                            return
                        self._work.wait()
                        key = self._pick_key()
                    if not do_drain:
                        q = self._queues[key]
                        budget = max_batch()
                        # Adaptive window: only wait for company when
                        # the occupancy EMA says concurrent traffic
                        # exists; always bounded by the oldest item's
                        # age.  With a launch in flight the kernel IS
                        # the company — skip the wait and pack now.
                        if (self._pending is None and self._ema > 1.05
                                and self._queue_weight(q) < budget):
                            deadline = q[0][1]._t_enq + window_s()
                            while (self._queue_weight(q) < budget
                                   and not self._stopped):
                                left = deadline - time.monotonic()
                                if left <= 0:
                                    break
                                self._work.wait(left)
                        items: list[tuple] = []
                        w = 0
                        while q and (not items
                                     or w + q[0][1].weight <= budget):
                            payload, h = q.popleft()
                            items.append((payload, h))
                            w += h.weight
                        self._pending_weight -= w
                        self._pending_items -= len(items)
                        fn = self._fns[key]
                        self._dispatching = True
                        self._space.notify_all()
                if do_drain:
                    self._drain_pipeline()
                else:
                    self._dispatch(items, w, fn, pipelined=True)
                with self._mu:
                    # Stay "dispatching" while a launch is unresolved so
                    # the inline fast path cannot race a pending batch.
                    self._dispatching = self._pending is not None
        except BaseException as e:  # noqa: BLE001 — scheduler death
            # _dispatch contains kernel faults itself, so anything
            # escaping here is scheduler logic dying — fail everything
            # queued rather than leaving submitters parked on handles
            # no thread will ever resolve.
            self._abort(e)

    def _abort(self, exc: BaseException) -> None:
        """Dispatcher death: error every queued handle, route all future
        submits inline (direct-dispatch degradation — correctness and
        liveness over packing)."""
        with self._mu:
            self._broken = exc
            victims: list[Handle] = []
            pending, self._pending = self._pending, None
            if pending is not None:
                victims.extend(h for _, h in pending[1])
            for q in self._queues.values():
                victims.extend(h for _, h in q)
                q.clear()
            self._queues.clear()
            self._fns.clear()
            self._pending_weight = 0
            self._pending_items = 0
            self._dispatching = False
            self._space.notify_all()
            self._work.notify_all()
        err = RuntimeError(f"coalescer dispatcher died: {exc!r}")
        for h in victims:
            h._exc = err
            h._ev.set()

    def _dispatch(self, items: list[tuple], w: int, fn,
                  pipelined: bool = False) -> None:
        if pipelined:
            launch = getattr(fn, "launch", None)
            if launch is not None and devcache.h2d_pipeline_enabled():
                if self._dispatch_pipelined(items, w, fn, launch):
                    return
            # Serial dispatch from the lane thread must not outrun a
            # still-pending launch (per-key FIFO): resolve it first.
            if self._pending is not None:
                self._drain_pipeline()
        t_disp = time.monotonic()
        ctx = DispatchCtx(self._bufs, len(items))
        try:
            if len(items) == 1:
                stacked = items[0][0]
            else:
                stacked = np.concatenate([p for p, _ in items], axis=0)
            spans = []
            lo = 0
            for _, h in items:
                spans.append((lo, lo + h.nrows))
                lo += h.nrows
            results = fn(stacked, spans, ctx)
        except BaseException as e:  # noqa: BLE001 — contain the fault
            if ctx.buf is not None:
                self._bufs.give(ctx.buf)
                ctx.buf = None
            with self._mu:
                self.batch_faults += 1
            if len(items) == 1:
                h = items[0][1]
                h._t_disp = t_disp
                h._exc = e
                h._ev.set()
                DATA_PATH.record_co_fault(0)
                return
            # Fault containment: a packed batch carries spans from
            # UNRELATED requests — one poisoned member must not fail
            # its neighbors.  Retry each span as its own dispatch; only
            # the member(s) that still fail get the exception.
            DATA_PATH.record_co_fault(len(items))
            for payload, h in items:
                mctx = DispatchCtx(self._bufs, 1)
                try:
                    res = fn(payload, [(0, h.nrows)], mctx)[0]
                except BaseException as me:  # noqa: BLE001 — guilty span
                    if mctx.buf is not None:
                        self._bufs.give(mctx.buf)
                        mctx.buf = None
                    h._exc = me
                else:
                    h._ctx = mctx
                    h._res = res
                with self._mu:
                    self.member_retries += 1
                h._t_disp = t_disp
                h._ev.set()
            return
        wait_sum = 0.0
        for (_, h), res in zip(items, results):
            wait_sum += t_disp - h._t_enq
            h._t_disp = t_disp
            h._ctx = ctx
            h._res = res
            h._ev.set()
        with self._mu:
            self.dispatches += 1
            self.items += len(items)
            self.weight += w
            self.wait_s += wait_sum
            self.max_items = max(self.max_items, len(items))
            self._ema = 0.75 * self._ema + 0.25 * len(items)
        DATA_PATH.record_coalesce_dispatch(len(items), w, wait_sum)
        DATA_PATH.record_lane_dispatch(self.device, len(items), w, wait_sum)

    # -- pinned-staging H2D pipeline (ISSUE 17 tentpole) ---------------------

    def _staging_view(self, slot: int, nbytes: int) -> np.ndarray:
        """The slot's page-aligned bpool staging lease, grown on demand.
        A slot is only ever reused two dispatches later, by which point
        the batch that last packed into it has been resolved (resolve
        syncs the kernel), so growth may release the old lease safely."""
        from . import bpool

        lease = self._staging[slot]
        if lease is None or lease.view is None \
                or lease.view.nbytes < nbytes:
            if lease is not None:
                lease.release()
            lease = self._staging[slot] = bpool.default_pool().get(nbytes)
        return lease.view[:nbytes]

    def _dispatch_pipelined(self, items: list[tuple], w: int, fn,
                            launch) -> bool:
        """Pack the batch into the spare staging buffer, ship it with an
        async device_put, launch the kernel, and resolve the PREVIOUS
        launch afterwards — so this batch's host work (pack + upload
        issue) overlaps the previous batch's device execution.  Returns
        False (nothing dispatched) when the batch is not pipeline-
        eligible; the caller falls back to the serial path."""
        from . import devices as devices_mod

        dev = devices_mod.jax_device(self.device)
        first = items[0][0]
        if dev is None or first.dtype != np.uint8 or first.ndim < 2:
            return False
        row_shape = first.shape[1:]
        row_bytes = first.itemsize
        for d in row_shape:
            row_bytes *= int(d)
        if row_bytes <= 0:
            return False
        for p, _ in items:
            if p.dtype != np.uint8 or p.shape[1:] != row_shape:
                return False
        t0 = time.monotonic()
        n = sum(h.nrows for _, h in items)
        mult = int(getattr(fn, "pad_rows", 1) or 1)
        padded = n + (-n) % mult
        need = padded * row_bytes
        slot = self._staging_flip
        self._staging_flip ^= 1
        view = self._staging_view(slot, need).reshape(
            (padded,) + row_shape)
        lo = 0
        for p, h in items:
            view[lo:lo + h.nrows] = p
            lo += h.nrows
        if padded > n:
            view[n:] = 0
        t_pack = time.monotonic()
        import jax

        x = jax.device_put(view, dev)     # async H2D from pinned staging
        devcache.note_h2d(need, self.device)
        t_h2d = time.monotonic()
        spans = []
        lo = 0
        for _, h in items:
            spans.append((lo, lo + h.nrows))
            lo += h.nrows
        ctx = DispatchCtx(self._bufs, len(items))
        try:
            resolve = launch(x, n, spans, ctx)
        except BaseException:  # noqa: BLE001 — fall back to serial
            # Launch is the cheap half (placement + trace); a fault here
            # re-runs the batch on the serial path, whose containment
            # retries members solo.
            if ctx.buf is not None:
                self._bufs.give(ctx.buf)
                ctx.buf = None
            return False
        prev, self._pending = self._pending, (
            resolve, items, w, fn, ctx, t_pack)
        host_s = time.monotonic() - t0
        with self._mu:
            self.h2d_bytes += need
            self.h2d_dispatches += 1
            self.pipeline_dispatches += 1
            self.pack_s += t_pack - t0
            self.h2d_s += t_h2d - t_pack
            if prev is not None:
                # Everything this batch just did on the host ran while
                # `prev`'s kernel executed on-device.
                self.overlap_s += host_s
        if prev is not None:
            self._resolve(prev)
        return True

    def _drain_pipeline(self) -> None:
        pending, self._pending = self._pending, None
        if pending is not None:
            self._resolve(pending)

    def _resolve(self, pending: tuple) -> None:
        """Sync one launched batch and scatter its results — the second
        phase of `_dispatch`, deferred one dispatch behind the launch."""
        resolve, items, w, fn, ctx, t_disp = pending
        t0 = time.monotonic()
        try:
            results = resolve()
        except BaseException:  # noqa: BLE001 — contain the fault
            if ctx.buf is not None:
                self._bufs.give(ctx.buf)
                ctx.buf = None
            with self._mu:
                self.batch_faults += 1
            # Same containment contract as the serial path: a packed
            # batch carries spans from unrelated requests — retry each
            # member solo; only the guilty span(s) keep the exception.
            DATA_PATH.record_co_fault(len(items))
            for payload, h in items:
                mctx = DispatchCtx(self._bufs, 1)
                try:
                    res = fn(payload, [(0, h.nrows)], mctx)[0]
                except BaseException as me:  # noqa: BLE001
                    if mctx.buf is not None:
                        self._bufs.give(mctx.buf)
                        mctx.buf = None
                    h._exc = me
                else:
                    h._ctx = mctx
                    h._res = res
                with self._mu:
                    self.member_retries += 1
                h._t_disp = t_disp
                h._ev.set()
            with self._mu:
                self.resolve_s += time.monotonic() - t0
            return
        wait_sum = 0.0
        for (_, h), res in zip(items, results):
            wait_sum += t_disp - h._t_enq
            h._t_disp = t_disp
            h._ctx = ctx
            h._res = res
            h._ev.set()
        with self._mu:
            self.dispatches += 1
            self.items += len(items)
            self.weight += w
            self.wait_s += wait_sum
            self.max_items = max(self.max_items, len(items))
            self._ema = 0.75 * self._ema + 0.25 * len(items)
            self.resolve_s += time.monotonic() - t0
        DATA_PATH.record_coalesce_dispatch(len(items), w, wait_sum)
        DATA_PATH.record_lane_dispatch(self.device, len(items), w,
                                       wait_sum)

    # -- lifecycle / introspection ------------------------------------------

    def close(self) -> None:
        with self._mu:
            self._stopped = True
            # Anything still queued will never be served — fail it now
            # (a retiring scheduler must not leave submitters waiting
            # out their result() timeout).
            victims: list[Handle] = []
            for q in self._queues.values():
                victims.extend(h for _, h in q)
                q.clear()
            self._queues.clear()
            self._fns.clear()
            self._pending_weight = 0
            self._pending_items = 0
            self._work.notify_all()
            self._space.notify_all()
        for h in victims:
            h._exc = RuntimeError("coalescer closed")
            h._ev.set()

    def stats(self) -> dict:
        with self._mu:
            return {
                "device": self.device,
                "dispatches": self.dispatches,
                "items": self.items,
                "weight": self.weight,
                "wait_s": self.wait_s,
                "max_items": self.max_items,
                "occupancy": (self.items / self.dispatches
                              if self.dispatches else 0.0),
                "pending_items": self._pending_items,
                "pending_weight": self._pending_weight,
                "batch_faults": self.batch_faults,
                "member_retries": self.member_retries,
                "h2d_bytes": self.h2d_bytes,
                "h2d_dispatches": self.h2d_dispatches,
                "pipeline_dispatches": self.pipeline_dispatches,
                "pack_s": self.pack_s,
                "h2d_s": self.h2d_s,
                "resolve_s": self.resolve_s,
                "overlap_s": self.overlap_s,
                "broken": self._broken is not None,
            }


class DispatchCoalescer:
    """Per-device lane facade: routes each submit to the lane owning
    the target device (`device % n_lanes`, so a lane index is always
    valid even when the topology shrank) and aggregates lane stats.
    Lane count is resolved lazily from `ops/devices.n_devices()` on
    first use and then frozen for the instance — tests flip
    MTPU_DEVICES and call `coalesce.reset()` for a fresh topology.

    With one lane (the host/oracle default) the facade is a thin
    pass-through around the exact pre-sharding scheduler."""

    def __init__(self, nlanes: int | None = None):
        self._lanes_mu = threading.Lock()
        self._want_lanes = nlanes
        self._lanes: dict[int, DispatchLane] = {}
        self._closed = False

    def nlanes(self) -> int:
        n = self._want_lanes
        if n is None:
            from . import devices

            n = self._want_lanes = devices.n_devices()
        return n

    def lane(self, device: int = 0) -> DispatchLane:
        d = int(device) % self.nlanes()
        lane = self._lanes.get(d)
        if lane is None:
            with self._lanes_mu:
                lane = self._lanes.get(d)
                if lane is None:
                    lane = DispatchLane(device=d)
                    if self._closed:
                        # Post-close stragglers (a late note_read in a
                        # request's finally) get a lane that refuses
                        # submits but never hangs or raises elsewhere.
                        lane._stopped = True
                    self._lanes[d] = lane
        return lane

    # -- pass-throughs keyed by device --------------------------------------

    def submit(self, key: tuple, payload: np.ndarray, fn,
               weight: int | None = None, device: int = 0) -> Handle:
        return self.lane(device).submit(key, payload, fn, weight)

    def hot(self, device: int | None = None) -> bool:
        if device is not None:
            return self.lane(device).hot()
        return any(ln.hot() for ln in list(self._lanes.values()))

    def note_read(self, delta: int, device: int = 0) -> None:
        self.lane(device).note_read(delta)

    # -- single-lane compatibility surface ----------------------------------
    # The scheduler unit tests (and the idle fast-path contract) poke
    # lane internals through the facade; with lanes these map to lane 0.

    @property
    def _ema(self) -> float:
        return self.lane(0)._ema

    @_ema.setter
    def _ema(self, v: float) -> None:
        self.lane(0)._ema = v

    @property
    def _thread(self):
        ln = self._lanes.get(0)
        return None if ln is None else ln._thread

    # -- lifecycle / introspection ------------------------------------------

    def close(self) -> None:
        with self._lanes_mu:
            self._closed = True
            lanes = list(self._lanes.values())
        for ln in lanes:
            ln.close()

    def lane_stats(self) -> dict[int, dict]:
        """Per-lane stats for lanes that have actually been touched."""
        return {d: ln.stats() for d, ln in sorted(self._lanes.items())}

    def stats(self) -> dict:
        per = self.lane_stats()
        out = {
            "dispatches": 0, "items": 0, "weight": 0, "wait_s": 0.0,
            "max_items": 0, "pending_items": 0, "pending_weight": 0,
            "batch_faults": 0, "member_retries": 0,
            "h2d_bytes": 0, "h2d_dispatches": 0,
            "pipeline_dispatches": 0, "pack_s": 0.0, "h2d_s": 0.0,
            "resolve_s": 0.0, "overlap_s": 0.0,
        }
        broken = False
        for st in per.values():
            for k in ("dispatches", "items", "weight", "wait_s",
                      "pending_items", "pending_weight", "batch_faults",
                      "member_retries", "h2d_bytes", "h2d_dispatches",
                      "pipeline_dispatches", "pack_s", "h2d_s",
                      "resolve_s", "overlap_s"):
                out[k] += st[k]
            out["max_items"] = max(out["max_items"], st["max_items"])
            broken = broken or st["broken"]
        out["occupancy"] = (out["items"] / out["dispatches"]
                            if out["dispatches"] else 0.0)
        out["broken"] = broken
        out["n_lanes"] = self.nlanes()
        out["lanes"] = per
        return out


# -- shared kernels ----------------------------------------------------------

def make_digest_kernel(algo: str, pad_rows: int = 0):
    """Batched bitrot digest over stacked (N, S) rows — the healthy-GET
    verify and heal-verify workhorse.  `pad_rows`: bound jit shapes on
    device backends (0 = host kernels, no padding needed)."""
    from ..storage import bitrot_io

    def kernel(stacked, spans, ctx):
        if pad_rows:
            x, n = pad_batch(stacked, pad_rows)
            out = bitrot_io._hash_batch(x, algo)[:n]
        else:
            out = bitrot_io._hash_batch(stacked, algo)
        return [out[lo:hi] for lo, hi in spans]

    if pad_rows:
        from . import fused

        if algo in fused.DEVICE_ALGOS and bitrot_io.device_preferred(algo):
            # Pipeline form: the lane pre-placed the (padded) rows on
            # its device — hash them asynchronously and defer the sync
            # to resolve().  Same algorithm, same digests, as
            # _hash_batch produces for the serial path.
            def launch(x, n, spans, ctx):
                out_dev = fused.hash_rows_async(x, algo)

                def resolve():
                    out = np.asarray(out_dev)[:n]
                    return [out[lo:hi] for lo, hi in spans]

                return resolve

            kernel.launch = launch
            kernel.pad_rows = pad_rows

    return kernel


# -- process singleton -------------------------------------------------------

_CO: DispatchCoalescer | None = None
_CO_MU = threading.Lock()

#: Remote-submit front end (ops/ipc_dispatch.RemoteCoalescer), attached
#: by server/workers.py inside a forked HTTP worker.  When set, every
#: engine call site that does `coalesce.get()` transparently routes
#: remote-eligible keys to the device-owner process and keeps the rest
#: on the worker's own in-process scheduler.
_REMOTE = None


def get():
    r = _REMOTE
    if r is not None:
        return r
    global _CO
    co = _CO
    if co is None:
        with _CO_MU:
            if _CO is None:
                _CO = DispatchCoalescer()
            co = _CO
    return co


def attach_remote(remote) -> None:
    """Install a cross-process front end as THE coalescer for this
    (worker) process.  detach_remote() restores in-process dispatch."""
    global _REMOTE
    _REMOTE = remote


def detach_remote() -> None:
    global _REMOTE
    r, _REMOTE = _REMOTE, None
    if r is not None:
        r.close()


def reset() -> None:
    """Tests: retire the singleton (its daemon threads exit) so flag
    changes start from a cold scheduler."""
    global _CO
    with _CO_MU:
        if _CO is not None:
            _CO.close()
        _CO = None


def _reset_after_fork() -> None:
    # A forked child inherits the parent's singleton OBJECT but not its
    # dispatcher threads — submits would queue forever.  Drop both the
    # scheduler and any remote front end (its listener thread is gone
    # too); the child lazily builds fresh ones.
    global _CO, _REMOTE
    _CO = None
    _REMOTE = None


os.register_at_fork(after_in_child=_reset_after_fork)

