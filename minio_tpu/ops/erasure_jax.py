"""TPU-native Reed-Solomon codec: GF(2^8) shard math as MXU matmuls.

The reference's hot loop is a (parity x data) GF(2^8) matrix multiply per
1 MiB block, executed as AVX512 Galois-multiply assembly
(/root/reference/cmd/erasure-coding.go:77, klauspost/reedsolomon). TPUs have
no byte-level Galois ops — instead we exploit that multiplication by a
constant in GF(2^8) is linear over GF(2): unpack shard bytes into 8 bit-planes
and the whole codec becomes a
    (8*rows x 8*cols) binary-matrix @ (8*cols x shard_size) bit-plane
matmul with XOR accumulation (= integer matmul mod 2) — exactly the batched
matmul shape the MXU is built for. Bits are carried as bf16 0/1 values
(products and sums here are exact: max inner dim 8*16=128 << 2^8 mantissa).

Layout: *plane-major* bit rows (row j*C + c = bit j of byte-column c), which
lets unpack/pack be one broadcasted shift/weighted-sum over the whole tile.

This module is the portable XLA path (runs on CPU/TPU, used by tests and as
the sharding building block); ops/erasure_pallas.py fuses unpack->matmul->pack
into one VMEM pass to cut HBM traffic 16x.

All codec entry points take batches of blocks: (B, C, S) uint8 — B blocks
staged into HBM at once, the TPU analogue of the reference's per-block
streaming SIMD calls (SURVEY.md §5 "blocks are the natural batch dimension").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256


# ---------------------------------------------------------------------------
# Host-side matrix preparation.
# ---------------------------------------------------------------------------

def _plane_major_bits(gf_matrix: np.ndarray) -> np.ndarray:
    """Expand an (R, C) GF(2^8) matrix to plane-major (8R, 8C) GF(2) bits.

    out[i*R + r, j*C + c] = bit i of (gf_matrix[r, c] * 2^j).
    """
    gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
    r, c = gf_matrix.shape
    bits = gf256.expand_matrix_to_bits(gf_matrix)  # byte-major (8r, 8c)
    row_perm = np.arange(8 * r).reshape(8, r)  # [i, rr] -> position i*r+rr
    row_src = (np.arange(r)[None, :] * 8 + np.arange(8)[:, None]).ravel()
    col_src = (np.arange(c)[None, :] * 8 + np.arange(8)[:, None]).ravel()
    del row_perm
    return bits[row_src][:, col_src]


@functools.lru_cache(maxsize=256)
def _encode_matrix_bits(data_shards: int, parity_shards: int) -> np.ndarray:
    return _plane_major_bits(gf256.parity_matrix(data_shards, parity_shards))


@functools.lru_cache(maxsize=4096)
def _transform_matrix_bits(data_shards: int, parity_shards: int,
                           sources: tuple[int, ...],
                           targets: tuple[int, ...]) -> np.ndarray:
    """Bit matrix mapping `sources` shard rows -> `targets` shard rows.

    sources: indices of >= data_shards available shards (first K used).
    targets: arbitrary shard indices to (re)compute — missing data rows for a
    GET-path decode, any missing rows for a heal, parity rows for encode.
    This single primitive covers the reference's Encode / ReconstructData /
    Heal seams (cmd/erasure-coding.go:77,96; cmd/erasure-lowlevel-heal.go:31).
    """
    k = data_shards
    full = gf256.build_matrix(k, k + parity_shards)
    use = list(sources)[:k]
    inv = gf256.gf_mat_invert(full[use, :])
    target_rows = full[list(targets), :]
    gf_mat = gf256.gf_matmul(target_rows, inv)
    return _plane_major_bits(gf_mat)


# ---------------------------------------------------------------------------
# Device kernels (portable XLA).
# ---------------------------------------------------------------------------

def _unpack_planes(x: jax.Array) -> jax.Array:
    """(B, C, S) uint8 -> (B, 8C, S) bf16 bit-planes, plane-major."""
    b, c, s = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)[None, :, None, None]
    planes = (x[:, None, :, :] >> shifts) & jnp.uint8(1)
    return planes.reshape(b, 8 * c, s).astype(jnp.bfloat16)


def _pack_planes(y: jax.Array, rows: int) -> jax.Array:
    """(B, 8R, S) f32 integer counts -> (B, R, S) uint8 (mod-2 then pack)."""
    b, r8, s = y.shape
    bits = jnp.bitwise_and(y.astype(jnp.int32), 1)
    planes = bits.reshape(b, 8, rows, s)
    weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32))[None, :, None, None]
    return jnp.sum(planes * weights, axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("rows",))
def _gf_matmul_blocks(mat_bits: jax.Array, x: jax.Array, rows: int) -> jax.Array:
    """Batched GF(2^8) matmul via bit-planes.

    mat_bits: (8R, 8C) bf16 0/1 (plane-major); x: (B, C, S) uint8.
    Returns (B, R, S) uint8 = GF-matmul of the underlying (R, C) GF matrix.
    """
    planes = _unpack_planes(x)  # (B, 8C, S)
    y = jnp.einsum("rc,bcs->brs", mat_bits, planes,
                   preferred_element_type=jnp.float32)
    return _pack_planes(y, rows)


class ReedSolomonTPU:
    """Device codec with the same narrow seam as the reference's `Erasure`.

    Encode/reconstruct/heal all lower onto one batched bit-plane matmul; the
    (tiny) GF matrix algebra runs on host, mirroring how the reference keeps
    matrix inversion in Go while the shard math is SIMD
    (cmd/erasure-coding.go:35 holds the codec behind a narrow closure).
    """

    def __init__(self, data_shards: int, parity_shards: int,
                 use_pallas: bool | None = None):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self.use_pallas = use_pallas

    # -- core primitive -------------------------------------------------------

    def _apply(self, mat_bits: np.ndarray, x: jax.Array, rows: int,
               salt: jax.Array | None = None) -> jax.Array:
        mat = jnp.asarray(mat_bits, dtype=jnp.bfloat16)
        if self.use_pallas:
            from . import erasure_pallas
            return erasure_pallas.gf_matmul_blocks(mat, x, rows, salt=salt)
        if salt is not None:
            x = x ^ salt[0].astype(jnp.uint8)
        return _gf_matmul_blocks(mat, x, rows)

    # -- public API -----------------------------------------------------------

    def encode_blocks(self, data: jax.Array | np.ndarray,
                      salt: jax.Array | None = None) -> jax.Array:
        """(B, K, S) data shards -> (B, M, S) parity shards.

        salt: benchmark-protocol scalar xor of the input inside the
        kernel (see erasure_pallas.gf_matmul_blocks); production None.
        """
        data = jnp.asarray(data, dtype=jnp.uint8)
        mat = _encode_matrix_bits(self.data_shards, self.parity_shards)
        return self._apply(mat, data, self.parity_shards, salt=salt)

    def transform_blocks(self, shards: jax.Array | np.ndarray,
                         sources: tuple[int, ...],
                         targets: tuple[int, ...],
                         salt: jax.Array | None = None) -> jax.Array:
        """(B, K, S) shards at rows `sources[:K]` -> (B, T, S) rows `targets`.

        The universal decode/heal primitive: reconstruct any target rows from
        any K available rows.
        """
        shards = jnp.asarray(shards, dtype=jnp.uint8)
        mat = _transform_matrix_bits(self.data_shards, self.parity_shards,
                                     tuple(sources), tuple(targets))
        return self._apply(mat, shards, len(targets), salt=salt)

    def reconstruct_blocks(self, shards: list[jax.Array | np.ndarray | None],
                           data_only: bool = False) -> list[jax.Array]:
        """Fill missing entries of a (total_shards)-list of (B, S) arrays."""
        available = [i for i, s in enumerate(shards) if s is not None]
        if len(available) < self.data_shards:
            raise ValueError("too few shards to reconstruct")
        limit = self.data_shards if data_only else self.total_shards
        missing = [i for i in range(limit)
                   if i < len(shards) and shards[i] is None]
        if not missing:
            return list(shards)
        use = available[:self.data_shards]
        x = jnp.stack([jnp.asarray(shards[i], dtype=jnp.uint8) for i in use],
                      axis=1)  # (B, K, S)
        out = self.transform_blocks(x, tuple(use), tuple(missing))
        result = list(shards)
        for j, idx in enumerate(missing):
            result[idx] = out[:, j, :]
        return result
