"""Device path for mxh256 (ops/mxhash.py): the digest as MXU int8 matmuls.

Every level is one (rows, 256) int8 @ (256, 8) int8 -> int32 matmul with
exact integer accumulation — bytes feed the MXU directly (no bit-plane
unpack, so HBM traffic stays ~1x the hashed bytes).  The level loop is a
Python loop over STATIC shapes: a fixed input length compiles to a fixed
chain of shrinking matmuls (depth ceil(log8(L/32))), all inside one jit.

`mxh256_rows` is the traceable core shared with ops/fused.py, where the
digest rides in the same dispatch as the erasure matmul (north-star
config #5): the shard bytes cross HBM once for verify + reconstruct.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import mxhash


def _matrix_a_dev():
    # jnp.asarray of the cached numpy constant; inside a jit this traces to
    # a compile-time constant (caching the jnp array would leak tracers).
    return jnp.asarray(mxhash.matrix_a())


def _level(rows: jax.Array) -> jax.Array:
    """(n, L) uint8 -> (n, 32*ceil(L/256)) uint8. Static-shape tree level."""
    n, ln = rows.shape
    pad = (-ln) % mxhash.CHUNK
    if pad or ln == 0:
        rows = jnp.pad(rows, ((0, 0), (0, max(pad, mxhash.CHUNK - ln))))
    chunks = jax.lax.bitcast_convert_type(
        rows.reshape(n, -1, mxhash.CHUNK), jnp.int8)
    h = jnp.matmul(chunks, _matrix_a_dev(),
                   preferred_element_type=jnp.int32)        # (n, nc, 8)
    # Serialize words little-endian: byte k of word w -> offset 4w + k.
    # bitcast_convert_type appends a (4,) LE byte dim — one op instead
    # of the 4x shift/mask/stack chain (verified bit-identical on chip).
    b = jax.lax.bitcast_convert_type(h, jnp.uint8)          # (n, nc, 8, 4)
    return b.reshape(n, -1)


def mxh256_rows(x: jax.Array) -> jax.Array:
    """Traceable core: (n, L) uint8 -> (n, 32) uint8 digests."""
    n, ln = x.shape
    cur = x
    while True:
        cur = _level(cur)
        if cur.shape[1] == mxhash.DIGEST_SIZE:
            break
    tag = jnp.asarray(mxhash.length_tag(ln))   # trace-time constant
    return cur ^ tag[None, :]


@functools.partial(jax.jit)
def _mxh256_batch_jit(x):
    return mxh256_rows(x)


def mxh256_batch_jax(blocks) -> jax.Array:
    """Jitted batch digest: (n, L) uint8 -> (n, 32) uint8."""
    return _mxh256_batch_jit(jnp.asarray(blocks, dtype=jnp.uint8))
