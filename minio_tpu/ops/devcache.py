"""Device-resident shard cache + host<->device boundary accounting.

ROADMAP item 4 (the tunnel wall): the Pallas kernels run at 74-104 GB/s
but the real-TPU e2e path crawls because every dispatch re-uploads its
shard batch through a ~20-36 MB/s host<->device tunnel.  This module is
the residency half of the fix: verified (nb, K, S) shard batches from
healthy GETs are kept keyed by `(owner, bucket, object, part, range)`
and guarded by the same `_mark_dirty` generation discipline as the PR 14
hot-object cache, so a re-read (healthy verify, hedged retry, heal) of a
resident range performs ZERO uploads — the bytes either serve straight
from the verified host copy or dispatch against the already-placed
device array.

Fill discipline (mirrors engine/hotcache.py): only a fully-verified
healthy fast-path read may fill — degraded reads, decode fallbacks, and
anything that tripped a digest mismatch never populate the cache — and
the generation is captured BEFORE the shard reads, so a racing write
invalidates the fill rather than the fill masking the write.  A process
restart (crash recovery, pre-fork worker respawn) starts from an empty
cache and fresh owner tokens, so stale generations can never survive a
boot.

The same module owns the process-wide H2D boundary ledger: every
host->device byte crossing (`fused._placed`, `devices.put`, the
coalescer lanes' pipelined staging uploads) is recorded here, per lane,
so benches and tests can assert bytes-crossing-per-byte-served ~= 1.0 on
first touch and ~0 on cache hits without real tunnel hardware attached.

Env (read per call so tests flip them without re-importing):

- MTPU_DEVCACHE=0 disables the cache — the byte-identical direct-read
  oracle the differential tests diff against;
- MTPU_DEVCACHE_MB caps resident payload bytes (default 64);
- MTPU_H2D_PIPELINE=0 disables the lanes' pinned-staging double-buffered
  upload pipeline (ops/coalesce.py) — the serial-upload oracle.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np


def enabled() -> bool:
    return os.environ.get("MTPU_DEVCACHE", "1") != "0"


def cache_bytes() -> int:
    try:
        mb = int(os.environ.get("MTPU_DEVCACHE_MB", "64"))
    except ValueError:
        mb = 64
    return max(1, mb) << 20


def h2d_pipeline_enabled() -> bool:
    return os.environ.get("MTPU_H2D_PIPELINE", "1") != "0"


# -- H2D boundary ledger ------------------------------------------------------

_H2D_MU = threading.Lock()
_H2D_BYTES = 0
_H2D_DISPATCHES = 0
_H2D_LANES: dict[int, dict] = {}


def note_h2d(nbytes: int, device: int | None = None) -> None:
    """Record one host->device crossing of `nbytes` bytes.  Called by
    every upload site (fused._placed, devices.put, the lanes' staged
    device_put) — and by nothing else, so the ledger IS the boundary."""
    global _H2D_BYTES, _H2D_DISPATCHES
    with _H2D_MU:
        _H2D_BYTES += int(nbytes)
        _H2D_DISPATCHES += 1
        if device is not None:
            lane = _H2D_LANES.setdefault(
                int(device), {"h2d_bytes": 0, "h2d_dispatches": 0})
            lane["h2d_bytes"] += int(nbytes)
            lane["h2d_dispatches"] += 1


def h2d_stats() -> dict:
    with _H2D_MU:
        return {
            "h2d_bytes": _H2D_BYTES,
            "h2d_dispatches": _H2D_DISPATCHES,
            "lanes": {d: dict(v) for d, v in sorted(_H2D_LANES.items())},
        }


def reset_h2d() -> None:
    global _H2D_BYTES, _H2D_DISPATCHES
    with _H2D_MU:
        _H2D_BYTES = 0
        _H2D_DISPATCHES = 0
        _H2D_LANES.clear()


# -- owner tokens + generations ----------------------------------------------

_OWNER_MU = threading.Lock()
_NEXT_OWNER = 0


def next_owner() -> int:
    """Monotonic per-process owner token, one per ErasureSet instance.
    A reopened set (crash recovery, decom re-attach) gets a fresh token,
    so entries filled by the previous incarnation are unreachable — the
    recovery-boot invalidation guarantee without any persisted state."""
    global _NEXT_OWNER
    with _OWNER_MU:
        _NEXT_OWNER += 1
        return _NEXT_OWNER


class Entry:
    """One resident range: the VERIFIED systematic data matrix
    (nb, K, S) for blocks [b0, b1) of one part, plus the (tiny) tail
    fragment when the range covers it.  `host` is the verified numpy
    copy — healthy hits serve from it with zero disk reads, zero
    uploads, zero dispatches, and stay honest under post-fill disk
    corruption (the bytes served are the bytes that passed verify).
    `dev` is the committed jax array, created at fill time when the
    verify dispatch already placed the batch (zero extra upload) or
    lazily on first device consumer otherwise."""

    __slots__ = ("key", "gen", "host", "tail", "dev", "device",
                 "nbytes")

    def __init__(self, key, gen, host, tail, dev, device, nbytes):
        self.key = key
        self.gen = gen
        self.host = host
        self.tail = tail
        self.dev = dev
        self.device = device
        self.nbytes = nbytes


class DeviceShardCache:
    """LRU of verified shard batches, capacity-bounded by payload bytes
    (MTPU_DEVCACHE_MB).  All staleness is generational: `note_mutation`
    bumps `(owner, bucket)` and every later lookup of an entry filled
    under the old generation reaps it — the exact `_mark_dirty` ride the
    PR 14 hot cache uses, one layer down."""

    def __init__(self):
        self._mu = threading.Lock()
        self._entries: "OrderedDict[tuple, Entry]" = OrderedDict()
        self._gen: dict[tuple, int] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.invalidations = 0
        self.stale_drops = 0
        self.rejects = 0

    # -- generations ---------------------------------------------------------

    def current_gen(self, owner: int, bucket: str) -> int:
        with self._mu:
            return self._gen.get((owner, bucket), 0)

    def note_mutation(self, owner: int, bucket: str) -> None:
        with self._mu:
            self._gen[(owner, bucket)] = \
                self._gen.get((owner, bucket), 0) + 1
            self.invalidations += 1

    # -- fill / lookup -------------------------------------------------------

    def fill(self, key: tuple, gen0: int, host: np.ndarray,
             tail: np.ndarray | None = None, dev=None,
             device: int | None = None) -> bool:
        """Admit one verified range.  `gen0` is the (owner, bucket)
        generation captured BEFORE the shard reads; a mutation since
        then rejects the fill (the read's bytes may predate the write).
        Returns whether the entry was admitted."""
        owner, bucket = key[0], key[1]
        nbytes = int(host.nbytes) + (int(tail.nbytes) if tail is not None
                                     else 0)
        cap = cache_bytes()
        with self._mu:
            if self._gen.get((owner, bucket), 0) != gen0:
                self.stale_drops += 1
                return False
            if nbytes > cap:
                self.rejects += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = Entry(key, gen0, host, tail, dev,
                                       device, nbytes)
            self._bytes += nbytes
            self.fills += 1
            while self._bytes > cap and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self.evictions += 1
        return True

    def _valid(self, e: Entry) -> bool:
        return self._gen.get((e.key[0], e.key[1]), 0) == e.gen

    def lookup(self, key: tuple) -> Entry | None:
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            if not self._valid(e):
                del self._entries[key]
                self._bytes -= e.nbytes
                self.stale_drops += 1
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e

    def lookup_range(self, owner: int, bucket: str, obj: str,
                     part: int, data_dir: str, algo: str,
                     lo: int, hi: int) -> tuple[Entry, int] | None:
        """Find an entry covering blocks [lo, hi) of the part (heal and
        hedged re-reads probe sub-ranges of what a whole-object GET
        filled).  Returns (entry, block offset of `lo` inside it)."""
        with self._mu:
            for key in list(self._entries):
                if key[:5] != (owner, bucket, obj, part, data_dir) \
                        or key[7] != algo:
                    continue
                e = self._entries[key]
                if not self._valid(e):
                    del self._entries[key]
                    self._bytes -= e.nbytes
                    self.stale_drops += 1
                    continue
                b0, b1 = key[5], key[6]
                if b0 <= lo and hi <= b1:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return e, lo - b0
            self.misses += 1
            return None

    # -- device residency ----------------------------------------------------

    def device_array(self, e: Entry):
        """The entry's committed jax array, created lazily (and counted
        as ONE crossing) when no verify dispatch pre-placed it.  Returns
        None when jax placement is unavailable."""
        dev = e.dev
        if dev is not None:
            return dev
        from . import devices as devices_mod
        jd = devices_mod.jax_device(e.device if e.device is not None
                                    else 0)
        if jd is None:
            return None
        import jax
        placed = jax.device_put(e.host, jd)
        note_h2d(e.host.nbytes, e.device)
        e.dev = placed
        return placed

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": (self.hits / total) if total else 0.0,
                "fills": self.fills,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "stale_drops": self.stale_drops,
                "rejects": self.rejects,
                "entries": len(self._entries),
                "resident_bytes": self._bytes,
                "capacity_bytes": cache_bytes(),
            }

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._bytes = 0


# -- process singleton -------------------------------------------------------

_CACHE: DeviceShardCache | None = None
_CACHE_MU = threading.Lock()


def get() -> DeviceShardCache:
    global _CACHE
    c = _CACHE
    if c is None:
        with _CACHE_MU:
            if _CACHE is None:
                _CACHE = DeviceShardCache()
            c = _CACHE
    return c


def stats() -> dict | None:
    """Scrape-side stats: None when no cache was ever created."""
    with _CACHE_MU:
        return None if _CACHE is None else _CACHE.stats()


def reset() -> None:
    """Tests: drop the singleton (fresh generations, zero counters)."""
    global _CACHE
    with _CACHE_MU:
        _CACHE = None
    reset_h2d()


def _reset_after_fork() -> None:
    # A forked child inherits the parent's cache object but its device
    # arrays belong to the parent's jax runtime — drop everything; the
    # child refills from its own verified reads.
    global _CACHE
    _CACHE = None
    reset_h2d()


os.register_at_fork(after_in_child=_reset_after_fork)
