"""Per-drive metadata lanes: group-commit writes + coalesced reads.

The shard plane batches (ops/coalesce.py), but until PR 19 the METADATA
plane did not: a 4 KiB inline PUT paid one fsynced ``write_metadata``
per drive through a per-request fan-out, and every HEAD/GET metadata
miss paid an all-N ``read_version`` fan-out — N threads x M requests of
tiny, unbatchable drive calls.  This module applies the DispatchLane
discipline to that traffic (ROADMAP open item 2; the reference's
format-v2 small-object war, cmd/xl-storage-format-v2.go:25):

- one ``MetaLane`` per (drive, kind) owns a FIFO queue and a lazy
  daemon dispatcher.  Write lanes drain concurrent ``_put_inline``
  publishes landing on the same drive into ONE
  ``drive.write_metadata_many`` call — every xl.meta blob in the batch
  shares a single journal fsync before any caller is acked
  (group commit; durability ordering unchanged: ack strictly after
  fsync).  Read lanes drain distinct keys' metadata reads into one
  ``drive.read_version_many`` round per drive.
- the same adaptive-window EMA + inline-degradation discipline as the
  shard coalescer: an idle lane executes the item on the caller's
  thread through the EXACT single-op drive path (``write_metadata`` /
  ``read_version``), so a lone request keeps oracle latency and oracle
  bytes; packing only engages once the engine's in-flight counters (or
  a busy lane) prove concurrency.
- fault containment: a failed batch retries its members solo, so one
  poisoned item cannot fail or block an unrelated acked caller; a dead
  dispatcher fails queued handles and degrades every later submit to
  inline single-op dispatch.

Env (read per call so tests flip them without re-importing):

- MTPU_METABATCH=0 disables the whole plane — the byte-identical
  oracle (single-op fan-outs, one fsync per xl.meta publish);
- MTPU_METABATCH_WINDOW_US: max time the oldest queued item waits for
  company once the window engages (default 250);
- MTPU_METABATCH_DEPTH: max items per batched drive call (default 64);
- MTPU_METABATCH_SOLO=1 forces even a lone PUT through the journaled
  batch path (batch of one) — the kill-9 matrix uses this to land the
  ``meta.{stage,fsync,publish}`` crash points deterministically;
- MTPU_META_TRIM gates the engine-side K+1 read fan-out trim (see
  erasure_set._read_version_fanout) — it rides this module's flags so
  MTPU_METABATCH=0 restores the full all-N oracle.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..observe import span as ospan
from ..observe.metrics import DATA_PATH


def enabled() -> bool:
    return os.environ.get("MTPU_METABATCH", "1") != "0"


def trim_enabled() -> bool:
    return enabled() and os.environ.get("MTPU_META_TRIM", "1") != "0"


def solo_forced() -> bool:
    return os.environ.get("MTPU_METABATCH_SOLO", "") == "1"


def window_s() -> float:
    try:
        us = float(os.environ.get("MTPU_METABATCH_WINDOW_US", "250"))
    except ValueError:
        us = 250.0
    return max(0.0, us) / 1e6


def depth() -> int:
    try:
        return max(1, int(os.environ.get("MTPU_METABATCH_DEPTH", "64")))
    except ValueError:
        return 64


class MetaHandle:
    """Future for one submitted metadata op."""

    __slots__ = ("_ev", "_res", "_exc", "_t_enq", "_t_disp")

    def __init__(self):
        self._ev = threading.Event()
        self._res = None
        self._exc: BaseException | None = None
        self._t_enq = time.monotonic()
        self._t_disp: float | None = None

    def result(self, timeout: float | None = 120.0):
        if not self._ev.wait(timeout):
            raise TimeoutError("batched metadata op did not complete")
        if self._t_disp is not None:
            ospan.record("metalane.wait",
                         max(0.0, self._t_disp - self._t_enq))
            self._t_disp = None
        if self._exc is not None:
            raise self._exc
        return self._res

    def _resolve(self, t_disp: float, res=None,
                 exc: BaseException | None = None) -> None:
        self._t_disp = t_disp
        self._res = res
        self._exc = exc
        self._ev.set()


class MetaLane:
    """One drive's scheduler for one op kind ("write" or "read").

    `solo_fn(item)` is the exact oracle single-op path; `batch_fn`
    (feature-detected `write_metadata_many` / `read_version_many`, or
    None for drives without one) takes a list of items and returns one
    `(result, exc)` pair per item.  Without a batch op the lane still
    packs items into one dispatcher round of solo calls — no fsync
    amortization, but the N-threads-x-M-requests fan-out collapses.
    """

    #: queued-item cap as a multiple of the batch depth — beyond this,
    #: submit() blocks (backpressure) instead of buffering unboundedly.
    QUEUE_FACTOR = 4

    def __init__(self, name: str, solo_fn, batch_fn=None):
        self.name = name
        self._solo = solo_fn
        self._batch = batch_fn
        self._mu = threading.Lock()
        self._work = threading.Condition(self._mu)
        self._space = threading.Condition(self._mu)
        self._queue: deque = deque()
        self._dispatching = False
        self._inline = 0
        # Occupancy EMA, same policy as DispatchLane: ~1.0 means lone
        # requests (inline immediately), >1 means packing pays.
        self._ema = 1.0
        self._thread: threading.Thread | None = None
        self._stopped = False
        self._broken: BaseException | None = None
        # Lifetime stats (mirrored into DATA_PATH per dispatch).
        self.dispatches = 0
        self.items = 0
        self.max_items = 0
        self.inline_ops = 0
        self.batch_faults = 0
        self.member_retries = 0

    def busy(self) -> bool:
        return (len(self._queue) > 0 or self._dispatching
                or self._inline > 0 or self._ema > 1.05)

    # -- submission ----------------------------------------------------------

    def submit(self, item) -> MetaHandle:
        h = MetaHandle()
        cap = self.QUEUE_FACTOR * depth()
        with self._mu:
            if self._stopped:
                raise RuntimeError("metadata lane closed")
            # Idle fast path: nothing queued, nothing dispatching, no
            # recent packing — run the ORACLE single-op path on this
            # thread (zero handoff latency, oracle durability
            # mechanics).  MTPU_METABATCH_SOLO disables it so the
            # crash matrix exercises the journal on a batch of one.
            inline = (self._broken is not None
                      or (not solo_forced() and not self._queue
                          and not self._dispatching
                          and self._inline == 0 and self._ema <= 1.05))
            if inline:
                self._inline += 1
            else:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._loop,
                        name=f"mtpu-metalane-{self.name}", daemon=True)
                    self._thread.start()
                while len(self._queue) >= cap:
                    self._space.wait(0.05)
                    cap = self.QUEUE_FACTOR * depth()
                self._queue.append((item, h))
                self._work.notify()
        if inline:
            t0 = time.monotonic()
            try:
                res = self._solo(item)
            except BaseException as e:  # noqa: BLE001 — caller raises
                h._resolve(t0, exc=e)
            else:
                h._resolve(t0, res=res)
            with self._mu:
                self._inline -= 1
                self.inline_ops += 1
            DATA_PATH.record_meta_inline_op()
        return h

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        try:
            while True:
                with self._mu:
                    while not self._queue:
                        if self._stopped:
                            return
                        self._work.wait()
                    budget = depth()
                    # Adaptive window: only hold the head item for
                    # company when recent dispatches actually packed;
                    # always bounded by the oldest item's age.
                    if self._ema > 1.05 and len(self._queue) < budget:
                        deadline = self._queue[0][1]._t_enq + window_s()
                        while (len(self._queue) < budget
                               and not self._stopped):
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            self._work.wait(left)
                    items = []
                    while self._queue and len(items) < budget:
                        items.append(self._queue.popleft())
                    self._dispatching = True
                    self._space.notify_all()
                self._dispatch(items)
                with self._mu:
                    self._dispatching = False
        except BaseException as e:  # noqa: BLE001 — scheduler death
            self._abort(e)

    def _abort(self, exc: BaseException) -> None:
        """Dispatcher death: error every queued handle, route all
        future submits inline (degraded to single-op dispatch — no
        submitter can hang on a scheduler that no longer exists)."""
        with self._mu:
            self._broken = exc
            victims = [h for _, h in self._queue]
            self._queue.clear()
            self._dispatching = False
            self._space.notify_all()
            self._work.notify_all()
        err = RuntimeError(f"metadata lane dispatcher died: {exc!r}")
        t = time.monotonic()
        for h in victims:
            h._resolve(t, exc=err)

    def _dispatch(self, items: list) -> None:
        t_disp = time.monotonic()
        wait_sum = sum(t_disp - h._t_enq for _, h in items)
        try:
            if self._batch is not None:
                results = self._batch([it for it, _ in items])
            else:
                results = []
                for it, _ in items:
                    try:
                        results.append((self._solo(it), None))
                    except Exception as e:  # noqa: BLE001 — per item
                        results.append((None, e))
            if len(results) != len(items):
                raise RuntimeError(
                    f"batch returned {len(results)} results for "
                    f"{len(items)} items")
        except BaseException as e:  # noqa: BLE001 — contain the fault
            with self._mu:
                self.batch_faults += 1
            if len(items) == 1:
                items[0][1]._resolve(t_disp, exc=e)
                return
            # Fault containment: a packed batch carries items from
            # UNRELATED requests — one poisoned member must not fail
            # its neighbors.  Retry each item solo; only the member(s)
            # that still fail get the exception.
            for it, h in items:
                try:
                    res = self._solo(it)
                except BaseException as me:  # noqa: BLE001 — guilty one
                    h._resolve(t_disp, exc=me)
                else:
                    h._resolve(t_disp, res=res)
                with self._mu:
                    self.member_retries += 1
            return
        for (_, h), (res, exc) in zip(items, results):
            h._resolve(t_disp, res=res, exc=exc)
        with self._mu:
            self.dispatches += 1
            self.items += len(items)
            self.max_items = max(self.max_items, len(items))
            self._ema = 0.75 * self._ema + 0.25 * len(items)
        DATA_PATH.record_meta_lane_dispatch(len(items), wait_sum)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._mu:
            self._stopped = True
            victims = [h for _, h in self._queue]
            self._queue.clear()
            self._work.notify_all()
            self._space.notify_all()
        t = time.monotonic()
        for h in victims:
            h._resolve(t, exc=RuntimeError("metadata lane closed"))

    def stats(self) -> dict:
        with self._mu:
            return {
                "dispatches": self.dispatches,
                "items": self.items,
                "max_items": self.max_items,
                "inline_ops": self.inline_ops,
                "batch_faults": self.batch_faults,
                "member_retries": self.member_retries,
                "occupancy": (self.items / self.dispatches
                              if self.dispatches else 0.0),
                "pending": len(self._queue),
                "broken": self._broken is not None,
            }


class MetaBatcher:
    """Facade owning one write lane + one read lane per drive, plus
    the request-level concurrency counters that ignite packing (the
    note_read role of the shard coalescer: queue depth alone cannot
    prove concurrency when every idle submit runs inline)."""

    def __init__(self):
        self._mu = threading.Lock()
        # (id(drive), kind) -> (drive ref, lane).  The drive ref keeps
        # the id stable for the lane's lifetime.
        self._lanes: dict[tuple, tuple] = {}
        self._closed = False
        self._inflight_puts = 0
        self._inflight_reads = 0

    # -- lane plumbing -------------------------------------------------------

    def _lane(self, drive, kind: str, solo_fn, batch_fn) -> MetaLane:
        key = (id(drive), kind)
        got = self._lanes.get(key)
        if got is not None:
            return got[1]
        with self._mu:
            got = self._lanes.get(key)
            if got is None:
                name = f"{getattr(drive, 'endpoint', '?')}-{kind}"
                lane = MetaLane(os.path.basename(str(name)) or name,
                                solo_fn, batch_fn)
                if self._closed:
                    lane._stopped = True
                got = self._lanes[key] = (drive, lane)
        return got[1]

    def write_lane(self, drive) -> MetaLane:
        def solo(item):
            vol, obj, fi = item
            drive.write_metadata(vol, obj, fi)

        wmm = getattr(drive, "write_metadata_many", None)

        def batch(items):
            return [(None, e) for e in wmm(items)]

        return self._lane(drive, "write", solo,
                          batch if wmm is not None else None)

    def read_lane(self, drive) -> MetaLane:
        def solo(item):
            vol, obj, vid = item
            fi = drive.read_version(vol, obj, vid)
            DATA_PATH.record_meta_read_round(1, 1)
            return fi

        rvm = getattr(drive, "read_version_many", None)

        def batch(items):
            out = rvm(items)
            DATA_PATH.record_meta_read_round(1, len(items))
            return out

        return self._lane(drive, "read", solo,
                          batch if rvm is not None else None)

    # -- submission ----------------------------------------------------------

    def submit_write(self, drive, vol: str, obj: str, fi) -> MetaHandle:
        return self.write_lane(drive).submit((vol, obj, fi))

    def submit_read(self, drive, vol: str, obj: str,
                    version_id: str) -> MetaHandle:
        return self.read_lane(drive).submit((vol, obj, version_id))

    # -- ignition signals ----------------------------------------------------

    def note_put(self, delta: int) -> None:
        with self._mu:
            self._inflight_puts += delta

    def note_read(self, delta: int) -> None:
        with self._mu:
            self._inflight_reads += delta

    def put_hot(self) -> bool:
        """Whether routing a small-PUT publish fan-out through the
        write lanes is likely to group-commit (vs. taxing a lone
        request with a scheduler handoff)."""
        return (self._inflight_puts > 1
                or any(lane.busy()
                       for (_, kind), (_, lane) in list(self._lanes.items())
                       if kind == "write"))

    def read_hot(self) -> bool:
        return (self._inflight_reads > 1
                or any(lane.busy()
                       for (_, kind), (_, lane) in list(self._lanes.items())
                       if kind == "read"))

    # -- lifecycle / introspection ------------------------------------------

    def close(self) -> None:
        with self._mu:
            self._closed = True
            lanes = [lane for _, lane in self._lanes.values()]
        for lane in lanes:
            lane.close()

    def stats(self) -> dict:
        out = {"dispatches": 0, "items": 0, "inline_ops": 0,
               "batch_faults": 0, "member_retries": 0, "max_items": 0,
               "lanes": 0}
        for _, lane in list(self._lanes.values()):
            st = lane.stats()
            out["lanes"] += 1
            for k in ("dispatches", "items", "inline_ops",
                      "batch_faults", "member_retries"):
                out[k] += st[k]
            out["max_items"] = max(out["max_items"], st["max_items"])
        out["occupancy"] = (out["items"] / out["dispatches"]
                            if out["dispatches"] else 0.0)
        return out


# -- process singleton -------------------------------------------------------

_MB: MetaBatcher | None = None
_MB_MU = threading.Lock()


def get() -> MetaBatcher:
    global _MB
    mb = _MB
    if mb is None:
        with _MB_MU:
            if _MB is None:
                _MB = MetaBatcher()
            mb = _MB
    return mb


def reset() -> None:
    """Tests: retire the singleton (its daemon threads exit) so flag
    changes start from cold lanes."""
    global _MB
    with _MB_MU:
        if _MB is not None:
            _MB.close()
        _MB = None


def _reset_after_fork() -> None:
    # A forked child inherits the parent's singleton OBJECT but not its
    # dispatcher threads — submits would queue forever.
    global _MB
    _MB = None


os.register_at_fork(after_in_child=_reset_after_fork)
