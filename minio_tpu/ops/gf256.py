"""GF(2^8) arithmetic for Reed-Solomon erasure coding.

Field: GF(2^8) with generator polynomial x^8 + x^4 + x^3 + x^2 + 1 (0x11D)
and generator element 2 — the same field as klauspost/reedsolomon (the codec
behind the reference's `Erasure` struct, /root/reference/cmd/erasure-coding.go:63),
so shard bytes produced here are interoperable with the reference on-disk format.

Two representations are maintained:

1. Byte-level log/exp and full 256x256 multiplication tables (numpy, host side)
   — used for matrix construction/inversion and the CPU oracle codec.
2. Bit-matrix decomposition: multiplication by a constant c is GF(2)-linear on
   the 8 bit-planes of the operand, i.e. y = M_c @ x (mod 2) for an 8x8 binary
   matrix M_c. This turns the entire (parity x data) GF(2^8) coding matmul into
   a ((8*parity) x (8*data)) binary matmul over bit-planes — which is exactly
   the shape the TPU MXU wants (see ops/erasure_jax.py / ops/erasure_pallas.py).
"""

from __future__ import annotations

import functools

import numpy as np

# Generator polynomial for GF(2^8): x^8+x^4+x^3+x^2+1.
POLY = 0x11D


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """Build exp/log tables for GF(2^8) with generator 2."""
    exp = np.zeros(512, dtype=np.uint8)  # doubled to avoid mod in hot paths
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = -1  # log(0) undefined; sentinel
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Multiply two field elements."""
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[LOG_TABLE[a] + LOG_TABLE[b]])


def gf_div(a: int, b: int) -> int:
    """Divide a by b in the field. b must be nonzero."""
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inv(a: int) -> int:
    """Multiplicative inverse."""
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(EXP_TABLE[255 - LOG_TABLE[a]])


def gf_exp(a: int, n: int) -> int:
    """a ** n in the field; matches klauspost galExp (a=0,n=0 -> 1)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


@functools.cache
def mul_table() -> np.ndarray:
    """Full 256x256 multiplication table, MUL[a, b] = a*b in GF(2^8)."""
    la = LOG_TABLE.copy()
    la[0] = 0
    s = la[:, None] + la[None, :]
    t = EXP_TABLE[s]
    t[0, :] = 0
    t[:, 0] = 0
    return t.astype(np.uint8)


# ---------------------------------------------------------------------------
# Vectorized numpy field ops on uint8 arrays.
# ---------------------------------------------------------------------------

def gf_mul_vec(c: int, x: np.ndarray) -> np.ndarray:
    """Multiply every byte of x by constant c."""
    if c == 0:
        return np.zeros_like(x)
    if c == 1:
        return x.copy()
    return mul_table()[c][x]


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix multiply: a (m,k) uint8 @ b (k,n) uint8 -> (m,n) uint8.

    Host-side reference path (small m,k; n can be large). XOR-accumulates
    table-lookup rows; used by the CPU oracle codec and matrix algebra.
    """
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    mt = mul_table()
    out = np.zeros((m, n), dtype=np.uint8)
    for i in range(m):
        acc = out[i]
        row = a[i]
        for j in range(k):
            c = row[j]
            if c == 0:
                continue
            acc ^= mt[c][b[j]]
        out[i] = acc
    return out


# ---------------------------------------------------------------------------
# Matrix algebra over GF(2^8) (small matrices, host side).
# ---------------------------------------------------------------------------

def gf_identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def gf_mat_invert(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix via Gauss-Jordan elimination.

    Raises ValueError if singular (matches klauspost errSingular behavior).
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    mt = mul_table()
    # Augmented [m | I] as int work array.
    work = np.concatenate([m.copy(), gf_identity(n)], axis=1)
    for r in range(n):
        if work[r, r] == 0:
            # Find a pivot row below.
            below = np.nonzero(work[r + 1:, r])[0]
            if below.size == 0:
                raise ValueError("singular matrix")
            swap = r + 1 + below[0]
            work[[r, swap]] = work[[swap, r]]
        # Scale pivot row to 1.
        pivot = int(work[r, r])
        if pivot != 1:
            inv = gf_inv(pivot)
            work[r] = mt[inv][work[r]]
        # Eliminate all other rows.
        for rr in range(n):
            if rr != r and work[rr, r] != 0:
                work[rr] ^= mt[int(work[rr, r])][work[r]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """Vandermonde matrix m[r, c] = r^c in GF(2^8) (klauspost `vandermonde`)."""
    m = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            m[r, c] = gf_exp(r, c)
    return m


@functools.cache
def build_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic coding matrix identical to klauspost/reedsolomon's default.

    Extended Vandermonde times the inverse of its top square: the top
    data_shards rows become the identity, the remaining rows are the parity
    coding rows. Any data_shards x data_shards submatrix is invertible.
    """
    vm = vandermonde(total_shards, data_shards)
    top = vm[:data_shards, :data_shards]
    top_inv = gf_mat_invert(top)
    return gf_matmul(vm, top_inv)


@functools.cache
def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (parity x data) rows of the systematic coding matrix."""
    full = build_matrix(data_shards, data_shards + parity_shards)
    return full[data_shards:, :].copy()


# ---------------------------------------------------------------------------
# Bit-matrix decomposition (the TPU-enabling transform).
# ---------------------------------------------------------------------------

@functools.cache
def _const_mul_bit_matrices() -> np.ndarray:
    """B[c] is the 8x8 GF(2) matrix of multiplication by c.

    Column j of B[c] is the byte c * 2^j as bits (LSB-first), because
    y = c*x = XOR_j x_j * (c * 2^j).
    Returned shape: (256, 8, 8) uint8 with B[c, i, j] = bit i of (c * 2^j).
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        for j in range(8):
            v = gf_mul(c, 1 << j)
            for i in range(8):
                out[c, i, j] = (v >> i) & 1
    return out


def expand_matrix_to_bits(gf_matrix: np.ndarray) -> np.ndarray:
    """Expand an (m, k) GF(2^8) matrix to the (8m, 8k) GF(2) bit matrix.

    With data bytes unpacked to bit-planes (row k*8+j = bit j of shard k),
    `bits_out = (expanded @ bits_in) mod 2` computes the GF(2^8) matmul.
    """
    gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gf_matrix.shape
    b = _const_mul_bit_matrices()
    out = np.zeros((8 * m, 8 * k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = b[gf_matrix[i, j]]
    return out


def unpack_bits(x: np.ndarray) -> np.ndarray:
    """(k, n) uint8 -> (8k, n) bit-planes, row k*8+j = bit j (LSB-first)."""
    k, n = x.shape
    planes = ((x[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None]) & 1)
    return planes.reshape(8 * k, n)


def pack_bits(bits: np.ndarray) -> np.ndarray:
    """(8m, n) bit-planes -> (m, n) uint8 (inverse of unpack_bits)."""
    m8, n = bits.shape
    assert m8 % 8 == 0
    b = bits.reshape(m8 // 8, 8, n).astype(np.uint8)
    weights = (1 << np.arange(8, dtype=np.uint16))[None, :, None]
    return (b.astype(np.uint16) * weights).sum(axis=1).astype(np.uint8)
