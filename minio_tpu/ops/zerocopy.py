"""Zero-copy socket transport: gather-writes and kernel file sends.

The serving path used to assemble every response in userspace — header
bytes through the BufferedWriter, body through a second write, ranged
hot-cache hits through a fresh bytes() slice — which on a permanently
1-core, GIL-bound host turns straight into CPU-seconds-per-GB (the
throughput ceiling, ISSUE 16).  This module is the transport half of
the MTPU_ZEROCOPY vertical:

* ``send_gather(sock, segments)`` — one ``socket.sendmsg`` carries the
  coalesced header block plus any number of body segments (bytes,
  memoryviews, ShmArena ndarray views) with a partial-send
  continuation loop and IOV_MAX chunking, so a k-segment response is
  one or two syscalls and the segments are never joined in userspace.
* ``send_file(sock, fd, runs)`` — ``os.sendfile`` of verified on-disk
  shard ranges (the k=1 "framing allows" case): object bytes go page
  cache -> socket without ever entering the process, with a pread
  fallback when sendfile is refused mid-stream.

Both map EPIPE/ECONNRESET ``OSError``s back to ``BrokenPipeError`` /
``ConnectionResetError`` so the server's existing quiet-499
client-disconnect handling covers the new syscall paths — a killed
client must never surface as a raw OSError traceback.

``MTPU_ZEROCOPY=0`` is the byte-identical oracle: every caller keeps
its buffered/copying path and tests assert both modes byte-exact
(tests/conftest.py zerocopy_mode).
"""

from __future__ import annotations

import errno
import os
import select

#: Linux UIO_MAXIOV is 1024; stay under it with headroom so a
#: many-segment response chunks instead of bouncing with EMSGSIZE.
IOV_MAX = 512

#: sendfile per-call cap: bounded so a slow client can't pin one
#: syscall forever (the kernel blocks until the socket buffer drains).
SENDFILE_CHUNK = 8 << 20

_DISCONNECT_ERRNOS = (errno.EPIPE, errno.ECONNRESET, errno.ESHUTDOWN,
                      errno.ETIMEDOUT)


def zerocopy_enabled() -> bool:
    """Default ON; =0 is the byte-identical buffered-write oracle,
    the same hot-path-flag contract as MTPU_GET_FASTPATH /
    MTPU_HOTCACHE.  Read per call so tests flip it live."""
    return os.environ.get("MTPU_ZEROCOPY", "1") != "0"


def _map_disconnect(e: OSError):
    """sendmsg/sendfile surface client disconnects as plain OSErrors;
    re-raise the two the server's 499 handling already catches."""
    if e.errno == errno.EPIPE or e.errno == errno.ESHUTDOWN:
        raise BrokenPipeError(e.errno, e.strerror or "broken pipe") from e
    if e.errno == errno.ECONNRESET:
        raise ConnectionResetError(e.errno,
                                   e.strerror or "connection reset") from e
    raise e


def send_gather(sock, segments) -> int:
    """Vectored send of `segments` (any buffer-protocol objects) via
    sendmsg: IOV_MAX chunking + partial-send continuation.  Returns
    total bytes sent; raises BrokenPipeError/ConnectionResetError on
    client disconnect."""
    iov = [memoryview(s).cast("B") for s in segments if len(s)]
    total = 0
    while iov:
        try:
            n = sock.sendmsg(iov[:IOV_MAX])
        except OSError as e:
            _map_disconnect(e)
        if n <= 0:
            raise BrokenPipeError(errno.EPIPE, "zero-length send")
        total += n
        # Continuation: drop fully-sent segments, slice the partial one.
        while iov and n >= len(iov[0]):
            n -= len(iov[0])
            iov.pop(0)
        if n:
            iov[0] = iov[0][n:]
    return total


def send_file(sock, fd: int, runs) -> int:
    """sendfile each (file_offset, length) run of `fd` to `sock`.

    Object bytes cross page cache -> socket in kernel space.  When the
    kernel refuses (EINVAL/ENOSYS/EOVERFLOW — e.g. an exotic fs or a
    non-stream socket) the remaining bytes of the run degrade to
    pread+sendall, so a response that already has its headers on the
    wire always completes.  Returns total payload bytes sent."""
    total = 0
    for off, ln in runs:
        sent = 0
        while sent < ln:
            want = min(ln - sent, SENDFILE_CHUNK)
            try:
                n = os.sendfile(sock.fileno(), fd, off + sent, want)
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    # Raw sendfile bypasses the socket-object timeout
                    # machinery: a full send buffer surfaces EAGAIN
                    # here.  Wait for writability under the socket's
                    # own timeout, then retry.
                    _wait_writable(sock)
                    continue
                if e.errno in (errno.EINVAL, errno.ENOSYS,
                               errno.EOVERFLOW, errno.ENOTSOCK):
                    _pread_send(sock, fd, off + sent, ln - sent)
                    sent = ln
                    break
                _map_disconnect(e)
            if n == 0:
                raise BrokenPipeError(errno.EPIPE,
                                      "sendfile hit EOF short")
            sent += n
        total += sent
    return total


def _wait_writable(sock) -> None:
    """Block until `sock` accepts more bytes, honoring its timeout —
    the wait socket.send would have done had the kernel call gone
    through the socket object instead of raw sendfile."""
    timeout = sock.gettimeout()
    _, w, _ = select.select((), (sock,), (), timeout)
    if not w:
        raise TimeoutError("timed out waiting for socket writability")


def _pread_send(sock, fd: int, off: int, ln: int) -> None:
    """Userspace fallback for one run (sendfile refused)."""
    sent = 0
    while sent < ln:
        chunk = os.pread(fd, min(ln - sent, SENDFILE_CHUNK), off + sent)
        if not chunk:
            raise BrokenPipeError(errno.EPIPE, "file truncated mid-send")
        try:
            sock.sendall(chunk)
        except OSError as e:
            _map_disconnect(e)
        sent += len(chunk)


class FilePlan:
    """One part's worth of verified, kernel-sendable byte runs.

    Carries an OPEN fd (dup'd from the verification pass) so the bytes
    sendfile will ship are the bytes that were digest-verified — a
    racing delete only unlinks the name, never this content.  The
    server closes it after the send; __del__ is the GC backstop for
    responses that never reach the writer (client vanished first).
    """

    __slots__ = ("fd", "runs", "nbytes")

    def __init__(self, fd: int, runs, nbytes: int):
        self.fd = fd
        self.runs = runs
        self.nbytes = nbytes

    def close(self) -> None:
        fd, self.fd = self.fd, -1
        if fd >= 0:
            try:
                os.close(fd)
            except OSError:
                pass

    def read_all(self) -> bytes:
        """Userspace materialization of the plan (the oracle/TLS path
        and tests): pread every run in order."""
        out = bytearray()
        for off, ln in self.runs:
            got = 0
            while got < ln:
                chunk = os.pread(self.fd, ln - got, off + got)
                if not chunk:
                    raise OSError(errno.EIO, "file truncated under plan")
                out += chunk
                got += len(chunk)
        return bytes(out)

    def __del__(self):
        self.close()
