"""CLI entry: `python -m minio_tpu.server --drives /tmp/d{1...4} --port 9001`.

The serverMain equivalent (/root/reference/cmd/server-main.go:441): expand
drive endpoints, run startup self-tests, build the object layer
(pools -> sets -> drives), start the S3 front door, serve until signalled.
Credentials come from MTPU_ROOT_USER / MTPU_ROOT_PASSWORD (the reference's
MINIO_ROOT_USER convention), defaulting to minioadmin/minioadmin.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def expand_ellipses(pattern: str) -> list[str]:
    """Expand `/tmp/d{1...4}` patterns
    (cf. cmd/endpoint-ellipses.go:341)."""
    from ..topology.endpoints import expand_one, has_ellipses
    if has_ellipses(pattern):
        return expand_one(pattern)
    return pattern.split()


def bucket_dns_from_env(host: str, port: int):
    """Federation wiring (the reference's MINIO_ETCD_ENDPOINTS +
    MINIO_DOMAIN convention): MTPU_ETCD_ENDPOINTS=host:port and
    MTPU_DOMAIN=cluster.domain enable bucket-DNS federation; absent ->
    standalone namespace (cf. cmd/etcd.go + internal/config/dns)."""
    ep = os.environ.get("MTPU_ETCD_ENDPOINTS", "")
    domain = os.environ.get("MTPU_DOMAIN", "")
    if not ep or not domain:
        return None
    from ..bucket.event_targets import _hostport
    from ..cluster.federation import BucketDNS, EtcdClient
    ehost, eport = _hostport(ep, 2379)   # handles http://, bare hosts
    try:
        return BucketDNS(EtcdClient(ehost, eport or 2379),
                         domain, host, port)
    except Exception as e:  # noqa: BLE001 — misconfig must be loud
        print(f"minio_tpu: federation config invalid: {e}",
              file=sys.stderr)
        raise SystemExit(2) from None


def parse_pool_paths(drive_groups: list[list[str]]) -> list[list[str]] | None:
    """Expand --drives groups into per-pool path lists; None on a
    mixed ellipsis/plain group (caller exits 2).

    Each --drives flag is one pool, and within a flag each
    space-separated ellipsis group is ALSO one pool — `--drives
    '/data{1...4} /newdata{1...4}'` is a two-pool deployment exactly
    like the reference's capacity-expansion syntax
    (cmd/endpoint-ellipses.go:341: one zone/pool per arg). Plain paths
    with no ellipses keep the legacy meaning: one pool over all."""
    from ..topology.endpoints import has_ellipses
    pool_paths: list[list[str]] = []
    for group in drive_groups:
        if len(group) > 1 and any(has_ellipses(a) for a in group):
            if not all(has_ellipses(a) for a in group):
                # The reference rejects mixed args too — a plain path
                # next to ellipsis pools would become a nonsensical
                # 1-drive pool.
                print("--drives: cannot mix ellipsis pool patterns "
                      f"with plain paths in one group: {group}",
                      file=sys.stderr)
                return None
            pool_paths.extend(expand_ellipses(a) for a in group)
        else:
            pool_paths.append(
                [p for a in group for p in expand_ellipses(a)])
    return pool_paths


def install_signal_handlers(stop) -> None:
    """SIGTERM and SIGINT both start a graceful drain (cmd/signals.go:
    the reference treats them identically); a SECOND signal of either
    kind forces immediate exit — the escape hatch when a drain hangs."""
    def _sig(signum, frame):
        if stop.is_set():
            try:
                os.write(2, b"minio_tpu: second signal, forcing exit\n")
            except OSError:
                pass
            os._exit(130 if signum == signal.SIGINT else 143)
        stop.set()
    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="minio_tpu.server")
    ap.add_argument("--drives", required=False, action="append",
                    default=None,
                    help="drive paths, ellipses ok: /tmp/d{1...4}; "
                         "repeat the flag to add a POOL (capacity "
                         "expansion) — each --drives is one pool")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--set-drive-count", type=int, default=None)
    ap.add_argument("--certs-dir",
                    default=os.environ.get("MTPU_CERTS_DIR", ""),
                    help="dir with public.crt/private.key -> serve HTTPS")
    args = ap.parse_args(argv)

    from .sigv4 import Credentials

    creds = Credentials(os.environ.get("MTPU_ROOT_USER", "minioadmin"),
                        os.environ.get("MTPU_ROOT_PASSWORD", "minioadmin"))
    # Each --drives flag is one endpoint group; within a group, args
    # are space-separated (a node list in cluster mode, or ellipsis
    # pool groups standalone).  MTPU_POOLS is the flag-free spelling
    # (containers, harnesses): semicolon-separated pools, each a
    # space-separated ellipsis group — appended after any --drives.
    drive_flags = list(args.drives or [])
    env_pools = os.environ.get("MTPU_POOLS", "")
    if env_pools:
        drive_flags.extend(p for p in env_pools.split(";") if p.strip())
    if not drive_flags:
        print("minio_tpu: --drives (or MTPU_POOLS) required",
              file=sys.stderr)
        return 2
    drive_groups = [g.split() for g in drive_flags]
    endpoint_args = [a for g in drive_groups for a in g]
    cluster_mode = any("://" in a for a in endpoint_args)

    certs = None
    if args.certs_dir:
        cert = os.path.join(args.certs_dir, "public.crt")
        key = os.path.join(args.certs_dir, "private.key")
        if not (os.path.exists(cert) and os.path.exists(key)):
            print(f"--certs-dir: missing {cert} or {key}",
                  file=sys.stderr)
            return 2
        certs = (cert, key)

    # Pre-fork worker pool (server/workers.py): MTPU_WORKERS=N forks N
    # SO_REUSEPORT HTTP workers plus one device-owner process.  The
    # branch sits BEFORE any engine/jax import — forking after XLA
    # spins up its thread pools is undefined behavior, so the
    # supervisor must stay light and each child builds its own stack.
    from .workers import nworkers_env
    nworkers = nworkers_env()
    if nworkers and cluster_mode:
        print("minio_tpu: MTPU_WORKERS ignored in cluster mode "
              "(one process per node)", file=sys.stderr, flush=True)
    elif nworkers:
        pool_paths = parse_pool_paths(drive_groups)
        if pool_paths is None:
            return 2
        from .workers import run_pool
        return run_pool(nworkers, pool_paths, creds, args.host,
                        args.port, args.set_drive_count, certs)

    # Startup self-test guards (hard-fail like cmd/erasure-coding.go:158,
    # cmd/bitrot.go:214).
    from ..ops.selftest import run_startup_self_tests
    run_startup_self_tests()

    from .server import S3Server

    if cluster_mode:
        # Distributed boot: URL endpoints, every node launched with the
        # same list (cf. serverMain distributed path,
        # cmd/server-main.go:441). The front door starts first; S3
        # serves 503 until format quorum + peer verify complete.
        from .cluster import boot_cluster_node

        if certs is not None and not all(
                a.startswith("https://") for a in endpoint_args):
            # TLS without https endpoints would serve the planes over
            # TLS while peers dial plaintext — fail loudly, don't
            # silently downgrade either side.
            print("--certs-dir requires https:// cluster endpoints",
                  file=sys.stderr)
            return 2
        if certs is None and any(a.startswith("https://")
                                 for a in endpoint_args):
            print("https:// endpoints require --certs-dir",
                  file=sys.stderr)
            return 2

        from ..bucket.notify import NotificationSystem

        def factory(node):
            srv = S3Server(None, creds, host=args.host, port=args.port,
                           rpc_router=node.router, certs=certs,
                           notify=NotificationSystem(),
                           bucket_dns=bucket_dns_from_env(
                               args.host, args.port)).start()
            print(f"minio_tpu cluster node on {srv.endpoint} "
                  f"(first={node.is_first}, "
                  f"{len(node.local_drives)} local / "
                  f"{len(node.endpoints)} total drives, "
                  f"set={node.set_drive_count}) — waiting for cluster",
                  flush=True)
            return srv

        import threading
        stop = threading.Event()
        install_signal_handlers(stop)
        while True:
            try:
                node, srv0, pools = boot_cluster_node(
                    drive_groups if len(drive_groups) > 1
                    else endpoint_args,
                    args.host, args.port, creds,
                    set_drive_count=args.set_drive_count,
                    server_factory=factory, certs_dir=args.certs_dir,
                    timeout=float(os.environ.get("MTPU_BOOT_TIMEOUT",
                                                 "120")))
            except Exception as e:  # noqa: BLE001
                print(f"minio_tpu: cluster boot failed: {e}",
                      file=sys.stderr, flush=True)
                return 1
            print(f"minio_tpu cluster node ready on {srv0.endpoint} "
                  f"(deployment ok)", flush=True)
            try:
                while not stop.wait(timeout=1.0):
                    if srv0.service_event:
                        break
            except KeyboardInterrupt:
                break
            if srv0.service_event == "restart" and not stop.is_set():
                # Full re-boot: tear down, rejoin the cluster (format
                # adopt + peer verify run again), same as the
                # standalone restart loop. Each boot builds a fresh
                # scanner; stop the outgoing one.
                print("minio_tpu: service restart requested", flush=True)
                srv0.shutdown()
                if srv0.scanner is not None:
                    srv0.scanner.stop()
                node.close()
                continue
            break
        # Cluster stop path: same drain as standalone — inflight
        # requests finish, heal/MRF checkpoint, then the node leaves.
        srv0.drain()
        srv0.shutdown()
        if srv0.scanner is not None:
            srv0.scanner.stop()
        node.close()
        return 0

    from ..engine.pools import ServerPools
    from ..engine.sets import ErasureSets
    from ..storage.drive import LocalDrive

    pool_paths = parse_pool_paths(drive_groups)
    if pool_paths is None:
        return 2
    from ..background.mrf import attach_mrf
    from ..storage.health_wrap import wrap_drives

    from ..storage.recovery import boot_recovery_sweep

    pool_sets: list[ErasureSets] = []
    swept = {"drives": 0, "tmp_entries": 0, "mp_stage": 0}
    for paths in pool_paths:
        # Health wrap at boot: per-API latency/error stats plus the
        # drive circuit breaker (ok -> suspect -> offline + background
        # probe), the xl-storage-disk-id-check.go:68 layering.
        local = [LocalDrive(p) for p in paths]
        # Boot-time recovery sweep BEFORE the engine takes traffic:
        # stale tmp/trash from the previous epoch, orphaned multipart
        # staging (cmd/prepare-storage.go role).
        rec = boot_recovery_sweep(local)
        for key in swept:
            swept[key] += rec[key]
        drives = wrap_drives(local)
        pool_sets.append(ErasureSets(
            drives,
            set_drive_count=args.set_drive_count or len(drives),
            deployment_id=(pool_sets[0].deployment_id
                           if pool_sets else None)))
    pools = ServerPools(pool_sets)
    if swept["tmp_entries"] or swept["mp_stage"]:
        print(f"minio_tpu: recovery sweep: {swept['tmp_entries']} stale "
              f"tmp entr(ies), {swept['mp_stage']} orphaned multipart "
              f"staging file(s) across {swept['drives']} drive(s)",
              flush=True)
    # MRF heal queues: writes that missed a breaker-offline drive heal
    # back to full width as soon as the drive recovers.  Journaled to
    # each pool's first drive so pending heals survive restarts.
    mrf_queues = attach_mrf(pools)
    replayed = sum(q.replayed for q in mrf_queues)
    if replayed:
        print(f"minio_tpu: MRF journal: replayed {replayed} pending "
              f"heal(s)", flush=True)
    # RAM hot-object tier (single-process: one private segment; the
    # pool path builds it pre-fork in WorkerPlane instead).
    from ..engine.hotcache import attach_pools as attach_hotcache
    if attach_hotcache(pools) is not None:
        print("minio_tpu: hot-object cache: "
              f"{pools.hot_tier.stats()['segment_bytes'] >> 20} MiB "
              "segment attached", flush=True)
    # Live-added pools survive a restart with stale --drives flags:
    # pool-topology.json (written by admin pool/add / decommission)
    # wins over the boot flags, and interrupted drains resume from
    # their journals — the kill-9 recovery path.
    from ..background.decom import resume_decommissions
    from .topology import adopt_topology
    adopted = adopt_topology(pools)
    if adopted:
        print(f"minio_tpu: topology: attached {adopted} live-added "
              f"pool(s)", flush=True)
    for d in resume_decommissions(pools):
        print(f"minio_tpu: resumed decommission of pool {d.pool_idx} "
              f"({d.state})", flush=True)

    # Full subsystem stack, the newAllSubsystems role
    # (cmd/server-main.go:441): IAM, scanner, notifications.
    from ..background.scanner import DataScanner
    from ..bucket.notify import NotificationSystem
    from ..bucket.replication import ReplicationPool
    from ..iam.iam import IAMSys
    iam = IAMSys(pools)
    # Replication journal replays BEFORE traffic — intents a kill-9
    # stranded re-enter the backlog here and drain once the persisted
    # bucket configs re-wire their targets.
    replication = ReplicationPool(pools)
    if replication.replayed:
        print(f"minio_tpu: replication journal: replayed "
              f"{replication.replayed} pending task(s)", flush=True)
    # Perpetual scanner lifecycle: an idle server crawls, accounts
    # usage, heals missing metadata, and bitrot-verifies every
    # deep_every-th cycle (cf. initDataScanner, cmd/server-main.go:441).
    # MTPU_SCANNER=0 disables it (deterministic-write harnesses: the
    # scanner's usage persistence writes through the same drive paths
    # the crash points instrument).
    scanner = (DataScanner(pools).start()
               if os.environ.get("MTPU_SCANNER", "1") != "0" else None)
    notify = NotificationSystem()
    # ILM/tiering plane: persisted tiers reload and the tier journal
    # replays BEFORE traffic — a kill-9 mid-transition resolves to
    # either the full hot version or a valid stub + tier object here.
    from ..bucket.tier import TierManager
    tier_mgr = TierManager(pools)
    replay = getattr(tier_mgr, "journal", None)
    if tier_mgr.counters.get("replayed"):
        print(f"minio_tpu: tier journal: replayed "
              f"{tier_mgr.counters['replayed']} record(s) "
              f"({tier_mgr.counters['orphans_reaped']} orphan(s) "
              f"reaped), {replay.pending() if replay else 0} pending",
              flush=True)

    import threading
    stop = threading.Event()
    install_signal_handlers(stop)
    port = args.port
    while True:
        srv = S3Server(pools, creds, host=args.host, port=port,
                       iam=iam, scanner=scanner, notify=notify,
                       replication=replication, certs=certs,
                       tier_mgr=tier_mgr,
                       bucket_dns=bucket_dns_from_env(args.host,
                                                      port)).start()
        port = srv.port                  # keep the port across restarts
        if srv.bucket_dns is not None:
            # SRV records must advertise the BOUND port (--port 0
            # binds an ephemeral one)
            srv.bucket_dns.my_port = srv.port
        n_drives = sum(len(p) for p in pool_paths)
        desc = ", ".join(f"pool{i}: {len(p)} drives "
                         f"set={pool_sets[i].set_drive_count}"
                         for i, p in enumerate(pool_paths)) \
            if len(pool_paths) > 1 else \
            f"{n_drives} drives, set={pool_sets[0].set_drive_count}"
        print(f"minio_tpu server on {srv.endpoint} ({desc})",
              flush=True)
        try:
            # Event.wait is race-free against a signal arriving between
            # the check and the sleep (unlike signal.pause()); the admin
            # service endpoint shuts the listener down itself, flagged
            # via service_event.
            while not stop.wait(timeout=1.0):
                if srv.service_event:
                    break
        except KeyboardInterrupt:
            break
        if srv.service_event == "restart" and not stop.is_set():
            print("minio_tpu: service restart requested", flush=True)
            srv.service_event = ""
            # The admin handler schedules its own shutdown ~0.25 s out;
            # join it here so the port is released before rebinding
            # (shutdown is idempotent).
            srv.shutdown()
            continue             # scanner keeps running across restarts
        break
    # Graceful exit: drain (503 new requests, finish inflight, flush
    # digest lanes, checkpoint heal frontier + MRF journal), THEN drop
    # the listener and stop the background machinery.
    srv.drain()
    srv.shutdown()
    if scanner is not None:
        scanner.stop()
    for q in mrf_queues:
        q.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
