"""CLI entry: `python -m minio_tpu.server --drives /tmp/d{1...4} --port 9001`.

The serverMain equivalent (/root/reference/cmd/server-main.go:441): expand
drive endpoints, run startup self-tests, build the object layer
(pools -> sets -> drives), start the S3 front door, serve until signalled.
Credentials come from MTPU_ROOT_USER / MTPU_ROOT_PASSWORD (the reference's
MINIO_ROOT_USER convention), defaulting to minioadmin/minioadmin.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def expand_ellipses(pattern: str) -> list[str]:
    """Expand `/tmp/d{1...4}` patterns
    (cf. cmd/endpoint-ellipses.go:341)."""
    from ..topology.endpoints import expand_one, has_ellipses
    if has_ellipses(pattern):
        return expand_one(pattern)
    return pattern.split()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="minio_tpu.server")
    ap.add_argument("--drives", required=True,
                    help="drive paths, ellipses ok: /tmp/d{1...4}")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--set-drive-count", type=int, default=None)
    ap.add_argument("--certs-dir",
                    default=os.environ.get("MTPU_CERTS_DIR", ""),
                    help="dir with public.crt/private.key -> serve HTTPS")
    args = ap.parse_args(argv)

    # Startup self-test guards (hard-fail like cmd/erasure-coding.go:158,
    # cmd/bitrot.go:214).
    from ..ops.selftest import run_startup_self_tests
    run_startup_self_tests()

    from ..engine.pools import ServerPools
    from ..engine.sets import ErasureSets
    from ..storage.drive import LocalDrive
    from .server import S3Server
    from .sigv4 import Credentials

    paths = expand_ellipses(args.drives)
    drives = [LocalDrive(p) for p in paths]
    sets = ErasureSets(drives,
                       set_drive_count=args.set_drive_count or len(drives))
    pools = ServerPools([sets])
    creds = Credentials(os.environ.get("MTPU_ROOT_USER", "minioadmin"),
                        os.environ.get("MTPU_ROOT_PASSWORD", "minioadmin"))

    # Full subsystem stack, the newAllSubsystems role
    # (cmd/server-main.go:441): IAM, scanner, notifications.
    from ..background.scanner import DataScanner
    from ..bucket.notify import NotificationSystem
    from ..iam.iam import IAMSys
    iam = IAMSys(pools)
    scanner = DataScanner(pools)
    notify = NotificationSystem()

    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    certs = None
    if args.certs_dir:
        cert = os.path.join(args.certs_dir, "public.crt")
        key = os.path.join(args.certs_dir, "private.key")
        if not (os.path.exists(cert) and os.path.exists(key)):
            print(f"--certs-dir: missing {cert} or {key}",
                  file=sys.stderr)
            return 2
        certs = (cert, key)

    port = args.port
    while True:
        srv = S3Server(pools, creds, host=args.host, port=port,
                       iam=iam, scanner=scanner, notify=notify,
                       certs=certs).start()
        port = srv.port                  # keep the port across restarts
        print(f"minio_tpu server on {srv.endpoint} "
              f"({len(paths)} drives, set={sets.set_drive_count})",
              flush=True)
        try:
            # Event.wait is race-free against a signal arriving between
            # the check and the sleep (unlike signal.pause()); the admin
            # service endpoint shuts the listener down itself, flagged
            # via service_event.
            while not stop.wait(timeout=1.0):
                if srv.service_event:
                    break
        except KeyboardInterrupt:
            break
        if srv.service_event == "restart" and not stop.is_set():
            print("minio_tpu: service restart requested", flush=True)
            srv.service_event = ""
            # The admin handler schedules its own shutdown ~0.25 s out;
            # join it here so the port is released before rebinding
            # (shutdown is idempotent).
            srv.shutdown()
            continue
        break
    srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
