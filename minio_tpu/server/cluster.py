"""Multi-node cluster bootstrap: URL endpoints -> a serving node.

The serverMain distributed path (/root/reference/cmd/server-main.go:441 +
cmd/prepare-storage.go:298 + cmd/bootstrap-peer-server.go): every node
is launched with the SAME endpoint list (`http://host{1...N}/drive{1...M}`)
plus its own address; it

1. starts its front door FIRST (S3 + all RPC planes on one port, routed
   by path — cmd/routers.go:27-39) so peers can reach its storage plane
   while it waits,
2. waits for format quorum: the FIRST node (owner of endpoint[0])
   formats the whole deployment — local drives directly, remote drives
   through the storage plane — while every other node polls until the
   format lands on its local drives (the reference's firstDisk /
   errNotFirstDisk retry loop),
3. verifies cluster config against every peer (deployment id, layout
   hash, root access key — verifyServerSystemConfig),
4. builds the mixed Local/Remote erasure sets with a dsync-backed
   namespace lock over one locker per node, and binds the object layer.

The RPC bearer token is derived from the root credentials, so nodes
booted with the same MTPU_ROOT_USER/PASSWORD authenticate to each other
and nothing else does (the reference signs internode requests with the
root credentials the same way).
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time

from ..cluster.local_locker import LocalLocker
from ..cluster.nslock import NSLockMap
from ..rpc.lock_rpc import RemoteLocker, register_lock_rpc
from ..rpc.peer_rpc import (NotificationSys, PeerRegistry,
                            register_bootstrap_rpc, register_peer_rpc,
                            verify_cluster_config)
from ..rpc.rest import RPCClient, RPCRouter
from ..rpc.storage_rpc import RemoteDrive, register_storage_rpc
from ..storage.drive import LocalDrive
from ..storage.errors import StorageError
from ..storage.format import load_format
from ..topology.endpoints import Endpoint, parse_cluster_pools


class ClusterBootError(RuntimeError):
    pass


def internode_token(secret_key: str) -> str:
    """Shared-credential bearer token for the RPC planes."""
    return hmac.new(secret_key.encode(), b"mtpu-internode",
                    hashlib.sha256).hexdigest()


def layout_digest(pools: list[tuple[list[Endpoint], int]]) -> str:
    """Every node must agree on the global pool/drive order — a node
    booted with a reordered endpoint list (or different pool grouping)
    would place shards wrong."""
    h = hashlib.sha256()
    for eps, size in pools:
        for ep in eps:
            h.update(repr(ep).encode())
            h.update(b"\x00")
        h.update(str(size).encode())
        h.update(b"\x01")
    return h.hexdigest()


class ClusterNode:
    """One server process's view of the deployment."""

    def __init__(self, endpoint_args: list[str], my_host: str,
                 my_port: int, creds, set_drive_count: int | None = None,
                 certs_dir: str = ""):
        self.creds = creds
        self.token = internode_token(creds.secret_key)
        # endpoint_args: either a flat arg list (ONE pool spanning its
        # nodes — the legacy cluster syntax, where each arg is one
        # node's drive pattern) or a list of GROUPS, each group one
        # POOL (capacity-expansion: the CLI maps one --drives flag per
        # pool). The flat endpoint list keeps storage-plane drive order.
        if endpoint_args and isinstance(endpoint_args[0], str):
            pool_groups = [list(endpoint_args)]
        else:
            pool_groups = [list(g) for g in endpoint_args]
        pools, nodes = parse_cluster_pools(pool_groups, set_drive_count)
        self.pools = pools
        eps = [ep for pool_eps, _ in pools for ep in pool_eps]
        size = pools[0][1]
        # https endpoints: peers are dialed over TLS, trusting the
        # deployment cert (shared certs dir — the reference trusts
        # certs/CAs the same way).
        tls_ctx = None
        if eps and eps[0].scheme == "https":
            import ssl
            tls_ctx = ssl.create_default_context()
            ca = f"{certs_dir}/public.crt" if certs_dir else ""
            import os as _os
            if ca and _os.path.exists(ca):
                tls_ctx.load_verify_locations(ca)
            tls_ctx.check_hostname = False
        self.tls_context = tls_ctx
        self.endpoints = eps
        self.set_drive_count = size
        self.nodes = nodes
        self.my_host, self.my_port = my_host, my_port
        mine = [ep.is_local(my_host, my_port) for ep in eps]
        if not any(mine):
            raise ClusterBootError(
                f"none of the endpoints are local to "
                f"{my_host}:{my_port}")
        # Node identity = the node entry owning my first local endpoint.
        self.my_node = next(ep.node for ep, m in zip(eps, mine) if m)
        self.is_first = eps[0].is_local(my_host, my_port)

        # Per-node local endpoint lists, in global order: drive_idx on
        # the storage plane is the position within the SERVING node's
        # list, which every node derives identically from the shared
        # endpoint list.
        self.node_locals: dict[tuple[str, int], list[Endpoint]] = {}
        for ep in eps:
            self.node_locals.setdefault(ep.node, []).append(ep)

        # My drives (served to peers + used directly), health-wrapped so
        # the circuit breaker trips on the node that OWNS the drive —
        # peers then see fast ErrDiskNotFound over the wire instead of
        # each discovering the sick drive independently.
        from ..storage.health_wrap import wrap_drives
        self.local_drives = wrap_drives(
            [LocalDrive(ep.path) for ep in eps
             if ep.is_local(my_host, my_port)])
        # Boot-time recovery sweep, each node for its own disks: stale
        # tmp/trash from a dead epoch and orphaned multipart staging go
        # before the cluster format/verify phases take traffic.
        from ..storage.recovery import boot_recovery_sweep
        boot_recovery_sweep(self.local_drives)

        # Peers (every node but me).
        self.peer_clients: dict[tuple[str, int], RPCClient] = {
            node: RPCClient(f"{node[0]}:{node[1]}", self.token,
                            check_interval=1.0,
                            tls_context=self.tls_context)
            for node in nodes if node != self.my_node}

        # The router every plane mounts on (served under the S3 port).
        self.router = RPCRouter(self.token)
        register_storage_rpc(self.router, self.local_drives)
        self.locker = LocalLocker()
        register_lock_rpc(self.router, self.locker)
        self.peer_registry = PeerRegistry()
        register_peer_rpc(self.router, self.peer_registry)
        self.layout_sha = layout_digest(pools)
        # Mutated in place after wait_format adds the deployment id —
        # the verify handler only enforces keys it already knows, so a
        # peer that has not formatted yet is lenient about the id and
        # strict once it has one.
        self.bootstrap_expected = {
            "layout_sha": self.layout_sha,
            "access_key": creds.access_key}
        register_bootstrap_rpc(self.router, self.bootstrap_expected)
        self.notification = NotificationSys(
            list(self.peer_clients.values()))

    def close(self) -> None:
        """Stop peer health-check loops (restart/shutdown path)."""
        for cli in self.peer_clients.values():
            cli.close()
        for q in getattr(self, "mrf_queues", []):
            q.stop()

    # -- drive construction --------------------------------------------------

    def build_drives(self) -> list:
        """The global drive list: LocalDrive for mine, RemoteDrive for
        every other node's, in endpoint order.

        Remote drives get their own client-side HealthWrappedDrive
        breaker (the reference health-checks its storage REST clients
        the same way, cmd/storage-rest-client.go): a partitioned peer
        trips OK->SUSPECT->OFFLINE HERE, so reads fan out to parity
        spares and writes feed the MRF queue without every request
        first eating a transport timeout.  The wrapper's __class__
        spoof keeps isinstance gates honest — a wrapped RemoteDrive
        still reports as RemoteDrive, so local-only fast paths (serial
        fan-out, mmap views) stay off."""
        from ..storage.health_wrap import HealthWrappedDrive
        out = []
        local_iter = iter(self.local_drives)
        for ep in self.endpoints:
            if ep.is_local(self.my_host, self.my_port):
                out.append(next(local_iter))
            else:
                cli = self.peer_clients[ep.node]
                idx = self.node_locals[ep.node].index(ep)
                out.append(HealthWrappedDrive(
                    RemoteDrive(cli, idx, path=repr(ep))))
        return out

    # -- liveness ------------------------------------------------------------

    def peer_info(self) -> list[dict]:
        """Per-peer liveness rows (admin-info "peers" section and the
        mtpu_peer_* gauges): endpoint, online/offline, transition count,
        last-answer staleness, adaptive RPC deadline."""
        return [cli.peer_info() for cli in self.peer_clients.values()]

    # -- format phase --------------------------------------------------------

    def _pool_slices(self, drives: list) -> list[list]:
        """Slice the flat drive list back into per-pool lists."""
        out, off = [], 0
        for eps, _ in self.pools:
            out.append(drives[off:off + len(eps)])
            off += len(eps)
        return out

    def _pool_rows(self, drives: list) -> list[list[list]]:
        """Per-pool set rows: pool p chunked by ITS set size."""
        rows = []
        for (eps, k), pool_drives in zip(self.pools,
                                         self._pool_slices(drives)):
            rows.append([pool_drives[i:i + k]
                         for i in range(0, len(pool_drives), k)])
        return rows

    def _format_all_pools(self, drives: list) -> list[dict]:
        """Format/adopt every pool; pool 0 mints the deployment id, the
        rest share it (the reference's multi-pool format path keeps one
        deployment id across zones)."""
        from ..storage.format import init_format_sets
        fmts = []
        dep_id = None
        for rows in self._pool_rows(drives):
            fmt = init_format_sets(rows, deployment_id=dep_id)
            dep_id = fmt["id"]
            fmts.append(fmt)
        return fmts

    def wait_format(self, drives: list, timeout: float = 60.0,
                    poll: float = 0.3) -> list[dict]:
        """Format-quorum wait -> per-pool reference formats (one per
        pool, shared deployment id).

        First node: formats the whole deployment once every drive
        answers (fresh format needs ALL drives — the reference prints
        "Waiting for all other servers to be online" in exactly this
        loop); an already-formatted deployment loads at QUORUM, so one
        dead peer never blocks a restart. Other nodes: poll ANY of
        their local drives until the first node's format lands — only
        one surviving formatted local drive is needed, the rest heal
        into their recorded slots (errNotFirstDisk retry,
        cmd/prepare-storage.go:298)."""
        deadline = time.monotonic() + timeout
        last_err: Exception | None = None
        while time.monotonic() < deadline:
            if self.is_first:
                try:
                    return self._format_all_pools(drives)
                except StorageError as e:
                    last_err = e          # peers not all up yet: retry
            else:
                fmt = None
                for d in self.local_drives:
                    try:
                        fmt = load_format(d)
                    except StorageError as e:
                        last_err = e
                    if fmt is not None:
                        break
                if fmt is not None:
                    # Adopt + verify my position; heals my unformatted
                    # drives into their recorded slots.
                    try:
                        return self._format_all_pools(drives)
                    except StorageError as e:
                        last_err = e
            time.sleep(poll)
        raise ClusterBootError(
            f"format quorum not reached in {timeout:.0f}s "
            f"(first={self.is_first}): {last_err}")

    def wait_peers_verified(self, deployment_id: str,
                            timeout: float = 60.0,
                            poll: float = 0.3) -> None:
        """Every peer must agree on layout + credentials before we
        serve (verifyServerSystemConfig, cmd/bootstrap-peer-server.go).
        Peers still booting are retried until the deadline."""
        from ..rpc.rest import RPCVersionMismatch
        from ..storage.errors import ErrFileAccessDenied
        self.bootstrap_expected["deployment_id"] = deployment_id
        check = dict(self.bootstrap_expected)
        deadline = time.monotonic() + timeout
        clients = list(self.peer_clients.values())
        while True:
            bad = verify_cluster_config(clients, check)
            # Hard deployment errors fail FAST with the real cause:
            # a config mismatch response, a 403 (different root
            # credentials -> different bearer token), or a plane
            # version mismatch (mixed binaries). Only transport
            # errors mean "peer still booting".
            hard = [b for b in bad
                    if not isinstance(b[1], Exception)
                    or isinstance(b[1], (ErrFileAccessDenied,
                                         RPCVersionMismatch))]
            if hard:
                who = ", ".join(f"{c.host}:{c.port} {info}"
                                for c, info in hard)
                raise ClusterBootError(
                    f"cluster config mismatch: {who}")
            if not bad:
                return
            if time.monotonic() >= deadline:
                who = ", ".join(f"{c.host}:{c.port}" for c, _ in bad)
                raise ClusterBootError(
                    f"peers unreachable for bootstrap verify: {who}")
            time.sleep(poll)

    # -- object layer --------------------------------------------------------

    def build_object_layer(self, drives: list, default_parity=None,
                           fmt: list[dict] | None = None):
        """Mixed-drive sets with a cluster-wide namespace lock: dsync
        over one locker per NODE (mine direct, peers via the lock
        plane), the reference's granularity
        (cmd/namespace-lock.go:224). `fmt` is the per-pool format list
        wait_format already loaded — skips a second full-deployment
        scan. One ErasureSets per pool -> ServerPools."""
        from ..engine.pools import ServerPools
        from ..engine.sets import ErasureSets
        lockers = [self.locker] + [RemoteLocker(cli)
                                   for cli in self.peer_clients.values()]
        nslock = NSLockMap(lockers=lockers if self.peer_clients else None)
        fmts = fmt if fmt is not None else [None] * len(self.pools)
        pool_sets = []
        for (eps, size), pool_drives, pf in zip(
                self.pools, self._pool_slices(drives), fmts):
            pool_sets.append(ErasureSets(
                pool_drives, set_drive_count=size,
                default_parity=default_parity, nslock=nslock,
                preloaded_format=pf,
                deployment_id=(pool_sets[0].deployment_id
                               if pool_sets else None)))
        self.nslock = nslock
        return ServerPools(pool_sets)


def boot_cluster_node(endpoint_args: list[str], my_host: str,
                      my_port: int, creds,
                      set_drive_count: int | None = None,
                      server_factory=None, timeout: float = 60.0,
                      certs_dir: str = ""):
    """Full boot sequence -> (node, server, pools).

    server_factory(node) must return a STARTED S3Server with
    node.router mounted (the CLI passes its own; tests can wrap)."""
    node = ClusterNode(endpoint_args, my_host, my_port, creds,
                       set_drive_count, certs_dir=certs_dir)
    server = server_factory(node)
    # Admin-info and /metrics surface peer liveness through this back
    # reference (peers aren't reachable from the pools object).
    server.cluster_node = node
    # Obs verbs need the server back-reference (they snapshot the whole
    # node through it), so they mount here, not in ClusterNode.__init__.
    from ..rpc.peer_rpc import register_obs_rpc
    register_obs_rpc(node.router, server)
    try:
        drives = node.build_drives()
        fmt = node.wait_format(drives, timeout=timeout)
        node.wait_peers_verified(fmt[0]["id"], timeout=timeout)
        pools = node.build_object_layer(drives, fmt=fmt)
        from ..background.mrf import attach_mrf
        from ..background.scanner import DataScanner
        from ..iam.iam import IAMSys
        node.mrf_queues = attach_mrf(pools)
        iam = IAMSys(pools)
        node.peer_registry.on_reload("iam", iam.load)
        import os as _os
        scanner = (DataScanner(pools).start()
                   if _os.environ.get("MTPU_SCANNER", "1") != "0"
                   else None)
        server.bind_object_layer(pools, iam=iam, scanner=scanner)
        return node, server, pools
    except Exception:
        server.shutdown()
        node.close()
        raise
