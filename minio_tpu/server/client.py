"""Minimal signed S3 client — the test harness's `mc` analogue.

Signs every request with the same sigv4 module the server verifies with
is NOT circular: the signer follows the public SigV4 spec from the client
side (canonicalizing real HTTP bytes on the wire), so a mismatch in either
direction fails the round-trip tests. Used by tests and (later) internal
tooling.
"""

from __future__ import annotations

import http.client
import urllib.parse
import xml.etree.ElementTree as ET

from .sigv4 import Credentials, sign_request


class S3ClientError(Exception):
    def __init__(self, status: int, code: str, message: str):
        self.status = status
        self.code = code
        self.message = message
        super().__init__(f"{status} {code}: {message}")


class S3Client:
    def __init__(self, endpoint: str, access_key: str, secret_key: str,
                 region: str = "us-east-1", verify_tls: bool = True):
        u = urllib.parse.urlsplit(endpoint)
        self.host = u.hostname
        self.tls = u.scheme == "https"
        self.port = u.port or (443 if self.tls else 80)
        self.verify_tls = verify_tls
        self.creds = Credentials(access_key, secret_key, region)
        self._ssl_ctx = None             # built once, lazily

    def _connect(self, timeout: float = 60):
        if not self.tls:
            return http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout)
        if self._ssl_ctx is None:
            import ssl
            ctx = ssl.create_default_context()
            if not self.verify_tls:
                # explicit opt-out only (tests with self-signed certs)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl_ctx = ctx
        return http.client.HTTPSConnection(self.host, self.port,
                                           timeout=timeout,
                                           context=self._ssl_ctx)

    # -- core ----------------------------------------------------------------

    def request(self, method: str, path: str,
                query: dict[str, str] | None = None,
                body: bytes = b"", headers: dict[str, str] | None = None,
                raw_query: str | None = None):
        q = {k: [v] for k, v in (query or {}).items()}
        headers = dict(headers or {})
        headers["Host"] = f"{self.host}:{self.port}"
        # Sign over the DECODED path; send the percent-encoded form on the
        # wire (keys with spaces/non-ASCII would otherwise break the
        # request line and the signature).
        wire_path = urllib.parse.quote(path, safe="/~-._")
        if raw_query is None:
            auth = sign_request(self.creds, method, path, q, headers, body)
            headers.update(auth)
            qs = urllib.parse.urlencode({k: v[0] for k, v in q.items()})
            url = wire_path + ("?" + qs if qs else "")
        else:
            url = wire_path + "?" + raw_query
        conn = self._connect(60)
        try:
            conn.request(method, url, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def put_object_stream(self, bucket: str, key: str, reader, size: int,
                          headers: dict[str, str] | None = None) -> dict:
        """Streamed PUT: body is a .read(n) reader sent with
        Content-Length and an UNSIGNED-PAYLOAD signature — the body
        never materializes client- or server-side."""
        path = f"/{bucket}/{key}"
        headers = dict(headers or {})
        headers["Host"] = f"{self.host}:{self.port}"
        headers["Content-Length"] = str(size)
        auth = sign_request(self.creds, "PUT", path, {}, headers,
                            "UNSIGNED-PAYLOAD")
        headers.update(auth)
        wire_path = urllib.parse.quote(path, safe="/~-._")
        conn = self._connect(120)
        try:
            conn.request("PUT", wire_path, body=reader, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            _, h, _ = self._check(resp.status, dict(resp.getheaders()),
                                  data)
            return h
        finally:
            conn.close()

    def get_object_stream(self, bucket: str, key: str,
                          chunk_size: int = 1 << 20):
        """Streamed GET: yields body chunks as they arrive."""
        path = f"/{bucket}/{key}"
        headers = {"Host": f"{self.host}:{self.port}"}
        auth = sign_request(self.creds, "GET", path, {}, headers, b"")
        headers.update(auth)
        wire_path = urllib.parse.quote(path, safe="/~-._")
        conn = self._connect(120)
        try:
            conn.request("GET", wire_path, headers=headers)
            resp = conn.getresponse()
            if resp.status not in (200, 206):
                body = resp.read()
                self._check(resp.status, dict(resp.getheaders()), body)
            while True:
                piece = resp.read(chunk_size)
                if not piece:
                    return
                yield piece
        finally:
            conn.close()

    def _check(self, status, headers, data, ok=(200, 204, 206)):
        if status in ok:
            return status, headers, data
        code, msg = "Unknown", ""
        try:
            root = ET.fromstring(data)
            code = root.findtext("Code", "Unknown")
            msg = root.findtext("Message", "")
        except ET.ParseError:
            pass
        raise S3ClientError(status, code, msg)

    # -- buckets -------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self._check(*self.request("PUT", f"/{bucket}"))

    def delete_bucket(self, bucket: str) -> None:
        self._check(*self.request("DELETE", f"/{bucket}"))

    def bucket_exists(self, bucket: str) -> bool:
        status, _, _ = self.request("HEAD", f"/{bucket}")
        return status == 200

    def list_buckets(self) -> list[str]:
        _, _, data = self._check(*self.request("GET", "/"))
        root = ET.fromstring(data)
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        return [b.findtext(f"{ns}Name") or b.findtext("Name")
                for b in root.iter(f"{ns}Bucket")] or \
               [b.findtext("Name") for b in root.iter("Bucket")]

    def set_versioning(self, bucket: str, enabled: bool) -> None:
        status = "Enabled" if enabled else "Suspended"
        body = (f'<VersioningConfiguration><Status>{status}</Status>'
                f'</VersioningConfiguration>').encode()
        self._check(*self.request("PUT", f"/{bucket}",
                                  query={"versioning": ""}, body=body))

    # -- objects -------------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes,
                   headers: dict | None = None) -> dict:
        _, h, _ = self._check(
            *self.request("PUT", f"/{bucket}/{key}", body=data,
                          headers=headers))
        return h

    def get_object(self, bucket: str, key: str,
                   range_: tuple[int, int] | None = None,
                   version_id: str = "") -> bytes:
        headers = {}
        if range_:
            headers["Range"] = f"bytes={range_[0]}-{range_[1]}"
        q = {"versionId": version_id} if version_id else None
        _, _, data = self._check(
            *self.request("GET", f"/{bucket}/{key}", query=q,
                          headers=headers))
        return data

    def head_object(self, bucket: str, key: str) -> dict:
        status, h, data = self.request("HEAD", f"/{bucket}/{key}")
        if status != 200:
            raise S3ClientError(status, "HeadFailed", "")
        return h

    def delete_object(self, bucket: str, key: str,
                      version_id: str = "") -> dict:
        q = {"versionId": version_id} if version_id else None
        _, h, _ = self._check(
            *self.request("DELETE", f"/{bucket}/{key}", query=q))
        return h

    def copy_object(self, src_bucket: str, src_key: str, dst_bucket: str,
                    dst_key: str) -> None:
        self._check(*self.request(
            "PUT", f"/{dst_bucket}/{dst_key}",
            headers={"x-amz-copy-source": f"/{src_bucket}/{src_key}"}))

    def list_objects(self, bucket: str, prefix: str = "",
                     delimiter: str = "", v2: bool = True,
                     start_after: str = "", max_keys: int = 0):
        """Listing that follows truncation markers (v2 continuation
        tokens, v1 NextMarker/last-key) so a remote capping responses
        at 1000 keys still yields every key. max_keys > 0 bounds the
        result AND is pushed to the remote, stopping the pagination
        loop as soon as enough keys arrived (paged gateway walks must
        not refetch the whole remainder per page)."""
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        keys: list[str] = []
        prefixes: list[str] = []
        token = ""
        marker = ""
        while True:
            q = {"prefix": prefix}
            if v2:
                q["list-type"] = "2"
            if delimiter:
                q["delimiter"] = delimiter
            if max_keys > 0:
                q["max-keys"] = str(max_keys - len(keys))
            if v2 and start_after:
                q["start-after"] = start_after
            if not v2 and (marker or start_after):
                q["marker"] = marker or start_after
            if token:
                q["continuation-token"] = token
            _, _, data = self._check(*self.request("GET", f"/{bucket}",
                                                   query=q))
            root = ET.fromstring(data)
            page = [c.findtext(f"{ns}Key")
                    for c in root.iter(f"{ns}Contents")]
            keys += page
            prefixes += [c.findtext(f"{ns}Prefix")
                         for c in root.iter(f"{ns}CommonPrefixes")]
            truncated = root.findtext(f"{ns}IsTruncated") == "true"
            token = root.findtext(f"{ns}NextContinuationToken") or ""
            marker = (root.findtext(f"{ns}NextMarker")
                      or (page[-1] if page else ""))
            if max_keys > 0 and len(keys) >= max_keys:
                return keys[:max_keys], prefixes
            if not truncated or not (token if v2 else marker):
                return keys, prefixes

    def delete_objects(self, bucket: str, keys: list[str]):
        objs = "".join(f"<Object><Key>{k}</Key></Object>" for k in keys)
        body = f"<Delete>{objs}</Delete>".encode()
        _, _, data = self._check(*self.request(
            "POST", f"/{bucket}", query={"delete": ""}, body=body))
        return data

    # -- multipart -----------------------------------------------------------

    def create_multipart(self, bucket: str, key: str) -> str:
        _, _, data = self._check(*self.request(
            "POST", f"/{bucket}/{key}", query={"uploads": ""}))
        root = ET.fromstring(data)
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        return root.findtext(f"{ns}UploadId") or root.findtext("UploadId")

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        _, h, _ = self._check(*self.request(
            "PUT", f"/{bucket}/{key}",
            query={"partNumber": str(part_number), "uploadId": upload_id},
            body=data))
        return h.get("ETag", "").strip('"')

    def complete_multipart(self, bucket: str, key: str, upload_id: str,
                           parts: list[tuple[int, str]]) -> None:
        inner = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>\"{e}\"</ETag></Part>"
            for n, e in parts)
        body = f"<CompleteMultipartUpload>{inner}</CompleteMultipartUpload>" \
            .encode()
        self._check(*self.request(
            "POST", f"/{bucket}/{key}", query={"uploadId": upload_id},
            body=body))

    def abort_multipart(self, bucket: str, key: str, upload_id: str) -> None:
        self._check(*self.request(
            "DELETE", f"/{bucket}/{key}", query={"uploadId": upload_id}))
