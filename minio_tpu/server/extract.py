"""Snowball auto-extract (tar PUT) and serving files inside zip objects.

- PUT with `X-Amz-Meta-Snowball-Auto-Extract: true` and a tar body
  explodes the archive into individual objects under the key prefix
  (cf. PutObjectExtract / untar, cmd/untar.go:100). gzip/bzip2/xz tars
  are handled by tarfile transparently.
- GET with `x-minio-extract: true` on `bucket/archive.zip/inner/path`
  serves the zip member without extracting the whole archive
  (cf. cmd/s3-zip-handlers.go).
"""

from __future__ import annotations

import io
import tarfile
import zipfile

from .api_errors import S3Error

SNOWBALL_HEADER = "x-amz-meta-snowball-auto-extract"
ZIP_EXTRACT_HEADER = "x-minio-extract"


def is_snowball_put(headers: dict) -> bool:
    h = {k.lower(): v for k, v in headers.items()}
    return h.get(SNOWBALL_HEADER, "").lower() == "true"


def extract_tar(body: bytes, key_prefix: str):
    """Yield (key, data, metadata) per regular tar member."""
    try:
        tf = tarfile.open(fileobj=io.BytesIO(body), mode="r:*")
    except tarfile.TarError:
        raise S3Error("MalformedXML", "body is not a tar archive") from None
    with tf:
        for member in tf:
            if not member.isreg():
                continue
            name = member.name
            # Path-escape guard BEFORE any normalization: absolute paths
            # and any '..' component are dropped, matching untar.go's
            # sanitization.
            if (not name or name.startswith("/")
                    or ".." in name.split("/")):
                continue
            while name.startswith("./"):
                name = name[2:]
            if not name:
                continue
            f = tf.extractfile(member)
            if f is None:
                continue
            key = f"{key_prefix.rstrip('/')}/{name}" if key_prefix \
                else name
            yield key, f.read(), {}


def is_zip_extract_get(headers: dict) -> bool:
    h = {k.lower(): v for k, v in headers.items()}
    return h.get(ZIP_EXTRACT_HEADER, "").lower() == "true"


def split_zip_path(key: str) -> tuple[str, str] | None:
    """'a/b.zip/inner/x' -> ('a/b.zip', 'inner/x')."""
    low = key.lower()
    idx = low.find(".zip/")
    if idx < 0:
        return None
    return key[:idx + 4], key[idx + 5:]


def read_zip_member(zip_bytes: bytes, member: str) -> bytes:
    try:
        with zipfile.ZipFile(io.BytesIO(zip_bytes)) as zf:
            try:
                return zf.read(member)
            except KeyError:
                raise S3Error("NoSuchKey",
                              f"no such member {member!r}") from None
    except zipfile.BadZipFile:
        raise S3Error("InvalidRequest", "object is not a zip") from None


def list_zip_members(zip_bytes: bytes) -> list[str]:
    try:
        with zipfile.ZipFile(io.BytesIO(zip_bytes)) as zf:
            return [i.filename for i in zf.infolist()
                    if not i.is_dir()]
    except zipfile.BadZipFile:
        raise S3Error("InvalidRequest", "object is not a zip") from None
